"""ktlint — the project-native multi-pass static analyzer.

Run as a CLI (``python -m tools.ktlint [--format=json] [paths]``) or
call :func:`lint` from tests/benches. Rule IDs are stable:

=======  ==============================================================
KT001    jit purity: no host syncs / impure calls inside jax.jit
         functions; static_argnames/donate_argnames name real params
KT002    lock discipline: self-attributes written both inside and
         outside ``with self._lock`` blocks
KT003    exception hygiene: broad excepts in controllers/kubelet/server
         must log with context or re-raise
KT004    bounded I/O: socket/HTTP operations carry explicit timeouts
KT005    metric naming: snake_case, unit-suffixed, via metrics.DEFAULT
KT006    parity: jitted ops kernels need a registered NumPy oracle
         twin (ops/parity.py) exercised by the named suite
KT007    kernel recompilation hazards: host round-trips in trace-time
         helpers, raw-cardinality device-array dims, dtype-unpinned
         literal arrays (scope: kubernetes_tpu/ops/)
KT008    fault-injection sites are registered named constants
         (utils/faults.py inventory); no string literals at
         fire()/inject(), no site minting outside the registry
KT009    mesh hygiene in ops/: device_put carries an explicit
         sharding, no jax.devices() indexing/slicing, no pmap, mesh
         construction only via the matrices seam
=======  ==============================================================

The interprocedural lock analysis (lock-order cycles KTSAN01, the
cross-module ``*_locked`` contract KTSAN02/KTSAN03) lives in
tools/ktlint/lockgraph.py and runs via ``python -m tools.ktlint
--lock-graph`` — see that module's docstring.

The kernel shape/dtype/sharding contract checker (abstract
interpretation of jaxprs against ops/contracts.py, zero kernel
executions) lives in tools/ktlint/ktshape.py and runs via ``python -m
tools.ktlint --kernel-contracts`` — see that module's docstring.

The static SPMD partitioning analyzer (partitioned lowering of every
kernel under a forced multi-device CPU mesh, collective inventories
verified against the declared communication budgets) lives in
tools/ktlint/ktmesh.py and runs via ``python -m tools.ktlint
--mesh-analysis [--devices N]`` — see that module's docstring.

Suppress one finding with ``# ktlint: disable=KT00N`` (on the line or
the line above); grandfather a backlog with the baseline file
(``python -m tools.ktlint --write-baseline``).
"""

from __future__ import annotations

import pathlib
from typing import Optional, Sequence

from tools.ktlint.framework import (  # noqa: F401  (public API)
    DEFAULT_BASELINE,
    REPO_ROOT,
    Baseline,
    Finding,
    Report,
    Rule,
    run,
)
from tools.ktlint.rules_jit import JitPurityRule
from tools.ktlint.rules_locks import LockDisciplineRule
from tools.ktlint.rules_except import ExceptionHygieneRule
from tools.ktlint.rules_io import BoundedIORule
from tools.ktlint.rules_metrics import MetricNamingRule
from tools.ktlint.rules_parity import OracleTwinRule
from tools.ktlint.rules_shape import ShapeHazardRule
from tools.ktlint.rules_faults import FaultSiteRule
from tools.ktlint.rules_mesh import MeshHygieneRule
from tools.ktlint.lockgraph import (  # noqa: F401  (public API)
    LockGraphReport,
    analyze as lock_graph,
)

#: Registry, in rule-id order. Adding a pass = appending here.
ALL_RULES = (
    JitPurityRule(),
    LockDisciplineRule(),
    ExceptionHygieneRule(),
    BoundedIORule(),
    MetricNamingRule(),
    OracleTwinRule(),
    ShapeHazardRule(),
    FaultSiteRule(),
    MeshHygieneRule(),
)


def rules_by_id(select: Optional[Sequence[str]] = None):
    if not select:
        return list(ALL_RULES)
    wanted = {s.strip().upper() for s in select}
    unknown = wanted - {r.id for r in ALL_RULES}
    if unknown:
        raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
    return [r for r in ALL_RULES if r.id in wanted]


def lint(
    paths: Sequence = (),
    select: Optional[Sequence[str]] = None,
    baseline_path: Optional[pathlib.Path] = DEFAULT_BASELINE,
) -> Report:
    """Lint `paths` (default: the kubernetes_tpu package) and return a
    Report. The default baseline applies; pass baseline_path=None for a
    baseline-free run (fixture tests)."""
    paths = [pathlib.Path(p) for p in paths] or [REPO_ROOT / "kubernetes_tpu"]
    baseline = (
        Baseline.load(baseline_path) if baseline_path is not None else None
    )
    return run(paths, rules_by_id(select), baseline)
