"""KT006 — kernel/oracle parity registration.

Every ``jax.jit``-decorated function under ``kubernetes_tpu/ops/``
must have a registered NumPy oracle twin in
``kubernetes_tpu/ops/parity.py`` (ORACLE_TWINS), and the registry must
stay live: oracles must resolve to real functions, suites must exist
and actually mention what they claim to exercise, and stale keys
(kernels that no longer exist) are findings too.

Pure-AST on purpose: the CLI lints the whole tree in milliseconds
without importing jax. The runtime complement (imports + getattr over
the same registry) lives in tests/test_ktsan.py.

Finding placement: missing-twin findings attach to the kernel's def
line in its ops file; registry-health findings attach to the entry's
line in parity.py — both sites accept the usual ``# ktlint:
disable=KT006`` pragma.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Dict, List, Optional, Tuple

from tools.ktlint.framework import (
    REPO_ROOT,
    FileContext,
    Finding,
    Rule,
    attr_chain,
)

OPS_DIR = "kubernetes_tpu/ops"
REGISTRY_PATH = "kubernetes_tpu/ops/parity.py"


#: Decorator names that mean "this def is a jitted kernel". traced_jit
#: (ops/ledger.py) is jax.jit plus the compile ledger — same parity
#: contract, same registry.
_JIT_NAMES = ("jit", "traced_jit")


def _is_jit_decorator(dec: ast.AST) -> bool:
    """jax.jit / jit / traced_jit bare, or functools.partial(jax.jit,
    ...) / partial(jit, ...), or jax.jit(...) / traced_jit(...) used as
    a decorator factory."""
    chain = attr_chain(dec)
    if chain and chain[-1] in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fchain = attr_chain(dec.func)
        if fchain and fchain[-1] in _JIT_NAMES:
            return True
        if fchain and fchain[-1] == "partial" and dec.args:
            achain = attr_chain(dec.args[0])
            return bool(achain) and achain[-1] in _JIT_NAMES
    return False


def jitted_kernels(tree: ast.Module, module_stem: str) -> List[Tuple[str, int]]:
    """[(registry key, lineno)] for every jitted def/assignment in one
    ops module. Nested defs key through their enclosing functions:
    'preemption._victim_prefix_kernel.kernel'."""
    out: List[Tuple[str, int]] = []

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{prefix}{child.name}"
                if any(_is_jit_decorator(d) for d in child.decorator_list):
                    out.append((f"{module_stem}.{name}", child.lineno))
                visit(child, name + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            elif isinstance(child, ast.Assign) and isinstance(
                child.value, ast.Call
            ):
                fchain = attr_chain(child.value.func)
                if fchain and fchain[-1] in _JIT_NAMES:
                    for t in child.targets:
                        if isinstance(t, ast.Name):
                            out.append(
                                (f"{module_stem}.{prefix}{t.id}", child.lineno)
                            )
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


def _load_registry(path: pathlib.Path) -> Tuple[Dict[str, dict], Dict[str, int]]:
    """(entries, key -> lineno) parsed from ORACLE_TWINS' dict literal.
    Raises ValueError when the registry is missing or not a literal."""
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "ORACLE_TWINS"
            for t in node.targets
        ):
            if not isinstance(node.value, ast.Dict):
                raise ValueError("ORACLE_TWINS must be a dict literal")
            entries: Dict[str, dict] = {}
            lines: Dict[str, int] = {}
            for k, v in zip(node.value.keys, node.value.values):
                if not (
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                ):
                    raise ValueError("ORACLE_TWINS keys must be str literals")
                entries[k.value] = ast.literal_eval(v)
                lines[k.value] = k.lineno
            return entries, lines
    raise ValueError("ORACLE_TWINS not found")


def _function_defined_in(path: pathlib.Path, func: str) -> bool:
    """Does `path` define (top-level, or as an assignment alias)
    `func`? AST check — no import."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError, ValueError):
        return False
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == func:
                return True
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == func:
                    return True
    return False


def resolve_oracle(ref: str) -> Optional[pathlib.Path]:
    """File defining the dotted oracle `ref`, or None. The module part
    resolves under kubernetes_tpu/ first (registry refs are package-
    relative), then from the repo root (tests.* helpers)."""
    if "." not in ref:
        return None
    modpath, func = ref.rsplit(".", 1)
    rel = modpath.replace(".", "/") + ".py"
    for cand in (REPO_ROOT / "kubernetes_tpu" / rel, REPO_ROOT / rel):
        if cand.exists() and _function_defined_in(cand, func):
            return cand
    return None


class OracleTwinRule(Rule):
    id = "KT006"
    title = "jitted ops kernels must have a registered NumPy oracle twin"

    def __init__(self):
        self._kernel_index: Optional[Dict[str, Tuple[str, int]]] = None

    def applies(self, ctx: FileContext) -> bool:
        return ctx.relpath.replace("\\", "/").startswith(OPS_DIR)

    # -- shared indexes (built once per process) -----------------------

    def _kernels_in_tree(self) -> Dict[str, Tuple[str, int]]:
        """registry key -> (relpath, lineno) over the whole ops dir
        (the stale-key check needs the full inventory regardless of
        which files this run lints)."""
        if self._kernel_index is None:
            idx: Dict[str, Tuple[str, int]] = {}
            for path in sorted((REPO_ROOT / OPS_DIR).glob("*.py")):
                try:
                    tree = ast.parse(path.read_text(), filename=str(path))
                except (OSError, SyntaxError, ValueError):
                    continue
                for key, line in jitted_kernels(tree, path.stem):
                    idx[key] = (f"{OPS_DIR}/{path.name}", line)
            self._kernel_index = idx
        return self._kernel_index

    # -- the pass ------------------------------------------------------

    def check(self, ctx: FileContext) -> List[Finding]:
        reg_path = REPO_ROOT / REGISTRY_PATH
        try:
            entries, entry_lines = _load_registry(reg_path)
        except (OSError, ValueError) as e:
            # Attach the broken-registry finding to whichever ops file
            # we're linting — every kernel is unverifiable without it.
            return [ctx.finding(self.id, 1, f"ops/parity.py unusable: {e}")]

        out: List[Finding] = []
        if ctx.relpath.replace("\\", "/") == REGISTRY_PATH:
            out.extend(self._check_registry(ctx, entries, entry_lines))
            return out

        module_stem = pathlib.Path(ctx.relpath).stem
        for key, line in jitted_kernels(ctx.tree, module_stem):
            if key not in entries:
                out.append(
                    ctx.finding(
                        self.id,
                        line,
                        f"jitted kernel {key} has no NumPy oracle twin "
                        "registered in ops/parity.py ORACLE_TWINS "
                        "(kernels land WITH their referee or not at all)",
                    )
                )
        return out

    def _check_registry(
        self, ctx: FileContext, entries: Dict[str, dict],
        entry_lines: Dict[str, int],
    ) -> List[Finding]:
        out: List[Finding] = []
        kernels = self._kernels_in_tree()
        for key, entry in entries.items():
            line = entry_lines.get(key, 1)
            if key not in kernels:
                out.append(
                    ctx.finding(
                        self.id, line,
                        f"ORACLE_TWINS entry {key!r} matches no jitted "
                        "kernel in ops/ (stale after a rename/removal?)",
                    )
                )
                continue
            oracle = entry.get("oracle", "")
            if not oracle or resolve_oracle(oracle) is None:
                out.append(
                    ctx.finding(
                        self.id, line,
                        f"ORACLE_TWINS[{key!r}].oracle = {oracle!r} does "
                        "not resolve to a defined function",
                    )
                )
            suite_rel = entry.get("suite", "")
            suite = REPO_ROOT / suite_rel
            if not suite_rel or not suite.exists():
                out.append(
                    ctx.finding(
                        self.id, line,
                        f"ORACLE_TWINS[{key!r}].suite = {suite_rel!r} "
                        "does not exist",
                    )
                )
                continue
            src = suite.read_text()
            mentions = [key.rsplit(".", 1)[-1]]
            if oracle:
                mentions.append(oracle.rsplit(".", 1)[-1])
            if entry.get("exercised_as"):
                mentions.append(entry["exercised_as"])
            if not any(m in src for m in mentions):
                out.append(
                    ctx.finding(
                        self.id, line,
                        f"suite {suite_rel} never mentions "
                        f"{' / '.join(sorted(set(mentions)))} — the "
                        f"registered twin for {key} is not exercised",
                    )
                )
        return out
