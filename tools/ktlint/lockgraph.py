"""ktsan, static half: a repo-wide lock-order graph and the
interprocedural ``*_locked`` contract.

ktlint's per-file rules (KT002) see one function at a time; the bugs
PR 6 made possible live BETWEEN functions and modules — the apiserver
holds its state lock and calls into the store, the watch-cache seeds
under its set lock while listing the store, the WAL group commit
crosses three locks. This pass builds one picture of all of it:

1. **Lock inventory.** Every ``threading.Lock/RLock/Condition`` or
   ``sanitizer.lock/rlock`` assigned to ``self.<attr>`` (or a module
   global). Sanitizer-factory locks contribute their runtime NAME as
   the graph node, so the static graph and a runtime graph dumped by
   ``KT_SANITIZE_REPORT`` merge on identical nodes.
2. **Ordering edges.** ``with a: with b:`` nesting (lexical), plus
   interprocedural closure: a call made while holding ``a`` to a
   function whose transitive acquisitions include ``b`` adds
   ``a -> b``. Call resolution covers ``self.m()``, ``self.attr.m()``
   via constructor-assignment type inference, module functions, and a
   unique-definer fallback for other receivers (skipped when the
   method name is defined by more than one class).
3. **Cycles (KTSAN01).** Strongly connected components of the merged
   (static + optional runtime) graph — each is a potential deadlock.
4. **``*_locked`` contract (KTSAN02/KTSAN03).** A call to any
   ``*_locked`` function must lexically hold the target class's
   contract lock (its ``_lock``, or its only lock) — or the caller is
   itself ``*_locked`` on the same contract, or is ``__init__``
   (construction is single-threaded by convention). And a ``*_locked``
   body must never re-acquire its own contract lock (re-entrancy
   masks ordering bugs and double-pays even when the lock is an
   RLock).

Findings accept the standard ``# ktlint: disable=KTSAN02`` pragma on
the offending line (or the line above). There is deliberately no
baseline: the tree must be clean.

Entry points: :func:`analyze` (library; bench.py embeds its counts)
and ``python -m tools.ktlint --lock-graph [--runtime-graph FILE]``.
"""

from __future__ import annotations

import ast
import json
import pathlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.ktlint.framework import (
    REPO_ROOT,
    attr_chain,
    is_suppressed,
    iter_files,
    pragma_map,
    relpath_of,
)
from tools.ktlint.framework import Finding

_THREADING_FACTORIES = {"Lock", "RLock", "Condition"}
_SAN_FACTORIES = {"lock": False, "rlock": True}  # name -> reentrant

#: Method names too generic for unique-definer call resolution even
#: when only one class currently defines them — a collision with a
#: future class would silently flip resolution.
_COMMON_NAMES = {
    "get", "put", "list", "add", "update", "delete", "close", "start",
    "stop", "run", "push", "pop", "next", "send", "clear", "items",
}


@dataclass
class LockDef:
    node: str  # graph node name (sanitizer name, or module.Class.attr)
    attr: str
    path: str
    line: int
    reentrant: bool
    io_gate: bool


@dataclass
class ClassInfo:
    module: str  # dotted module ("kubernetes_tpu.store.kvstore")
    name: str
    path: str
    locks: Dict[str, LockDef] = field(default_factory=dict)
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    attr_class: Dict[str, str] = field(default_factory=dict)

    @property
    def qual(self) -> str:
        return f"{self.module}.{self.name}"

    def contract_node(self) -> Optional[str]:
        """The lock the class's ``*_locked`` suffix names: ``_lock``
        when present, else the only lock, else undeterminable."""
        if "_lock" in self.locks:
            return self.locks["_lock"].node
        if len(self.locks) == 1:
            return next(iter(self.locks.values())).node
        return None


@dataclass
class CallSite:
    target_key: Optional[str]  # resolved summary key, or None
    target_cls: Optional[ClassInfo]
    target_name: str
    held: Tuple[str, ...]
    path: str
    line: int


@dataclass
class FnSummary:
    key: str  # "module.Class.method" / "module.func"
    cls: Optional[ClassInfo]
    name: str
    path: str
    line: int
    direct: List[Tuple[str, int]] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)


@dataclass
class Edge:
    src: str
    dst: str
    kind: str  # "static" | "static-call" | "runtime"
    site: str
    count: int = 1


@dataclass
class LockGraphReport:
    locks: List[LockDef] = field(default_factory=list)
    edges: List[Edge] = field(default_factory=list)
    cycles: List[dict] = field(default_factory=list)
    violations: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    runtime_findings: List[dict] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if (self.cycles or self.violations or
                     self.runtime_findings) else 0

    def counts(self) -> Dict[str, int]:
        out = {"KTSAN01": len(self.cycles), "KTSAN02": 0, "KTSAN03": 0}
        for v in self.violations:
            out[v.rule] = out.get(v.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "locks": [
                {"node": l.node, "path": l.path, "line": l.line,
                 "reentrant": l.reentrant, "io_gate": l.io_gate}
                for l in self.locks
            ],
            "edges": [
                {"from": e.src, "to": e.dst, "kind": e.kind,
                 "site": e.site, "count": e.count}
                for e in self.edges
            ],
            "cycles": self.cycles,
            "violations": [
                {"rule": v.rule, "path": v.path, "line": v.line,
                 "message": v.message}
                for v in self.violations
            ],
            "counts": self.counts(),
            "suppressed": self.suppressed,
            "runtime_findings": self.runtime_findings,
        }

    def render(self) -> str:
        lines = [
            f"lock graph: {len(self.locks)} locks, {len(self.edges)} "
            f"ordering edges ({sum(1 for e in self.edges if e.kind == 'runtime')}"
            " runtime-observed)",
        ]
        for e in sorted(self.edges, key=lambda e: (e.src, e.dst)):
            lines.append(f"  {e.src} -> {e.dst}  [{e.kind}] {e.site}")
        if self.cycles:
            lines.append(f"CYCLES ({len(self.cycles)}):")
            for c in self.cycles:
                lines.append(f"  KTSAN01 {' -> '.join(c['path'])}")
                for s in c.get("sites", []):
                    lines.append(f"    {s}")
        for v in self.violations:
            lines.append(f"{v.render()}")
        for f in self.runtime_findings:
            lines.append(f"RUNTIME {f.get('kind')}: {f}")
        lines.append(
            f"ktsan: {len(self.cycles)} cycle(s), "
            f"{len(self.violations)} contract violation(s), "
            f"{len(self.runtime_findings)} runtime finding(s) "
            f"({self.suppressed} suppressed)"
        )
        return "\n".join(lines)


# -- lock constructor detection ----------------------------------------


def lock_ctor_info(value: ast.AST) -> Optional[dict]:
    """{"name", "reentrant", "io_gate"} when `value` constructs a lock
    (threading.* or sanitizer factory, possibly behind an IfExp or
    wrapped in threading.Condition(...)), else None."""
    if isinstance(value, ast.IfExp):
        return lock_ctor_info(value.body) or lock_ctor_info(value.orelse)
    if not isinstance(value, ast.Call):
        return None
    chain = attr_chain(value.func)
    if not chain:
        return None
    tail = chain[-1]
    if tail == "Condition" and value.args:
        inner = lock_ctor_info(value.args[0])
        if inner:
            return inner
        ref = attr_chain(value.args[0])
        if ref:
            # Condition(self._lock) / Condition(_LOCK): the condition
            # wraps an EXISTING lock — same runtime object, so it must
            # resolve to the same graph node, not a phantom sibling
            # (otherwise a static edge through the condition and a
            # runtime edge through the lock never merge into a cycle).
            return {
                "name": None, "reentrant": False, "io_gate": False,
                "alias": ref[-1], "alias_self": ref[0] == "self",
            }
    if tail in _THREADING_FACTORIES:
        return {"name": None, "reentrant": tail == "RLock", "io_gate": False}
    if tail in _SAN_FACTORIES and len(chain) >= 2 and chain[-2] == "sanitizer":
        name = None
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            name = value.args[0].value
        io_gate = any(
            kw.arg == "io_gate" and isinstance(kw.value, ast.Constant)
            and bool(kw.value.value)
            for kw in value.keywords
        )
        return {"name": name, "reentrant": _SAN_FACTORIES[tail],
                "io_gate": io_gate}
    return None


def _self_attr(node: ast.AST) -> str:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


# -- index --------------------------------------------------------------


class _Index:
    def __init__(self):
        self.classes: Dict[str, ClassInfo] = {}  # qual -> info
        self.by_name: Dict[str, List[ClassInfo]] = {}
        self.module_locks: Dict[Tuple[str, str], LockDef] = {}
        self.module_funcs: Dict[str, Tuple[ast.AST, str]] = {}  # key->(fn,path)
        self.method_definers: Dict[str, List[ClassInfo]] = {}
        self.pragmas: Dict[str, Dict[int, frozenset]] = {}  # relpath->map

    def class_by_simple_name(self, name: Optional[str]) -> Optional[ClassInfo]:
        if not name:
            return None
        hits = self.by_name.get(name, ())
        return hits[0] if len(hits) == 1 else None

    def unique_definer(self, method: str) -> Optional[ClassInfo]:
        if method in _COMMON_NAMES or method.startswith("__"):
            return None
        hits = self.method_definers.get(method, ())
        return hits[0] if len(hits) == 1 else None


def _module_of(relpath: str) -> str:
    return relpath.replace("\\", "/").removesuffix(".py").replace("/", ".")


def _index_file(idx: _Index, tree: ast.Module, relpath: str) -> None:
    module = _module_of(relpath)
    mod_stem = module.rsplit(".", 1)[-1]
    idx.pragmas[relpath] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            info = lock_ctor_info(node.value)
            if info:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        alias = info.get("alias")
                        if alias and not info.get("alias_self"):
                            target = idx.module_locks.get((module, alias))
                            if target is not None:
                                idx.module_locks[(module, t.id)] = target
                                continue
                        nodename = info["name"] or f"{mod_stem}.{t.id}"
                        idx.module_locks[(module, t.id)] = LockDef(
                            nodename, t.id, relpath, node.lineno,
                            info["reentrant"], info["io_gate"],
                        )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            idx.module_funcs[f"{module}.{node.name}"] = (node, relpath)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ci = ClassInfo(module, node.name, relpath)
        aliases: List[Tuple[str, str, int]] = []  # (attr, target attr, line)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                info = lock_ctor_info(sub.value)
                for t in sub.targets:
                    attr = _self_attr(t)
                    if not attr:
                        continue
                    if info:
                        alias = info.get("alias")
                        if alias and info.get("alias_self"):
                            # Resolved after the walk: the wrapped lock
                            # attr may be assigned later in the class.
                            aliases.append((attr, alias, sub.lineno))
                            continue
                        nodename = (
                            info["name"] or f"{mod_stem}.{node.name}.{attr}"
                        )
                        ci.locks[attr] = LockDef(
                            nodename, attr, relpath, sub.lineno,
                            info["reentrant"], info["io_gate"],
                        )
                    else:
                        cls_name = _ctor_class_name(sub.value)
                        if cls_name and attr not in ci.attr_class:
                            ci.attr_class[attr] = cls_name
        for attr, target_attr, lineno in aliases:
            target = ci.locks.get(target_attr)
            if target is not None:
                ci.locks[attr] = target
            else:
                ci.locks[attr] = LockDef(
                    f"{mod_stem}.{node.name}.{attr}", attr, relpath,
                    lineno, False, False,
                )
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[item.name] = item
        idx.classes[ci.qual] = ci
        idx.by_name.setdefault(ci.name, []).append(ci)
        for m in ci.methods:
            idx.method_definers.setdefault(m, []).append(ci)


def _ctor_class_name(value: ast.AST) -> Optional[str]:
    """Class simple name when `value` looks like ClassName(...) (also
    through `x or ClassName(...)` / IfExp) — the light type inference
    behind self.<attr>.method() resolution."""
    if isinstance(value, ast.BoolOp):
        for v in value.values:
            got = _ctor_class_name(v)
            if got:
                return got
        return None
    if isinstance(value, ast.IfExp):
        return _ctor_class_name(value.body) or _ctor_class_name(value.orelse)
    if isinstance(value, ast.Call):
        chain = attr_chain(value.func)
        if chain and chain[-1][:1].isupper():
            return chain[-1]
    return None


# -- per-function analysis ---------------------------------------------


class _Analyzer:
    def __init__(self, idx: _Index):
        self.idx = idx
        self.summaries: Dict[str, FnSummary] = {}
        self.edges: Dict[Tuple[str, str, str], Edge] = {}
        self.violations: List[Finding] = []
        self.suppressed = 0

    # .. resolution ....................................................

    def _resolve_lock_expr(
        self, expr: ast.AST, cls: Optional[ClassInfo], module: str
    ) -> Optional[LockDef]:
        chain = attr_chain(expr)
        if not chain:
            return None
        if chain[0] == "self" and cls is not None:
            if len(chain) == 2:
                return cls.locks.get(chain[1])
            if len(chain) == 3:
                target = self.idx.class_by_simple_name(
                    cls.attr_class.get(chain[1])
                )
                if target:
                    return target.locks.get(chain[2])
            return None
        if len(chain) == 1:
            return self.idx.module_locks.get((module, chain[0]))
        return None

    def _resolve_call(
        self, call: ast.Call, cls: Optional[ClassInfo], module: str
    ) -> Tuple[Optional[str], Optional[ClassInfo], str]:
        """(summary key or None, target class or None, method name)."""
        chain = attr_chain(call.func)
        if not chain:
            return None, None, ""
        name = chain[-1]
        if len(chain) == 1:
            key = f"{module}.{name}"
            if key in self.idx.module_funcs:
                return key, None, name
            target = self.idx.class_by_simple_name(name)
            if target and "__init__" in target.methods:
                return f"{target.qual}.__init__", target, "__init__"
            return None, None, name
        if chain[0] == "self" and cls is not None:
            if len(chain) == 2:
                if name in cls.methods:
                    return f"{cls.qual}.{name}", cls, name
                return None, cls, name
            if len(chain) == 3:
                target = self.idx.class_by_simple_name(
                    cls.attr_class.get(chain[1])
                )
                if target and name in target.methods:
                    return f"{target.qual}.{name}", target, name
                return None, target, name
        # Fallback: obj.m() with exactly one definer repo-wide.
        target = self.idx.unique_definer(name)
        if target:
            return f"{target.qual}.{name}", target, name
        return None, None, name

    # .. walking .......................................................

    def analyze_function(
        self, fn, cls: Optional[ClassInfo], module: str, relpath: str,
        key: str,
    ) -> None:
        held: Tuple[str, ...] = ()
        if cls is not None and fn.name.endswith("_locked"):
            c = cls.contract_node()
            if c:
                held = (c,)
        summary = FnSummary(key, cls, fn.name, relpath, fn.lineno)
        self.summaries[key] = summary
        self._visit_block(fn.body, held, summary, cls, module, relpath)

    def _visit_block(self, stmts, held, summary, cls, module, relpath):
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._collect_calls(
                        item.context_expr, held, summary, cls, module, relpath
                    )
                acquired: List[LockDef] = []
                for item in st.items:
                    ld = self._resolve_lock_expr(item.context_expr, cls, module)
                    if ld is not None:
                        acquired.append(ld)
                for ld in acquired:
                    self._on_acquire(
                        held, ld, summary, cls, relpath, st.lineno
                    )
                new = held + tuple(
                    ld.node for ld in acquired if ld.node not in held
                )
                self._visit_block(st.body, new, summary, cls, module, relpath)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Closures run on the same threads by convention here
                # (KT002 makes the same call) — analyze under the
                # current held set.
                self._visit_block(
                    st.body, held, summary, cls, module, relpath
                )
                continue
            for node in self._own_exprs(st):
                self._collect_calls(
                    node, held, summary, cls, module, relpath, walk=False
                )
            for fld in ("body", "orelse", "finalbody"):
                sub = getattr(st, fld, None)
                if isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    self._visit_block(sub, held, summary, cls, module, relpath)
            for h in getattr(st, "handlers", ()):
                self._visit_block(h.body, held, summary, cls, module, relpath)

    @staticmethod
    def _own_exprs(st: ast.stmt):
        """Every AST node belonging to `st` except nested statement
        blocks (those get their own _visit_block pass)."""
        blocked = {"body", "orelse", "finalbody", "handlers"}
        stack: List[ast.AST] = []
        for fld, value in ast.iter_fields(st):
            if fld in blocked:
                continue
            if isinstance(value, ast.AST):
                stack.append(value)
            elif isinstance(value, list):
                stack.extend(v for v in value if isinstance(v, ast.AST))
        out = []
        while stack:
            n = stack.pop()
            out.append(n)
            stack.extend(ast.iter_child_nodes(n))
        return out

    def _collect_calls(
        self, node, held, summary, cls, module, relpath, walk=True
    ):
        nodes = ast.walk(node) if walk else (node,)
        for n in nodes:
            if not isinstance(n, ast.Call):
                continue
            key, target_cls, name = self._resolve_call(n, cls, module)
            summary.calls.append(
                CallSite(key, target_cls, name, held, relpath, n.lineno)
            )
            if name.endswith("_locked"):
                self._check_locked_call(
                    summary, cls, target_cls, name, held, relpath, n.lineno
                )

    def _on_acquire(self, held, ld: LockDef, summary, cls, relpath, line):
        summary.direct.append((ld.node, line))
        contract = cls.contract_node() if cls else None
        if (
            summary.name.endswith("_locked")
            and contract is not None
            and ld.node == contract
        ):
            self._violation(
                "KTSAN03", relpath, line,
                f"{summary.key.rsplit('.', 2)[-2]}.{summary.name} "
                f"re-acquires its own contract lock {ld.node} — the "
                "_locked suffix promises the caller already holds it "
                "(re-entrancy masks ordering bugs even on an RLock)",
            )
            return
        for h in held:
            if h == ld.node:
                continue
            self._edge(h, ld.node, "static", f"{relpath}:{line}")

    def _edge(self, src: str, dst: str, kind: str, site: str) -> None:
        k = (src, dst, kind)
        hit = self.edges.get(k)
        if hit is None:
            self.edges[k] = Edge(src, dst, kind, site)
        else:
            hit.count += 1

    def _check_locked_call(
        self, summary, cls, target_cls, name, held, relpath, line
    ):
        if summary.name == "__init__":
            return  # construction is single-threaded by convention
        if target_cls is None:
            target_cls = self.idx.unique_definer(name)
        if target_cls is None or name not in target_cls.methods:
            return  # unresolvable receiver — runtime half covers it
        contract = target_cls.contract_node()
        if contract is None:
            return
        if contract in held:
            return
        self._violation(
            "KTSAN02", relpath, line,
            f"call to {target_cls.name}.{name}() without holding its "
            f"contract lock {contract} on this path — *_locked means "
            "the CALLER holds the lock (take it, or rename the callee "
            "if the contract no longer applies)",
        )

    def _violation(self, rule, relpath, line, message):
        f = Finding(rule, relpath, line, message)
        pragmas = self.idx.pragmas.get(relpath, {})
        if is_suppressed(f, pragmas):
            self.suppressed += 1
        else:
            self.violations.append(f)

    # .. interprocedural closure .......................................

    def propagate(self) -> None:
        """Fixpoint transitive acquisitions, then call-site edges."""
        acq: Dict[str, Set[str]] = {
            k: {n for n, _ in s.direct} for k, s in self.summaries.items()
        }
        changed = True
        while changed:
            changed = False
            for k, s in self.summaries.items():
                cur = acq[k]
                for cs in s.calls:
                    if cs.target_key and cs.target_key in acq:
                        extra = acq[cs.target_key] - cur
                        if extra:
                            cur |= extra
                            changed = True
        for k, s in self.summaries.items():
            for cs in s.calls:
                if not cs.target_key or cs.target_key not in acq:
                    continue
                for h in cs.held:
                    for L in acq[cs.target_key]:
                        if L == h:
                            continue
                        self._edge(
                            h, L, "static-call",
                            f"{cs.path}:{cs.line} via {cs.target_name}()",
                        )


# -- cycles -------------------------------------------------------------


def _find_cycles(edges: Sequence[Edge]) -> List[dict]:
    adj: Dict[str, List[Tuple[str, Edge]]] = {}
    nodes: Set[str] = set()
    for e in edges:
        adj.setdefault(e.src, []).append((e.dst, e))
        nodes.add(e.src)
        nodes.add(e.dst)

    # Tarjan SCC (iterative).
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v0: str) -> None:
        work = [(v0, iter(adj.get(v0, ())))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w, _e in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(comp)

    for n in sorted(nodes):
        if n not in index:
            strongconnect(n)

    out = []
    for comp in sccs:
        compset = set(comp)
        # One concrete cycle path inside the SCC for the report.
        start = sorted(comp)[0]
        path = [start]
        seen = {start}
        cur = start
        while True:
            nxt = None
            for w, _e in adj.get(cur, ()):
                if w in compset and (w == start or w not in seen):
                    nxt = w
                    break
            if nxt is None or nxt == start:
                path.append(start)
                break
            path.append(nxt)
            seen.add(nxt)
            cur = nxt
        sites = []
        for a, b in zip(path, path[1:]):
            for e in adj.get(a, ()):
                if e[0] == b:
                    sites.append(f"{a} -> {b}: [{e[1].kind}] {e[1].site}")
                    break
        out.append({
            "rule": "KTSAN01",
            "nodes": sorted(comp),
            "path": path,
            "sites": sites,
        })
    return out


# -- entry point --------------------------------------------------------


def analyze(
    paths: Sequence = (),
    runtime: Optional[dict] = None,
) -> LockGraphReport:
    """Run the whole-tree lock-graph analysis. `runtime` is an
    optional sanitizer report dict ({"edges": [...], "findings":
    [...]}, the KT_SANITIZE_REPORT format) merged into the graph."""
    roots = [pathlib.Path(p) for p in paths] or [REPO_ROOT / "kubernetes_tpu"]
    idx = _Index()
    parsed: List[Tuple[ast.Module, str]] = []
    for path in iter_files(roots):
        try:
            src = path.read_text()
            tree = ast.parse(src, filename=str(path))
        except (OSError, SyntaxError, ValueError):
            continue
        relpath = relpath_of(path)
        parsed.append((tree, relpath))
        _index_file(idx, tree, relpath)
        idx.pragmas[relpath] = pragma_map(src.splitlines())

    ana = _Analyzer(idx)
    for tree, relpath in parsed:
        module = _module_of(relpath)
        for ci in [c for c in idx.classes.values() if c.path == relpath]:
            for mname, fn in ci.methods.items():
                ana.analyze_function(
                    fn, ci, module, relpath, f"{ci.qual}.{mname}"
                )
        for key, (fn, fpath) in idx.module_funcs.items():
            if fpath == relpath and key.rsplit(".", 1)[0] == module:
                ana.analyze_function(fn, None, module, relpath, key)
    ana.propagate()

    report = LockGraphReport()
    seen_locks = set()
    for ci in idx.classes.values():
        for ld in ci.locks.values():
            if ld.node not in seen_locks:
                seen_locks.add(ld.node)
                report.locks.append(ld)
    for ld in idx.module_locks.values():
        if ld.node not in seen_locks:
            seen_locks.add(ld.node)
            report.locks.append(ld)
    report.locks.sort(key=lambda l: l.node)

    edges = list(ana.edges.values())
    if runtime:
        for e in runtime.get("edges", ()):
            edges.append(Edge(
                e["from"], e["to"], "runtime",
                e.get("site", ""), int(e.get("count", 1)),
            ))
        report.runtime_findings = list(runtime.get("findings", ()))
    report.edges = edges
    report.cycles = _find_cycles(edges)
    report.violations = sorted(
        ana.violations, key=lambda f: (f.path, f.line, f.rule)
    )
    report.suppressed = ana.suppressed
    return report


def load_runtime_report(path) -> dict:
    return json.loads(pathlib.Path(path).read_text())
