"""KT008 — fault-injection sites must be registered named constants.

The chaos plane (kubernetes_tpu/utils/faults.py) keys everything on
site identity: the soak schedule arms rules by site, the artifact
reports per-site counters, and reviewers audit the blast radius by
reading ONE inventory. A stringly-typed call — ``faults.fire(
"kvstore.wal.fsync")`` — silently forks that inventory: a typo'd name
never fires, never shows in stats, and the "tested under faults" claim
quietly becomes false. Same discipline as the sanitizer's factory lock
names (KT002 recognizes those for the same reason).

Checked shapes:

- ``faults.fire(...)`` / ``faults.inject(...)`` (or bare ``fire``/
  ``inject`` imported from the faults module) whose first argument is
  a string/constant literal instead of a site reference;
- minting sites — ``faults.FaultSite(...)`` / the module's ``_site``
  helper — anywhere outside ``kubernetes_tpu/utils/faults.py``: ad-hoc
  sites bypass the audited inventory.

A dynamic site variable (``fire(site)`` in a loop over the registry)
is fine — the rule only rejects literals and out-of-module minting.
"""

from __future__ import annotations

import ast
from typing import List

from tools.ktlint.framework import FileContext, Finding, Rule, attr_chain

_FAULTS_MODULE = "kubernetes_tpu.utils.faults"
_FAULTS_FILE = "kubernetes_tpu/utils/faults.py"
_CALLS = ("fire", "inject")
_MINTERS = ("FaultSite", "_site")


class FaultSiteRule(Rule):
    id = "KT008"
    title = "fault-injection sites must be registered named constants"

    @staticmethod
    def _alias_map(tree: ast.Module) -> dict:
        """Name -> the dotted module path it refers to, for every
        import that could reach the faults module: ``faults`` (or an
        asname), ``utils`` from ``from kubernetes_tpu import utils``,
        ``kubernetes_tpu`` from a plain dotted import, and members
        imported straight from the faults module (``fire``, ...)."""
        aliases: dict = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == _FAULTS_MODULE or _FAULTS_MODULE.startswith(
                        alias.name + "."
                    ):
                        if alias.asname:
                            aliases[alias.asname] = alias.name
                        else:
                            # `import a.b.c` binds the top-level `a`;
                            # usage spells the full dotted path.
                            top = alias.name.split(".", 1)[0]
                            aliases[top] = top
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    full = f"{node.module}.{alias.name}"
                    if full == _FAULTS_MODULE or _FAULTS_MODULE.startswith(
                        full + "."
                    ) or full.startswith(_FAULTS_MODULE + "."):
                        aliases[alias.asname or alias.name] = full
        return aliases

    @staticmethod
    def _resolve(chain: List[str], aliases: dict) -> str:
        """The dotted path a chain like ['utils','faults','fire']
        refers to, with its head substituted through the alias map;
        '' when the head isn't a tracked import."""
        head = aliases.get(chain[0])
        if head is None:
            return ""
        return ".".join([head] + chain[1:])

    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        aliases = self._alias_map(ctx.tree)
        if not aliases and _FAULTS_FILE not in ctx.relpath:
            return out
        in_faults_module = ctx.relpath.endswith(_FAULTS_FILE)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain:
                continue
            name = chain[-1]
            resolved = self._resolve(chain, aliases)
            is_faults_call = resolved == f"{_FAULTS_MODULE}.{name}"
            if name in _CALLS and is_faults_call and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    out.append(
                        ctx.finding(
                            self.id, node,
                            f"{name}() takes a registered site constant "
                            f"(faults.WAL_FSYNC, ...), not the string "
                            f"literal {first.value!r} — stringly-typed "
                            "sites fork the audited inventory",
                        )
                    )
            if (
                name in _MINTERS
                and not in_faults_module
                and is_faults_call
            ):
                # FaultSite(...)/_site(...) outside the registry module
                # mints an unaudited ad-hoc site.
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{name}() mints a fault site outside "
                        f"{_FAULTS_FILE}; add it to the registry's "
                        "inventory instead",
                    )
                )
        return out
