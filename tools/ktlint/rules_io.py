"""KT004 — bounded I/O.

Every blocking network operation must carry an explicit timeout: an
unbounded ``urlopen`` in a kubelet probe or an unbounded connect in the
apiserver's log-relay path wedges a worker thread forever the first
time a peer hangs (not crashes), and thread-per-connection daemons run
out of workers long before anyone notices. Checked shapes:

- ``urllib.request.urlopen(...)`` needs ``timeout=`` (or the 3rd
  positional argument);
- ``socket.create_connection(...)`` needs ``timeout=`` (or the 2nd
  positional argument);
- ``http.client.HTTPConnection(...)`` / ``HTTPSConnection(...)`` need
  ``timeout=``;
- ``<sock>.connect(...)`` where ``<sock>`` was built by
  ``socket.socket(...)`` in the same function and no
  ``<sock>.settimeout(...)`` appears in that function.

UDP ``connect()`` (which only sets the peer address and cannot block)
and deliberately-unbounded streams get a ``# ktlint: disable=KT004``
pragma at the call site.
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.ktlint.framework import FileContext, Finding, Rule, attr_chain


def _has_kw(node: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in node.keywords) or any(
        kw.arg is None for kw in node.keywords  # **kwargs: assume bounded
    )


class BoundedIORule(Rule):
    id = "KT004"
    title = "network operations must carry an explicit timeout"

    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain:
                continue
            name = chain[-1]
            if name == "urlopen" and "urlopen" in chain:
                if not _has_kw(node, "timeout") and len(node.args) < 3:
                    out.append(
                        ctx.finding(
                            self.id, node,
                            "urlopen() without timeout= blocks forever on "
                            "a hung peer",
                        )
                    )
            elif name == "create_connection":
                if not _has_kw(node, "timeout") and len(node.args) < 2:
                    out.append(
                        ctx.finding(
                            self.id, node,
                            "socket.create_connection() without timeout= "
                            "blocks forever on a hung peer",
                        )
                    )
            elif name in ("HTTPConnection", "HTTPSConnection"):
                if not _has_kw(node, "timeout"):
                    out.append(
                        ctx.finding(
                            self.id, node,
                            f"{name}() without timeout= gives every request "
                            "on this connection an unbounded wait",
                        )
                    )
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_raw_sockets(ctx, node))
        return out

    def _check_raw_sockets(self, ctx: FileContext, fn) -> List[Finding]:
        """Flag <name>.connect() where <name> = socket.socket(...) in
        this function and <name>.settimeout(...) never appears."""
        created: Set[str] = set()
        timed: Set[str] = set()
        connects: List[tuple] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if attr_chain(node.value.func)[-1:] == ["socket"]:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            created.add(t.id)
            elif isinstance(node, ast.withitem) and isinstance(
                node.context_expr, ast.Call
            ):
                if attr_chain(node.context_expr.func)[-1:] == ["socket"]:
                    if isinstance(node.optional_vars, ast.Name):
                        created.add(node.optional_vars.id)
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if len(chain) == 2 and chain[1] == "settimeout":
                    timed.add(chain[0])
                elif len(chain) == 2 and chain[1] == "connect":
                    connects.append((chain[0], node))
        out: List[Finding] = []
        for name, node in connects:
            if name in created and name not in timed:
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{name}.connect() on a socket with no settimeout() "
                        "blocks forever on a hung peer",
                    )
                )
        return out
