"""KT007 — kernel recompilation hazards (the ktshape AST family).

The contract checker (tools/ktlint/ktshape.py) verifies declared
shapes/dtypes by abstract interpretation; KT007 is its AST complement,
catching the hazards that produce recompile storms or dtype drift
BEFORE a kernel ever traces. Scope: ``kubernetes_tpu/ops/`` — the
kernel layer.

Three checks:

- **host round-trips in traced context** — ``.item()``, ``.tolist()``,
  ``int()/float()/bool()`` casts, ``np.asarray``/``np.array``,
  ``jax.device_get`` inside a *trace-time helper*: a function that is
  not itself jit-decorated but is referenced (called, or passed as a
  callback) from a jitted kernel in the same file. KT001 already
  polices directly-decorated bodies; KT007 closes the interprocedural
  gap — ops/ kernels are built from helper pyramids (``_feasible``,
  ``run_windowed``, ``choose`` callbacks) and a host sync buried two
  helpers deep stalls the solve exactly the same.
- **unbucketed device-array dims** — ``jnp.zeros/ones/full/empty/
  arange`` whose size expression contains a raw cardinality
  (``len(...)``, ``.count``, ``.n_pods``, ``.n_nodes``) not routed
  through a bucket helper (``pow2_bucket``/``_pod_axis_bucket``/
  ``_round_up``/``_svc_pad``/``_bucket``/``node_axis_multiple``).
  Every distinct device-array shape is a fresh XLA executable; a shape
  keyed on a raw cluster count recompiles on every drift (seconds per
  compile — the storm the pow2 lattice exists to prevent).
- **dtype-unpinned literal arrays** — ``jnp.array(...)`` without
  ``dtype=``, and ``jnp.asarray(<literal>)`` without ``dtype=``:
  dtype inference from Python literals is promotion-dependent (weak
  f32 / i32 by accident), and kernel dtypes are contract-pinned to the
  NumPy oracle twins' (ops/contracts.py).

Standard pragmas apply (``# ktlint: disable=KT007``); ``--select
KT007`` runs the family alone.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from tools.ktlint.framework import FileContext, Finding, Rule, attr_chain
from tools.ktlint.rules_jit import _jit_decoration

#: Bucket helpers that launder a raw cardinality onto the lattice.
_BUCKET_HELPERS = {
    "pow2_bucket",
    "_pod_axis_bucket",
    "_round_up",
    "_svc_pad",
    "_bucket",
    "node_axis_multiple",
}

#: jnp constructors whose first argument is a shape/size.
_SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange"}

#: Raw-cardinality attribute names: live object counts, never shapes.
_RAW_COUNT_ATTRS = {"count", "n_pods", "n_nodes"}

_HOST_SYNC_CALLS = {
    ("np", "asarray"): "np.asarray",
    ("np", "array"): "np.array",
    ("numpy", "asarray"): "numpy.asarray",
    ("numpy", "array"): "numpy.array",
    ("jax", "device_get"): "jax.device_get",
}
_CAST_BUILTINS = {"int", "float", "bool"}


def _is_jnp_call(chain: List[str], name: str) -> bool:
    """jnp.<name> / jax.numpy.<name>."""
    return (
        len(chain) >= 2
        and chain[-1] == name
        and (chain[0] in ("jnp",) or chain[:2] == ["jax", "numpy"])
    )


class _RawDimScanner(ast.NodeVisitor):
    """Does a size expression contain a raw cardinality NOT dominated
    by a bucket-helper call?"""

    def __init__(self):
        self.raw: List[ast.AST] = []

    def visit_Call(self, node: ast.Call):
        chain = attr_chain(node.func)
        if chain and chain[-1] in _BUCKET_HELPERS:
            return  # everything below is laundered onto the lattice
        if chain == ["len"]:
            self.raw.append(node)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        if node.attr in _RAW_COUNT_ATTRS:
            self.raw.append(node)
        self.generic_visit(node)


class ShapeHazardRule(Rule):
    id = "KT007"
    title = "kernel recompilation hazards (host syncs, unbucketed dims)"

    def applies(self, ctx: FileContext) -> bool:
        return "ops" in ctx.path.parts

    # -- traced-context closure ----------------------------------------

    def _traced_helpers(self, ctx: FileContext) -> Dict[str, ast.AST]:
        """Same-file functions reachable from a jitted kernel: seeds
        are jit/traced_jit-decorated defs; any module-level def whose
        NAME is loaded inside traced context (a call, or a callback
        reference like ``choose=_priced_choose``) joins the closure.
        Returns {helper name: def node} for the NON-decorated members
        (KT001 owns the decorated bodies)."""
        defs: Dict[str, ast.AST] = {}
        seeds: List[ast.AST] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if any(_jit_decoration(d) for d in node.decorator_list):
                seeds.append(node)
            else:
                defs.setdefault(node.name, node)
        traced: Dict[str, ast.AST] = {}
        frontier = list(seeds)
        while frontier:
            fn = frontier.pop()
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in defs
                    and node.id not in traced
                ):
                    traced[node.id] = defs[node.id]
                    frontier.append(defs[node.id])
        return traced

    def _check_helper(
        self, ctx: FileContext, fn: ast.AST, helper_of: str
    ) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("item", "tolist")
            ):
                out.append(
                    ctx.finding(
                        self.id, node,
                        f".{node.func.attr}() in {fn.name}() — a trace-"
                        f"time helper of jitted {helper_of}() — forces "
                        "a device->host round-trip mid-solve",
                    )
                )
            elif chain and tuple(chain[-2:]) in _HOST_SYNC_CALLS:
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{'.'.join(chain)}() in {fn.name}() — a trace-"
                        f"time helper of jitted {helper_of}() — forces "
                        "a device->host sync inside the traced region",
                    )
                )
            elif (
                len(chain) == 1
                and chain[0] in _CAST_BUILTINS
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
            ):
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{chain[0]}({node.args[0].id}) in {fn.name}() "
                        f"— a trace-time helper of jitted {helper_of}()"
                        " — concretizes a traced value (host sync / "
                        "TracerError; hoist statics to the jit "
                        "boundary)",
                    )
                )
        return out

    # -- the pass ------------------------------------------------------

    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []

        # (a) host round-trips in trace-time helpers.
        jitted_names = {
            node.name
            for node in ast.walk(ctx.tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and any(_jit_decoration(d) for d in node.decorator_list)
        }
        anchor = ", ".join(sorted(jitted_names)) or "?"
        for _, fn in sorted(self._traced_helpers(ctx).items()):
            out.extend(self._check_helper(ctx, fn, anchor))

        # (b) + (c): one walk over every call site.
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain:
                continue
            kwargs = {kw.arg for kw in node.keywords}
            # (b) unbucketed dims in jnp constructors.
            if chain[-1] in _SHAPE_CTORS and _is_jnp_call(chain, chain[-1]):
                scanner = _RawDimScanner()
                size_args = (
                    list(node.args)
                    if chain[-1] == "arange"
                    else list(node.args[:1])
                )
                size_args += [
                    kw.value for kw in node.keywords if kw.arg == "shape"
                ]
                for a in size_args:
                    scanner.visit(a)
                for raw in scanner.raw[:1]:
                    what = (
                        "len(...)"
                        if isinstance(raw, ast.Call)
                        else f".{raw.attr}"
                    )
                    out.append(
                        ctx.finding(
                            self.id, node,
                            f"{'.'.join(chain)}() sized by raw "
                            f"cardinality {what} — every distinct "
                            "device shape is a fresh XLA executable; "
                            "route the dim through pow2_bucket/"
                            "_pod_axis_bucket so cluster drift reuses "
                            "the compiled kernel",
                        )
                    )
            # (c) dtype-unpinned literal arrays.
            elif _is_jnp_call(chain, "array") and "dtype" not in kwargs:
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{'.'.join(chain)}() without dtype= — literal "
                        "dtype inference is promotion-dependent; "
                        "kernel dtypes are contract-pinned to the "
                        "oracle twins (ops/contracts.py)",
                    )
                )
            elif (
                _is_jnp_call(chain, "asarray")
                and "dtype" not in kwargs
                and node.args
                and isinstance(
                    node.args[0], (ast.Constant, ast.List, ast.Tuple)
                )
            ):
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{'.'.join(chain)}(<literal>) without dtype= "
                        "— Python literals infer weak/default dtypes; "
                        "pin the dtype the contract declares",
                    )
                )
        return out
