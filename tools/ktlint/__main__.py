"""CLI: python -m tools.ktlint [options] [paths]

Text output (default) is one line per finding plus a summary; --format
json emits a machine-readable report (bench.py and dashboards count
findings per rule over time from it). Exit 0 iff no active findings.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

if __package__ in (None, ""):  # `python tools/ktlint` (not -m)
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent.parent))

from tools import ktlint
from tools.ktlint.framework import Baseline, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.ktlint",
        description="project-native multi-pass static analyzer",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: kubernetes_tpu/)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--select", default="",
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--baseline", default=str(ktlint.DEFAULT_BASELINE),
        help="baseline file ('' disables)",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate the baseline from current findings and exit 0",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print rule ids and exit"
    )
    ap.add_argument(
        "--lock-graph", action="store_true",
        help="run the interprocedural lock analysis instead of the "
        "per-file rules: lock-order cycles (KTSAN01) and the "
        "*_locked contract (KTSAN02/KTSAN03)",
    )
    ap.add_argument(
        "--runtime-graph", default="",
        help="with --lock-graph: merge a runtime edge graph dumped by "
        "a KT_SANITIZE_REPORT=<file> sanitizer run",
    )
    ap.add_argument(
        "--kernel-contracts", action="store_true",
        help="run the kernel shape/dtype/sharding contract checker "
        "instead of the per-file rules: abstract interpretation of "
        "every ORACLE_TWINS kernel against ops/contracts.py (zero "
        "kernel executions; forces JAX_PLATFORMS=cpu when unset)",
    )
    ap.add_argument(
        "--mesh-analysis", action="store_true",
        help="run the static SPMD partitioning analyzer instead of "
        "the per-file rules: partitioned-lower every ORACLE_TWINS "
        "kernel under a forced multi-device CPU mesh and verify its "
        "collective inventory against the declared communication "
        "budget (compile only, zero kernel executions; <2 visible "
        "devices degrades to 'skipped' + exit 0)",
    )
    ap.add_argument(
        "--devices", type=int, default=8,
        help="with --mesh-analysis: host-platform device count to "
        "force (and mesh size); only binds if jax's CPU backend has "
        "not initialized yet (default: 8)",
    )
    args = ap.parse_args(argv)

    if args.mesh_analysis:
        from tools.ktlint import ktmesh

        if args.paths:
            # Same contract as --kernel-contracts: positional args are
            # kernel-registry keys, and an unknown one must error, not
            # silently shrink the gate to zero kernels.
            from kubernetes_tpu.ops.contracts import CONTRACTS
            from kubernetes_tpu.ops.parity import ORACLE_TWINS

            known = set(CONTRACTS) | set(ORACLE_TWINS)
            unknown = [p for p in args.paths if p not in known]
            if unknown:
                print(
                    "--mesh-analysis takes ORACLE_TWINS kernel keys "
                    f"(e.g. 'solver.explain_rows'), not paths: {unknown}",
                    file=sys.stderr,
                )
                return 2
        report = ktmesh.analyze(
            devices=args.devices, kernels=args.paths or None
        )
        if args.format == "json":
            print(json.dumps(report.to_json(), indent=2, sort_keys=True))
        else:
            print(report.render(), file=sys.stderr)
        return report.exit_code

    if args.kernel_contracts:
        from tools.ktlint import ktshape

        if args.paths:
            # Positional args are kernel-registry keys here, not file
            # paths — an unrecognized one (or a file path out of
            # habit) must error, not silently filter the gate down to
            # zero kernels and exit green.
            from kubernetes_tpu.ops.contracts import CONTRACTS
            from kubernetes_tpu.ops.parity import ORACLE_TWINS

            known = set(CONTRACTS) | set(ORACLE_TWINS)
            unknown = [p for p in args.paths if p not in known]
            if unknown:
                print(
                    "--kernel-contracts takes ORACLE_TWINS kernel keys "
                    f"(e.g. 'solver._solve_xla'), not paths: {unknown}",
                    file=sys.stderr,
                )
                return 2
        report = ktshape.analyze(kernels=args.paths or None)
        if args.format == "json":
            print(json.dumps(report.to_json(), indent=2, sort_keys=True))
        else:
            print(report.render(), file=sys.stderr)
        return report.exit_code

    if args.lock_graph:
        from tools.ktlint import lockgraph

        runtime = None
        if args.runtime_graph:
            try:
                runtime = lockgraph.load_runtime_report(args.runtime_graph)
            except (OSError, ValueError) as e:
                print(f"--runtime-graph: {e}", file=sys.stderr)
                return 2
        report = lockgraph.analyze(args.paths, runtime=runtime)
        if args.format == "json":
            print(json.dumps(report.to_json(), indent=2, sort_keys=True))
        else:
            print(report.render(), file=sys.stderr)
        return report.exit_code

    if args.list_rules:
        for rule in ktlint.ALL_RULES:
            print(f"{rule.id}  {rule.title}")
        return 0

    select = [s for s in args.select.split(",") if s.strip()]
    try:
        rules = ktlint.rules_by_id(select)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    paths = [pathlib.Path(p) for p in args.paths] or [
        ktlint.REPO_ROOT / "kubernetes_tpu"
    ]

    if args.write_baseline:
        # The baseline is a whole-tree, all-rules artifact: a narrowed
        # regeneration would silently drop every entry the narrowed run
        # never produced (e.g. --select KT005 wiping the KT003 backlog).
        if select or args.paths:
            print(
                "--write-baseline regenerates the FULL baseline; do not "
                "combine it with --select or explicit paths",
                file=sys.stderr,
            )
            return 2
        report = run(paths, rules, baseline=None)
        baseline = Baseline.from_findings(report.findings)
        out = pathlib.Path(args.baseline or str(ktlint.DEFAULT_BASELINE))
        baseline.dump(out)
        print(
            f"baseline: {len(report.findings)} finding(s) written to {out}",
            file=sys.stderr,
        )
        return 0

    baseline = Baseline.load(pathlib.Path(args.baseline)) if args.baseline else None
    report = run(paths, rules, baseline)

    if args.format == "json":
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        for f in report.findings:
            print(f.render(), file=sys.stderr)
        for err in report.errors:
            print(f"ERROR {err}", file=sys.stderr)
        counts = ", ".join(
            f"{rule}={n}" for rule, n in sorted(report.counts().items())
        )
        print(
            f"ktlint: {len(report.findings)} finding(s) "
            f"({len(report.suppressed)} suppressed, "
            f"{len(report.baselined)} baselined) [{counts}]",
            file=sys.stderr,
        )
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
