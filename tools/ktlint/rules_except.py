"""KT003 — exception hygiene in daemons.

A bare ``except:`` / ``except Exception:`` whose body neither logs,
re-raises, nor reports the failure upward swallows the only evidence a
controller/kubelet/apiserver code path is broken — the reference
codebase's util.HandleCrash at least prints the stack. Scope is the
long-running daemon packages (``controllers/``, ``kubelet/``,
``server/``): crash containment there is CORRECT, silent crash
containment is not.

A handler passes if it contains any of:
- a logging call (``*.exception/error/warning/warn/info/debug/critical/
  log`` or ``traceback.print_exc``/``format_exc``),
- a ``raise``,
- a response write that forwards the error to the caller
  (``*.send*(...)`` / returning a value derived from the exception —
  approximated as: the handler binds the exception (``as e``) AND
  references it).

Anything else needs a ``# ktlint: disable=KT003`` pragma with a reason,
or a baseline entry while the backlog is burned down.
"""

from __future__ import annotations

import ast
from typing import List

from tools.ktlint.framework import FileContext, Finding, Rule, attr_chain

_SCOPE_DIRS = {"controllers", "kubelet", "server"}
_LOG_METHODS = {
    "exception", "error", "warning", "warn", "info", "debug", "critical",
    "log", "print_exc", "format_exc",
}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    if isinstance(handler.type, ast.Name):
        return handler.type.id in ("Exception", "BaseException")
    return False


def _reports(handler: ast.ExceptHandler) -> bool:
    bound = handler.name  # `except Exception as e` binds e
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in _LOG_METHODS:
                return True
        if bound and isinstance(node, ast.Name) and node.id == bound:
            if isinstance(node.ctx, ast.Load):
                return True  # error value is used, not dropped
    return False


class ExceptionHygieneRule(Rule):
    id = "KT003"
    title = "broad except handlers in daemons must log or re-raise"

    def applies(self, ctx: FileContext) -> bool:
        return bool(_SCOPE_DIRS & set(ctx.path.parts))

    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _reports(node):
                continue
            what = "bare except:" if node.type is None else "except Exception:"
            out.append(
                ctx.finding(
                    self.id,
                    node,
                    f"{what} swallows the failure — log with context "
                    "(logger.exception / traceback) or re-raise",
                )
            )
        return out
