"""KT002 — lock discipline.

For every class that constructs a ``threading.Lock``/``RLock``/
``Condition`` and stashes it on ``self``, any OTHER self-attribute that
is rebound both inside a ``with self.<lock>:`` block and outside one
(in some other method) is a candidate data race: one writer thinks the
attribute is lock-protected, the other doesn't.

Scope decisions that keep the pass honest rather than noisy:

- Only direct rebinds (``self.x = ...``, ``self.x += ...``) count.
  Container mutation (``self.d[k] = v``, ``self.s.add(...)``) is out of
  scope — tracking it without aliasing analysis drowns real findings.
- ``__init__`` writes never count (construction is single-threaded by
  convention here; every daemon finishes wiring before start()).
- Methods whose name ends in ``_locked`` are treated as executing under
  the lock — that suffix is this codebase's documented caller-holds-
  the-lock contract (kvstore._expire_locked, _snapshot_locked, ...).

A flagged attribute means: either take the lock at the bare write
site, or pragma it with a comment explaining why the race is benign.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from tools.ktlint.framework import FileContext, Finding, Rule, attr_chain

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if isinstance(node, ast.IfExp):
        return _is_lock_ctor(node.body) or _is_lock_ctor(node.orelse)
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        if not chain:
            return False
        if chain[-1] in _LOCK_FACTORIES:
            # threading.Condition(sanitizer.lock(...)) is still a lock.
            return True
        # The ktsan factory (utils/sanitizer.py): sanitizer.lock("name")
        # / sanitizer.rlock("name") — adopted components must not fall
        # out of KT002's lock-attr inventory.
        return (
            len(chain) >= 2
            and chain[-2] == "sanitizer"
            and chain[-1] in {"lock", "rlock"}
        )
    return False


def _self_attr_target(node: ast.AST) -> str:
    """'x' for a `self.x` store target, else ''."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


def _with_locks(stmt: ast.With, lock_attrs: Set[str]) -> Set[str]:
    """Lock attrs entered by this with-statement's items."""
    held = set()
    for item in stmt.items:
        name = _self_attr_target(item.context_expr)
        if name in lock_attrs:
            held.add(name)
    return held


class LockDisciplineRule(Rule):
    id = "KT002"
    title = "self-attributes written both inside and outside lock blocks"

    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(ctx, node))
        return out

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef) -> List[Finding]:
        lock_attrs: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    name = _self_attr_target(t)
                    if name:
                        lock_attrs.add(name)
        if not lock_attrs:
            return []
        # attr -> {"locked": [(method, line)], "bare": [(method, line)]}
        writes: Dict[str, Dict[str, List[Tuple[str, int]]]] = {}
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name == "__init__":
                continue
            base_held = item.name.endswith("_locked")
            self._walk(item.body, item.name, base_held, lock_attrs, writes)
        out: List[Finding] = []
        for attr in sorted(writes):
            w = writes[attr]
            if w["locked"] and w["bare"]:
                locked_in = sorted({m for m, _ in w["locked"]})
                for method, line in sorted(set(w["bare"]), key=lambda x: x[1]):
                    out.append(
                        ctx.finding(
                            self.id,
                            line,
                            f"{cls.name}.{attr} is written without the lock "
                            f"in {method}() but under it in "
                            f"{', '.join(locked_in)}() — take the lock or "
                            "pragma with a reason",
                        )
                    )
        return out

    def _walk(self, stmts, method: str, held: bool, lock_attrs, writes) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                now_held = held or bool(_with_locks(stmt, lock_attrs))
                self._walk(stmt.body, method, now_held, lock_attrs, writes)
                continue
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets = list(stmt.targets)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            for t in targets:
                for leaf in self._flatten(t):
                    attr = _self_attr_target(leaf)
                    if attr and attr not in lock_attrs:
                        bucket = writes.setdefault(
                            attr, {"locked": [], "bare": []}
                        )
                        bucket["locked" if held else "bare"].append(
                            (method, stmt.lineno)
                        )
            # Recurse into nested blocks (loops, ifs, try, nested defs —
            # a closure defined in a method runs on the same threads).
            for name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, name, None)
                if isinstance(sub, list):
                    self._walk(sub, method, held, lock_attrs, writes)
            for h in getattr(stmt, "handlers", ()):
                self._walk(h.body, method, held, lock_attrs, writes)

    @staticmethod
    def _flatten(target: ast.AST) -> List[ast.AST]:
        if isinstance(target, (ast.Tuple, ast.List)):
            out = []
            for elt in target.elts:
                out.extend(LockDisciplineRule._flatten(elt))
            return out
        return [target]
