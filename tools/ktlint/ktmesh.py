"""ktmesh — the static SPMD partitioning analyzer.

``python -m tools.ktlint --mesh-analysis [--devices N]`` verifies every
kernel in the KT006 ORACLE_TWINS registry against its declared
:class:`~kubernetes_tpu.ops.contracts.MeshSharding` leaf WITHOUT
executing anything: each kernel is partitioned-LOWERED (compile only —
``TracedJit.lower(...).compile()`` on avals, never called) under a
forced multi-device CPU mesh (``XLA_FLAGS=
--xla_force_host_platform_device_count=N``, no TPU needed), and the
compiled/partitioned module's text is walked for the **collective
inventory** GSPMD inserted — all-gather / all-reduce / reduce-scatter /
collective-permute / all-to-all op counts and byte volumes.

Verified, per kernel:

- **completeness** — every ORACLE_TWINS kernel carries a contract AND
  a sharding leaf (both ways, like every other contract field), the
  leaf's sharded dim appears in the argument schema, and its axis is a
  real mesh axis (``pods``/``nodes``).
- **communication budget** — the inventory must match the declared
  :class:`~kubernetes_tpu.ops.contracts.CommBudget` EXACTLY at the
  pinned probe point: a phantom collective is a sharding regression
  (the classic silent-scaling-loss bug, cf. the GSPMD/Megatron
  communication analyses in PAPERS.md); a vanished one is a stale
  budget. ``explain_rows`` must lower collective-FREE under pod-axis
  sharding — the go-case ROADMAP item 1 rests on.
- **no pod-axis full-gather** — no all-gather may materialize the full
  pod axis (gathered dim size == the pod dim's probe size). Probe dim
  sizes are all DISTINCT (contracts._distinct_bindings) precisely so
  this size test cannot alias another axis.
- **ktshape coupling cross-check** — a kernel ktshape classifies
  ``shardable`` that is sharded over its pod dim yet emits ANY
  collective is a finding (the embarrassingly-parallel claim broke);
  a ``reduces`` kernel whose sharding leaf shards a real dim yet
  lowers collective-free is one too (the declaration or the leaf is
  stale). Kernels whose leaf declares full replication (dim=None:
  pallas/preemption/scatter) are exempt — an empty inventory is their
  contract, not a contradiction.

Off-mesh degradation: with fewer than two visible devices every kernel
reports ``skipped`` and the analyzer exits 0 — a laptop without the
forced host platform must not fail CI, it just cannot add evidence.

Runs under ``JAX_PLATFORMS=cpu`` (forced when unset) and sets the
host-platform device-count flag BEFORE jax's CPU backend initializes —
which happens at first use, so setting it at analyze() start works
even when jax is already imported but idle.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

#: Mesh axis names a sharding leaf may declare — the two axes of the
#: paper's dense pod x node formulation.
MESH_AXES = ("pods", "nodes")


@dataclass
class MeshFinding:
    kernel: str
    check: str  # completeness | budget | pod-gather | coupling-xcheck | error
    message: str

    def render(self) -> str:
        return f"{self.kernel}: [{self.check}] {self.message}"


@dataclass
class MeshReport:
    devices: int = 0
    findings: List[MeshFinding] = field(default_factory=list)
    kernels: List[dict] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if (self.findings or self.errors) else 0

    @property
    def collectives_total(self) -> int:
        return sum(k.get("collectives_total", 0) for k in self.kernels)

    @property
    def collective_bytes_total(self) -> int:
        return sum(k.get("collective_bytes", 0) for k in self.kernels)

    def to_json(self) -> dict:
        return {
            "devices": self.devices,
            "kernels_checked": len(self.kernels),
            "kernels": self.kernels,
            "collectives_total": self.collectives_total,
            "collective_bytes_total": self.collective_bytes_total,
            "skipped": sum(
                1 for k in self.kernels if k["status"] == "skipped"
            ),
            "findings": [
                {"kernel": f.kernel, "check": f.check, "message": f.message}
                for f in self.findings
            ],
            "errors": self.errors,
        }

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines += [f"ERROR {e}" for e in self.errors]
        skipped = sum(1 for k in self.kernels if k["status"] == "skipped")
        lines.append(
            f"ktmesh: {len(self.kernels)} kernel(s) on {self.devices} "
            f"device(s), {self.collectives_total} collective(s) "
            f"({self.collective_bytes_total} bytes), "
            f"{skipped} skipped, {len(self.findings)} finding(s)"
        )
        return "\n".join(lines)


# -- per-kernel probe ---------------------------------------------------


def _build_mesh(n: int, axis: str):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]), axis_names=(axis,))


def static_inventory(
    name: str, mesh, bindings: Optional[Dict[str, int]] = None
) -> Dict[str, object]:
    """ktmesh's static prediction for ONE kernel on `mesh`: partitioned
    lowering at `bindings` (default: the distinct-dims probe point)
    under the contract's sharding leaf, collective inventory of the
    compiled module. The runtime<->static cross-check in
    tests/test_multichip.py calls this with the bucket it actually
    executed."""
    from kubernetes_tpu.ops import contracts as C

    contract = C.CONTRACTS[name]
    bindings = dict(bindings or C._distinct_bindings(contract))
    args, kwargs = C.sharded_abstract_args(contract, bindings, mesh)
    kern = C.resolve_kernel(name)
    compiled = kern.lower(*args, **kwargs).compile()
    return C.collective_inventory(compiled.as_text())


def check_kernel(
    name: str, contract, n_devices: int, meta: Optional[dict] = None
) -> List[MeshFinding]:
    """Partitioned-lower ONE kernel and verify its inventory against
    the declared budget — the unit the drift-injection tests drive
    with doctored contracts. `meta` (the summary row) receives the
    observed counts/bytes and the status."""
    from kubernetes_tpu.ops import contracts as C

    out: List[MeshFinding] = []
    sh = contract.sharding
    meta = meta if meta is not None else {}

    if sh is None:
        meta["status"] = "error"
        return [
            MeshFinding(
                name, "completeness",
                "contract has no sharding leaf — every registered "
                "kernel declares its mesh partitioning + communication "
                "budget (ops/contracts.py MeshSharding)",
            )
        ]
    if sh.axis not in MESH_AXES:
        meta["status"] = "error"
        return [
            MeshFinding(
                name, "completeness",
                f"sharding axis {sh.axis!r} is not one of {MESH_AXES}",
            )
        ]
    arg_dims = {
        d
        for _, spec in C.declared_array_leaves(contract)
        for d in spec.dims
    }
    if sh.dim is not None and sh.dim not in arg_dims:
        meta["status"] = "error"
        return [
            MeshFinding(
                name, "completeness",
                f"sharded dim {sh.dim!r} appears in no argument leaf — "
                "the partitioning declaration is unverifiable",
            )
        ]

    bindings = C._distinct_bindings(contract)
    if sh.dim is not None and bindings[sh.dim] % n_devices != 0:
        meta["status"] = "skipped"
        meta["skip_reason"] = (
            f"probe size {sh.dim}={bindings[sh.dim]} not divisible "
            f"by {n_devices} devices"
        )
        return out

    t0 = time.perf_counter()
    try:
        mesh = _build_mesh(n_devices, sh.axis)
        args, kwargs = C.sharded_abstract_args(contract, bindings, mesh)
        kern = C.resolve_kernel(name)
        compiled = kern.lower(*args, **kwargs).compile()
        inventory = C.collective_inventory(compiled.as_text())
    except Exception as e:
        meta["status"] = "error"
        out.append(
            MeshFinding(
                name, "error",
                f"partitioned lowering failed at {bindings}: {e!r}",
            )
        )
        return out
    meta.update(
        status="ok",
        collectives=inventory["counts"],
        collectives_total=inventory["total"],
        collective_bytes=sum(inventory["bytes"].values()),
        seconds=round(time.perf_counter() - t0, 3),
    )

    declared = sh.budget.as_dict()
    if inventory["counts"] != declared:
        out.append(
            MeshFinding(
                name, "budget",
                f"collective inventory {inventory['counts'] or '{}'} "
                f"!= declared budget {declared or '{}'} — a phantom "
                "collective is a sharding regression, a vanished one "
                "a stale CommBudget; re-pin deliberately or fix the "
                "kernel",
            )
        )

    pod_size = bindings.get(contract.pod_dim) if contract.pod_dim else None
    if pod_size is not None:
        for op in inventory["ops"]:
            gdim = op.get("gather_dim")
            if (
                op["kind"] == "all-gather"
                and gdim is not None
                and gdim < len(op["shape"])
                and op["shape"][gdim] == pod_size
            ):
                out.append(
                    MeshFinding(
                        name, "pod-gather",
                        f"all-gather materializes the FULL pod axis "
                        f"({op['dtype']}{op['shape']}, gathered dim "
                        f"{gdim} == {contract.pod_dim}={pod_size}) — "
                        "the classic way a sharded solver silently "
                        "loses all scaling",
                    )
                )

    if (
        contract.pod_axis == "shardable"
        and sh.dim == contract.pod_dim
        and inventory["total"] > 0
    ):
        out.append(
            MeshFinding(
                name, "coupling-xcheck",
                f"ktshape classifies this kernel 'shardable' yet its "
                f"pod-sharded lowering emits {inventory['counts']} — "
                "pods are NOT independent under a Mesh; one of the two "
                "analyses is wrong",
            )
        )
    if (
        contract.pod_axis == "reduces"
        and sh.dim is not None
        and inventory["total"] == 0
    ):
        out.append(
            MeshFinding(
                name, "coupling-xcheck",
                "ktshape classifies this kernel 'reduces' yet its "
                "sharded lowering is collective-free — either the "
                "sharding leaf replicates the coupled axis away or the "
                "coupling class is stale",
            )
        )
    return out


def _kernel_row(name: str, contract) -> dict:
    sh = contract.sharding
    return {
        "kernel": name,
        "pod_axis": contract.pod_axis,
        "sharded_dim": sh.dim if sh else None,
        "mesh_axis": sh.axis if sh else None,
        "budget": sh.budget.as_dict() if sh else None,
        "status": "pending",
        "collectives": {},
        "collectives_total": 0,
        "collective_bytes": 0,
    }


# -- the full pass ------------------------------------------------------


def analyze(
    devices: int = 8, kernels: Optional[Sequence[str]] = None
) -> MeshReport:
    """Run the full mesh analysis over the registry (or a named
    subset). Forces JAX_PLATFORMS=cpu and the host-platform device
    count when the caller hasn't chosen — the flag only binds if the
    CPU backend hasn't initialized yet, so an already-warm jax keeps
    whatever topology it has (the in-process test gate runs on
    conftest's forced 8 devices)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    report = MeshReport()
    try:
        from kubernetes_tpu.ops import contracts as C
    except Exception as e:  # pragma: no cover - broken tree
        report.errors.append(f"cannot import ops/contracts.py: {e!r}")
        return report

    registry = set(C.registry_keys())
    contracted = set(C.CONTRACTS)
    for missing in sorted(registry - contracted):
        report.findings.append(
            MeshFinding(
                missing, "completeness",
                "registered in ORACLE_TWINS but has no contract (and "
                "so no sharding leaf) in ops/contracts.py",
            )
        )
    for stale in sorted(contracted - registry):
        report.findings.append(
            MeshFinding(
                stale, "completeness",
                "contracted in ops/contracts.py but not registered in "
                "ORACLE_TWINS (stale after a rename/removal?)",
            )
        )

    try:
        import jax

        n_avail = len(jax.devices())
    except Exception as e:  # pragma: no cover - no jax at all
        report.errors.append(f"cannot initialize jax: {e!r}")
        return report
    n = min(devices, n_avail)
    report.devices = n

    todo = sorted(contracted & registry)
    if kernels is not None:
        todo = [k for k in todo if k in set(kernels)]
    for name in todo:
        contract = C.CONTRACTS[name]
        row = _kernel_row(name, contract)
        if n < 2:
            row["status"] = "skipped"
            row["skip_reason"] = (
                f"{n} visible device(s) — a mesh needs >= 2 (run under "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
            )
            report.kernels.append(row)
            continue
        report.findings.extend(check_kernel(name, contract, n, meta=row))
        report.kernels.append(row)
    return report
