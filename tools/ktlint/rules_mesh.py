"""KT009: mesh hygiene in ops/ — the AST half of the ktmesh pass.

The kernel layer is becoming mesh-capable (ROADMAP item 1): staged
arrays carry NamedShardings, the node axis shards, and the ktmesh
budgets pin what communication each kernel may emit. Four idioms
silently break that world and are cheap to catch statically:

- ``jax.device_put(x)`` with no explicit sharding/device — the array
  lands wherever jax defaults (device 0), so a sharded pipeline
  quietly concentrates its inputs on one chip. Every staging put names
  its placement.
- **indexing or slicing ``jax.devices()`` / ``jax.local_devices()``**
  (``jax.devices()[0]``, ``jax.devices()[:8]``) — hard-codes a device
  count or pins work to chip 0; topology belongs to the Mesh, and the
  ONE sanctioned default-device seam is ``matrices.shardings_for``
  (pragma'd at its definition).
- ``jax.pmap`` — the legacy per-device-replica path; this codebase
  partitions with ``jit`` + ``NamedSharding`` (GSPMD), and mixing the
  two models corrupts the ktmesh budget story (pmap collectives never
  appear in a jit lowering's inventory).
- **Mesh construction outside the sanctioned seam** — ``Mesh(...)`` /
  ``jax.sharding.Mesh(...)`` anywhere in ops/ except
  ``ops/matrices.py`` (``host_mesh``/``shardings_for``, the seams the
  session and the ``KT_MESH_DEVICES`` escape hatch route through). Ad
  hoc meshes fragment the one-topology invariant the budgets assume.

Scope: ``ops`` modules only (the mesh-capable layer) — the control
plane never imports jax, and tests/tools legitimately build probe
meshes.
"""

from __future__ import annotations

import ast
from typing import List

from tools.ktlint.framework import FileContext, Finding, Rule, attr_chain

#: The one ops/ file allowed to construct meshes: the staging layer's
#: sanctioned seam (shardings_for / host_mesh).
_MESH_SEAM = "matrices.py"

_DEVICE_LISTS = {
    ("jax", "devices"),
    ("jax", "local_devices"),
}


class MeshHygieneRule(Rule):
    id = "KT009"
    title = (
        "mesh hygiene in ops/: explicit shardings on device_put, no "
        "jax.devices() indexing, no pmap, mesh construction only via "
        "the matrices seam"
    )

    def applies(self, ctx: FileContext) -> bool:
        return "ops" in ctx.path.parts

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        in_seam = ctx.path.name == _MESH_SEAM
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                chain = tuple(attr_chain(node.func))
                if chain == ("jax", "device_put"):
                    if len(node.args) < 2 and not any(
                        kw.arg in ("device", "sharding")
                        for kw in node.keywords
                    ):
                        findings.append(
                            ctx.finding(
                                self.id, node,
                                "jax.device_put without an explicit "
                                "sharding/device — in a mesh-capable "
                                "module the array silently lands on "
                                "device 0; pass the staging sharding "
                                "(matrices.shardings_for)",
                            )
                        )
                elif chain and not in_seam and (
                    chain == ("Mesh",)
                    or chain[-2:] == ("sharding", "Mesh")
                    or chain == ("jax", "Mesh")
                ):
                    findings.append(
                        ctx.finding(
                            self.id, node,
                            "Mesh construction outside the sanctioned "
                            "seam — ops/ builds meshes only through "
                            "matrices.host_mesh / matrices."
                            "shardings_for so the whole kernel layer "
                            "shares one topology",
                        )
                    )
            elif isinstance(node, ast.Subscript):
                inner = node.value
                if isinstance(inner, ast.Call):
                    chain = tuple(attr_chain(inner.func))
                    if chain in _DEVICE_LISTS:
                        findings.append(
                            ctx.finding(
                                self.id, node,
                                f"indexing/slicing {'.'.join(chain)}() "
                                "hard-codes device topology — chip "
                                "counts and default devices belong to "
                                "the Mesh (matrices.host_mesh) or the "
                                "shardings_for seam",
                            )
                        )
            elif isinstance(node, ast.Attribute):
                chain = tuple(attr_chain(node))
                if chain == ("jax", "pmap"):
                    findings.append(
                        ctx.finding(
                            self.id, node,
                            "jax.pmap is the legacy replica path — "
                            "this codebase partitions with jit + "
                            "NamedSharding (GSPMD); pmap collectives "
                            "are invisible to the ktmesh budgets",
                        )
                    )
        return findings
