"""KT005 — metric naming and registration (promtool-check analog).

Absorbed from the PR-1 standalone ``tools/lint_metrics.py`` (which now
shims onto this pass). Enforces, for every metric registration:

1. names are snake_case (``^[a-z][a-z0-9_]*$``);
2. names carry a unit/kind suffix — one of ``_seconds``, ``_bytes``,
   ``_total``, ``_ratio``, ``_info`` — so a scrape reader never has to
   guess units (``_count``/``_sum``/``_bucket`` are reserved for
   histogram/summary child series; a small reference-parity allowlist
   is grandfathered);
3. metrics are registered through ``metrics.DEFAULT`` (the registry the
   /metrics endpoints render); a bare ``metrics.Counter(...)`` outside
   utils/metrics.py would silently never be scraped;
4. names are string literals (a dynamic name defeats static lint and
   risks unbounded metric families).
"""

from __future__ import annotations

import ast
import re
from typing import List

from tools.ktlint.framework import FileContext, Finding, Rule, attr_chain

NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# NOTE: "_count" is deliberately NOT a valid suffix — promtool reserves
# _count/_sum/_bucket for histogram/summary child series.
UNIT_SUFFIXES = ("_seconds", "_bytes", "_total", "_ratio", "_info")
FACTORY_METHODS = {"counter", "gauge", "summary", "histogram"}
METRIC_CLASSES = {"Counter", "Gauge", "Summary", "Histogram"}

#: Reference-parity names grandfathered in (they match the reference
#: codebase's own metrics packages verbatim, and dashboards key on
#: them); everything new must carry a unit suffix.
ALLOWLIST = {
    "apiserver_request_count",  # pkg/apiserver/metrics.go
    "kubelet_running_pods",  # pkg/kubelet/metrics/metrics.go
}

#: Gang-scheduling metric family (scheduler/gang.py +
#: controllers/gangs.py). gang_solve_outcomes_total and
#: gang_controller_syncs_total satisfy the suffix rule on their own;
#: gang_pending_groups is a unitless snapshot gauge (a count of
#: objects, like kubelet_running_pods) and is allowlisted explicitly so
#: the linter documents — rather than silently tolerates — the family.
GANG_METRICS = {
    "gang_solve_outcomes_total",
    "gang_controller_syncs_total",
    "gang_pending_groups",
}
ALLOWLIST |= GANG_METRICS

#: Priority & preemption family (scheduler/daemon.py). The counters
#: carry _total on their own; preemption_active_nominations is a
#: unitless snapshot gauge (a count of held reservations, like
#: gang_pending_groups) and is allowlisted explicitly so the linter
#: documents the whole family rather than silently tolerating it.
PREEMPTION_METRICS = {
    "preemption_victims_total",
    "preemption_solve_outcomes_total",
    "preemption_active_nominations",
}
ALLOWLIST |= PREEMPTION_METRICS

#: Explainability & solver-convergence family (utils/flightrecorder.py,
#: observed by ops/sinkhorn.py, ops/wave.py, ops/pipeline.py,
#: ops/incremental.py). scheduler_decisions_total carries _total on its
#: own; the residual gauge (a log-domain mass excess) and the iteration
#: histogram (a count of price updates / waves) are unit-less by nature
#: and allowlisted explicitly so the linter documents the family rather
#: than silently tolerating it.
EXPLAIN_METRICS = {
    "scheduler_decisions_total",
    "scheduler_sinkhorn_residual",
    "scheduler_solve_iterations",
}
ALLOWLIST |= EXPLAIN_METRICS

#: SLI/SLO telemetry-plane family (utils/sli.py, store/watch.py,
#: scheduler/daemon.py — see docs/architecture.md "Telemetry plane &
#: SLOs"). Most names carry standard unit suffixes on their own; the
#: exceptions are unit-less by nature — watch_stream_queue_depth (a
#: count of queued events, like gang_pending_groups),
#: watch_fanout_lag_versions (a count of store versions), and
#: solver_xla_compile_cache_entries (a count of cached executables) —
#: and are allowlisted explicitly so the linter documents the whole
#: family rather than silently tolerating it.
SLI_METRICS = {
    "pod_startup_latency_seconds",
    "watch_streams_dropped_total",
    "watch_stream_queue_depth",
    "watch_fanout_lag_versions",
    "scheduler_informer_staleness_seconds",
    "solver_device_transfer_bytes_total",
    "solver_xla_compiles_total",
    "solver_xla_compile_cache_entries",
    "device_memory_bytes",
}
ALLOWLIST |= SLI_METRICS

#: Device-time profiling-plane family (ops/ledger.py,
#: utils/profiler.py, scheduler/daemon.py — see docs/performance.md
#: "Profiling the solve path"). solver_compile_seconds_total and
#: scheduler_device_busy_seconds_total carry standard suffixes on
#: their own; the duty-cycle and overlap-efficiency histograms are
#: unit-less [0, 1] ratios observed into ratio bucket ladders and are
#: allowlisted explicitly so the linter documents the whole family
#: rather than silently tolerating it.
PROFILER_METRICS = {
    "solver_compile_seconds_total",
    "scheduler_device_busy_seconds_total",
    "scheduler_device_duty_cycle",
    "scheduler_overlap_efficiency",
}
ALLOWLIST |= PROFILER_METRICS

#: Capacity & fragmentation plane family (utils/capacity.py, sampled
#: by scheduler/daemon.py — see docs/architecture.md "Capacity &
#: fragmentation plane"). node_utilization_ratio carries _ratio and
#: capacity_zero_headroom_ticks_total carries _total on their own;
#: the score/rate histograms are unit-less [0, 1] ratios on the
#: profiler's ratio ladder, cluster_headroom_pods is a unitless
#: snapshot gauge (a count of placeable probe pods, like
#: gang_pending_groups), and scheduler_backlog_pressure is a composite
#: (pods x seconds) watermark — all allowlisted explicitly so the
#: linter documents the whole family rather than silently tolerating
#: it.
CAPACITY_METRICS = {
    "cluster_fragmentation_score",
    "cluster_headroom_pods",
    "slice_alloc_success_rate",
    "scheduler_backlog_pressure",
}
ALLOWLIST |= CAPACITY_METRICS

#: Rebalancing-plane family (utils/rebalance.py, driven by
#: controllers/descheduler.py — see docs/architecture.md "Rebalancing
#: plane"). rebalance_moves_total and rebalance_stranded_pods_total
#: carry _total on their own; the improvement histogram is a unit-less
#: [0, 1] score delta on the profiler's ratio ladder and
#: rebalance_moves_per_improvement is a composite efficiency quotient
#: (evictions per score unit, the defrag-efficiency SLO series) — the
#: whole family is declared so the linter documents it rather than
#: silently tolerating the unsuffixed members.
REBALANCE_METRICS = {
    "rebalance_moves_total",
    "rebalance_score_improvement",
    "rebalance_moves_per_improvement",
    "rebalance_stranded_pods_total",
}
ALLOWLIST |= REBALANCE_METRICS

#: Elastic node-pool autoscaler family (controllers/autoscaler.py).
#: autoscaler_scale_events_total carries _total on its own;
#: autoscaler_pool_size is a unitless snapshot gauge (a node count per
#: pool, like cluster_headroom_pods) — declared as a family for the
#: same documentation reason.
AUTOSCALER_METRICS = {
    "autoscaler_pool_size",
    "autoscaler_scale_events_total",
}
ALLOWLIST |= AUTOSCALER_METRICS

#: HA control-plane family (store/replication.py, utils/lease.py,
#: scheduler/standby.py — see docs/architecture.md "HA control
#: plane"). leader_elections_total and the standby activation summary
#: carry standard suffixes on their own; replication_commit_index is a
#: store-version watermark and replication_follower_lag_versions a
#: count of store versions (like watch_fanout_lag_versions) — both
#: unit-less by nature and allowlisted explicitly so the linter
#: documents the whole family rather than silently tolerating it.
REPLICATION_METRICS = {
    "replication_commit_index",
    "replication_follower_lag_versions",
    "leader_elections_total",
    "scheduler_standby_activation_seconds",
}
ALLOWLIST |= REPLICATION_METRICS

#: Health-plane family (utils/timeseries.py, utils/alerts.py,
#: utils/lease.py — see docs/architecture.md "Alerting & health
#: plane"). timeseries_samples_total / timeseries_sample_seconds /
#: alert_transitions_total / lease_renew_latency_seconds carry
#: standard suffixes on their own; timeseries_retained_series is a
#: unitless snapshot gauge (a count of retained label sets, like
#: cluster_headroom_pods) and alerts_firing a 0/1 state gauge per
#: rule — both allowlisted explicitly so the linter documents the
#: whole family rather than silently tolerating it.
HEALTH_METRICS = {
    "timeseries_samples_total",
    "timeseries_retained_series",
    "timeseries_sample_seconds",
    "alerts_firing",
    "alert_transitions_total",
    "lease_renew_latency_seconds",
}
ALLOWLIST |= HEALTH_METRICS


class MetricNamingRule(Rule):
    id = "KT005"
    title = "metric names are snake_case, unit-suffixed, on metrics.DEFAULT"

    def applies(self, ctx: FileContext) -> bool:
        # The metric classes themselves live in utils/metrics.py.
        return not (
            ctx.path.name == "metrics.py" and ctx.path.parent.name == "utils"
        )

    def check(self, ctx: FileContext) -> List[Finding]:
        problems: List[Finding] = []
        # Names bound by `from ...metrics import Counter` (possibly
        # aliased) — a bare `Counter(...)` call through such an import
        # is the same registry bypass as `metrics.Counter(...)`.
        imported_classes = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "metrics" or node.module.endswith(".metrics")
            ):
                for alias in node.names:
                    if alias.name in METRIC_CLASSES:
                        imported_classes.add(alias.asname or alias.name)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            via_registry = (
                len(chain) >= 2
                and chain[-2] == "DEFAULT"
                and chain[-1] in FACTORY_METHODS
            )
            direct_class = (
                chain
                and chain[-1] in METRIC_CLASSES
                and "metrics" in chain[:-1]
            ) or (len(chain) == 1 and chain[0] in imported_classes)
            if not (via_registry or direct_class):
                continue
            if direct_class:
                problems.append(
                    ctx.finding(
                        self.id, node,
                        f"metrics.{chain[-1]}(...) bypasses metrics.DEFAULT "
                        "— unregistered metrics never reach /metrics",
                    )
                )
                continue
            if not node.args:
                problems.append(
                    ctx.finding(
                        self.id, node, "metric registration without a name"
                    )
                )
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
                problems.append(
                    ctx.finding(
                        self.id, node, "metric name must be a string literal"
                    )
                )
                continue
            name = arg.value
            if not NAME_RE.match(name):
                problems.append(
                    ctx.finding(
                        self.id, node,
                        f"metric name {name!r} is not snake_case",
                    )
                )
            if name not in ALLOWLIST and not name.endswith(UNIT_SUFFIXES):
                problems.append(
                    ctx.finding(
                        self.id, node,
                        f"metric name {name!r} lacks a unit suffix "
                        f"({'/'.join(UNIT_SUFFIXES)})",
                    )
                )
        return problems
