"""KT001 — jit purity.

Inside a function compiled with ``jax.jit`` (directly or through
``functools.partial(jax.jit, ...)``), host-side effects either crash at
trace time, silently freeze into the compiled graph (``time.*``,
``random.*``, ``print`` fire ONCE per compilation, not per call), or —
worst for a scheduler hot path — force a device->host sync in the
middle of the solve pipeline (``np.asarray``, ``.item()``,
``float()``/``int()`` on traced arrays, ``jax.device_get``). The rule
also cross-checks ``static_argnames``/``donate_argnames`` against the
wrapped function's real parameter list: jit raises for unknown static
names only at first CALL, and a typo'd donate name silently stops
donating (an allocation regression no test asserts on).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.ktlint.framework import FileContext, Finding, Rule, attr_chain, str_constants

#: Calls whose dotted name means a host sync / impurity inside jit.
_HOST_CALLS = {
    ("np", "asarray"): "forces a device->host sync inside jit",
    ("np", "array"): "forces a device->host sync inside jit",
    ("numpy", "asarray"): "forces a device->host sync inside jit",
    ("numpy", "array"): "forces a device->host sync inside jit",
    ("jax", "device_get"): "forces a device->host sync inside jit",
}
_HOST_MODULES = {
    "time": "runs at TRACE time only — the compiled graph never sees it",
    "random": "runs at TRACE time only — use jax.random with a key",
}
_CAST_BUILTINS = {"float", "int", "bool"}


#: Name chains that denote jit compilation. traced_jit (ops/ledger.py)
#: wraps jax.jit with the compile ledger — same purity contract, same
#: static/donate cross-check.
_JIT_CHAINS = (["jax", "jit"], ["jit"], ["traced_jit"], ["ledger", "traced_jit"])


def _jit_decoration(dec: ast.AST) -> Optional[dict]:
    """If `dec` is a jit decorator, return {static, donate} name lists
    (None for 'not specified / dynamic'); else None."""
    chain = attr_chain(dec)
    if chain in _JIT_CHAINS:
        return {"static": None, "donate": None}
    if isinstance(dec, ast.Call):
        fchain = attr_chain(dec.func)
        # functools.partial(jax.jit, ...) / partial(jax.jit, ...)
        if fchain and fchain[-1] == "partial" and dec.args:
            if attr_chain(dec.args[0]) in _JIT_CHAINS:
                out = {"static": None, "donate": None}
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        out["static"] = str_constants(kw.value)
                    elif kw.arg == "donate_argnames":
                        out["donate"] = str_constants(kw.value)
                return out
        # jax.jit(static_argnames=...) / traced_jit(...) decorator
        # factories
        if fchain in _JIT_CHAINS:
            out = {"static": None, "donate": None}
            for kw in dec.keywords:
                if kw.arg == "static_argnames":
                    out["static"] = str_constants(kw.value)
                elif kw.arg == "donate_argnames":
                    out["donate"] = str_constants(kw.value)
            return out
    return None


def _param_names(fn: ast.FunctionDef) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


class JitPurityRule(Rule):
    id = "KT001"
    title = "no host syncs or impure calls inside jax.jit functions"

    def check(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            spec = None
            for dec in node.decorator_list:
                spec = _jit_decoration(dec)
                if spec is not None:
                    break
            if spec is None:
                continue
            params = _param_names(node)
            static = set(spec["static"] or ())
            for kind in ("static", "donate"):
                for name in spec[kind] or ():
                    if name not in params:
                        out.append(
                            ctx.finding(
                                self.id,
                                node,
                                f"{kind}_argnames names {name!r}, which is "
                                f"not a parameter of {node.name}()",
                            )
                        )
            out.extend(self._check_body(ctx, node, params - static))
        return out

    def _check_body(
        self, ctx: FileContext, fn: ast.FunctionDef, traced: Set[str]
    ) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain:
                # Method call on an expression: still catch .item().
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                ):
                    out.append(
                        ctx.finding(
                            self.id, node,
                            f".item() in jitted {fn.name}() forces a "
                            "device->host sync",
                        )
                    )
                continue
            dotted = ".".join(chain)
            key = tuple(chain[-2:]) if len(chain) >= 2 else None
            if key in _HOST_CALLS:
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{dotted}() in jitted {fn.name}() "
                        f"{_HOST_CALLS[key]}",
                    )
                )
            elif chain[0] in _HOST_MODULES and len(chain) > 1:
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{dotted}() in jitted {fn.name}() "
                        f"{_HOST_MODULES[chain[0]]}",
                    )
                )
            elif chain == ["print"]:
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"print() in jitted {fn.name}() fires once per "
                        "TRACE, not per call — use jax.debug.print",
                    )
                )
            elif chain[-1] == "item" and len(chain) >= 2:
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{dotted}() in jitted {fn.name}() forces a "
                        "device->host sync",
                    )
                )
            elif (
                len(chain) == 1
                and chain[0] in _CAST_BUILTINS
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in traced
            ):
                out.append(
                    ctx.finding(
                        self.id, node,
                        f"{chain[0]}({node.args[0].id}) in jitted "
                        f"{fn.name}() concretizes a traced argument "
                        "(host sync / TracerError)",
                    )
                )
        return out
