"""ktshape — the kernel shape/dtype/sharding contract checker.

``python -m tools.ktlint --kernel-contracts`` verifies every kernel in
the KT006 ORACLE_TWINS registry against its declared contract
(kubernetes_tpu/ops/contracts.py) WITHOUT executing anything — all
evidence comes from abstract interpretation:

- **completeness** — CONTRACTS and ORACLE_TWINS must cover exactly the
  same kernel set (a kernel lands with its oracle twin AND its
  contract), and each contract must be internally consistent (a
  declared pod axis must actually appear in the argument schema).
- **abstract eval** — ``jax.eval_shape`` over ``ShapeDtypeStruct``
  probes at several bucket-lattice points: result tree/shape/dtype
  must match the declaration (which pins the registered oracle twin's
  dtypes), results must not be weak-typed, and nothing may promote to
  f64 (x64 creep breaks bit-parity with the NumPy oracles).
- **jaxpr walk** — trace each kernel at a probe point whose dim sizes
  are all distinct (so the pod axis is identifiable by size) and walk
  the jaxpr (including scan/while/pjit/pallas sub-jaxprs) for
  (a) *materialized* weak-typed or f64 intermediates — weak scalar
  literals broadcast into real arrays are silent promotion hazards;
  loop counters and other weak SCALARS are ubiquitous and benign, so
  only ndim >= 1 avals count — and (b) **pod-axis coupling**:
  reductions, scans, sorts, cumsums, gathers/scatters, contractions,
  or opaque pallas calls along the pod axis. A kernel declared
  ``pod_axis: shardable`` with coupling evidence is a finding (it
  would decide differently under a pod-axis Mesh); a kernel declared
  ``reduces`` with NO evidence is one too (the declaration is stale —
  tighten it). The surviving ``shardable`` set is the static go/no-go
  list for threading a Mesh through the daemons (ROADMAP item #2).

Zero kernel executions by construction: only ``eval_shape`` and
``.trace`` are used (tests pin the jit dispatch caches untouched).
Runs under ``JAX_PLATFORMS=cpu`` — the checker forces it when unset so
a CI box never grabs an accelerator to type-check shapes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

#: Reduction/contraction primitives whose reduced axes matter.
_REDUCE_PRIMS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "reduce_xor", "argmax", "argmin",
}
_CUM_PRIMS = {"cumsum", "cummax", "cummin", "cumprod", "cumlogsumexp"}
_SCATTER_PRIMS = {
    "scatter", "scatter-add", "scatter-mul", "scatter-min",
    "scatter-max", "scatter-apply",
}


@dataclass
class ShapeFinding:
    kernel: str
    check: str  # completeness | abstract-eval | weak-type | pod-axis | error
    message: str

    def render(self) -> str:
        return f"{self.kernel}: [{self.check}] {self.message}"


@dataclass
class ShapeReport:
    findings: List[ShapeFinding] = field(default_factory=list)
    kernels: List[dict] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if (self.findings or self.errors) else 0

    @property
    def shardable(self) -> List[str]:
        flagged = {f.kernel for f in self.findings}
        return sorted(
            k["kernel"]
            for k in self.kernels
            if k["pod_axis"] == "shardable" and k["kernel"] not in flagged
        )

    def to_json(self) -> dict:
        return {
            "kernels_checked": len(self.kernels),
            "kernels": self.kernels,
            "shardable": self.shardable,
            "findings": [
                {"kernel": f.kernel, "check": f.check, "message": f.message}
                for f in self.findings
            ],
            "errors": self.errors,
        }

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines += [f"ERROR {e}" for e in self.errors]
        lines.append(
            f"ktshape: {len(self.kernels)} kernel(s) checked, "
            f"{len(self.shardable)} pod-axis shardable "
            f"({', '.join(self.shardable) or 'none'}), "
            f"{len(self.findings)} finding(s)"
        )
        return "\n".join(lines)


# -- jaxpr helpers ------------------------------------------------------


def _sub_jaxprs(eqn):
    for pv in eqn.params.values():
        vals = pv if isinstance(pv, (list, tuple)) else [pv]
        for item in vals:
            if hasattr(item, "eqns"):
                yield item
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr


def _src_of(eqn) -> str:
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return "?"
        return f"{os.path.basename(frame.file_name)}:{frame.start_line}"
    except Exception:
        return "?"


def _aval(var):
    return getattr(var, "aval", None)


def _shape_of(var) -> Tuple[int, ...]:
    aval = _aval(var)
    shape = getattr(aval, "shape", None)
    return tuple(shape) if shape is not None else ()


def _coupling_of(eqn, pod: int) -> Optional[str]:
    """Why this eqn couples the pod axis (probe size `pod`), or None.
    Conservative for reduction-style primitives; batching dims (vmap
    residue — per-pod independent work) never count."""
    prim = eqn.primitive.name
    params = eqn.params
    if prim == "scan":
        length = params.get("length")
        n_fixed = params.get("num_consts", 0) + params.get("num_carry", 0)
        if length == pod and len(eqn.invars) > n_fixed:
            return "scan over the pod axis (sequential dependence)"
        return None
    if prim in _REDUCE_PRIMS:
        axes = params.get("axes", ())
        shape = _shape_of(eqn.invars[0])
        if any(a < len(shape) and shape[a] == pod for a in axes):
            return f"{prim} reduces the pod axis"
        return None
    if prim in _CUM_PRIMS:
        axis = params.get("axis", 0)
        shape = _shape_of(eqn.invars[0])
        if axis < len(shape) and shape[axis] == pod:
            return f"{prim} along the pod axis"
        return None
    if prim == "sort":
        dim = params.get("dimension", 0)
        for v in eqn.invars:
            shape = _shape_of(v)
            if dim < len(shape) and shape[dim] == pod:
                return "sort along the pod axis"
        return None
    if prim == "gather":
        dnums = params.get("dimension_numbers")
        slice_sizes = params.get("slice_sizes", ())
        shape = _shape_of(eqn.invars[0])
        batching = tuple(getattr(dnums, "operand_batching_dims", ()) or ())
        for i, size in enumerate(shape):
            if (
                size == pod
                and i not in batching
                and i < len(slice_sizes)
                and slice_sizes[i] != size
            ):
                return "gather indexes into the pod axis"
        return None
    if prim in _SCATTER_PRIMS:
        dnums = params.get("dimension_numbers")
        batching = tuple(getattr(dnums, "operand_batching_dims", ()) or ())
        inserted = tuple(getattr(dnums, "inserted_window_dims", ()) or ())
        to_operand = tuple(
            getattr(dnums, "scatter_dims_to_operand_dims", ()) or ()
        )
        shape = _shape_of(eqn.invars[0])
        for i, size in enumerate(shape):
            if size == pod and i not in batching and (
                i in inserted or i in to_operand
            ):
                return "scatter into the pod axis"
        if len(eqn.invars) >= 3:
            up_shape = _shape_of(eqn.invars[2])
            window = tuple(getattr(dnums, "update_window_dims", ()) or ())
            for i, size in enumerate(up_shape):
                if size == pod and i not in window:
                    return (
                        f"{prim} accumulates pod-axis rows "
                        "(segment reduction)"
                    )
        return None
    if prim == "dot_general":
        dnums = params.get("dimension_numbers")
        if dnums:
            (lc, rc), _ = dnums
            for var, cdims in ((eqn.invars[0], lc), (eqn.invars[1], rc)):
                shape = _shape_of(var)
                if any(c < len(shape) and shape[c] == pod for c in cdims):
                    return "dot_general contracts the pod axis"
        return None
    if prim == "pallas_call":
        for v in eqn.invars:
            if pod in _shape_of(v):
                return "opaque pallas_call consumes the pod axis"
        return None
    if prim == "conv_general_dilated":
        for v in eqn.invars[:2]:
            if pod in _shape_of(v):
                return "convolution touches the pod axis"
        return None
    return None


def walk_jaxpr(
    jaxpr, pod: Optional[int]
) -> Tuple[List[Tuple[str, str]], List[Tuple[str, str, str]]]:
    """Walk one (sub)jaxpr tree. Returns (couplings, weak_hits):
    couplings = [(reason, src)], weak_hits = [(kind 'weak'|'f64',
    aval description, src)] for MATERIALIZED (ndim >= 1) offenders."""
    import numpy as np

    couplings: List[Tuple[str, str]] = []
    weak: List[Tuple[str, str, str]] = []
    seen_srcs = set()

    def walk(j):
        for eqn in j.eqns:
            for v in eqn.outvars:
                aval = _aval(v)
                dt = getattr(aval, "dtype", None) if aval is not None else None
                if dt is None or getattr(aval, "ndim", 0) < 1:
                    continue
                desc = f"{eqn.primitive.name} -> {dt}{list(aval.shape)}"
                if np.dtype(dt) in (np.float64, np.complex128):
                    weak.append(("f64", desc, _src_of(eqn)))
                elif getattr(aval, "weak_type", False):
                    key = ("weak", _src_of(eqn))
                    if key not in seen_srcs:
                        seen_srcs.add(key)
                        weak.append(("weak", desc, _src_of(eqn)))
            if pod is not None:
                reason = _coupling_of(eqn, pod)
                if reason is not None:
                    couplings.append((reason, _src_of(eqn)))
            for sub in _sub_jaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    return couplings, weak


# -- per-kernel checks --------------------------------------------------


def _leaf_desc(leaf) -> str:
    return (
        f"{getattr(leaf, 'dtype', '?')}{list(getattr(leaf, 'shape', ()))}"
        f"{' (weak)' if getattr(leaf, 'weak_type', False) else ''}"
    )


def check_kernel(
    name: str, fn, contract, meta: Optional[dict] = None
) -> List[ShapeFinding]:
    """Verify ONE kernel object against ONE contract — the unit the
    fixture tests drive directly. `fn` must expose the jit surface
    (eval_shape + trace); ops kernels do via TracedJit. `meta`, when
    given, receives the walk's evidence counts for the summary row."""
    import jax
    import numpy as np

    from kubernetes_tpu.ops import contracts as C

    out: List[ShapeFinding] = []

    if contract.pod_axis not in C.POD_AXIS_KINDS:
        return [
            ShapeFinding(
                name, "completeness",
                f"pod_axis {contract.pod_axis!r} is not one of "
                f"{C.POD_AXIS_KINDS}",
            )
        ]
    arg_dims = {
        d
        for _, spec in C.declared_array_leaves(contract)
        for d in spec.dims
    }
    if contract.pod_axis == "replicated":
        if contract.pod_dim is not None:
            return [
                ShapeFinding(
                    name, "completeness",
                    "pod_axis 'replicated' contradicts a declared "
                    f"pod_dim {contract.pod_dim!r} — a kernel that "
                    "stages pod-axis arrays must declare shardable or "
                    "reduces",
                )
            ]
    elif contract.pod_dim not in arg_dims:
        return [
            ShapeFinding(
                name, "completeness",
                f"pod_dim {contract.pod_dim!r} appears in no argument "
                "leaf — the coupling declaration is unverifiable",
            )
        ]

    # -- abstract eval over the bucket lattice -------------------------
    for bindings in contract.samples:
        for sym, size in bindings.items():
            if not C.dim_ok(sym, size):
                out.append(
                    ShapeFinding(
                        name, "completeness",
                        f"sample point {sym}={size} is off the "
                        f"{sym} lattice "
                        f"({C.DIM_LATTICES[sym][0]})",
                    )
                )
        try:
            args, kwargs = C.abstract_args(contract, bindings)
            observed = fn.eval_shape(*args, **kwargs)
        except Exception as e:
            out.append(
                ShapeFinding(
                    name, "abstract-eval",
                    f"eval_shape failed at {bindings}: {e!r}",
                )
            )
            continue
        expected = C.expected_results(contract, bindings)
        obs_leaves, obs_tree = jax.tree_util.tree_flatten(observed)
        exp_leaves, exp_tree = jax.tree_util.tree_flatten(expected)
        if obs_tree != exp_tree:
            out.append(
                ShapeFinding(
                    name, "abstract-eval",
                    f"result tree mismatch at {bindings}: observed "
                    f"{obs_tree}, declared {exp_tree}",
                )
            )
            continue
        for i, (obs, exp) in enumerate(zip(obs_leaves, exp_leaves)):
            if tuple(obs.shape) != tuple(exp.shape) or np.dtype(
                obs.dtype
            ) != np.dtype(exp.dtype):
                out.append(
                    ShapeFinding(
                        name, "abstract-eval",
                        f"result leaf {i} at {bindings}: observed "
                        f"{_leaf_desc(obs)}, declared {_leaf_desc(exp)} "
                        "— drifted from the registered oracle twin's "
                        "contract",
                    )
                )
            elif getattr(obs, "weak_type", False):
                out.append(
                    ShapeFinding(
                        name, "weak-type",
                        f"result leaf {i} at {bindings} is WEAK-typed "
                        f"({_leaf_desc(obs)}) — its dtype floats with "
                        "downstream promotion instead of the contract",
                    )
                )
            if np.dtype(obs.dtype) in (np.float64, np.complex128):
                out.append(
                    ShapeFinding(
                        name, "abstract-eval",
                        f"result leaf {i} at {bindings} promoted to "
                        f"{np.dtype(obs.dtype)} — x64 creep breaks "
                        "oracle bit-parity",
                    )
                )

    # -- jaxpr walk at the distinct-dims probe -------------------------
    bindings = C._distinct_bindings(contract)
    pod = bindings.get(contract.pod_dim) if contract.pod_dim else None
    try:
        args, kwargs = C.abstract_args(contract, bindings)
        traced = fn.trace(*args, **kwargs)
        jaxpr = traced.jaxpr.jaxpr
    except Exception as e:
        out.append(
            ShapeFinding(
                name, "error", f"trace failed at {bindings}: {e!r}"
            )
        )
        return out
    couplings, weak_hits = walk_jaxpr(jaxpr, pod)
    if meta is not None:
        meta["coupling_evidence"] = len(couplings)
        meta["weak_intermediates"] = sum(
            1 for k, _, _ in weak_hits if k == "weak"
        )
    for kind, desc, src in weak_hits:
        if kind == "f64":
            out.append(
                ShapeFinding(
                    name, "abstract-eval",
                    f"f64 intermediate {desc} at {src} — x64 creep "
                    "breaks oracle bit-parity",
                )
            )
        else:
            out.append(
                ShapeFinding(
                    name, "weak-type",
                    f"weak-typed intermediate materialized: {desc} at "
                    f"{src} — pin the scalar literal's dtype "
                    "(jnp.int32(...)/jnp.float32(...))",
                )
            )
    if contract.pod_axis == "shardable" and couplings:
        ev = "; ".join(f"{r} at {s}" for r, s in couplings[:3])
        out.append(
            ShapeFinding(
                name, "pod-axis",
                f"declared shardable but the jaxpr couples pods: {ev} "
                "— this kernel would decide differently under a "
                "pod-axis Mesh",
            )
        )
    if contract.pod_axis == "reduces" and pod is not None and not couplings:
        out.append(
            ShapeFinding(
                name, "pod-axis",
                "declared 'reduces' but no cross-pod primitive found — "
                "tighten the declaration to 'shardable' (it widens the "
                "Mesh go-list) or the contract is stale",
            )
        )
    return out


def _kernel_row(name: str, contract) -> dict:
    return {
        "kernel": name,
        "pod_axis": contract.pod_axis,
        "pod_dim": contract.pod_dim,
        "samples": len(contract.samples),
        "coupling_evidence": 0,
        "weak_intermediates": 0,
    }


def analyze(kernels: Optional[Sequence[str]] = None) -> ShapeReport:
    """Run the full contract check over the registry (or a named
    subset). Imports jax — force the CPU platform when the caller
    hasn't chosen one (shape checking must never grab a TPU)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    report = ShapeReport()
    try:
        from kubernetes_tpu.ops import contracts as C
    except Exception as e:  # pragma: no cover - broken tree
        report.errors.append(f"cannot import ops/contracts.py: {e!r}")
        return report

    registry = set(C.registry_keys())
    contracted = set(C.CONTRACTS)
    for missing in sorted(registry - contracted):
        report.findings.append(
            ShapeFinding(
                missing, "completeness",
                "registered in ORACLE_TWINS but has no contract in "
                "ops/contracts.py CONTRACTS — kernels land with their "
                "contract or not at all",
            )
        )
    for stale in sorted(contracted - registry):
        report.findings.append(
            ShapeFinding(
                stale, "completeness",
                "contracted in ops/contracts.py but not registered in "
                "ORACLE_TWINS (stale after a rename/removal?)",
            )
        )

    todo = sorted(contracted & registry)
    if kernels is not None:
        todo = [k for k in todo if k in set(kernels)]
    for name in todo:
        contract = C.CONTRACTS[name]
        try:
            fn = C.resolve_kernel(name)
        except Exception as e:
            report.errors.append(f"{name}: cannot resolve kernel: {e!r}")
            continue
        row = _kernel_row(name, contract)
        report.findings.extend(check_kernel(name, fn, contract, meta=row))
        report.kernels.append(row)
    return report
