"""ktlint core: file walker, rule registry, pragma suppression,
baseline matching, reporting.

The analyzer is the Python/JAX analog of the vet/race tooling the
reference codebase leans on: each rule encodes an invariant of THIS
codebase (jit purity, lock discipline, exception hygiene, bounded I/O,
metric naming) as an AST pass. Rules are pure functions over a parsed
file; the framework owns everything shared:

- walking a set of paths into ``*.py`` files (repo-root-relative paths
  in reports, so baselines survive checkouts at different prefixes);
- pragma suppression: ``# ktlint: disable=KT001`` (comma-separate for
  several rules, or ``disable=all``) on the offending line or the line
  directly above it suppresses matching findings;
- the baseline file: grandfathered findings keyed by
  (rule, path, fingerprint-of-source-line) so line-number drift never
  resurrects them, with per-key counts so N identical offenses on
  distinct lines need N entries. Regenerate with ``--write-baseline``.

Exit status: 0 iff no finding survives pragmas + baseline.
"""

from __future__ import annotations

import ast
import hashlib
import json
import pathlib
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

#: Repo root (ktlint lives at tools/ktlint/framework.py).
REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent
DEFAULT_BASELINE = pathlib.Path(__file__).resolve().parent / "baseline.json"

_PRAGMA_RE = re.compile(r"#\s*ktlint:\s*disable=([A-Za-z0-9_,\s]+?|all)\s*(?:#|$)")


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative when possible
    line: int  # 1-indexed
    message: str
    source_line: str = ""  # stripped offending line (fingerprint input)

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1(
            f"{self.rule}:{self.source_line.strip()}".encode()
        ).hexdigest()
        return h[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class FileContext:
    """Everything a rule sees for one file."""

    path: pathlib.Path
    relpath: str
    tree: ast.Module
    lines: List[str]  # source lines, 1-indexed via lines[lineno - 1]

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        src = self.lines[line - 1] if 0 < line <= len(self.lines) else ""
        return Finding(rule, self.relpath, line, message, src)


class Rule:
    """One pass. Subclasses set ``id``/``title`` and implement check()."""

    id: str = ""
    title: str = ""

    def applies(self, ctx: FileContext) -> bool:
        return True

    def check(self, ctx: FileContext) -> List[Finding]:
        raise NotImplementedError


# -- shared AST helpers (used by several rules) ------------------------


def attr_chain(node: ast.AST) -> List[str]:
    """['jax', 'jit'] for ``jax.jit``; [] when the base isn't a Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def str_constants(node: ast.AST) -> Optional[List[str]]:
    """Strings out of 'x' / ('x','y') / ['x','y']; None if dynamic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append(elt.value)
            else:
                return None
        return out
    return None


# -- pragma + baseline -------------------------------------------------


def pragma_map(lines: Sequence[str]) -> Dict[int, frozenset]:
    """line number -> rules disabled by a pragma ON that line."""
    out: Dict[int, frozenset] = {}
    for i, line in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(line)
        if m:
            names = {n.strip() for n in m.group(1).split(",") if n.strip()}
            out[i] = frozenset(names)
    return out


def is_suppressed(finding: Finding, pragmas: Dict[int, frozenset]) -> bool:
    for line in (finding.line, finding.line - 1):
        rules = pragmas.get(line)
        if rules and (finding.rule in rules or "all" in rules):
            return True
    return False


class Baseline:
    """Grandfathered findings: {(rule, path, fingerprint): count}."""

    def __init__(self, entries: Optional[Dict[Tuple[str, str, str], int]] = None):
        self.entries = dict(entries or {})

    @classmethod
    def load(cls, path: pathlib.Path) -> "Baseline":
        if not path.exists():
            return cls()
        data = json.loads(path.read_text())
        entries: Dict[Tuple[str, str, str], int] = {}
        for e in data.get("entries", []):
            key = (e["rule"], e["path"], e["fingerprint"])
            entries[key] = entries.get(key, 0) + int(e.get("count", 1))
        return cls(entries)

    @classmethod
    def from_findings(cls, findings: Sequence[Finding]) -> "Baseline":
        entries: Dict[Tuple[str, str, str], int] = {}
        for f in findings:
            key = (f.rule, f.path, f.fingerprint)
            entries[key] = entries.get(key, 0) + 1
        return cls(entries)

    def dump(self, path: pathlib.Path) -> None:
        entries = [
            {"rule": r, "path": p, "fingerprint": fp, "count": c}
            for (r, p, fp), c in sorted(self.entries.items())
        ]
        path.write_text(
            json.dumps({"entries": entries}, indent=2, sort_keys=True) + "\n"
        )

    def match(self, finding: Finding) -> bool:
        """Consume one baseline slot for this finding if available."""
        key = (finding.rule, finding.path, finding.fingerprint)
        left = self.entries.get(key, 0)
        if left > 0:
            self.entries[key] = left - 1
            return True
        return False


# -- runner ------------------------------------------------------------


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)  # active
    suppressed: List[Finding] = field(default_factory=list)  # by pragma
    baselined: List[Finding] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)  # unparseable files
    rules: List[str] = field(default_factory=list)  # rule ids that ran

    @property
    def exit_code(self) -> int:
        return 1 if (self.findings or self.errors) else 0

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {r: 0 for r in self.rules}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "rules": self.rules,
            "counts": self.counts(),
            "findings": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "line": f.line,
                    "message": f.message,
                    "fingerprint": f.fingerprint,
                }
                for f in self.findings
            ],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "errors": self.errors,
        }


def iter_files(paths: Sequence[pathlib.Path]) -> List[pathlib.Path]:
    """Every .py under `paths`, each file once — overlapping arguments
    (a dir plus a file inside it) must not lint a file twice, which
    would burn its baseline slots on the first pass and re-report the
    grandfathered findings as active on the second."""
    files: List[pathlib.Path] = []
    seen = set()
    for p in paths:
        cands = sorted(p.rglob("*.py")) if p.is_dir() else (
            [p] if p.suffix == ".py" else []
        )
        for f in cands:
            key = f.resolve()
            if key not in seen:
                seen.add(key)
                files.append(f)
    return files


def relpath_of(path: pathlib.Path) -> str:
    try:
        return str(path.resolve().relative_to(REPO_ROOT))
    except ValueError:
        return str(path)


def run(
    paths: Sequence[pathlib.Path],
    rules: Sequence[Rule],
    baseline: Optional[Baseline] = None,
) -> Report:
    report = Report(rules=[r.id for r in rules])
    baseline = baseline or Baseline()
    for path in iter_files(paths):
        try:
            src = path.read_text()
            tree = ast.parse(src, filename=str(path))
        except (OSError, SyntaxError, ValueError) as e:
            report.errors.append(f"{relpath_of(path)}: {e}")
            continue
        lines = src.splitlines()
        ctx = FileContext(path, relpath_of(path), tree, lines)
        pragmas = pragma_map(lines)
        for rule in rules:
            if not rule.applies(ctx):
                continue
            for f in rule.check(ctx):
                if is_suppressed(f, pragmas):
                    report.suppressed.append(f)
                elif baseline.match(f):
                    report.baselined.append(f)
                else:
                    report.findings.append(f)
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report
