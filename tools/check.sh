#!/usr/bin/env bash
# One-command CI gate: ktlint (all passes) + the tier-1 test suite.
#
#   tools/check.sh            # lint + tests
#   tools/check.sh --lint-only
#
# ktlint JSON lands in /tmp/ktlint.json so dashboards/bench tooling can
# count findings per rule over time (bench.py embeds the same counts in
# its record).
set -o pipefail
cd "$(dirname "$0")/.."

echo "== ktlint =="
python -m tools.ktlint --format=json kubernetes_tpu/ > /tmp/ktlint.json
rc=$?
python - <<'EOF'
import json
d = json.load(open("/tmp/ktlint.json"))
print(
    f"ktlint: {len(d['findings'])} finding(s) "
    f"({d['suppressed']} suppressed, {d['baselined']} baselined) "
    f"{d['counts']}"
)
for f in d["findings"]:
    print(f"  {f['path']}:{f['line']}: {f['rule']} {f['message']}")
for err in d["errors"]:
    print(f"  ERROR {err}")
EOF
if [ $rc -ne 0 ]; then
    echo "ktlint FAILED (see above; pragma or --write-baseline only with a reason)"
    exit $rc
fi
if [ "$1" = "--lint-only" ]; then
    exit 0
fi

echo "== tier-1 tests =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
