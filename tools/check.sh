#!/usr/bin/env bash
# One-command CI gate: ktlint (all passes) + the tier-1 test suite.
#
#   tools/check.sh            # lint + tests
#   tools/check.sh --lint-only
#
# ktlint JSON lands in /tmp/ktlint.json so dashboards/bench tooling can
# count findings per rule over time (bench.py embeds the same counts in
# its record).
set -o pipefail
cd "$(dirname "$0")/.."

echo "== ktlint =="
python -m tools.ktlint --format=json kubernetes_tpu/ > /tmp/ktlint.json
rc=$?
python - <<'EOF'
import json
d = json.load(open("/tmp/ktlint.json"))
print(
    f"ktlint: {len(d['findings'])} finding(s) "
    f"({d['suppressed']} suppressed, {d['baselined']} baselined) "
    f"{d['counts']}"
)
for f in d["findings"]:
    print(f"  {f['path']}:{f['line']}: {f['rule']} {f['message']}")
for err in d["errors"]:
    print(f"  ERROR {err}")
EOF
if [ $rc -ne 0 ]; then
    echo "ktlint FAILED (see above; pragma or --write-baseline only with a reason)"
    exit $rc
fi

echo "== ktsan lock graph (static) =="
python -m tools.ktlint --lock-graph --format=json > /tmp/ktsan_lockgraph.json
rc=$?
python - <<'EOF'
import json
d = json.load(open("/tmp/ktsan_lockgraph.json"))
print(
    f"ktsan: {len(d['locks'])} locks, {len(d['edges'])} edges, "
    f"{len(d['cycles'])} cycle(s), {len(d['violations'])} contract "
    f"violation(s) ({d['suppressed']} suppressed)"
)
for c in d["cycles"]:
    print(f"  KTSAN01 {' -> '.join(c['path'])}")
for v in d["violations"]:
    print(f"  {v['path']}:{v['line']}: {v['rule']} {v['message']}")
EOF
if [ $rc -ne 0 ]; then
    echo "ktsan lock graph FAILED (zero cycles / zero *_locked violations is the gate)"
    exit $rc
fi

echo "== ktshape kernel contracts (abstract eval, no execution) =="
JAX_PLATFORMS=cpu python -m tools.ktlint --kernel-contracts --format=json \
    > /tmp/ktshape.json
rc=$?
python - <<'EOF'
import json
d = json.load(open("/tmp/ktshape.json"))
print(
    f"ktshape: {d['kernels_checked']} kernel(s) checked, "
    f"{len(d['shardable'])} pod-axis shardable "
    f"({', '.join(d['shardable']) or 'none'}), "
    f"{len(d['findings'])} finding(s)"
)
for f in d["findings"]:
    print(f"  {f['kernel']}: [{f['check']}] {f['message']}")
EOF
if [ $rc -ne 0 ]; then
    echo "ktshape FAILED (every ORACLE_TWINS kernel contracted + zero shape/dtype/sharding findings is the gate)"
    exit $rc
fi
echo "== ktmesh SPMD partitioning (partitioned lowering, no execution) =="
JAX_PLATFORMS=cpu python -m tools.ktlint --mesh-analysis --format=json \
    > /tmp/ktmesh.json
rc=$?
python - <<'EOF'
import json
d = json.load(open("/tmp/ktmesh.json"))
print(
    f"ktmesh: {d['kernels_checked']} kernel(s) on {d['devices']} "
    f"device(s), {d['collectives_total']} collective(s) "
    f"({d['collective_bytes_total']} bytes), {d['skipped']} skipped, "
    f"{len(d['findings'])} finding(s)"
)
for f in d["findings"]:
    print(f"  {f['kernel']}: [{f['check']}] {f['message']}")
for err in d["errors"]:
    print(f"  ERROR {err}")
EOF
if [ $rc -ne 0 ]; then
    echo "ktmesh FAILED (every kernel within its declared communication budget — re-pin ops/contracts.py deliberately or fix the sharding)"
    exit $rc
fi
if [ "$1" = "--lint-only" ]; then
    exit 0
fi

echo "== ktsan runtime (sanitizer-on concurrency subset) =="
# The concurrency-heavy modules under KT_SANITIZE=locks, dumping the
# OBSERVED lock-order graph; the merge below closes the loop: a cycle
# needs both halves in neither order. The module list IS
# conftest.KTSAN_MODULES (one source of truth) minus test_ktsan — its
# deliberate-inversion fixtures run in tier-1 but must not pollute
# the live merge. A stale report from a killed earlier run must not
# survive into the merge either.
rm -f /tmp/ktsan_runtime.json
KTSAN_TESTS=$(python - <<'EOF'
import sys
sys.path.insert(0, "tests")
from conftest import KTSAN_MODULES
print(" ".join(
    f"tests/{m}.py" for m in sorted(KTSAN_MODULES) if m != "test_ktsan"
))
EOF
)
env JAX_PLATFORMS=cpu KT_SANITIZE=locks \
    KT_SANITIZE_REPORT=/tmp/ktsan_runtime.json \
    python -m pytest $KTSAN_TESTS \
    -q -m 'not slow' -p no:cacheprovider
rc=$?
if [ $rc -ne 0 ]; then
    echo "ktsan runtime subset FAILED (sanitizer finding or test regression)"
    exit $rc
fi
if [ -f /tmp/ktsan_runtime.json ]; then
    python -m tools.ktlint --lock-graph \
        --runtime-graph /tmp/ktsan_runtime.json --format=json \
        > /tmp/ktsan_merged.json
    rc=$?
    python - <<'EOF'
import json
d = json.load(open("/tmp/ktsan_merged.json"))
runtime = sum(1 for e in d["edges"] if e["kind"] == "runtime")
print(
    f"ktsan merged: {len(d['edges'])} edges ({runtime} runtime-observed), "
    f"{len(d['cycles'])} cycle(s), "
    f"{len(d['runtime_findings'])} runtime finding(s)"
)
EOF
    if [ $rc -ne 0 ]; then
        echo "ktsan merged static+runtime graph FAILED"
        exit $rc
    fi
fi

echo "== tier-1 tests =="
env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
rc=$?

# Surface preemption solve counts alongside the per-phase latency
# fields (bench.py embeds the same series in its JSON record): a tiny
# in-process exercise of the scalar victim selector proves the series
# are live and prints them the way dashboards will scrape them.
echo "== preemption metrics smoke =="
env JAX_PLATFORMS=cpu python - <<'EOF'
from kubernetes_tpu.models.objects import (
    Container, Node, NodeCondition, NodeStatus, ObjectMeta, Pod,
    PodSpec, ResourceRequirements,
)
from kubernetes_tpu.models.quantity import parse_quantity
from kubernetes_tpu.scheduler.batch import preempt_backlog_scalar
from kubernetes_tpu.scheduler.daemon import (
    _PREEMPT_OUTCOMES, _PREEMPT_VICTIMS,
)

def pod(name, cpu, prio=0, node=""):
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PodSpec(
            containers=[Container(name="c", image="x",
                resources=ResourceRequirements(
                    limits={"cpu": parse_quantity(cpu)}))],
            priority=prio, node_name=node,
        ),
    )

node = Node(metadata=ObjectMeta(name="n0"), status=NodeStatus(
    capacity={"cpu": parse_quantity("1"), "pods": parse_quantity("10")},
    conditions=[NodeCondition(type="Ready", status="True")]))
decisions = preempt_backlog_scalar(
    [pod("hi", "800m", prio=100)], [node], [pod("lo", "900m", node="n0")]
)
granted = sum(1 for d in decisions if d)
victims = sum(len(d.victims) for d in decisions if d)
_PREEMPT_OUTCOMES.inc(outcome="nominated", amount=granted)
_PREEMPT_VICTIMS.inc(victims)
print(
    f"preemption_solve_outcomes_total{{outcome=\"nominated\"}} "
    f"{_PREEMPT_OUTCOMES.value(outcome='nominated')}"
)
print(f"preemption_victims_total {_PREEMPT_VICTIMS.value()}")
EOF
smoke_rc=$?
if [ $rc -eq 0 ]; then
    rc=$smoke_rc  # a broken smoke must fail CI even when tests passed
fi

# Explain smoke: boot the in-process e2e cluster, schedule one
# feasible and one infeasible pod through the batch daemon, and assert
# `ktctl explain` reports the bind (with its score) and a per-predicate
# "why not" reason — the flight-recorder surface end to end.
echo "== explain smoke =="
env JAX_PLATFORMS=cpu python - <<'EOF'
import io
import time
from contextlib import redirect_stdout

from kubernetes_tpu.cli import ktctl
from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.scheduler.daemon import BatchScheduler, SchedulerConfig
from kubernetes_tpu.server import APIServer

api = APIServer()
client = Client(LocalTransport(api))
for j in range(2):
    client.create("nodes", {
        "kind": "Node", "metadata": {"name": f"n{j}"},
        "status": {"capacity": {"cpu": "4", "memory": "8Gi", "pods": "10"},
                   "conditions": [{"type": "Ready", "status": "True"}]}})

def pod(name, selector=None):
    return {"kind": "Pod", "metadata": {"name": name, "namespace": "default"},
            "spec": {"nodeSelector": selector or {},
                     "containers": [{"name": "c", "image": "x",
                                     "resources": {"limits": {
                                         "cpu": "100m", "memory": "64Mi"}}}]}}

client.create("pods", pod("ok-pod"))
client.create("pods", pod("stuck-pod", {"disk": "ssd"}))  # no node matches
cfg = SchedulerConfig(Client(LocalTransport(api))).start()
assert cfg.wait_for_sync(timeout=60), "caches never synced"
sched = BatchScheduler(cfg)
bound = ""
deadline = time.monotonic() + 120
while time.monotonic() < deadline and not bound:
    sched.schedule_batch(timeout=0.5)
    bound = client.get("pods", "ok-pod").spec.node_name
cfg.stop()
assert bound, "ok-pod never bound"

out = io.StringIO()
with redirect_stdout(out):
    rc = ktctl.main(["explain", "pod", "ok-pod"], client=client)
text = out.getvalue()
assert rc == 0, text
assert "outcome bound" in text and f"-> {bound}" in text, text
assert "score" in text, text
out = io.StringIO()
with redirect_stdout(out):
    rc = ktctl.main(["explain", "pod", "stuck-pod"], client=client)
text = out.getvalue()
assert rc == 0, text
assert "MatchNodeSelector" in text, text
print(f"explain smoke OK: ok-pod bound to {bound}; stuck-pod explained "
      "with a per-predicate reason")
EOF
explain_rc=$?
if [ $rc -eq 0 ]; then
    rc=$explain_rc
fi

# Bulk-path smoke (ISSUE 6): boot the HTTP control plane, push 5k pods
# through POST pods:bulk in a handful of group-committed batches, and
# assert the informer-fed incremental daemon drains and binds them all
# — the whole new API plane (bulk write fast path, watch cache reads,
# reflector feed) exercised end to end.
echo "== bulk-path smoke =="
env JAX_PLATFORMS=cpu python - <<'EOF'
import time

from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.scheduler.daemon import (
    IncrementalBatchScheduler, SchedulerConfig,
)
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer

N_PODS, N_NODES, BATCH = 5000, 64, 1000

api = APIServer()
srv = APIHTTPServer(api, max_in_flight=800).start()
client = Client(HTTPTransport(srv.address))
client.create_bulk("nodes", [
    {"kind": "Node", "metadata": {"name": f"n{j}"},
     "status": {"capacity": {"cpu": "64", "memory": "256Gi", "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}]}}
    for j in range(N_NODES)
])

def pod(name):
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "app",
                     "resources": {"limits": {"cpu": "50m",
                                              "memory": "32Mi"}}}]}}

cfg = SchedulerConfig(
    Client(HTTPTransport(srv.address)), raw_scheduled_cache=True
).start()
assert cfg.wait_for_sync(timeout=60), "scheduler caches never synced"
sched = IncrementalBatchScheduler(cfg, max_batch=2048).start()

t0 = time.monotonic()
for s in range(0, N_PODS, BATCH):
    results = client.create_bulk(
        "pods", [pod(f"bp{i}") for i in range(s, s + BATCH)],
        namespace="default",
    )
    bad = [r for r in results if r.get("status") != "Success"]
    assert not bad, bad[:3]

deadline = time.monotonic() + 120
bound = 0
while time.monotonic() < deadline:
    pods, _ = client.list("pods", namespace="default")
    bound = sum(1 for p in pods if p.spec.node_name)
    if bound == N_PODS:
        break
    time.sleep(0.5)
wall = time.monotonic() - t0
sched.stop()
srv.stop()
assert bound == N_PODS, f"only {bound}/{N_PODS} pods bound"
print(f"bulk smoke OK: {N_PODS} pods bulk-created over HTTP and bound "
      f"by the informer-fed daemon in {wall:.1f}s")
EOF
bulk_rc=$?
if [ $rc -eq 0 ]; then
    rc=$bulk_rc
fi

# SLO smoke (ISSUE 9): in a FRESH process (fresh metrics registry),
# assert the `ktctl slo` empty-cluster miss contract first, then churn
# ~200 pods through the HTTP control plane (bulk create -> informer-fed
# incremental daemon binds -> stand-in kubelet flips Running) and
# assert the telemetry plane end to end: /debug/slo serves verdicts, a
# populated pod_startup_latency objective, and `ktctl slo` exits 0.
echo "== slo smoke =="
env JAX_PLATFORMS=cpu python - <<'EOF'
import io
import json
import time
import urllib.request
from contextlib import redirect_stderr, redirect_stdout

from kubernetes_tpu.cli import ktctl
from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.scheduler.daemon import (
    IncrementalBatchScheduler, SchedulerConfig,
)
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer

N_PODS = 200

api = APIServer()
srv = APIHTTPServer(api, max_in_flight=800).start()
client = Client(HTTPTransport(srv.address))

# Miss contract FIRST (empty cluster, no SLI samples yet): exit 1,
# empty stdout, the reason on stderr — mirror of ktctl trace/explain.
out, err = io.StringIO(), io.StringIO()
with redirect_stdout(out), redirect_stderr(err):
    rc = ktctl.main(["slo"], client=client)
assert rc == 1, (rc, out.getvalue(), err.getvalue())
assert out.getvalue() == "", out.getvalue()
assert "no SLI samples recorded" in err.getvalue(), err.getvalue()

client.create_bulk("nodes", [
    {"kind": "Node", "metadata": {"name": f"n{j}"},
     "status": {"capacity": {"cpu": "64", "memory": "256Gi", "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}]}}
    for j in range(8)
])
cfg = SchedulerConfig(
    Client(HTTPTransport(srv.address)), raw_scheduled_cache=True
).start()
assert cfg.wait_for_sync(timeout=60), "scheduler caches never synced"
sched = IncrementalBatchScheduler(cfg, max_batch=512).start()

def pod(name):
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "app",
                     "resources": {"limits": {"cpu": "50m",
                                              "memory": "32Mi"}}}]}}

res = client.create_bulk(
    "pods", [pod(f"slo-{i}") for i in range(N_PODS)], namespace="default"
)
assert all(r.get("status") == "Success" for r in res)
deadline = time.monotonic() + 120
bound = 0
while time.monotonic() < deadline and bound < N_PODS:
    pods, _ = client.list("pods", namespace="default")
    bound = sum(1 for p in pods if p.spec.node_name)
    if bound < N_PODS:
        time.sleep(0.25)
assert bound == N_PODS, f"only {bound}/{N_PODS} bound"
# Stand-in kubelet: flip every pod Running through the status
# subresource; the collector reads the resulting watch events.
for p in pods:
    p.status.phase = "Running"
    client.update_status("pods", p, namespace="default")

def slo_report():
    with urllib.request.urlopen(srv.address + "/debug/slo", timeout=10) as r:
        return json.loads(r.read())

deadline = time.monotonic() + 30
objs = {}
while time.monotonic() < deadline:
    objs = {o["name"]: o for o in slo_report()["objectives"]}
    if objs.get("pod_startup_latency", {}).get("samples", 0) >= N_PODS:
        break
    time.sleep(0.25)
assert objs["pod_startup_latency"]["samples"] >= N_PODS, objs
assert objs["pod_startup_latency"]["verdict"] in ("pass", "warn", "burn")
assert objs["pod_bound_latency"]["samples"] >= N_PODS, objs

out = io.StringIO()
with redirect_stdout(out):
    rc = ktctl.main(["slo"], client=client)
text = out.getvalue()
assert rc == 0, text
assert "pod_startup_latency" in text and "overall:" in text, text
sched.stop()
srv.stop()
print(f"slo smoke OK: {N_PODS} pods churned; pod_startup_latency "
      f"p99={objs['pod_startup_latency'].get('p99')}s verdict="
      f"{objs['pod_startup_latency']['verdict']}; empty-cluster miss "
      "contract held")
EOF
slo_rc=$?
if [ $rc -eq 0 ]; then
    rc=$slo_rc
fi

# Latency smoke (ISSUE 12): boot local-up with the (now default)
# incremental session daemon — micro-ticks, pipelined commits,
# compile-cache pre-warm — trickle pods through it, and assert the
# PR-9 SLO contract flips to PASS on the bound-latency objective:
# `ktctl slo` exits 0 and pod_bound_latency verdicts "pass". This is
# the burn->pass acceptance gate of the always-resident solve loop,
# reused as CI.
echo "== latency smoke (micro-tick path) =="
env JAX_PLATFORMS=cpu python - <<'EOF'
import io
import time
from contextlib import redirect_stdout

from kubernetes_tpu.cli import ktctl
from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.cmd.localup import LocalCluster, build_parser

N_PODS = 30

args = build_parser().parse_args(
    ["--port", "0", "--nodes", "2", "--batch-scheduler"]
)
cluster = LocalCluster(args).start()
try:
    client = Client(HTTPTransport(cluster.http.address))
    # Wait out the pre-warm: the daemon builds its session (and
    # compiles the small pod buckets) on its first idle tick — the
    # trickle below must measure micro-ticks, not compiles.
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if getattr(cluster.scheduler, "_session", None) is not None:
            break
        time.sleep(0.25)
    assert getattr(cluster.scheduler, "_session", None) is not None, (
        "incremental session never pre-warmed"
    )
    def pod(name):
        return {"kind": "Pod",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"containers": [{"name": "c", "image": "pause",
                         "resources": {"limits": {"cpu": "50m",
                                                  "memory": "32Mi"}}}]}}
    for i in range(N_PODS):
        client.create("pods", pod(f"lat-{i}"), namespace="default")
        time.sleep(0.05)  # trickle: every pod gets its own micro-tick
    deadline = time.monotonic() + 120
    bound = 0
    while time.monotonic() < deadline and bound < N_PODS:
        pods, _ = client.list("pods", namespace="default")
        bound = sum(1 for p in pods if p.spec.node_name)
        if bound < N_PODS:
            time.sleep(0.2)
    assert bound == N_PODS, f"only {bound}/{N_PODS} bound"
    # The SLO engine's verdict on the bound-latency objective must be
    # a clean PASS (the pre-PR-12 state was burn: BENCH_r06).
    from kubernetes_tpu.utils import slo
    deadline = time.monotonic() + 30
    obj = {}
    while time.monotonic() < deadline:
        report = slo.evaluate()
        obj = {o["name"]: o for o in report["objectives"]}
        if obj.get("pod_bound_latency", {}).get("samples", 0) >= N_PODS:
            break
        time.sleep(0.25)
    pbl = obj.get("pod_bound_latency", {})
    assert pbl.get("samples", 0) >= N_PODS, obj
    assert pbl["verdict"] == "pass", (
        f"pod_bound_latency must PASS on the micro-tick path: {pbl}"
    )
    out = io.StringIO()
    with redirect_stdout(out):
        rc = ktctl.main(["slo"], client=client)
    assert rc == 0, out.getvalue()
    assert "pod_bound_latency" in out.getvalue()
    print(f"latency smoke OK: {N_PODS} trickled pods bound; "
          f"pod_bound_latency p99={pbl.get('p99')}s verdict=pass")
finally:
    cluster.stop()
EOF
lat_rc=$?
if [ $rc -eq 0 ]; then
    rc=$lat_rc
fi

# Profile smoke (ISSUE 13): the device-time profiling plane end to
# end — miss contract first (`ktctl profile kernels` exits 1 with "no
# compiles recorded" before anything compiled), then boot local-up
# with the micro-tick daemon, bind pods, and assert the populated
# contract: `ktctl profile kernels` exits 0 with a non-empty ledger
# (every compile row named like the KT006 registry) and
# /debug/profile?format=collapsed returns folded stacks.
echo "== profile smoke (compile ledger + collapsed stacks) =="
env JAX_PLATFORMS=cpu python - <<'EOF'
import io
import re
import time
import urllib.request
from contextlib import redirect_stderr, redirect_stdout

from kubernetes_tpu.cli import ktctl
from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.server.api import APIServer

# Miss contract FIRST (nothing compiled in this process yet): exit 1,
# empty stdout, the reason on stderr — mirror of ktctl trace/explain/slo.
out, err = io.StringIO(), io.StringIO()
with redirect_stdout(out), redirect_stderr(err):
    rc = ktctl.main(
        ["profile", "kernels"], client=Client(LocalTransport(APIServer()))
    )
assert rc == 1, f"empty-ledger ktctl profile must exit 1, got {rc}"
assert out.getvalue() == ""
assert "no compiles recorded" in err.getvalue()

from kubernetes_tpu.cmd.localup import LocalCluster, build_parser

N_PODS = 8
args = build_parser().parse_args(
    ["--port", "0", "--nodes", "2", "--batch-scheduler"]
)
cluster = LocalCluster(args).start()
try:
    client = Client(HTTPTransport(cluster.http.address))
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        if getattr(cluster.scheduler, "_session", None) is not None:
            break
        time.sleep(0.25)
    def pod(name):
        return {"kind": "Pod",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"containers": [{"name": "c", "image": "pause",
                         "resources": {"limits": {"cpu": "50m",
                                                  "memory": "32Mi"}}}]}}
    for i in range(N_PODS):
        client.create("pods", pod(f"prof-{i}"), namespace="default")
    deadline = time.monotonic() + 120
    bound = 0
    while time.monotonic() < deadline and bound < N_PODS:
        pods, _ = client.list("pods", namespace="default")
        bound = sum(1 for p in pods if p.spec.node_name)
        if bound < N_PODS:
            time.sleep(0.2)
    assert bound == N_PODS, f"only {bound}/{N_PODS} bound"

    # Populated contract: the ledger carries the solve-path kernels
    # the daemon just compiled, named like the KT006 registry.
    out = io.StringIO()
    with redirect_stdout(out):
        rc = ktctl.main(["profile", "kernels"], client=client)
    text = out.getvalue()
    assert rc == 0, text
    assert "solver._solve_with_state_xla" in text, text
    data = client.t.get_json("/debug/kernels")
    assert data["summary"]["compiles"] >= 1, data["summary"]

    # Folded stacks for flamegraph tooling.
    with urllib.request.urlopen(
        cluster.http.address + "/debug/profile?seconds=0.5&format=collapsed",
        timeout=30,
    ) as resp:
        folded = resp.read().decode()
    lines = [ln for ln in folded.splitlines() if ln.strip()]
    assert lines, "collapsed profile produced no stacks"
    assert all(re.match(r"^.+ \d+$", ln) for ln in lines), lines[:3]
    assert any(";" in ln for ln in lines), "no multi-frame stack folded"
    print(f"profile smoke OK: {N_PODS} pods bound; "
          f"{data['summary']['compiles']} compiles in the ledger "
          f"({data['summary']['compile_seconds_total']}s); "
          f"{len(lines)} folded stacks")
finally:
    cluster.stop()
EOF
prof_rc=$?
if [ $rc -eq 0 ]; then
    rc=$prof_rc
fi

# Capacity smoke (ISSUE 16): the capacity & fragmentation plane end
# to end — miss contract first (`ktctl top capacity` exits 1 with "no
# capacity samples recorded" before any daemon sampled), then fill a
# small cluster until every probe shape hits ZERO headroom with free
# capacity still on every node (the textbook stranded state) and
# assert the populated contract: /debug/capacity reports stranded
# nodes, `ktctl top capacity` exits 0 with the probe table, and the
# capacity_fragmentation SLO objective flips to warn.
echo "== capacity smoke (fragmentation + stranded headroom) =="
env JAX_PLATFORMS=cpu python - <<'EOF'
import io
import json
import time
import urllib.request
from contextlib import redirect_stderr, redirect_stdout

from kubernetes_tpu.cli import ktctl
from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.scheduler.daemon import (
    IncrementalBatchScheduler, SchedulerConfig,
)
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer

N_NODES = 6

api = APIServer()
srv = APIHTTPServer(api, max_in_flight=800).start()
client = Client(HTTPTransport(srv.address))

# Miss contract FIRST (no sample taken yet): exit 1, empty stdout,
# the reason on stderr — mirror of ktctl slo/trace/explain.
out, err = io.StringIO(), io.StringIO()
with redirect_stdout(out), redirect_stderr(err):
    rc = ktctl.main(["top", "capacity"], client=client)
assert rc == 1, (rc, out.getvalue(), err.getvalue())
assert out.getvalue() == "", out.getvalue()
assert "no capacity samples recorded" in err.getvalue(), err.getvalue()

client.create_bulk("nodes", [
    {"kind": "Node", "metadata": {"name": f"n{j}"},
     "status": {"capacity": {"cpu": "1", "memory": "2Gi", "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}]}}
    for j in range(N_NODES)
])
cfg = SchedulerConfig(Client(HTTPTransport(srv.address))).start()
assert cfg.wait_for_sync(timeout=60), "scheduler caches never synced"
sched = IncrementalBatchScheduler(cfg).start()

def pod(name):
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "pause",
                     "resources": {"limits": {"cpu": "800m",
                                              "memory": "256Mi"}}}]}}

# One 800m pod per 1000m node: every node keeps 200m free, which no
# probe shape (smallest: 250m) can use — zero headroom everywhere
# while capacity still exists. Two more stay Pending for backlog
# pressure.
res = client.create_bulk(
    "pods", [pod(f"cap-{i}") for i in range(N_NODES + 2)],
    namespace="default",
)
assert all(r.get("status") == "Success" for r in res)
deadline = time.monotonic() + 120
bound = 0
while time.monotonic() < deadline and bound < N_NODES:
    pods, _ = client.list("pods", namespace="default")
    bound = sum(1 for p in pods if p.spec.node_name)
    if bound < N_NODES:
        time.sleep(0.25)
assert bound == N_NODES, f"only {bound}/{N_NODES} bound"

def capacity_report():
    with urllib.request.urlopen(
        srv.address + "/debug/capacity", timeout=10
    ) as r:
        return json.loads(r.read())

deadline = time.monotonic() + 30
snap = {}
while time.monotonic() < deadline:
    snap = capacity_report()
    if (snap.get("sampled") and snap.get("stranded_node_count", 0) > 0
            and any(p["headroom_pods"] == 0 for p in snap["probes"])):
        break
    time.sleep(0.25)
assert snap.get("sampled"), snap
assert snap["stranded_node_count"] > 0, snap
zero = [p["shape"] for p in snap["probes"] if p["headroom_pods"] == 0]
assert zero, snap["probes"]
assert snap["fragmentation_score"] > 0.5, snap

# The SLO plane must read the same state: capacity_fragmentation warns.
def slo_report():
    with urllib.request.urlopen(srv.address + "/debug/slo", timeout=10) as r:
        return json.loads(r.read())

deadline = time.monotonic() + 30
frag_obj = {}
while time.monotonic() < deadline:
    objs = {o["name"]: o for o in slo_report()["objectives"]}
    frag_obj = objs.get("capacity_fragmentation", {})
    if frag_obj.get("verdict") in ("warn", "burn"):
        break
    time.sleep(0.25)
assert frag_obj.get("verdict") in ("warn", "burn"), frag_obj

# Populated ktctl contract: exit 0, probe table present.
out = io.StringIO()
with redirect_stdout(out):
    rc = ktctl.main(["top", "capacity"], client=client)
text = out.getvalue()
assert rc == 0, text
assert "slice-1x250m" in text and "fragmentation" in text, text
sched.stop()
srv.stop()
print(f"capacity smoke OK: {N_NODES} nodes filled to zero headroom "
      f"({', '.join(zero)}); fragmentation="
      f"{snap['fragmentation_score']} stranded="
      f"{snap['stranded_node_count']} -> capacity_fragmentation "
      f"{frag_obj['verdict']}; miss contract held")
EOF
cap_rc=$?
if [ $rc -eq 0 ]; then
    rc=$cap_rc
fi

# Rebalance smoke (ISSUE 17): the continuous-rebalancing plane end to
# end — miss contract first (`ktctl rebalance status` exits 1 with
# "no rebalance samples recorded" before any cycle ran), then stage
# the textbook fragmented cluster (three 1000m fillers born bound on
# every 4000m node: a 1000m shard free each, so the 2000m slice probe
# has zero headroom cluster-wide), run ONE forced defrag cycle, and
# assert the populated contract: measured fragmentation drops, every
# mover re-binds at its pinned destination, the move journal drains,
# a 2000m probe binds post-defrag, and `ktctl rebalance status`
# exits 0 — with zero stranded pods.
echo "== rebalance smoke (defrag cycle + pinned rebinds) =="
env JAX_PLATFORMS=cpu python - <<'EOF'
import io
import json
import time
import urllib.request
from contextlib import redirect_stderr, redirect_stdout

from kubernetes_tpu.cli import ktctl
from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.controllers.descheduler import Descheduler
from kubernetes_tpu.models.objects import (
    REBALANCE_DEST_ANNOTATION, REBALANCE_JOURNAL_LABEL,
)
from kubernetes_tpu.scheduler.daemon import (
    IncrementalBatchScheduler, SchedulerConfig,
)
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer

N_NODES = 6

api = APIServer()
srv = APIHTTPServer(api, max_in_flight=800).start()
client = Client(HTTPTransport(srv.address))

# Miss contract FIRST (no defrag cycle ran yet): exit 1, empty
# stdout, the reason on stderr — mirror of ktctl top capacity.
out, err = io.StringIO(), io.StringIO()
with redirect_stdout(out), redirect_stderr(err):
    rc = ktctl.main(["rebalance", "status"], client=client)
assert rc == 1, (rc, out.getvalue(), err.getvalue())
assert out.getvalue() == "", out.getvalue()
assert "no rebalance samples recorded" in err.getvalue(), err.getvalue()

client.create_bulk("nodes", [
    {"kind": "Node", "metadata": {"name": f"n{j}"},
     "status": {"capacity": {"cpu": "4", "memory": "8Gi", "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}]}}
    for j in range(N_NODES)
])

def pod(name, cpu, node=""):
    spec = {"containers": [{"name": "c", "image": "pause",
            "resources": {"limits": {"cpu": cpu, "memory": "256Mi"}}}]}
    if node:
        spec["nodeName"] = node  # born bound: the static-pod shape
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": spec}

res = client.create_bulk(
    "pods",
    [pod(f"f{j}-{k}", "1", node=f"n{j}")
     for j in range(N_NODES) for k in range(3)],
    namespace="default",
)
assert all(r.get("status") == "Success" for r in res)

cfg = SchedulerConfig(Client(HTTPTransport(srv.address))).start()
assert cfg.wait_for_sync(timeout=60), "scheduler caches never synced"
sched = IncrementalBatchScheduler(cfg).start()

d = Descheduler(client, frag_threshold=0.01, move_budget=8,
                disruption_cap=8, wait_timeout_s=10.0)
summary = d.sync_once(force=True)
assert summary["triggered"] and summary["moves_executed"] > 0, summary
assert summary["score_after"] < summary["score_before"], summary

# Every mover re-binds at its pinned destination; the journal drains.
deadline = time.monotonic() + 60
settled = False
while time.monotonic() < deadline and not settled:
    pods, _ = client.list("pods", namespace="default")
    movers = [p for p in pods if (p.metadata.annotations or {}).get(
        REBALANCE_DEST_ANNOTATION)]
    journals, _ = client.list(
        "podtemplates", label_selector=REBALANCE_JOURNAL_LABEL)
    settled = bool(movers) and not journals and all(
        p.spec.node_name == (p.metadata.annotations or {}).get(
            REBALANCE_DEST_ANNOTATION) for p in movers)
    if not settled:
        time.sleep(0.25)
assert settled, "movers never settled at their pins / journal stuck"
pods, _ = client.list("pods", namespace="default")
assert len(pods) == N_NODES * 3, f"a move stranded a pod: {len(pods)}"

# The payoff: the 2000m probe that had zero headroom pre-defrag binds.
client.create("pods", pod("probe", "2"), namespace="default")
deadline = time.monotonic() + 60
probe_node = ""
while time.monotonic() < deadline and not probe_node:
    probe_node = client.get(
        "pods", "probe", namespace="default").spec.node_name or ""
    if not probe_node:
        time.sleep(0.25)
assert probe_node, "post-defrag 2000m probe never bound"

with urllib.request.urlopen(
    srv.address + "/debug/rebalance", timeout=10
) as r:
    snap = json.loads(r.read())
assert snap["sampled"] and snap["samples"] >= 1, snap
assert snap["outcomes"].get("stranded", 0) == 0, snap

out = io.StringIO()
with redirect_stdout(out):
    rc = ktctl.main(["rebalance", "status"], client=client)
text = out.getvalue()
assert rc == 0, text
assert "evicted=" in text, text
sched.stop()
srv.stop()
print(f"rebalance smoke OK: fragmentation "
      f"{summary['score_before']} -> {summary['score_after']} in "
      f"{summary['moves_executed']} moves; probe bound on "
      f"{probe_node}; journal drained; zero stranded; miss contract "
      f"held")
EOF
reb_rc=$?
if [ $rc -eq 0 ]; then
    rc=$reb_rc
fi

# Failover smoke (ISSUE 19): the HA control plane end to end, both
# tiers, over the real HTTP planes. Tier 1 — a 3-replica kvstore
# (leader + two WAL-shipped followers); kill -9 the leader (store
# crash, HTTP down), promote a follower, and the multi-endpoint client
# rotates onto it and keeps writing. Tier 2 — abrupt scheduler kill
# with a PREWARMED warm standby; kill -> first bind must land inside
# the failover_to_first_bind_s gate (1 s, utils/slo.py). Then `ktctl
# slo` over the survivor exits 0.
echo "== failover smoke (HA control plane: kvstore promote + warm standby) =="
env JAX_PLATFORMS=cpu python - <<'EOF'
import io
import json
import time
import urllib.request
from contextlib import redirect_stdout

from kubernetes_tpu.cli import ktctl
from kubernetes_tpu.client import Client
from kubernetes_tpu.client.rest import HTTPTransport
from kubernetes_tpu.scheduler.standby import WarmStandbyScheduler
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.store.kvstore import KVStore
from kubernetes_tpu.store.replication import (
    FollowerReplica, HTTPLink, ReplicationHub,
)
from kubernetes_tpu.utils import slo as _slo


def wait(cond, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def node_wire(j):
    return {
        "kind": "Node", "metadata": {"name": f"n{j}"},
        "status": {
            "capacity": {"cpu": "8", "memory": "16Gi", "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def pod_wire(name):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{
            "name": "c", "image": "pause",
            "resources": {"limits": {"cpu": "100m", "memory": "64Mi"}},
        }]},
    }


# Tier 1 — replicated kvstore over the HTTP replication plane.
leader_store = KVStore()
leader_api = APIServer(store=leader_store)
leader_http = APIHTTPServer(leader_api).start()
hub = ReplicationHub(leader_store).attach()
leader_api.replication = hub
followers = []
for fname in ("f1", "f2"):
    rep = FollowerReplica(name=fname)
    fapi = APIServer(store=rep.store)
    fapi.replication = rep
    fapi.leader_url = leader_http.address
    fhttp = APIHTTPServer(fapi).start()
    hub.add_follower(HTTPLink(fhttp.address, name=fname))
    followers.append((rep, fapi, fhttp))

# One client, both endpoints: pins to the leader until it dies.
client = Client(HTTPTransport(
    [leader_http.address, followers[0][2].address]
))
for j in range(6):
    client.create("nodes", node_wire(j))
client.create("pods", pod_wire("pre-crash"))
assert wait(lambda: hub.status()["commitIndex"] == leader_store.version), (
    "followers never reached the leader's commit index"
)

# kill -9 the kvstore leader: HTTP down, store crashed, hub gone.
leader_http.stop(release_store=False)
leader_store.crash()
hub.stop()
rep1, f1_api, f1_http = followers[0]
promoted = rep1.promote()
assert promoted.version >= 0
h = json.loads(urllib.request.urlopen(f1_http.address + "/healthz").read())
assert h["checks"]["replication"]["role"] == "leader", h

# The same client rotates onto the promoted follower: the committed
# prefix is all there, and writes land locally (no forwarding).
assert wait(lambda: any(
    p.metadata.name == "pre-crash"
    for p in client.list("pods", namespace="default")[0]
)), "committed pre-crash write lost across promotion"
client.create("pods", pod_wire("post-promote"))
assert client.get(
    "pods", "post-promote", namespace="default"
).metadata.name == "post-promote"

# Tier 2 — scheduler failover on the surviving replica. Warm the
# solve path first (bucket compile); then the drill.
active = WarmStandbyScheduler(
    Client(HTTPTransport(f1_http.address)), sync_timeout=60.0
)
active.activate()
assert wait(lambda: client.get(
    "pods", "post-promote", namespace="default"
).spec.node_name), "warmup pod never bound"
standby = WarmStandbyScheduler(
    Client(HTTPTransport(f1_http.address)), sync_timeout=60.0
)
standby.prewarm()
active.kill()
t0 = time.monotonic()
client.create("pods", pod_wire("takeover"))
standby.activate()
assert wait(lambda: client.get(
    "pods", "takeover", namespace="default"
).spec.node_name, timeout=30.0), "standby never bound after takeover"
bind_s = time.monotonic() - t0
obj = _slo.BENCH_OBJECTIVES["failover_to_first_bind_s"]
assert _slo.verdict_for_value(obj, bind_s) == "pass", (
    f"failover first bind {bind_s:.3f}s breaches the {obj.target:.0f}s gate"
)

out = io.StringIO()
with redirect_stdout(out):
    rc = ktctl.main(["slo"], client=client)
assert rc == 0, out.getvalue()

standby.stop()
for _, _, fhttp in followers:
    fhttp.stop()
print(f"failover smoke OK: kvstore leader killed -> follower promoted, "
      f"client rotated, committed prefix intact; scheduler killed -> "
      f"warm standby first bind {bind_s * 1000:.0f} ms "
      f"(gate {obj.target:.0f} s); ktctl slo rc 0")
EOF
fo_rc=$?
if [ $rc -eq 0 ]; then
    rc=$fo_rc
fi

# Alert smoke (ISSUE 20): the health plane end to end in a fresh
# process — `ktctl alerts` / `ktctl top health` miss contracts first
# (exit 1, empty stdout, reason on stderr), then the HTTP control
# plane under a seeded watch-drop storm with the burn-rate engine on
# compressed clocks: watch_drop_storm must transition to firing while
# the storm runs, resolve after it clears, and the three debug
# endpoints (/debug/alerts, /debug/timeseries, /debug/health) must
# serve the populated contracts over HTTP.
echo "== alert smoke (burn-rate firing + resolution) =="
env JAX_PLATFORMS=cpu python - <<'EOF'
import dataclasses
import io
import json
import time
import urllib.request
from contextlib import redirect_stderr, redirect_stdout

from kubernetes_tpu.cli import ktctl
from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.scheduler.daemon import (
    IncrementalBatchScheduler, SchedulerConfig,
)
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.utils import alerts, faults, timeseries

api = APIServer()
srv = APIHTTPServer(api, max_in_flight=800).start()
client = Client(HTTPTransport(srv.address))

# Miss contracts FIRST (no evaluations yet): exit 1, empty stdout,
# the reason on stderr — the trace/explain/slo contract.
for argv, msg in (
    (["alerts"], "no alert evaluations recorded"),
    (["top", "health"], "no health samples recorded"),
):
    out, err = io.StringIO(), io.StringIO()
    with redirect_stdout(out), redirect_stderr(err):
        rc = ktctl.main(argv, client=client)
    assert rc == 1, (argv, rc, err.getvalue())
    assert out.getvalue() == "", (argv, out.getvalue())
    assert msg in err.getvalue(), (argv, err.getvalue())

# Drill config: compressed clocks (1h/5m windows -> 6s/0.5s) and a
# drop-rate threshold the seeded storm must cross; every other rule
# keeps its production shape.
drill = tuple(
    dataclasses.replace(r, threshold=0.005)
    if r.name == "watch_drop_storm" else r
    for r in alerts.DEFAULT_RULES
)
alerts.DEFAULT.configure(rules=drill, clock_scale=1.0 / 600.0)
alerts.ensure_started(interval_s=0.25, client=client)

client.create_bulk("nodes", [
    {"kind": "Node", "metadata": {"name": f"n{j}"},
     "status": {"capacity": {"cpu": "64", "memory": "256Gi", "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}]}}
    for j in range(8)
])
cfg = SchedulerConfig(
    Client(HTTPTransport(srv.address)), raw_scheduled_cache=True
).start()
assert cfg.wait_for_sync(timeout=60), "scheduler caches never synced"
sched = IncrementalBatchScheduler(cfg, max_batch=512).start()

# The storm: seeded slow-consumer drops on the watch fan-out while a
# pod wave churns the streams.
faults.inject(faults.WATCH_DROP, p=0.2, times=12)

def pod(name):
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "app",
                     "resources": {"limits": {"cpu": "50m",
                                              "memory": "32Mi"}}}]}}

res = client.create_bulk(
    "pods", [pod(f"al-{i}") for i in range(200)], namespace="default"
)
assert all(r.get("status") == "Success" for r in res)

deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    if "watch_drop_storm" in alerts.DEFAULT.firing():
        break
    time.sleep(0.25)
assert "watch_drop_storm" in alerts.DEFAULT.firing(), (
    f"storm never fired: {alerts.DEFAULT.snapshot()['rules']}"
)

# Populated contract while firing: table shows the rule firing.
out = io.StringIO()
with redirect_stdout(out):
    rc = ktctl.main(["alerts"], client=client)
text = out.getvalue()
assert rc == 0, text
assert "watch_drop_storm" in text and "firing" in text, text

# Clear the fault; the short windows drain in seconds at this scale,
# then the scaled hysteresis resolves the rule.
faults.clear()
deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    if not alerts.DEFAULT.firing():
        break
    time.sleep(0.25)
assert not alerts.DEFAULT.firing(), alerts.DEFAULT.firing()

out = io.StringIO()
with redirect_stdout(out):
    rc = ktctl.main(["alerts"], client=client)
text = out.getvalue()
assert rc == 0 and "resolved" in text, text

# The transition Events landed on the cluster (exactly once per
# transition; the alert engine posts through the shared broadcaster).
client.flush_events()
events, _ = client.list("events", namespace="default")
reasons = [e.reason for e in events if "watch_drop_storm" in (e.message or "")]
assert "AlertFiring" in reasons and "AlertResolved" in reasons, reasons

# The other two endpoints, populated, over HTTP.
with urllib.request.urlopen(
    srv.address + "/debug/timeseries?series=watch_streams_dropped_total"
    "&window=60", timeout=10,
) as r:
    ts = json.loads(r.read())
assert ts["sampled"] and ts["query"]["found"], ts
assert ts["query"]["labelSets"], ts
with urllib.request.urlopen(srv.address + "/debug/health", timeout=10) as r:
    health = json.loads(r.read())
assert health["kind"] == "HealthRollup" and health["sampled"], health
assert "alerts" in health["components"], health
out = io.StringIO()
with redirect_stdout(out):
    rc = ktctl.main(["top", "health"], client=client)
assert rc == 0 and "overall:" in out.getvalue(), out.getvalue()

timeseries.SAMPLER.stop()
sched.stop()
srv.stop()
print("alert smoke OK: watch_drop_storm fired under the storm, "
      "resolved after it cleared; Events posted; "
      "/debug/{alerts,timeseries,health} + ktctl alerts/top health "
      "contracts held")
EOF
alert_rc=$?
if [ $rc -eq 0 ]; then
    rc=$alert_rc
fi

# Soak smoke (ISSUE 15): ~200 hollow nodes (real kubelets, no-op
# runtime) driving the full API→solve→bind→kubelet loop while the
# seeded chaos schedule fires ONE apiserver kill -9 (torn WAL write →
# crash → snapshot+WAL replay) and ONE abrupt scheduler-daemon kill
# mid-gang (fresh daemon rebuilds its SolverSession from LIST+watch),
# plus ONE defrag_churn epoch (ISSUE 17: fragment the fleet, let the
# descheduler consolidate, probes bind — fragmentation_score_before >
# _after lands in the artifact's capacity_timeline). Gate: the
# invariant checker comes back green — replay consistency, bind
# immutability, gang all-or-nothing, exactly-one-DELETED, nominations
# recovered, move journal drained, SLO series advancing. The
# leader_kill_each_tier epoch (ISSUE 19) additionally kills the
# kvstore leader (WAL-shipped follower promotes, byte-identical
# committed prefix) and the scheduler leader (warm standby activates;
# kill -> first bind lands in the artifact's failover_to_first_bind_s
# series). Artifact in /tmp/soak_smoke.json for dashboards.
echo "== soak smoke (chaos + rebalance + HA plane, ~2min) =="
env JAX_PLATFORMS=cpu python -m tools.soak --nodes 200 --seed 7 \
    --epochs baseline,apiserver_restart,daemon_restart_mid_gang,defrag_churn,leader_kill_each_tier,final \
    --out /tmp/soak_smoke.json
soak_rc=$?
if [ $rc -eq 0 ]; then
    rc=$soak_rc  # invariant violations (exit 1) must fail CI
fi
exit $rc
