"""Hollow-node soak harness: the full API→solve→bind→kubelet loop under
a deterministic fault schedule.

Reference shape: kubemark hollow nodes (real kubelets over a no-op
container runtime) driving the real control plane, crossed with a
Jepsen-style seeded fault schedule. Everything here is REAL code under
test — the durable kvstore (WAL + snapshots on a data dir), the
apiserver with its watch cache, the incremental micro-tick scheduler,
and a fleet of genuine ``Kubelet`` agents with ``FakeRuntime`` — only
the containers are hollow. Faults come from the registered sites in
``kubernetes_tpu/utils/faults.py`` plus two process-level moves the
registry can't express: an apiserver "kill -9" (``KVStore.crash()`` +
replay into a fresh store/APIServer on the same data dir) and an abrupt
scheduler-daemon kill (queued commits dropped, no flush — the fresh
daemon must rebuild its SolverSession from LIST+watch and converge).

After every fault epoch an invariant checker asserts the contracts the
test suite defines on the happy path:

- **replay consistency**: kvstore LIST == the watch-derived mirror
  (no pod lost or duplicated across WAL replay / re-lists);
- **bind immutability**: a pod's nodeName never changes once set
  (no double-bind across daemon restarts — the server-side bind guard,
  observed end to end);
- **gang all-or-nothing**: no PodGroup sits half-bound;
- **exactly-one-DELETED**: no (key, uid) ever sees two DELETED events;
- **nominations recovered or expired**: preemptors holding a
  nomination eventually bind (or their nomination ages out and they
  re-solve) — including across a daemon restart that lost the
  nomination table;
- **SLO series advancing**: the lifecycle SLI milestones
  (decision/bound/running) kept counting through every fault;
- **alert oracle**: fault epochs declare the burn-rate alert rules
  they must fire (``expected_alerts`` in the schedule); the checker
  asserts each one transitioned to ``firing`` during its epoch and
  that every alert resolved by end of run. The engine runs on
  compressed clocks (``SOAK_ALERT_SCALE``) with drill-tuned
  thresholds, exercising the same rule/state-machine code production
  runs. Fault epochs with no declared alerts are reported in the
  artifact as coverage gaps (not failures).

Determinism: the fault *schedule* — epoch order, armed rule parameters,
wave sizes — is a pure function of ``--seed`` (``build_schedule``), and
each fault site fires on a per-site seeded sequence (utils/faults.py),
so a rerun with the same seed arms the same timeline. The artifact
records both the schedule and the realized per-site firing log.

Usage::

    python -m tools.soak --nodes 1000 --seed 7            # full default schedule
    python -m tools.soak --nodes 200 --seed 7 \
        --epochs baseline,apiserver_restart,daemon_restart_mid_gang   # CI smoke

Exit status 0 iff the run completed with ZERO invariant violations.
"""

from __future__ import annotations

import argparse
import json
import os
import queue as _queue
import random
import shutil
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.client import Client
from kubernetes_tpu.client.cache import Reflector, ThreadSafeStore
from kubernetes_tpu.client.rest import Transport
from kubernetes_tpu.controllers.autoscaler import Autoscaler
from kubernetes_tpu.controllers.descheduler import Descheduler
from kubernetes_tpu.kubelet.agent import Kubelet
from kubernetes_tpu.kubelet.runtime import FakeRuntime
from kubernetes_tpu.models.objects import (
    POD_GROUP_LABEL,
    REBALANCE_DEST_ANNOTATION,
    REBALANCE_JOURNAL_LABEL,
)
from kubernetes_tpu.scheduler.daemon import (
    IncrementalBatchScheduler,
    SchedulerConfig,
)
from kubernetes_tpu.scheduler.standby import WarmStandbyScheduler
from kubernetes_tpu.server.api import APIError, APIServer
from kubernetes_tpu.store.kvstore import KVStore
from kubernetes_tpu.store.replication import (
    FollowerReplica,
    LocalLink,
    ReplicationHub,
)
from kubernetes_tpu.utils import alerts as alertmod
from kubernetes_tpu.utils import capacity as capmod
from kubernetes_tpu.utils import faults, sli, tracing
from kubernetes_tpu.utils import timeseries as tsmod

#: Epoch registry order — the full default schedule. build_schedule
#: derives per-epoch parameters from the seed; the order is fixed so
#: early epochs warm the cluster the later ones stress.
EPOCHS = (
    "baseline",
    "watch_drops",
    "wal_fsync",
    "apiserver_restart",
    "daemon_restart_mid_gang",
    "preemption_storm",
    "defrag_churn",
    "defrag_daemon_crash",
    "pool_elastic",
    "leader_kill_each_tier",
    "final",
)

#: Alert-engine clock compression for the run: 1h/5m burn windows
#: become 6s/0.5s, the 60s hold-down 0.1s, the 120s hysteresis 0.2s —
#: the soak exercises the production state machine, not production
#: patience. Short windows must stay a few sampler beats wide
#: (ALERT_SAMPLE_S below) or windowed rates degrade to no-data.
SOAK_ALERT_SCALE = 1.0 / 600.0

#: Retention sampler cadence during the soak (seconds).
ALERT_SAMPLE_S = 0.25


def _soak_alert_rules() -> tuple:
    """DEFAULT_RULES with drill-tuned thresholds: the fault schedule's
    storms are small by production standards (a dozen watch drops, a
    few-percent fragmentation score), so the drill lowers the two
    oracle'd thresholds to levels the armed faults must cross while
    keeping every rule's kind, windows, and state machine intact."""
    import dataclasses

    drill = {
        # ~1 drop per fast-long window trips it (prod: 0.02/s budget).
        "watch_drop_storm": 0.005,
        # Between the fleet's ambient windowed p99 (measured ~0.010
        # clean, ~0.016 right after a defrag consolidates) and the
        # fragmenting fill's score (~0.037): fires only while the
        # shard pattern holds, resolves once the descheduler pairs
        # the fillers up and the windows drain.
        "fragmentation_burn": 0.02,
    }
    return tuple(
        dataclasses.replace(r, threshold=drill[r.name])
        if r.name in drill else r
        for r in alertmod.DEFAULT_RULES
    )


# -- wire helpers (mirror objects arrive typed from LIST, wire dicts
# -- from the watch stream; both shapes answer the same questions) -----


def _meta(obj) -> Tuple[str, str, str]:
    """(namespace-or-default, name, uid) over wire dicts or typed pods."""
    if isinstance(obj, dict):
        m = obj.get("metadata", {})
        return m.get("namespace") or "default", m.get("name", ""), m.get("uid", "")
    m = obj.metadata
    return m.namespace or "default", m.name, m.uid


def _pod_key(obj) -> str:
    ns, name, _ = _meta(obj)
    return f"{ns}/{name}"


def _node_of(obj) -> str:
    if isinstance(obj, dict):
        return obj.get("spec", {}).get("nodeName", "") or ""
    return obj.spec.node_name or ""


def _terminating(obj) -> bool:
    if isinstance(obj, dict):
        return bool(obj.get("metadata", {}).get("deletionTimestamp"))
    return bool(obj.metadata.deletion_timestamp)


def _nominated(obj) -> str:
    if isinstance(obj, dict):
        return obj.get("status", {}).get("nominatedNodeName", "") or ""
    return getattr(obj.status, "nominated_node_name", "") or ""


def _pod_wire(
    name, cpu="100m", mem="64Mi", group="", priority=None, node="",
) -> dict:
    labels = {POD_GROUP_LABEL: group} if group else {}
    spec: dict = {
        "containers": [
            {"name": "c", "image": "hollow",
             "resources": {"limits": {"cpu": cpu, "memory": mem}}}
        ]
    }
    if priority is not None:
        spec["priority"] = priority
    if node:
        # Born bound (the static-pod create shape): the defrag epochs
        # need an EXACT fragmented placement the live solver can never
        # race — a create-then-bind window would let it pack the wave.
        spec["nodeName"] = node
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default", "labels": labels},
        "spec": spec,
    }


def _pg_wire(name, min_member) -> dict:
    return {
        "kind": "PodGroup",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"minMember": min_member},
    }


def _wait_until(cond, timeout, interval=0.1) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# -- restartable in-process control plane ------------------------------


class RestartableTransport(Transport):
    """LocalTransport against a SWAPPABLE apiserver: the soak cluster
    replaces its APIServer across a simulated crash, and every client
    in the process follows the swap. While the "process" is down,
    requests fail with 503 — the same transient failure an HTTP client
    would see — so components exercise their retry/re-list paths."""

    def __init__(self, cluster: "SoakCluster"):
        self._cluster = cluster

    def _api(self) -> APIServer:
        api = self._cluster.api
        if api is None:
            raise APIError(
                503, "ServiceUnavailable", "apiserver restarting (soak)"
            )
        return api

    def request(self, verb, op, args, body=None, patch_type=None):
        api = self._api()
        with tracing.span(f"api.{op}"):
            fn = getattr(api, op)
            if patch_type is not None:
                return fn(*args, body, patch_type=patch_type)
            if body is not None:
                return fn(*args, body)
            return fn(*args)

    def watch(self, resource, namespace, since, lsel, fsel):
        return self._api().watch(
            resource, namespace, since=since,
            label_selector=lsel, field_selector=fsel,
        )


class SoakCluster:
    """Durable store + apiserver + incremental scheduler + hollow-node
    fleet, all in-process, with crash/restart controls."""

    def __init__(
        self,
        n_nodes: int,
        data_dir: str,
        heartbeat_period: float = 20.0,
        sync_period: float = 3.0,
        max_batch: int = 4096,
        fsync: bool = True,
    ):
        self.n_nodes = n_nodes
        self.data_dir = data_dir
        self.heartbeat_period = heartbeat_period
        self.sync_period = sync_period
        self.max_batch = max_batch
        self.fsync = fsync
        self.api: Optional[APIServer] = None
        self.store: Optional[KVStore] = None
        self.transport = RestartableTransport(self)
        self.kubelets: List[Kubelet] = []
        self.scheduler: Optional[IncrementalBatchScheduler] = None
        self.scheduler_config: Optional[SchedulerConfig] = None
        self.restarts = {"apiserver": 0, "scheduler": 0}

    def client(self) -> Client:
        return Client(self.transport)

    # -- lifecycle -----------------------------------------------------

    def _build_store(self) -> KVStore:
        # serialized_writes: the 1000-kubelet thread herd is exactly
        # the shape the single hot applier exists for.
        return KVStore(
            data_dir=self.data_dir,
            serialized_writes=True,
            fsync=self.fsync,
            snapshot_every=8192,
        )

    def start(self) -> "SoakCluster":
        self.store = self._build_store()
        self.api = APIServer(store=self.store)
        self._build_scheduler()
        # Hollow fleet: REAL kubelets, no containers. Registration +
        # informer sync ride a small pool so a 1000-node fleet comes
        # up in seconds, not serially.
        self.kubelets = [
            Kubelet(
                self.client(),
                node_name=f"hn-{i}",
                runtime=FakeRuntime(),
                heartbeat_period=self.heartbeat_period,
                sync_period=self.sync_period,
            )
            for i in range(self.n_nodes)
        ]
        work: "_queue.SimpleQueue" = _queue.SimpleQueue()
        for k in self.kubelets:
            work.put(k)
        errors: List[str] = []

        def starter():
            while True:
                try:
                    k = work.get_nowait()
                except _queue.Empty:
                    return
                try:
                    k.start()
                except Exception as e:  # noqa: BLE001 - collected below
                    errors.append(f"{k.node_name}: {e!r}")

        threads = [
            threading.Thread(target=starter, daemon=True)
            for _ in range(min(16, max(2, self.n_nodes // 8)))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(f"hollow fleet start failed: {errors[:3]}")
        return self

    def _build_scheduler(self) -> None:
        cfg = SchedulerConfig(self.client(), raw_scheduled_cache=True)
        cfg.start()
        if not cfg.wait_for_sync(timeout=60):
            raise RuntimeError("scheduler caches never synced")
        self.scheduler_config = cfg
        self.scheduler = IncrementalBatchScheduler(
            cfg, max_batch=self.max_batch
        ).start()

    # -- chaos controls ------------------------------------------------

    def restart_apiserver(self) -> None:
        """kill -9 the apiserver: crash the store (no final fsync, no
        graceful watcher drain beyond closing streams — queued writers
        fail with StoreClosedError), then recover a fresh store from
        the SAME data dir (snapshot + WAL replay, version clock intact)
        and swap in a new APIServer. Every informer in the process
        re-lists through the transport's 503 window."""
        self.restarts["apiserver"] += 1
        old, self.api = self.store, None
        try:
            if old is not None:
                old.crash()
            self.store = self._build_store()
            self.api = APIServer(store=self.store)
        except Exception:
            self.api = None
            raise

    def restart_scheduler(self) -> None:
        """Abrupt daemon kill: stop the solve loop, DROP queued commit
        jobs unexecuted (a dead process commits nothing), abandon the
        in-flight solve, then boot a fresh daemon whose SolverSession
        rebuilds from LIST+watch. Pods whose commits died stay Pending
        until the new daemon's informers feed them back in."""
        self.restarts["scheduler"] += 1
        sched, cfg = self.scheduler, self.scheduler_config
        self.scheduler = None
        self.scheduler_config = None
        if sched is not None:
            # stop() would flush queued commits — a crash doesn't.
            sched.kill()
        if cfg is not None:
            try:
                cfg.stop()
            except Exception:
                pass
        self._build_scheduler()

    def stop(self) -> None:
        for k in self.kubelets:
            try:
                k.stop()
            except Exception:
                pass
        sched, self.scheduler = self.scheduler, None
        if sched is not None:
            try:
                sched.stop()
            except Exception:
                pass
        cfg, self.scheduler_config = self.scheduler_config, None
        if cfg is not None:
            try:
                cfg.stop()
            except Exception:
                pass
        store, self.store = self.store, None
        self.api = None
        if store is not None:
            store.close()

    def node_pool(
        self, name: str = "elastic", cpu: str = "8", memory: str = "16Gi"
    ) -> "HollowNodePool":
        """An elastic hollow-node group for the Autoscaler (duck-typed
        pool provider: name/size()/grow()/shrink()/node_names())."""
        return HollowNodePool(self, name=name, cpu=cpu, memory=memory)


class HollowNodePool:
    """Autoscaler pool provider over the hollow fleet: ``grow`` boots
    REAL kubelets (they register their Node and heartbeat like the
    base fleet), ``shrink`` retires one — stop the kubelet, delete the
    Node object. The pool only ever touches nodes it created, so the
    base fleet is never a shrink victim."""

    def __init__(
        self,
        cluster: SoakCluster,
        name: str = "elastic",
        cpu: str = "8",
        memory: str = "16Gi",
    ):
        self.cluster = cluster
        self.name = name
        self.cpu = cpu
        self.memory = memory
        self._members: Dict[str, Kubelet] = {}
        self._serial = 0

    def size(self) -> int:
        return len(self._members)

    def node_names(self) -> List[str]:
        return sorted(self._members)

    def grow(self, k: int) -> List[str]:
        added = []
        for _ in range(k):
            nm = f"{self.name}-{self._serial}"
            self._serial += 1
            kb = Kubelet(
                self.cluster.client(),
                node_name=nm,
                runtime=FakeRuntime(),
                cpu=self.cpu,
                memory=self.memory,
                heartbeat_period=self.cluster.heartbeat_period,
                sync_period=self.cluster.sync_period,
            )
            kb.start()
            self._members[nm] = kb
            self.cluster.kubelets.append(kb)
            added.append(nm)
        return added

    def shrink(self, name: str) -> None:
        kb = self._members.pop(name, None)
        if kb is None:
            return
        try:
            kb.stop()
        except Exception:
            pass
        try:
            self.cluster.kubelets.remove(kb)
        except ValueError:
            pass
        try:
            self.cluster.client().delete("nodes", name)
        except APIError as e:
            if e.code != 404:
                raise


# -- watch-derived mirror + event invariants ---------------------------


class WatchMirror:
    """A second, independent consumer of the pods watch: a Reflector-fed
    mirror whose contents the checker compares against authoritative
    LISTs, plus per-event invariants caught AS THEY HAPPEN — duplicate
    DELETED per (key, uid) and a bound pod's nodeName changing."""

    def __init__(self, client: Client):
        self.store = ThreadSafeStore(key_func=_pod_key)
        self.violations: List[dict] = []
        self._deleted: Dict[Tuple[str, str], int] = {}
        self._node_of: Dict[str, str] = {}  # uid -> first bound node
        self._lock = threading.Lock()
        self.reflector = Reflector(
            client, "pods", self.store, on_event=self._on_event
        )

    def start(self) -> "WatchMirror":
        self.reflector.start()
        return self

    def stop(self) -> None:
        self.reflector.stop()

    def _on_event(self, etype: str, obj) -> None:
        key = _pod_key(obj)
        _ns, _name, uid = _meta(obj)
        if etype == "DELETED":
            if not uid:
                return
            with self._lock:
                n = self._deleted.get((key, uid), 0) + 1
                self._deleted[(key, uid)] = n
                self._node_of.pop(uid, None)
            if n > 1:
                self.violations.append({
                    "invariant": "exactly_one_deleted",
                    "detail": f"{key} (uid {uid}) saw DELETED x{n}",
                })
            return
        node = _node_of(obj)
        if not node or not uid:
            return
        with self._lock:
            prev = self._node_of.get(uid)
            if prev is None:
                self._node_of[uid] = node
        if prev is not None and prev != node:
            self.violations.append({
                "invariant": "bind_immutable",
                "detail": f"{key} (uid {uid}) rebound {prev} -> {node}",
            })

    def bound_node(self, key: str) -> str:
        obj = self.store.get(key)
        return _node_of(obj) if obj is not None else ""

    def has(self, key: str) -> bool:
        return self.store.get(key) is not None

    def snapshot(self) -> Dict[str, Tuple[str, str]]:
        """key -> (uid, node) for every pod the mirror holds."""
        out = {}
        for obj in self.store.list():
            ns, name, uid = _meta(obj)
            out[f"{ns}/{name}"] = (uid, _node_of(obj))
        return out


# -- invariant checker -------------------------------------------------


class InvariantChecker:
    def __init__(self, cluster: SoakCluster, mirror: WatchMirror):
        self.cluster = cluster
        self.mirror = mirror
        self.violations: List[dict] = []
        self._sli_start = self._sli_counts()
        self._sli_prev = dict(self._sli_start)
        self.capacity_timeline: List[dict] = []
        self._cap_prev = self._cap_samples()
        self._alerts_t0 = time.monotonic()

    @staticmethod
    def _sli_counts() -> Dict[str, int]:
        return {
            m: sli.STARTUP_LATENCY.count(milestone=m)
            for m in ("decision", "bound", "running")
        }

    @staticmethod
    def _cap_samples() -> int:
        return int(capmod.DEFAULT.snapshot().get("samples", 0))

    def _viol(self, epoch: str, invariant: str, detail: str) -> None:
        self.violations.append(
            {"epoch": epoch, "invariant": invariant, "detail": detail}
        )

    def _list_pods(self, client: Client):
        pods, version = client.list("pods", namespace="default")
        return pods, version

    def quiesce(self, client: Client, timeout: float = 60.0) -> bool:
        """Wait until the scheduler has no backlog or in-flight tick.
        (Mirror freshness is NOT waited on here — the pods watch only
        advances on pod events while heartbeats bump the store version
        forever; the consistency check below retries on content.)"""
        def settled():
            sched = self.cluster.scheduler
            if sched is None or self.cluster.store is None:
                return False
            return not len(sched.config.pod_queue) and sched._inflight is None

        return _wait_until(settled, timeout, interval=0.2)

    def check(
        self, epoch: str, client: Client, entry: Optional[dict] = None,
    ) -> None:
        """Run every invariant; append violations (never raises)."""
        self.quiesce(client)
        # Event-stream invariants detected live by the mirror.
        while self.mirror.violations:
            v = self.mirror.violations.pop(0)
            self._viol(epoch, v["invariant"], v["detail"])
        self._check_store_vs_mirror(epoch, client)
        self._check_gangs(epoch, client)
        self._check_nominations(epoch, client)
        self._check_move_journal(epoch, client)
        self._check_slo_epoch(epoch)
        self._check_capacity_epoch(epoch)
        self._check_alerts_epoch(epoch, entry)

    def _check_alerts_epoch(
        self, epoch: str, entry: Optional[dict],
    ) -> None:
        """The alert oracle: every rule the schedule declared for this
        epoch must have been FIRING at some point during it — a
        ``-> firing`` transition since the previous epoch's check, a
        ``firing -> resolved`` transition since then (it was firing
        inside the epoch before resolving), or a firing state still
        held over from a condition that never cleared. The high-water
        mark advances regardless of outcome so a late firing can't
        retroactively satisfy the next epoch."""
        expected = list((entry or {}).get("expected_alerts") or ())
        engine = alertmod.DEFAULT

        def fired_since(rule: str) -> bool:
            if any(
                t["rule"] == rule
                and (t["to"] == "firing" or t["from"] == "firing")
                and t["t_mono"] >= self._alerts_t0
                for t in engine.transitions()
            ):
                return True
            return rule in engine.firing()

        for rule in expected:
            # The storm ran during the epoch; firing may still be one
            # hold-down beat away when the churn settles.
            if not _wait_until(
                lambda: fired_since(rule), timeout=15.0, interval=0.25
            ):
                states = {
                    r["name"]: r["state"]
                    for r in engine.snapshot()["rules"]
                }
                self._viol(
                    epoch, "alert_fired",
                    f"expected alert {rule} never fired during the "
                    f"epoch (states: {states})",
                )
        self._alerts_t0 = time.monotonic()

    def _check_slo_epoch(self, epoch: str) -> None:
        """Every SLI milestone series must advance across EVERY epoch
        (each epoch binds a fresh wave, so new decision/bound/running
        observations are owed). The kubelet's running stamp is
        watch-driven and may trail the last bind by a sync beat —
        wait, then flag."""
        prev = self._sli_prev
        last = [prev]

        def advanced():
            now = self._sli_counts()
            last[0] = now
            return all(now[m] > prev[m] for m in now)

        if not _wait_until(advanced, timeout=30.0, interval=0.5):
            stalled = [m for m in last[0] if last[0][m] <= prev[m]]
            self._viol(
                epoch, "slo_series_advancing",
                f"milestones stalled across the epoch: {stalled} "
                f"(prev={prev}, now={last[0]})",
            )
        self._sli_prev = last[0]

    def _check_capacity_epoch(self, epoch: str) -> None:
        """The capacity monitor must take at least one new sample per
        epoch (per resolved tick + idle refresh, ISSUE 16) — a stalled
        counter means the fragmentation/headroom plane went dark under
        faults. The advance is also recorded as a per-epoch timeline
        row in the artifact."""
        prev = self._cap_prev

        def advanced():
            return self._cap_samples() > prev

        if not _wait_until(advanced, timeout=30.0, interval=0.5):
            self._viol(
                epoch, "capacity_sampling_advancing",
                f"capacity samples stalled across the epoch "
                f"(prev={prev}, now={self._cap_samples()})",
            )
        snap = capmod.DEFAULT.snapshot()
        self._cap_prev = int(snap.get("samples", 0))
        row = {"epoch": epoch, "samples": self._cap_prev}
        if snap.get("sampled"):
            row.update({
                "fragmentation_score": snap["fragmentation_score"],
                "slice_alloc_success_rate": snap[
                    "slice_alloc_success_rate"
                ],
                "stranded_node_count": snap["stranded_node_count"],
                "backlog_pressure": snap["backlog"]["pressure"],
            })
        self.capacity_timeline.append(row)

    def _check_store_vs_mirror(self, epoch: str, client: Client) -> None:
        """kvstore LIST == watch-derived mirror (retrying while the
        watch catches up): no pod lost or duplicated across replay."""
        last_diff = [""]

        def consistent():
            try:
                pods, _v = self._list_pods(client)
            except Exception as e:  # mid-restart: retry
                last_diff[0] = f"LIST failed: {e!r}"
                return False
            truth = {}
            for p in pods:
                ns, name, uid = _meta(p)
                truth[f"{ns}/{name}"] = (uid, _node_of(p))
            mirror = self.mirror.snapshot()
            if truth == mirror:
                return True
            missing = sorted(set(truth) - set(mirror))[:3]
            extra = sorted(set(mirror) - set(truth))[:3]
            drift = sorted(
                k for k in set(truth) & set(mirror) if truth[k] != mirror[k]
            )[:3]
            last_diff[0] = (
                f"missing_from_mirror={missing} phantom_in_mirror={extra} "
                f"drift={drift} (|store|={len(truth)} |mirror|={len(mirror)})"
            )
            return False

        if not _wait_until(consistent, timeout=30.0, interval=0.5):
            self._viol(epoch, "replay_consistency", last_diff[0])

    def _check_gangs(self, epoch: str, client: Client) -> None:
        """No PodGroup may SETTLE half-bound: fewer than minMember
        members bound while others sit Pending."""
        def no_half_bound():
            try:
                groups, _ = client.list("podgroups", namespace="default")
                pods, _ = self._list_pods(client)
            except Exception:
                return False
            members: Dict[str, List] = {}
            for p in pods:
                if isinstance(p, dict):
                    g = p.get("metadata", {}).get("labels", {}).get(
                        POD_GROUP_LABEL, ""
                    )
                else:
                    g = (p.metadata.labels or {}).get(POD_GROUP_LABEL, "")
                if g:
                    members.setdefault(g, []).append(p)
            for pg in groups:
                name = pg.metadata.name
                mem = members.get(name, [])
                live = [p for p in mem if not _terminating(p)]
                bound = [p for p in live if _node_of(p)]
                pending = [p for p in live if not _node_of(p)]
                if bound and pending and len(bound) < pg.spec.min_member:
                    self._last_gang = (
                        f"gang {name}: {len(bound)} bound < minMember "
                        f"{pg.spec.min_member} with {len(pending)} pending"
                    )
                    return False
            return True

        self._last_gang = ""
        if not _wait_until(no_half_bound, timeout=45.0, interval=0.5):
            self._viol(epoch, "gang_all_or_nothing", self._last_gang)

    def _check_nominations(self, epoch: str, client: Client) -> None:
        """Every pod holding a nomination either binds or sheds it
        (expiry + re-solve) — including across daemon restarts that
        lost the in-memory nomination table."""
        def resolved():
            try:
                pods, _ = self._list_pods(client)
            except Exception:
                return False
            stuck = [
                _pod_key(p) for p in pods
                if _nominated(p) and not _node_of(p) and not _terminating(p)
            ]
            self._last_nom = f"nominated-but-unbound: {stuck[:5]}"
            return not stuck

        self._last_nom = ""
        if not _wait_until(resolved, timeout=60.0, interval=0.5):
            self._viol(epoch, "nominations_recovered", self._last_nom)

    def _check_move_journal(self, epoch: str, client: Client) -> None:
        """The descheduler's move journal must drain: a PodTemplate
        entry outliving its epoch means a defrag move was neither
        completed nor recovered — exactly the stranded-pod state the
        rebalance SLO gate burns on. Trivially empty outside the
        defrag epochs."""
        last = [""]

        def drained():
            try:
                entries, _ = client.list(
                    "podtemplates", label_selector=REBALANCE_JOURNAL_LABEL
                )
            except Exception:
                return False
            orphans = [e.metadata.name for e in entries]
            last[0] = f"orphaned move journals: {orphans[:5]}"
            return not orphans

        if not _wait_until(drained, timeout=30.0, interval=0.5):
            self._viol(epoch, "defrag_journal_drained", last[0])

    def check_slo_advancing(self, epoch: str) -> None:
        now = self._sli_counts()
        stalled = [
            m for m in now
            if now[m] <= self._sli_start[m]
        ]
        if stalled:
            self._viol(
                epoch, "slo_series_advancing",
                f"milestones never advanced across the run: {stalled} "
                f"(start={self._sli_start}, end={now})",
            )


# -- churn driver ------------------------------------------------------


class ChurnDriver:
    """Creates/binds/deletes pod waves through a fault-tolerant client:
    every API call retries through restart windows, and a wave
    reconciles (re-creates pods lost to unacked torn writes) rather
    than assuming its creates stuck."""

    def __init__(self, cluster: SoakCluster, mirror: WatchMirror, rng):
        self.cluster = cluster
        self.mirror = mirror
        self.rng = rng
        self.client = cluster.client()
        self.bind_latencies: List[float] = []
        self.rebalance_log: List[dict] = []
        self.failover_bind_s: List[float] = []
        self._serial = 0

    # -- fault-tolerant verbs -----------------------------------------

    def _retrying(self, fn, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        while True:
            try:
                return fn()
            except Exception:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)

    def create_pods(self, wires: List[dict], tolerate: bool = False) -> None:
        for start in range(0, len(wires), 512):
            chunk = wires[start:start + 512]

            def put(chunk=chunk):
                res = self.client.create_bulk(
                    "pods", chunk, namespace="default"
                )
                bad = [
                    r for r in res
                    if r.get("status") != "Success" and r.get("code") != 409
                ]
                if bad and not tolerate:
                    raise RuntimeError(f"bulk create failed: {bad[:2]}")

            try:
                self._retrying(put)
            except Exception:
                if not tolerate:
                    raise

    def reconcile_missing(self, wires: List[dict]) -> int:
        """Re-create wave pods the store does not hold (a torn-write
        'create' the crash un-did was never acked — the client's job is
        to reconcile by reading current state and retrying)."""
        recreated = 0
        for w in wires:
            name = w["metadata"]["name"]

            def ensure(w=w, name=name):
                try:
                    self.client.get("pods", name, namespace="default")
                except APIError as e:
                    if e.code != 404:
                        raise
                    self.client.create("pods", w, namespace="default")
                    return True
                return False

            try:
                if self._retrying(ensure):
                    recreated += 1
            except Exception:
                pass
        return recreated

    def wait_bound(self, names: List[str], timeout: float) -> List[str]:
        """Wait for binds via the mirror; records per-pod latencies.
        Returns names that never bound (caller decides severity)."""
        t0 = time.monotonic()
        pending = {f"default/{n}" for n in names}
        seen_at: Dict[str, float] = {}
        deadline = t0 + timeout
        while pending and time.monotonic() < deadline:
            for key in list(pending):
                if self.mirror.bound_node(key):
                    seen_at[key] = time.monotonic() - t0
                    pending.discard(key)
            if pending:
                time.sleep(0.1)
        self.bind_latencies.extend(seen_at.values())
        return sorted(k.split("/", 1)[1] for k in pending)

    def delete_pods(self, names: List[str], graceful_frac: float = 0.5) -> None:
        """Half graceful (the kubelet Terminating confirm path), half
        immediate; waits until every key leaves the mirror."""
        graceful = [n for n in names if self.rng.random() < graceful_frac]
        graceful_set = set(graceful)
        for n in names:

            def rm(n=n):
                try:
                    self.client.delete(
                        "pods", n, namespace="default",
                        grace_period_seconds=1 if n in graceful_set else None,
                    )
                except APIError as e:
                    if e.code != 404:
                        raise

            try:
                self._retrying(rm)
            except Exception:
                pass
        gone = lambda: all(  # noqa: E731
            not self.mirror.has(f"default/{n}") for n in names
        )
        # Graceful deletes confirm at grace + the kubelet's next resync
        # tick; generous bound (invariant checks re-verify after).
        _wait_until(gone, timeout=90.0, interval=0.25)

    def delete_group(self, gname: str, names: List[str]) -> None:
        self.delete_pods(names, graceful_frac=0.0)

        def rm():
            try:
                self.client.delete("podgroups", gname, namespace="default")
            except APIError as e:
                if e.code != 404:
                    raise

        try:
            self._retrying(rm)
        except Exception:
            pass

    # -- waves ---------------------------------------------------------

    def next_prefix(self, tag: str) -> str:
        self._serial += 1
        # Epoch names carry underscores; pod names must be DNS-safe.
        return f"soak-{tag.replace('_', '-')}-{self._serial}"

    def plain_wave(
        self, n_pods: int, tag: str, bind_timeout: float = 90.0,
        tolerate: bool = False,
    ) -> List[str]:
        """Create → bind → delete a wave of plain pods. Returns the
        names that never bound."""
        prefix = self.next_prefix(tag)
        names = [f"{prefix}-{i}" for i in range(n_pods)]
        wires = [_pod_wire(n) for n in names]
        self.create_pods(wires, tolerate=tolerate)
        self.reconcile_missing(wires)
        unbound = self.wait_bound(names, bind_timeout)
        self.delete_pods(names)
        return unbound


# -- the schedule ------------------------------------------------------


def build_schedule(
    seed: int, epochs: Optional[List[str]] = None, n_nodes: int = 200
) -> List[dict]:
    """The deterministic fault timeline: one entry per epoch with every
    armed-rule parameter and wave size resolved from the seed. Pure —
    calling it twice with the same inputs returns identical schedules
    (the reproducibility half of the acceptance bar)."""
    rng = random.Random(f"soak-schedule:{seed}")
    wave = max(32, min(2 * n_nodes, 512))
    chosen = list(epochs) if epochs else list(EPOCHS)
    unknown = set(chosen) - set(EPOCHS)
    if unknown:
        raise ValueError(
            f"unknown epoch(s) {sorted(unknown)}; known: {', '.join(EPOCHS)}"
        )
    out = []
    for name in EPOCHS:  # fixed order regardless of selection order
        if name not in chosen:
            continue
        entry: dict = {"epoch": name, "wave_pods": wave}
        if name == "watch_drops":
            entry["rule"] = {
                "site": faults.WATCH_DROP.name,
                "p": round(rng.uniform(0.02, 0.08), 3),
                "times": rng.randrange(6, 14),
            }
            # The alert oracle: this storm MUST trip the drop-rate
            # burn rule while the epoch runs (and resolve by run end).
            entry["expected_alerts"] = ["watch_drop_storm"]
        elif name == "wal_fsync":
            entry["rule"] = {
                "site": faults.WAL_FSYNC.name,
                "every": rng.randrange(20, 60),
                "times": rng.randrange(4, 10),
            }
        elif name == "apiserver_restart":
            entry["rule"] = {
                "site": faults.WAL_TORN_WRITE.name,
                "every": rng.randrange(40, 120),
                "times": 1,
            }
        elif name == "daemon_restart_mid_gang":
            entry["rule"] = {
                "site": faults.SCHED_COMMIT_CRASH.name,
                # Armed only once the warm-up wave is fully bound, so
                # the FIRST commit job after arming — the gang tick —
                # is the one that dies.
                "every": 1,
                "times": 1,
            }
            entry["warmup_pods"] = 8
            entry["gangs"] = max(2, wave // 64)
            entry["gang_size"] = rng.randrange(3, 6)
        elif name == "preemption_storm":
            entry["rule"] = {
                "site": faults.SCHED_EVICT_ERROR.name,
                "p": round(rng.uniform(0.3, 0.6), 3),
                "times": rng.randrange(8, 24),
            }
            entry["preemptors"] = max(4, n_nodes // 50)
        elif name in ("defrag_churn", "defrag_daemon_crash"):
            # Fragmenting fill: three 1000m fillers per 4000m hollow
            # node leave a 1000m shard everywhere — movable (a filler
            # fits another node's shard) yet useless to a 2000m probe,
            # so the probes pend until the descheduler pairs fillers
            # up. The crash variant arms DESCHED_MOVE_CRASH so the
            # daemon dies mid-plan with the journal as the only
            # survivor.
            entry["fillers_per_node"] = 3
            entry["probe_pods"] = max(2, min(6, n_nodes // 64))
            # The measured score depends on the backlog-quantile
            # window (earlier epochs' small shapes dilute it), so the
            # threshold must sit safely below the fragmented-state
            # score — the trigger under test is "crossed with pending
            # backlog", not a calibrated absolute level.
            entry["frag_threshold"] = round(rng.uniform(0.01, 0.03), 3)
            entry["move_budget"] = rng.randrange(8, 17)
            # The fragmenting fill pushes the measured score past the
            # drill threshold (0.008 < the 0.01 floor above): the
            # fragmentation burn rule must fire while the shards pend.
            entry["expected_alerts"] = ["fragmentation_burn"]
            if name == "defrag_daemon_crash":
                entry["rule"] = {
                    "site": faults.DESCHED_MOVE_CRASH.name,
                    # Fires on the 2nd move of the cycle: at least one
                    # move completed, one is torn mid-protocol.
                    "every": 2,
                    "times": 1,
                }
        elif name == "leader_kill_each_tier":
            # HA failover drill: kvstore leader crash → follower
            # promotion, then scheduler leader kill → warm-standby
            # activation. Process-level moves, no armed fault rule.
            entry["trickle_pods"] = max(4, wave // 32)
        elif name == "pool_elastic":
            # Backlog no base node can hold (6000m > the fleet's 4000m
            # nodes); only grown 8000m pool nodes fit it. After the
            # backlog drains and is deleted, sustained idle shrinks
            # the pool back to zero through cordon-drain.
            entry["big_pods"] = rng.randrange(2, 5)
            entry["grow_after"] = 2
            entry["shrink_after"] = 3
        out.append(entry)
    return out


def _arm(rule: dict) -> "faults.FaultRule":
    site = faults.SITES[rule["site"]]
    kw = {k: v for k, v in rule.items() if k != "site"}
    return faults.inject(site, **kw)


# -- the run -----------------------------------------------------------


def run_soak(
    n_nodes: int = 200,
    seed: int = 0,
    epochs: Optional[List[str]] = None,
    data_dir: Optional[str] = None,
    fsync: bool = True,
    verbose: bool = True,
) -> dict:
    """Execute the schedule; returns the artifact dict (see __main__).
    Leaves the fault registry disarmed regardless of outcome."""

    def log(msg: str) -> None:
        if verbose:
            print(f"[soak +{time.monotonic() - t_start:6.1f}s] {msg}",
                  flush=True)

    t_start = time.monotonic()
    faults.clear()
    faults.reset_stats(reseed=seed)
    schedule = build_schedule(seed, epochs, n_nodes=n_nodes)
    tmp = None
    if data_dir is None:
        tmp = tempfile.mkdtemp(prefix="kt-soak-")
        data_dir = tmp
    # Kubelet cadences scale with fleet size: at 1000 nodes a 3s
    # resync period means ~333 full pod-resyncs/s of pure GIL churn —
    # the reference scales --node-status-update-frequency the same way.
    cluster = SoakCluster(
        n_nodes, data_dir, fsync=fsync,
        heartbeat_period=max(20.0, n_nodes / 25.0),
        sync_period=max(3.0, n_nodes / 100.0),
    )
    log(f"starting cluster: {n_nodes} hollow nodes, seed {seed}, "
        f"data dir {data_dir}")
    cluster.start()
    log("fleet up")
    # Health plane on compressed clocks: fresh retention, drill-tuned
    # rules, transition Events posted to the cluster under test. The
    # engine and sampler are the production singletons — the oracle
    # exercises the same code local-up/daemons run.
    tsmod.DEFAULT.reset()
    alertmod.DEFAULT.configure(
        rules=_soak_alert_rules(), clock_scale=SOAK_ALERT_SCALE,
    )
    alertmod.ensure_started(
        interval_s=ALERT_SAMPLE_S, client=cluster.client()
    )
    mirror = WatchMirror(cluster.client()).start()
    checker = InvariantChecker(cluster, mirror)
    driver = ChurnDriver(cluster, mirror, rng=random.Random(f"churn:{seed}"))
    epoch_reports: List[dict] = []
    n_before_final: Optional[int] = None
    n_first_fault = 0
    try:
        for entry in schedule:
            name = entry["epoch"]
            log(f"epoch {name}: {entry}")
            t0 = time.monotonic()
            if name == "final":
                n_before_final = len(driver.bind_latencies)
            elif name != "baseline" and not n_first_fault:
                n_first_fault = len(driver.bind_latencies)
            unbound: List[str] = []
            try:
                unbound = _run_epoch(cluster, driver, entry)
            except Exception as e:
                checker._viol(name, "epoch_crashed", repr(e))
            finally:
                faults.clear()
            if unbound:
                checker._viol(
                    name, "backlog_drained",
                    f"{len(unbound)} pods never bound: {unbound[:5]}",
                )
            checker.check(name, driver.client, entry)
            cycles = [
                c for c in driver.rebalance_log if c["epoch"] == name
            ]
            if cycles and checker.capacity_timeline:
                # The acceptance figure: the measured score moved.
                # Each cycle is its own measured before/after pair;
                # the row carries the best one.
                best = max(cycles, key=lambda c: c["improvement"])
                checker.capacity_timeline[-1].update({
                    "fragmentation_score_before": best["score_before"],
                    "fragmentation_score_after": best["score_after"],
                    "rebalance_moves": sum(
                        c["moves_executed"] for c in cycles
                    ),
                })
            epoch_reports.append({
                "epoch": name,
                "wall_s": round(time.monotonic() - t0, 2),
                "violations_so_far": len(checker.violations),
            })
            log(f"epoch {name} done ({epoch_reports[-1]['wall_s']}s, "
                f"{len(checker.violations)} violation(s) so far)")
        checker.check_slo_advancing("end")
        # Resolution half of the oracle: with every fault disarmed and
        # the final clean wave bound, nothing may still be firing —
        # the short burn windows drain in seconds at SOAK_ALERT_SCALE,
        # then the scaled hysteresis resolves the rule.
        if not _wait_until(
            lambda: not alertmod.DEFAULT.firing(),
            timeout=60.0, interval=0.5,
        ):
            checker._viol(
                "end", "alerts_resolved",
                f"still firing after the clean final epoch: "
                f"{alertmod.DEFAULT.firing()}",
            )
    finally:
        faults.clear()
        tsmod.SAMPLER.stop()
        try:
            mirror.stop()
        except Exception:
            pass
        try:
            cluster.stop()
        except Exception:
            pass
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)

    lat = sorted(driver.bind_latencies)
    # The post-fault figure: what the final (clean, after-every-fault)
    # epoch measured; when no final epoch was selected, everything
    # from the FIRST fault epoch on (never the clean baseline — it
    # would flatter the figure).
    post_start = n_before_final if n_before_final is not None else n_first_fault
    post_slice = sorted(driver.bind_latencies[post_start:])

    def _p(q, xs):
        return round(xs[min(len(xs) - 1, int(q * (len(xs) - 1)))], 4) \
            if xs else None

    alert_snap = alertmod.DEFAULT.snapshot()
    artifact = {
        "seed": seed,
        "nodes": n_nodes,
        "schedule": schedule,
        "epochs": epoch_reports,
        "restarts": cluster.restarts,
        "faults_injected": faults.stats(),
        "fault_timeline": [list(t) for t in faults.timeline()],
        "pods_bound": len(lat),
        "bind_p50_s": _p(0.50, lat),
        "bind_p99_s": _p(0.99, lat),
        "post_fault_bind_p50_s": _p(0.50, post_slice),
        "post_fault_bind_p99_s": _p(0.99, post_slice),
        "capacity_timeline": checker.capacity_timeline,
        "rebalance_cycles": driver.rebalance_log,
        "failover_to_first_bind_s": driver.failover_bind_s,
        "alerts": {
            "clock_scale": SOAK_ALERT_SCALE,
            "rules_evaluated": len(alertmod.DEFAULT.rules),
            "evaluations": alert_snap["evaluations"],
            "firing_at_end": alert_snap["firing"],
            # The firing timeline: every state transition the run
            # caused, in order (the oracle's evidence trail).
            "timeline": alertmod.DEFAULT.transitions(),
            # Fault epochs that declared no expected alerts — reported
            # coverage gaps, not failures: each is a storm the alert
            # plane does not yet oracle.
            "coverage_gaps": sorted(
                e["epoch"] for e in schedule
                if e.get("rule") and not e.get("expected_alerts")
            ),
        },
        "invariant_violations": checker.violations,
        "wall_s": round(time.monotonic() - t_start, 1),
    }
    return artifact


def _run_epoch(cluster: SoakCluster, driver: ChurnDriver, entry: dict):
    """One epoch: arm → churn (+ process-level moves) → return pods
    that never bound. The caller disarms and runs the checker."""
    name = entry["epoch"]
    wave = entry["wave_pods"]
    if name in ("baseline", "final"):
        return driver.plain_wave(wave, name)
    if name == "watch_drops":
        _arm(entry["rule"])
        return driver.plain_wave(wave, name, bind_timeout=120.0)
    if name == "wal_fsync":
        _arm(entry["rule"])
        # Writes may FAIL (flushed-not-durable acks refused): tolerate
        # and reconcile — that is the client contract under storage
        # faults.
        prefix = driver.next_prefix(name)
        names = [f"{prefix}-{i}" for i in range(wave)]
        wires = [_pod_wire(n) for n in names]
        driver.create_pods(wires, tolerate=True)
        faults.clear()
        driver.reconcile_missing(wires)
        unbound = driver.wait_bound(names, 120.0)
        driver.delete_pods(names)
        return unbound
    if name == "apiserver_restart":
        rule = _arm(entry["rule"])
        prefix = driver.next_prefix(name)
        names = [f"{prefix}-{i}" for i in range(wave)]
        wires = [_pod_wire(n) for n in names]
        driver.create_pods(wires, tolerate=True)
        # Wait (briefly) for the torn-write to fire mid-churn — the
        # store is DEAD from that instant (writes refused, exactly as
        # a real mid-append death) — then kill -9 and recover on the
        # same data dir.
        _wait_until(lambda: rule.fired > 0, timeout=10.0)
        faults.clear()
        cluster.restart_apiserver()
        driver.reconcile_missing(wires)
        unbound = driver.wait_bound(names, 240.0)
        driver.delete_pods(names)
        return unbound
    if name == "daemon_restart_mid_gang":
        gangs, gang_size = entry["gangs"], entry["gang_size"]
        prefix = driver.next_prefix(name)
        # Warm-up wave binds CLEAN first; arming after it means the
        # next commit job — the gang tick — is the one that dies.
        warmup = [f"{prefix}-w{i}" for i in range(entry["warmup_pods"])]
        driver.create_pods([_pod_wire(n) for n in warmup])
        driver.wait_bound(warmup, 90.0)
        rule = _arm(entry["rule"])
        groups = []
        for g in range(gangs):
            gname = f"{prefix}-g{g}"
            driver._retrying(
                lambda gname=gname: driver.client.create(
                    "podgroups", _pg_wire(gname, gang_size),
                    namespace="default",
                )
            )
            names = [f"{gname}-m{i}" for i in range(gang_size)]
            driver.create_pods([_pod_wire(n, group=gname) for n in names])
            groups.append((gname, names))
        # The commit crash fires mid-stream; kill the daemon while its
        # session still carries charges for never-bound pods.
        _wait_until(lambda: rule.fired > 0, timeout=15.0)
        faults.clear()
        cluster.restart_scheduler()
        all_names = [n for _g, ns in groups for n in ns]
        unbound = driver.wait_bound(all_names, 150.0)
        for gname, names in groups:
            driver.delete_group(gname, names)
        driver.delete_pods(warmup)
        return unbound
    if name == "preemption_storm":
        # Saturate every node with a low-priority filler, then launch
        # high-priority preemptors that fit NOWHERE without evictions —
        # under injected eviction failures.
        prefix = driver.next_prefix(name)
        fillers = [f"{prefix}-fill-{i}" for i in range(cluster.n_nodes)]
        driver.create_pods(
            [_pod_wire(n, cpu="3") for n in fillers]
        )
        driver.wait_bound(fillers, 150.0)
        _arm(entry["rule"])
        preemptors = [
            f"{prefix}-hi-{i}" for i in range(entry["preemptors"])
        ]
        driver.create_pods(
            [_pod_wire(n, cpu="2", priority=100) for n in preemptors]
        )
        # Preemptors must bind: nominate → evict (some injected
        # failures, retried) → victims drain grace → bind.
        unbound = driver.wait_bound(preemptors, 180.0)
        faults.clear()
        driver.delete_pods(preemptors)
        driver.delete_pods(fillers, graceful_frac=0.0)
        return unbound
    if name == "defrag_churn":
        return _run_defrag_epoch(cluster, driver, entry, crash=False)
    if name == "defrag_daemon_crash":
        return _run_defrag_epoch(cluster, driver, entry, crash=True)
    if name == "pool_elastic":
        return _run_pool_epoch(cluster, driver, entry)
    if name == "leader_kill_each_tier":
        return _run_leader_kill_epoch(cluster, driver, entry)
    raise ValueError(f"unknown epoch {name!r}")


def _run_leader_kill_epoch(
    cluster: SoakCluster, driver: ChurnDriver, entry: dict
) -> List[str]:
    """Kill the leader of EACH HA control-plane tier, mid-churn.

    Tier 1 (kvstore): a ReplicationHub forms a leader+follower pair
    around the live store (write acks gated on the follower's journal
    — quorum of 2), the leader crashes mid-wave, and the PROMOTED
    follower — serving exactly the committed prefix — backs a fresh
    APIServer. Every acked write must survive; the replay-consistency
    invariant re-verifies after the epoch.

    Tier 2 (scheduler): a WarmStandbyScheduler prewarms against the
    live cluster (informers hot, SolverSession device-resident), the
    active daemon is killed abruptly, a trickle of pods lands with NO
    scheduler running, and the standby's activation must bind them —
    the kill→first-bind wall time is the artifact's
    failover_to_first_bind_s sample."""
    name = entry["epoch"]
    prefix = driver.next_prefix(name)
    wave = entry["wave_pods"]

    # ---- tier 1: kvstore leader ------------------------------------
    follower = FollowerReplica(store=KVStore(), name="soak-standby")
    hub = ReplicationHub(cluster.store, name="soak-leader").attach()
    hub.add_follower(LocalLink(follower, "soak-standby"))
    names = [f"{prefix}-kv-{i}" for i in range(wave)]
    wires = [_pod_wire(n) for n in names]
    half = wave // 2
    driver.create_pods(wires[:half], tolerate=True)
    # Crash the leader mid-wave. A real crash never stops the hub
    # cleanly — crash first (in-flight writers die with the store),
    # then retire the shippers so nothing parks on a dead quorum.
    cluster.restarts["apiserver"] += 1
    old, cluster.api = cluster.store, None
    try:
        old.crash()
    except Exception:
        pass
    hub.stop()
    promoted = follower.promote()
    cluster.store = promoted
    cluster.api = APIServer(store=promoted)
    # The second half of the wave lands on the promoted store; the
    # first half reconciles (unacked creates may have died with the
    # old leader — acked ones MUST be in the promoted store already).
    driver.create_pods(wires[half:], tolerate=True)
    driver.reconcile_missing(wires)
    unbound = driver.wait_bound(names, 240.0)

    # ---- tier 2: scheduler leader ----------------------------------
    standby = WarmStandbyScheduler(cluster.client(), sync_timeout=120.0)
    standby.prewarm()
    # Abrupt kill: queued commits dropped, no flush, no abdication.
    cluster.restarts["scheduler"] += 1
    sched, cfg = cluster.scheduler, cluster.scheduler_config
    cluster.scheduler = None
    cluster.scheduler_config = None
    t_kill = time.monotonic()
    if sched is not None:
        sched.kill()
    if cfg is not None:
        try:
            cfg.stop()
        except Exception:
            pass
    # Trickled pods land with no scheduler alive...
    trickle = [f"{prefix}-fo-{i}" for i in range(entry["trickle_pods"])]
    driver.create_pods([_pod_wire(n) for n in trickle], tolerate=True)
    # ...then the warm standby activates and its first tick drains
    # the accumulated deltas.
    standby.activate()
    first_bound = _wait_until(
        lambda: any(
            driver.mirror.bound_node(f"default/{n}") for n in trickle
        ),
        timeout=120.0,
    )
    if first_bound:
        driver.failover_bind_s.append(
            round(time.monotonic() - t_kill, 4)
        )
    unbound += driver.wait_bound(trickle, 120.0)
    # The standby IS the scheduler now: hand its daemon/config to the
    # cluster so later epochs and stop() manage the live pair.
    cluster.scheduler = standby.daemon
    cluster.scheduler_config = standby.config
    driver.delete_pods(trickle)
    driver.delete_pods(names)
    return unbound


def _run_defrag_epoch(
    cluster: SoakCluster, driver: ChurnDriver, entry: dict, crash: bool
) -> List[str]:
    """Fragmenting churn → descheduler cycle(s) → probes bind. Every
    node gets `fillers_per_node` 1000m fillers bound DIRECTLY (the
    exact stranded placement, not the solver's), then 2000m probes
    pend against the 1000m shards until the defrag plan pairs fillers
    up. The crash variant kills the daemon mid-move (the armed
    DESCHED_MOVE_CRASH site raises between eviction and recreation)
    and a FRESH daemon must recover from the journal — the evicted
    pod re-pends and binds, stranding nothing."""
    name = entry["epoch"]
    prefix = driver.next_prefix(name)
    nodes = sorted(k.node_name for k in cluster.kubelets)
    wires: List[dict] = []
    fillers: List[str] = []
    for j, node in enumerate(nodes):
        for i in range(entry["fillers_per_node"]):
            nm = f"{prefix}-f{j}-{i}"
            fillers.append(nm)
            wires.append(_pod_wire(nm, cpu="1", node=node))
    driver.create_pods(wires)
    _wait_until(
        lambda: all(
            driver.mirror.bound_node(f"default/{n}") for n in fillers
        ),
        timeout=60.0,
    )
    probes = [f"{prefix}-p{i}" for i in range(entry["probe_pods"])]
    s0 = int(capmod.DEFAULT.snapshot().get("samples", 0))
    driver.create_pods([_pod_wire(n, cpu="2", mem="512Mi") for n in probes])
    # Let the daemon take a capacity sample with the probes pending so
    # the backlog quantiles join the probe set the planner optimizes.
    _wait_until(
        lambda: int(capmod.DEFAULT.snapshot().get("samples", 0)) > s0,
        timeout=15.0,
    )

    def fresh_daemon() -> Descheduler:
        return Descheduler(
            cluster.client(),
            frag_threshold=entry["frag_threshold"],
            move_budget=entry["move_budget"],
            disruption_cap=entry["move_budget"],
            wait_timeout_s=10.0,
        )

    desched = fresh_daemon()
    if crash:
        rule = _arm(entry["rule"])
        try:
            desched.sync_once()
        except Exception:
            pass  # the daemon "died" mid-move; the journal survives
        faults.clear()
        if not rule.fired:
            raise RuntimeError(
                "DESCHED_MOVE_CRASH armed but never fired mid-defrag"
            )
        desched = fresh_daemon()  # the restarted process

    def moves_settled() -> bool:
        # Every pin-annotated replacement has rebound: planning the
        # next cycle against a mid-flight cluster (evictees still
        # re-pending) reads as emptier than it is and churns moves
        # with no improvement.
        try:
            pods, _ = cluster.client().list("pods")
        except Exception:
            return False
        return all(
            _node_of(p)
            for p in pods
            if (p.metadata.annotations or {}).get(REBALANCE_DEST_ANNOTATION)
        )

    pending_probes = set(probes)
    for _ in range(entry["probe_pods"] + 2):
        summary = desched.sync_once()
        if summary.get("triggered"):
            driver.rebalance_log.append({
                "epoch": name,
                "score_before": summary["score_before"],
                "score_after": summary["score_after"],
                "improvement": summary["improvement"],
                "moves_executed": summary["moves_executed"],
                "recovered": summary.get("recovered", 0),
            })
            if summary.get("moves_executed"):
                _wait_until(moves_settled, timeout=30.0)
        pending_probes = {
            p for p in pending_probes
            if not driver.mirror.bound_node(f"default/{p}")
        }
        if not pending_probes:
            break
        time.sleep(0.5)
    unbound = driver.wait_bound(probes, 150.0)
    # One settling pass: completed moves flip to `rebound`, stale pins
    # are swept — with the backlog drained it plans nothing new.
    desched.sync_once()
    driver.delete_pods(probes, graceful_frac=0.0)
    driver.delete_pods(fillers, graceful_frac=0.0)
    return unbound


def _run_pool_epoch(
    cluster: SoakCluster, driver: ChurnDriver, entry: dict
) -> List[str]:
    """Elastic node-pool loop: a backlog no base node can hold starves
    the autoscaler into growing 8-CPU hollow nodes; once the backlog
    binds and is deleted, sustained idle cordon-drain-shrinks the pool
    back to empty — through the descheduler's eviction path, never a
    force-delete."""
    name = entry["epoch"]
    prefix = driver.next_prefix(name)
    pool = cluster.node_pool(name=f"{prefix}-nd")
    scaler = Autoscaler(
        cluster.client(),
        pool,
        min_size=0,
        max_size=max(4, entry["big_pods"]),
        grow_after=entry["grow_after"],
        grow_step=1,
        shrink_after=entry["shrink_after"],
        low_util=0.9,
        descheduler=Descheduler(cluster.client(), wait_timeout_s=10.0),
    )
    big = [f"{prefix}-big-{i}" for i in range(entry["big_pods"])]
    driver.create_pods([_pod_wire(n, cpu="6", mem="1Gi") for n in big])
    deadline = time.monotonic() + 180.0
    while time.monotonic() < deadline:
        scaler.sync_once()
        if all(driver.mirror.bound_node(f"default/{n}") for n in big):
            break
        time.sleep(1.0)
    unbound = driver.wait_bound(big, 30.0)
    driver.delete_pods(big, graceful_frac=0.0)
    deadline = time.monotonic() + 180.0
    while pool.size() > 0 and time.monotonic() < deadline:
        scaler.sync_once()
        time.sleep(1.0)
    leftover = pool.node_names()
    if leftover:
        for nm in list(leftover):
            pool.shrink(nm)  # later epochs must see the base fleet
        raise RuntimeError(
            f"autoscaler never drained the elastic pool: {leftover}"
        )
    return unbound


# -- CLI ---------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools.soak",
        description="hollow-node chaos soak (see module docstring)",
    )
    p.add_argument("--nodes", type=int, default=200)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--epochs", default="",
        help=f"comma-separated subset of: {','.join(EPOCHS)} "
        "(default: all)",
    )
    p.add_argument(
        "--no-fsync", dest="fsync", action="store_false", default=True,
        help="trade the fsync-before-ack contract for wall time",
    )
    p.add_argument("--data-dir", default="", help="default: a tempdir")
    p.add_argument("--out", default="", help="write the JSON artifact here")
    p.add_argument("--quiet", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    epochs = [e.strip() for e in args.epochs.split(",") if e.strip()] or None
    artifact = run_soak(
        n_nodes=args.nodes,
        seed=args.seed,
        epochs=epochs,
        data_dir=args.data_dir or None,
        fsync=args.fsync,
        verbose=not args.quiet,
    )
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
    fired = sum(s["fired"] for s in artifact["faults_injected"].values())
    print(json.dumps({
        k: artifact[k]
        for k in ("seed", "nodes", "pods_bound", "bind_p99_s",
                  "post_fault_bind_p99_s", "restarts", "wall_s")
    }, sort_keys=True))
    al = artifact["alerts"]
    fired_rules = sorted({
        t["rule"] for t in al["timeline"] if t["to"] == "firing"
    })
    print(
        f"alerts: {len(al['timeline'])} transition(s), "
        f"fired={','.join(fired_rules) or 'none'}, "
        f"firing-at-end={','.join(al['firing_at_end']) or 'none'}"
        + (
            f", coverage-gaps={','.join(al['coverage_gaps'])}"
            if al["coverage_gaps"] else ""
        )
    )
    if artifact["invariant_violations"]:
        print(f"soak FAILED: {len(artifact['invariant_violations'])} "
              "invariant violation(s):", file=sys.stderr)
        for v in artifact["invariant_violations"]:
            print(f"  [{v['epoch']}] {v['invariant']}: {v['detail']}",
                  file=sys.stderr)
        return 1
    print(f"soak OK: {fired} fault(s) fired across "
          f"{len(artifact['epochs'])} epoch(s), "
          f"{artifact['pods_bound']} pods bound, zero invariant violations")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
