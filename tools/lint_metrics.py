#!/usr/bin/env python3
"""DEPRECATED shim — the metric-name linter is now ktlint rule KT005.

Run ``python -m tools.ktlint --select KT005 [paths]`` instead; this
entry point execs that pass with the historical output format (one
``path:line: message`` per violation, a count summary, exit 1 on any
finding) so existing CI invocations and scripts keep working. The rule
constants (``ALLOWLIST``, ``GANG_METRICS``, ...) are re-exported from
the pass for the same reason.
"""

from __future__ import annotations

import pathlib
import sys
from typing import List, Tuple

# Script invocation (`python tools/lint_metrics.py`) puts tools/ on
# sys.path, not the repo root — fix that before the package import.
_REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(_REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(_REPO_ROOT))

from tools.ktlint.framework import run as _run  # noqa: E402
from tools.ktlint.rules_metrics import (  # noqa: E402,F401  (re-exports)
    ALLOWLIST,
    FACTORY_METHODS,
    GANG_METRICS,
    METRIC_CLASSES,
    NAME_RE,
    UNIT_SUFFIXES,
    MetricNamingRule,
)


def lint_file(path: pathlib.Path) -> List[Tuple[int, str]]:
    """Back-compat: (lineno, message) per violation in one file."""
    report = _run([pathlib.Path(path)], [MetricNamingRule()], baseline=None)
    out = [(f.line, f.message) for f in report.findings]
    out.extend(
        (0, err.split(": ", 1)[-1]) for err in report.errors
    )
    return out


def lint_tree(root: pathlib.Path) -> List[str]:
    report = _run([pathlib.Path(root)], [MetricNamingRule()], baseline=None)
    out = [f"{f.path}:{f.line}: {f.message}" for f in report.findings]
    out.extend(f"{err}" for err in report.errors)
    return out


def main(argv: List[str]) -> int:
    root = pathlib.Path(argv[1]) if len(argv) > 1 else (
        _REPO_ROOT / "kubernetes_tpu"
    )
    problems = lint_tree(root)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"{len(problems)} metric lint problem(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
