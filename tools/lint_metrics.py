#!/usr/bin/env python3
"""Metric-name linter (promtool-check analog, run in tier-1 CI).

Walks the package source for metric registrations and enforces:

1. names are snake_case (``^[a-z][a-z0-9_]*$``);
2. names carry a unit/kind suffix — one of ``_seconds``, ``_bytes``,
   ``_total``, ``_ratio``, ``_info`` — so a scrape reader never has to
   guess units (the Prometheus naming convention; ``_count``/``_sum``/
   ``_bucket`` are reserved for histogram/summary child series, and a
   small reference-parity allowlist is grandfathered);
3. metrics are registered through ``metrics.DEFAULT`` (the registry the
   /metrics endpoints render); a bare ``metrics.Counter(...)`` outside
   utils/metrics.py would silently never be scraped;
4. names are string literals (a dynamic name defeats static lint and
   risks unbounded metric families).

Usage: python tools/lint_metrics.py [root]  (default: kubernetes_tpu/)
Exits nonzero with one line per violation.
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys
from typing import List, Tuple

NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# NOTE: "_count" is deliberately NOT a valid suffix — promtool reserves
# _count/_sum/_bucket for histogram/summary child series.
UNIT_SUFFIXES = ("_seconds", "_bytes", "_total", "_ratio", "_info")
FACTORY_METHODS = {"counter", "gauge", "summary", "histogram"}
METRIC_CLASSES = {"Counter", "Gauge", "Summary", "Histogram"}

#: Reference-parity names grandfathered in (they match the reference
#: codebase's own metrics packages verbatim, and dashboards key on
#: them); everything new must carry a unit suffix.
ALLOWLIST = {
    "apiserver_request_count",  # pkg/apiserver/metrics.go
    "kubelet_running_pods",  # pkg/kubelet/metrics/metrics.go
}

#: Gang-scheduling metric family (scheduler/gang.py +
#: controllers/gangs.py). gang_solve_outcomes_total and
#: gang_controller_syncs_total satisfy the suffix rule on their own;
#: gang_pending_groups is a unitless snapshot gauge (a count of
#: objects, like kubelet_running_pods) and is allowlisted explicitly so
#: the linter documents — rather than silently tolerates — the family.
GANG_METRICS = {
    "gang_solve_outcomes_total",
    "gang_controller_syncs_total",
    "gang_pending_groups",
}
ALLOWLIST |= GANG_METRICS


def _attr_chain(node: ast.AST) -> List[str]:
    """['metrics', 'DEFAULT', 'counter'] for metrics.DEFAULT.counter."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def lint_file(path: pathlib.Path) -> List[Tuple[int, str]]:
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    problems: List[Tuple[int, str]] = []
    # Names bound by `from ...metrics import Counter` (possibly
    # aliased) — a bare `Counter(...)` call through such an import is
    # the same registry bypass as `metrics.Counter(...)`.
    imported_classes = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module == "metrics" or node.module.endswith(".metrics")
        ):
            for alias in node.names:
                if alias.name in METRIC_CLASSES:
                    imported_classes.add(alias.asname or alias.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        via_registry = (
            len(chain) >= 2
            and chain[-2] == "DEFAULT"
            and chain[-1] in FACTORY_METHODS
        )
        direct_class = (
            chain
            and chain[-1] in METRIC_CLASSES
            and "metrics" in chain[:-1]
        ) or (len(chain) == 1 and chain[0] in imported_classes)
        if not (via_registry or direct_class):
            continue
        if direct_class:
            problems.append(
                (
                    node.lineno,
                    f"metrics.{chain[-1]}(...) bypasses metrics.DEFAULT — "
                    "unregistered metrics never reach /metrics",
                )
            )
            continue
        if not node.args:
            problems.append((node.lineno, "metric registration without a name"))
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            problems.append(
                (node.lineno, "metric name must be a string literal")
            )
            continue
        name = arg.value
        if not NAME_RE.match(name):
            problems.append(
                (node.lineno, f"metric name {name!r} is not snake_case")
            )
        if name not in ALLOWLIST and not name.endswith(UNIT_SUFFIXES):
            problems.append(
                (
                    node.lineno,
                    f"metric name {name!r} lacks a unit suffix "
                    f"({'/'.join(UNIT_SUFFIXES)})",
                )
            )
    return problems


def lint_tree(root: pathlib.Path) -> List[str]:
    out: List[str] = []
    for path in sorted(root.rglob("*.py")):
        if path.name == "metrics.py" and path.parent.name == "utils":
            continue  # the metric classes themselves live here
        for lineno, msg in lint_file(path):
            out.append(f"{path}:{lineno}: {msg}")
    return out


def main(argv: List[str]) -> int:
    root = pathlib.Path(argv[1]) if len(argv) > 1 else (
        pathlib.Path(__file__).resolve().parent.parent / "kubernetes_tpu"
    )
    problems = lint_tree(root)
    for p in problems:
        print(p, file=sys.stderr)
    if problems:
        print(f"{len(problems)} metric lint problem(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
