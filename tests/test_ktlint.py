"""ktlint (tools/ktlint): per-rule fixture tests — one snippet that
violates, one that passes, one suppressed by pragma — plus framework
behavior (baseline round-trip, JSON output) and the tier-1 gate: all
passes over the live kubernetes_tpu/ tree report zero non-baselined
findings.
"""

import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))  # tools/ is a repo-root namespace package

from tools import ktlint  # noqa: E402
from tools.ktlint.framework import Baseline, run  # noqa: E402


def lint_src(tmp_path, source, rule_id, relname="x.py"):
    """Lint one fixture file with one rule; returns the Report."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run([path], ktlint.rules_by_id([rule_id]), baseline=None)


# -- KT001 jit purity -------------------------------------------------


class TestKT001:
    def test_detects_host_sync_and_impurity(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import functools, time
            import jax
            import numpy as np

            @functools.partial(jax.jit, static_argnames=("nope",))
            def f(x):
                t = time.monotonic()
                y = np.asarray(x)
                print(y)
                return float(x) + x.item() + t
            """,
            "KT001",
        )
        msgs = "\n".join(f.message for f in rep.findings)
        assert "static_argnames names 'nope'" in msgs
        assert "np.asarray" in msgs
        assert "time.monotonic" in msgs
        assert "print()" in msgs
        assert "float(x)" in msgs
        assert ".item()" in msgs

    def test_clean_jit_function_passes(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import functools
            import jax
            import jax.numpy as jnp

            @functools.partial(
                jax.jit, static_argnames=("n",), donate_argnames=("state",)
            )
            def f(state, x, n):
                return {k: state[k] + jnp.sum(x) for k in state}, n
            """,
            "KT001",
        )
        assert rep.findings == []

    def test_static_cast_is_allowed(self, tmp_path):
        # float()/int() on a STATIC argument is trace-time constant
        # folding, not a host sync.
        rep = lint_src(
            tmp_path,
            """\
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("n",))
            def f(x, n):
                return x * int(n)
            """,
            "KT001",
        )
        assert rep.findings == []

    def test_pragma_suppresses(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x)  # ktlint: disable=KT001
            """,
            "KT001",
        )
        assert rep.findings == []
        assert len(rep.suppressed) == 1


# -- KT002 lock discipline --------------------------------------------


class TestKT002:
    VIOLATION = """\
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def locked_write(self):
            with self._lock:
                self._n += 1

        def bare_write(self):
            self._n = 5
    """

    def test_detects_mixed_write(self, tmp_path):
        rep = lint_src(tmp_path, self.VIOLATION, "KT002")
        assert len(rep.findings) == 1
        f = rep.findings[0]
        assert "C._n" in f.message and "bare_write" in f.message

    def test_consistent_locking_passes(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def locked_write(self):
                    with self._lock:
                        self._n += 1

                def also_locked(self):
                    with self._lock:
                        self._n = 5
            """,
            "KT002",
        )
        assert rep.findings == []

    def test_locked_suffix_is_the_contract(self, tmp_path):
        # Methods named *_locked execute under the lock by convention
        # (kvstore._expire_locked et al); writes there are lock-held.
        rep = lint_src(
            tmp_path,
            """\
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def write(self):
                    with self._lock:
                        self._bump_locked()

                def _bump_locked(self):
                    self._n += 1
            """,
            "KT002",
        )
        assert rep.findings == []

    def test_pragma_suppresses(self, tmp_path):
        src = self.VIOLATION.replace(
            "self._n = 5", "self._n = 5  # ktlint: disable=KT002"
        )
        rep = lint_src(tmp_path, src, "KT002")
        assert rep.findings == []
        assert len(rep.suppressed) == 1


# -- KT003 exception hygiene ------------------------------------------


class TestKT003:
    def test_detects_swallow_in_scope(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            def loop(sync, metric):
                try:
                    sync()
                except Exception:
                    metric.inc(result="error")
            """,
            "KT003",
            relname="controllers/c.py",
        )
        assert len(rep.findings) == 1
        assert "swallows" in rep.findings[0].message

    def test_logging_handler_passes(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import logging
            _LOG = logging.getLogger(__name__)

            def loop(sync):
                try:
                    sync()
                except Exception:
                    _LOG.exception("sync failed")
            """,
            "KT003",
            relname="controllers/c.py",
        )
        assert rep.findings == []

    def test_using_the_exception_passes(self, tmp_path):
        # `except Exception as e` + referencing e forwards the error
        # (HTTP handlers send it to the caller) — not a swallow.
        rep = lint_src(
            tmp_path,
            """\
            def handler(send):
                try:
                    work()
                except Exception as e:
                    send(500, str(e))
            """,
            "KT003",
            relname="server/h.py",
        )
        assert rep.findings == []

    def test_out_of_scope_dirs_are_ignored(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            def loop(sync):
                try:
                    sync()
                except Exception:
                    pass
            """,
            "KT003",
            relname="ops/o.py",
        )
        assert rep.findings == []

    def test_pragma_suppresses(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            def loop(sync):
                try:
                    sync()
                except Exception:  # ktlint: disable=KT003
                    pass  # events are observability, never control flow
            """,
            "KT003",
            relname="kubelet/k.py",
        )
        assert rep.findings == []
        assert len(rep.suppressed) == 1


# -- KT004 bounded I/O ------------------------------------------------


class TestKT004:
    def test_detects_unbounded_ops(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import http.client
            import socket
            import urllib.request

            def f(url, path, host):
                r = urllib.request.urlopen(url)
                c = http.client.HTTPConnection(host, 80)
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.connect(path)
                return r, c, s
            """,
            "KT004",
        )
        msgs = "\n".join(f.message for f in rep.findings)
        assert len(rep.findings) == 3
        assert "urlopen" in msgs
        assert "HTTPConnection" in msgs
        assert "s.connect" in msgs

    def test_bounded_ops_pass(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import http.client
            import socket
            import urllib.request

            def f(url, path, host):
                r = urllib.request.urlopen(url, timeout=5)
                c = http.client.HTTPConnection(host, 80, timeout=5)
                d = socket.create_connection((host, 80), timeout=5)
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(5)
                s.connect(path)
                return r, c, d, s
            """,
            "KT004",
        )
        assert rep.findings == []

    def test_pragma_suppresses(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import urllib.request

            def f(url):
                return urllib.request.urlopen(url)  # ktlint: disable=KT004
            """,
            "KT004",
        )
        assert rep.findings == []
        assert len(rep.suppressed) == 1


# -- KT005 metric naming (full matrix in test_metrics_exposition) -----


class TestKT005:
    def test_detects_bad_names(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            from kubernetes_tpu.utils import metrics

            A = metrics.DEFAULT.counter("CamelCase", "x")
            B = metrics.DEFAULT.gauge("no_unit_suffix", "x")
            C = metrics.Summary("rogue_seconds", "x")
            """,
            "KT005",
        )
        msgs = "\n".join(f.message for f in rep.findings)
        assert "not snake_case" in msgs
        assert "lacks a unit suffix" in msgs
        assert "bypasses metrics.DEFAULT" in msgs

    def test_good_names_pass(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            from kubernetes_tpu.utils import metrics

            A = metrics.DEFAULT.counter("solver_ticks_total", "x")
            B = metrics.DEFAULT.histogram("bind_latency_seconds", "x")
            """,
            "KT005",
        )
        assert rep.findings == []

    def test_pragma_suppresses(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            from kubernetes_tpu.utils import metrics

            A = metrics.DEFAULT.gauge("weird", "x")  # ktlint: disable=KT005
            """,
            "KT005",
        )
        assert rep.findings == []
        assert len(rep.suppressed) == 1


# -- framework ---------------------------------------------------------


class TestFramework:
    def test_baseline_roundtrip(self, tmp_path):
        bad = tmp_path / "controllers" / "c.py"
        bad.parent.mkdir()
        bad.write_text(
            "def f(g):\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass\n"
        )
        rules = ktlint.rules_by_id(["KT003"])
        rep = run([bad], rules, baseline=None)
        assert len(rep.findings) == 1
        # Grandfather it; the same run is now clean but accounted.
        baseline = Baseline.from_findings(rep.findings)
        bpath = tmp_path / "baseline.json"
        baseline.dump(bpath)
        rep2 = run([bad], rules, Baseline.load(bpath))
        assert rep2.findings == [] and len(rep2.baselined) == 1
        # Line drift must not resurrect it: same content, new line no.
        bad.write_text("# a new leading comment\n" + bad.read_text())
        rep3 = run([bad], rules, Baseline.load(bpath))
        assert rep3.findings == [] and len(rep3.baselined) == 1
        # A SECOND distinct offense is not covered by the one entry.
        bad.write_text(
            bad.read_text()
            + "def h(g):\n"
            "    try:\n"
            "        g()\n"
            "    except Exception:\n"
            "        pass\n"
        )
        rep4 = run([bad], rules, Baseline.load(bpath))
        assert len(rep4.findings) + len(rep4.baselined) == 2
        assert len(rep4.findings) == 1

    def test_unknown_rule_id_rejected(self):
        with pytest.raises(ValueError):
            ktlint.rules_by_id(["KT999"])

    def test_syntax_error_is_reported_not_crash(self, tmp_path):
        bad = tmp_path / "b.py"
        bad.write_text("def f(:\n")
        rep = run([bad], ktlint.rules_by_id(None), baseline=None)
        assert rep.errors and rep.exit_code == 1

    def test_json_output_shape(self, tmp_path):
        bad = tmp_path / "b.py"
        bad.write_text("import urllib.request\nx = urllib.request.urlopen('u')\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.ktlint", "--format=json",
             "--baseline=", str(tmp_path)],
            capture_output=True, text=True, timeout=120, cwd=str(ROOT),
        )
        assert proc.returncode == 1
        data = json.loads(proc.stdout)
        assert data["counts"]["KT004"] == 1
        assert data["findings"][0]["rule"] == "KT004"
        assert set(data["rules"]) == {f"KT00{i}" for i in range(1, 10)}


# -- KT008 fault-site constants ---------------------------------------


class TestKT008:
    def test_detects_string_literal_sites(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            from kubernetes_tpu.utils import faults

            def f():
                faults.fire("kvstore.wal.fsync")
                faults.inject("watch.stream.drop", every=1)
            """,
            "KT008",
        )
        assert len(rep.findings) == 2
        assert all("site constant" in f.message for f in rep.findings)

    def test_detects_bare_imported_fire(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            from kubernetes_tpu.utils.faults import fire

            def f():
                fire("http.request.reset")
            """,
            "KT008",
        )
        assert len(rep.findings) == 1

    def test_detects_dotted_paths_through_parent_imports(self, tmp_path):
        """`utils.faults.fire(...)` and the fully dotted spelling are
        the same forked-inventory hazard as `faults.fire(...)`."""
        rep = lint_src(
            tmp_path,
            """\
            import kubernetes_tpu.utils.faults
            from kubernetes_tpu import utils

            def f():
                utils.faults.fire("kvstore.wal.fsnc")
                kubernetes_tpu.utils.faults.inject("watch.stream.drop", p=1)
            """,
            "KT008",
        )
        assert len(rep.findings) == 2

    def test_detects_out_of_module_site_minting(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            from kubernetes_tpu.utils.faults import FaultSite

            AD_HOC = FaultSite("my.sneaky.site", "trip")
            """,
            "KT008",
        )
        assert len(rep.findings) == 1
        assert "mints a fault site" in rep.findings[0].message

    def test_constant_references_and_dynamic_sites_pass(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            from kubernetes_tpu.utils import faults

            def f():
                faults.fire(faults.WAL_FSYNC)
                faults.inject(faults.WATCH_DROP, p=0.1)
                for site in faults.SITES.values():
                    faults.fire(site)
            """,
            "KT008",
        )
        assert rep.findings == []

    def test_files_without_faults_import_are_skipped(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            def fire(x):  # unrelated local helper
                return x

            fire("not a fault site")
            """,
            "KT008",
        )
        assert rep.findings == []

    def test_pragma_suppresses(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            from kubernetes_tpu.utils import faults

            faults.fire("x.y")  # ktlint: disable=KT008
            """,
            "KT008",
        )
        assert rep.findings == [] and len(rep.suppressed) == 1


# -- the tier-1 gate ---------------------------------------------------


def test_ktlint_clean_on_live_tree():
    """All five passes over kubernetes_tpu/: zero non-baselined
    findings, and the run proves it audited real code (>0 pragma
    suppressions, not a no-op walker). The grandfathered baseline was
    burned down to empty (PR 4: the kubelet agent/managers teardown
    handlers now log); it must STAY empty — new debt wants a pragma
    with a reason, not a baseline entry."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.ktlint", "--format=json",
         str(ROOT / "kubernetes_tpu")],
        capture_output=True, text=True, timeout=120, cwd=str(ROOT),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert len(data["rules"]) >= 5
    assert data["findings"] == []
    assert data["errors"] == []
    assert data["suppressed"] > 0  # pragmas with reasons exist in-tree
    assert data["baselined"] == 0  # backlog burned down; keep it that way
