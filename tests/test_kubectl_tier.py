"""kubectl operational tier: rolling update, reapers, scaler retry,
kubeconfig loading.

Reference: pkg/kubectl/rolling_updater.go, stop.go, scale.go,
pkg/client/clientcmd/ (VERDICT r1 #7)."""

import json
import time

import pytest

from kubernetes_tpu.cli.updater import Reaper, RollingUpdater, Scaler
from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.client.kubeconfig import (
    KubeconfigError,
    load_kubeconfig,
)
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import ReplicationController
from kubernetes_tpu.scheduler.daemon import Scheduler, SchedulerConfig
from kubernetes_tpu.server import APIServer


def wait_until(cond, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def rc_wire(name, replicas, labels, image="app:v1"):
    return {
        "kind": "ReplicationController",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "replicas": replicas,
            "selector": dict(labels),
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {
                    "containers": [
                        {
                            "name": "main",
                            "image": image,
                            "resources": {
                                "limits": {"cpu": "100m", "memory": "64Mi"}
                            },
                        }
                    ]
                },
            },
        },
    }


@pytest.fixture
def cluster():
    api = APIServer()
    client = Client(LocalTransport(api))
    kubelets = [
        Kubelet(
            Client(LocalTransport(api)),
            node_name=name,
            runtime=FakeRuntime(),
            heartbeat_period=0.5,
            sync_period=0.2,
        ).start()
        for name in ("node-1", "node-2")
    ]
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync()
    scheduler = Scheduler(cfg).start()
    manager = ControllerManager(Client(LocalTransport(api))).start()
    yield api, client
    manager.stop()
    scheduler.stop()
    for k in kubelets:
        k.stop()


def running_pods(client, selector):
    pods, _ = client.list("pods", namespace="default", label_selector=selector)
    return [p for p in pods if p.status.phase == "Running"]


class TestRollingUpdate:
    def test_replaces_rc_pod_by_pod(self, cluster):
        api, client = cluster
        client.create(
            "replicationcontrollers",
            rc_wire("web", 3, {"app": "web"}, image="app:v1"),
        )
        assert wait_until(lambda: len(running_pods(client, "app=web")) == 3)

        new_rc = serde.from_wire(
            ReplicationController,
            rc_wire(
                "web-v2", 3, {"app": "web", "deployment": "v2"}, image="app:v2"
            ),
        )
        updater = RollingUpdater(client, poll_interval=0.05, timeout=30.0)
        survivor = updater.update("web", new_rc, namespace="default")
        # Renamed back to the old identity (rolling_updater.go Rename).
        assert survivor == "web"
        rc = client.get("replicationcontrollers", "web", namespace="default")
        assert rc.spec.template.spec.containers[0].image == "app:v2"
        assert rc.spec.replicas == 3
        with pytest.raises(Exception):
            client.get("replicationcontrollers", "web-v2", namespace="default")
        assert wait_until(
            lambda: len(running_pods(client, "deployment=v2")) == 3
        )
        # Old pods are gone (RC deleted scales its pods away via the
        # reaper-less path: old RC was scaled to 0 first).
        assert wait_until(
            lambda: not [
                p
                for p in running_pods(client, "app=web")
                if "deployment" not in p.metadata.labels
            ]
        )

    def test_rejects_identical_selector(self, cluster):
        api, client = cluster
        client.create(
            "replicationcontrollers", rc_wire("same", 1, {"app": "same"})
        )
        new_rc = serde.from_wire(
            ReplicationController, rc_wire("same-v2", 1, {"app": "same"})
        )
        with pytest.raises(ValueError):
            RollingUpdater(client).update("same", new_rc, namespace="default")


class TestReaper:
    def test_rc_stop_drains_then_deletes(self, cluster):
        api, client = cluster
        client.create(
            "replicationcontrollers", rc_wire("doomed", 2, {"app": "doomed"})
        )
        assert wait_until(lambda: len(running_pods(client, "app=doomed")) == 2)
        Reaper(client, timeout=20.0).stop(
            "replicationcontrollers", "doomed", namespace="default"
        )
        with pytest.raises(Exception):
            client.get("replicationcontrollers", "doomed", namespace="default")
        # Pods drained BEFORE deletion -> nothing recreates them.
        assert wait_until(
            lambda: not running_pods(client, "app=doomed"), timeout=5
        )

    def test_scaler_waits_for_observed_replicas(self, cluster):
        api, client = cluster
        client.create(
            "replicationcontrollers", rc_wire("sized", 1, {"app": "sized"})
        )
        assert wait_until(lambda: len(running_pods(client, "app=sized")) == 1)
        Scaler(client).scale("sized", 3, namespace="default", wait=True, timeout=20.0)
        assert len(running_pods(client, "app=sized")) >= 1
        assert (
            client.get(
                "replicationcontrollers", "sized", namespace="default"
            ).spec.replicas
            == 3
        )


class TestKubeconfig:
    def _write(self, tmp_path, data):
        path = tmp_path / "config"
        path.write_text(json.dumps(data))
        return str(path)

    def test_resolves_current_context(self, tmp_path):
        path = self._write(
            tmp_path,
            {
                "current-context": "prod",
                "contexts": [
                    {
                        "name": "prod",
                        "context": {
                            "cluster": "c1",
                            "user": "u1",
                            "namespace": "team-a",
                        },
                    }
                ],
                "clusters": [
                    {"name": "c1", "cluster": {"server": "http://10.0.0.1:8080"}}
                ],
                "users": [
                    {"name": "u1", "user": {"token": "sekret"}}
                ],
            },
        )
        cfg = load_kubeconfig(path)
        assert cfg.server == "http://10.0.0.1:8080"
        assert cfg.namespace == "team-a"
        assert cfg.auth_headers() == {"Authorization": "Bearer sekret"}

    def test_context_override_and_basic_auth(self, tmp_path):
        path = self._write(
            tmp_path,
            {
                "current-context": "a",
                "contexts": [
                    {"name": "a", "context": {"cluster": "ca", "user": "ua"}},
                    {"name": "b", "context": {"cluster": "cb", "user": "ub"}},
                ],
                "clusters": [
                    {"name": "ca", "cluster": {"server": "http://a:1"}},
                    {"name": "cb", "cluster": {"server": "http://b:2"}},
                ],
                "users": [
                    {"name": "ua", "user": {}},
                    {
                        "name": "ub",
                        "user": {"username": "bob", "password": "pw"},
                    },
                ],
            },
        )
        cfg = load_kubeconfig(path, context="b")
        assert cfg.server == "http://b:2"
        assert cfg.auth_headers()["Authorization"].startswith("Basic ")

    def test_yaml_format(self, tmp_path):
        path = tmp_path / "config"
        path.write_text(
            "current-context: dev\n"
            "contexts:\n"
            "- name: dev\n"
            "  context: {cluster: c, user: u}\n"
            "clusters:\n"
            "- name: c\n"
            "  cluster: {server: 'http://yaml:9'}\n"
            "users:\n"
            "- name: u\n"
            "  user: {}\n"
        )
        cfg = load_kubeconfig(str(path))
        assert cfg.server == "http://yaml:9"

    def test_missing_explicit_path_raises(self):
        with pytest.raises(KubeconfigError):
            load_kubeconfig("/nonexistent/kubeconfig")

    def test_missing_default_gives_local_defaults(self, monkeypatch):
        monkeypatch.delenv("KTCONFIG", raising=False)
        monkeypatch.delenv("KUBECONFIG", raising=False)
        cfg = load_kubeconfig()
        assert cfg.server == "http://127.0.0.1:8080"
        assert cfg.auth_headers() == {}

    def test_ktctl_uses_kubeconfig_server(self, cluster, tmp_path, capsys):
        from kubernetes_tpu.cli.ktctl import main as ktctl_main
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api, client = cluster
        srv = APIHTTPServer(api).start()
        try:
            path = self._write(
                tmp_path,
                {
                    "current-context": "test",
                    "contexts": [
                        {"name": "test", "context": {"cluster": "c", "user": "u"}}
                    ],
                    "clusters": [
                        {"name": "c", "cluster": {"server": srv.address}}
                    ],
                    "users": [{"name": "u", "user": {}}],
                },
            )
            rc = ktctl_main(["get", "nodes", "--kubeconfig", path])
            assert rc == 0
            assert "node-1" in capsys.readouterr().out
        finally:
            srv.stop()
