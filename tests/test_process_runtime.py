"""Process runtime + kubelet HTTP API + pod log/exec subresources.

The pods here are REAL OS processes anchored by the native pause binary
(reference: dockertools/manager.go SyncPod + third_party/pause;
pkg/kubelet/server.go:130-144 for the HTTP surface)."""

import json
import os
import time
import urllib.request

import pytest

from kubernetes_tpu.kubelet.agent import Kubelet
from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime
from kubernetes_tpu.models.objects import (
    Container,
    EnvVar,
    ObjectMeta,
    Pod,
    PodSpec,
)


def mk_pod(name, command, uid="", containers=None, ns="default"):
    specs = containers or [Container(name="main", image="app", command=command)]
    pod = Pod(
        metadata=ObjectMeta(name=name, namespace=ns, uid=uid or name),
        spec=PodSpec(containers=specs),
    )
    return pod


def wait_for(cond, timeout=5.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


@pytest.fixture
def runtime(tmp_path):
    rt = ProcessRuntime(str(tmp_path / "kubelet"), node_name="n1")
    yield rt
    for uid in list(rt.list_pods()):
        rt.kill_pod(uid)


class TestSecurityContext:
    @pytest.mark.skipif(os.geteuid() != 0, reason="needs root to setuid")
    def test_run_as_user_drops_privileges(self, runtime):
        from kubernetes_tpu.models.objects import SecurityContext

        pod = mk_pod(
            "sec",
            None,
            containers=[
                Container(
                    name="main",
                    image="app",
                    command=["/bin/sh", "-c", "id -u; id -g"],
                    security_context=SecurityContext(run_as_user=65534),
                )
            ],
        )
        runtime.sync_pod(pod)
        assert wait_for(lambda: "65534" in runtime.read_logs("sec", "main"))
        lines = runtime.read_logs("sec", "main").split()
        assert lines[:2] == ["65534", "65534"]

    def test_no_security_context_inherits_kubelet_user(self, runtime):
        pod = mk_pod("plain", ["/bin/sh", "-c", "id -u"])
        runtime.sync_pod(pod)
        assert wait_for(lambda: runtime.read_logs("plain", "main").strip())
        assert runtime.read_logs("plain", "main").strip() == str(os.geteuid())


class TestProcessRuntime:
    def test_pod_runs_real_processes_with_anchor(self, runtime):
        pod = mk_pod("web", ["/bin/sh", "-c", "sleep 30"])
        containers = runtime.sync_pod(pod)
        assert len(containers) == 1
        assert containers[0].state == "running"
        pid = int(containers[0].container_id.split("//")[1])
        os.kill(pid, 0)  # real process exists
        anchor = runtime.anchor_pid("web")
        assert anchor is not None
        os.kill(anchor, 0)  # pause anchor is alive too
        runtime.kill_pod("web")
        assert wait_for(lambda: not _alive(pid))
        assert not _alive(anchor)

    def test_exited_container_reports_exit_code(self, runtime):
        pod = mk_pod("oneshot", ["/bin/sh", "-c", "exit 3"])
        runtime.sync_pod(pod)
        assert wait_for(
            lambda: runtime.sync_pod(pod)[0].state == "exited"
        )
        assert runtime.sync_pod(pod)[0].exit_code == 3

    def test_spec_change_recreates_with_restart_count(self, runtime):
        pod = mk_pod("app", ["/bin/sh", "-c", "sleep 30"])
        first = runtime.sync_pod(pod)[0]
        pod.spec.containers[0].command = ["/bin/sh", "-c", "sleep 60"]
        second = runtime.sync_pod(pod)[0]
        assert second.restart_count == first.restart_count + 1
        assert second.container_id != first.container_id

    def test_logs_capture_stdout(self, runtime):
        pod = mk_pod("logger", ["/bin/sh", "-c", "echo hello-from-pod; sleep 30"])
        runtime.sync_pod(pod)
        assert wait_for(
            lambda: "hello-from-pod" in runtime.read_logs("logger", "main")
        )

    def test_logs_tail(self, runtime):
        pod = mk_pod(
            "tailer", ["/bin/sh", "-c", "for i in 1 2 3 4 5; do echo line$i; done; sleep 30"]
        )
        runtime.sync_pod(pod)
        assert wait_for(lambda: "line5" in runtime.read_logs("tailer", "main"))
        tail = runtime.read_logs("tailer", "main", tail_lines=2)
        assert tail.splitlines() == ["line4", "line5"]

    def test_exec_in_container(self, runtime):
        pod = mk_pod("target", ["/bin/sh", "-c", "sleep 30"])
        runtime.sync_pod(pod)
        rc, out = runtime.exec_in_container(
            "target", "main", ["/bin/sh", "-c", "echo $KUBERNETES_CONTAINER_NAME"],
            pod=pod,
        )
        assert rc == 0
        assert "main" in out

    def test_exec_probe_success_and_failure(self, runtime):
        pod = mk_pod("probed", ["/bin/sh", "-c", "sleep 30"])
        runtime.sync_pod(pod)
        assert runtime.exec_probe(pod, "main", ["/bin/true"])
        assert not runtime.exec_probe(pod, "main", ["/bin/false"])

    def test_env_vars_reach_container(self, runtime):
        pod = mk_pod(
            "envy",
            None,
            containers=[
                Container(
                    name="main",
                    image="app",
                    command=["/bin/sh", "-c", "echo VAL=$MYVAR; sleep 30"],
                    env=[EnvVar(name="MYVAR", value="tpu42")],
                )
            ],
        )
        runtime.sync_pod(pod)
        assert wait_for(lambda: "VAL=tpu42" in runtime.read_logs("envy", "main"))

    def test_adoption_across_restart(self, runtime, tmp_path):
        """A new runtime instance (kubelet restart) adopts recorded live
        processes instead of orphaning them (kubelet.go:1154-1160)."""
        pod = mk_pod("survivor", ["/bin/sh", "-c", "sleep 30"])
        first = runtime.sync_pod(pod)[0]
        pid = int(first.container_id.split("//")[1])

        reborn = ProcessRuntime(str(tmp_path / "kubelet"), node_name="n1")
        pods = reborn.list_pods()
        assert "survivor" in pods
        adopted = {c.name: c for c in pods["survivor"]}["main"]
        assert int(adopted.container_id.split("//")[1]) == pid
        assert adopted.state == "running"
        # Same spec -> no restart (hash match); adopted process kept.
        resynced = reborn.sync_pod(pod)[0]
        assert int(resynced.container_id.split("//")[1]) == pid
        reborn.kill_pod("survivor")
        assert wait_for(lambda: not _alive(pid))

    def test_image_only_container_uses_anchor_command(self, runtime):
        """Reference manifests (image: nginx, no command) must run."""
        pod = mk_pod("imageonly", None, containers=[Container(name="main", image="nginx")])
        containers = runtime.sync_pod(pod)
        assert containers[0].state == "running"
        runtime.kill_pod("imageonly")


def _alive(pid: int) -> bool:
    """True if pid is a live (non-zombie) process. In-test adoption
    leaves zombies: the original runtime's Popen in THIS process still
    owns the child, so os.kill(pid, 0) succeeds after death. In real
    adoption the old kubelet process is gone and init reaps."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split(") ")[1].split()[0] != "Z"
    except OSError:
        return False


# ---------------------------------------------------------------------------
# Kubelet HTTP API + apiserver subresources, end to end
# ---------------------------------------------------------------------------


@pytest.fixture
def cluster(tmp_path):
    from kubernetes_tpu.client.rest import Client, LocalTransport
    from kubernetes_tpu.server.api import APIServer

    api = APIServer()
    client = Client(LocalTransport(api))
    runtime = ProcessRuntime(str(tmp_path / "kubelet"), node_name="node-1")
    kubelet = Kubelet(
        Client(LocalTransport(api)),
        node_name="node-1",
        runtime=runtime,
        heartbeat_period=0.5,
        sync_period=0.3,
        serve_http=True,
    ).start()
    yield api, client, kubelet, runtime
    kubelet.stop()
    for uid in list(runtime.list_pods()):
        runtime.kill_pod(uid)


def _pod_running(client, runtime, name, ns="default"):
    """True once the pod's (apiserver-assigned) uid shows up in the
    runtime with a running container."""
    try:
        pod = client.get("pods", name, namespace=ns)
    except Exception:
        return False
    uid = pod.metadata.uid or name
    containers = runtime.list_pods().get(uid, [])
    return any(c.state == "running" for c in containers)


def _schedule(client, name, command, ns="default"):
    """Create a pod pinned to node-1 (no scheduler in this fixture)."""
    client.create(
        "pods",
        {
            "kind": "Pod",
            "metadata": {"name": name, "namespace": ns},
            "spec": {
                "nodeName": "node-1",
                "containers": [
                    {"name": "main", "image": "app", "command": command}
                ],
            },
        },
        namespace=ns,
    )


class TestKubeletHTTPAPI:
    def test_healthz_and_pods(self, cluster):
        api, client, kubelet, runtime = cluster
        _schedule(client, "p1", ["/bin/sh", "-c", "sleep 30"])
        base = kubelet.http.address
        assert (
            urllib.request.urlopen(f"{base}/healthz").read() == b"ok"
        )
        assert wait_for(
            lambda: any(
                p["metadata"]["name"] == "p1"
                for p in json.loads(
                    urllib.request.urlopen(f"{base}/pods").read()
                )["items"]
            )
        )

    def test_stats_and_spec(self, cluster):
        api, client, kubelet, runtime = cluster
        _schedule(client, "p2", ["/bin/sh", "-c", "sleep 30"])
        base = kubelet.http.address
        assert wait_for(lambda: _pod_running(client, runtime, "p2"))
        spec = json.loads(urllib.request.urlopen(f"{base}/spec").read())
        assert spec["nodeName"] == "node-1"
        stats = json.loads(urllib.request.urlopen(f"{base}/stats").read())
        uid = client.get("pods", "p2").metadata.uid
        assert uid in stats["pods"]
        entry = {c["name"]: c for c in stats["pods"][uid]}["main"]
        assert entry["state"] == "running"
        assert entry["rssBytes"] > 0

    def test_node_publishes_daemon_endpoint(self, cluster):
        api, client, kubelet, runtime = cluster
        node = client.get("nodes", "node-1")
        assert node.status.daemon_endpoints.kubelet_endpoint.port == kubelet.http.port

    def test_pod_log_subresource_through_apiserver(self, cluster):
        api, client, kubelet, runtime = cluster
        _schedule(client, "weblog", ["/bin/sh", "-c", "echo api-visible-log; sleep 30"])
        assert wait_for(lambda: _pod_running(client, runtime, "weblog"))
        assert wait_for(
            lambda: "api-visible-log" in client.pod_logs("weblog"), timeout=5
        )

    def test_pod_exec_subresource_through_apiserver(self, cluster):
        api, client, kubelet, runtime = cluster
        _schedule(client, "execme", ["/bin/sh", "-c", "sleep 30"])
        assert wait_for(lambda: _pod_running(client, runtime, "execme"))
        result = client.pod_exec("execme", ["/bin/echo", "exec-through-stack"])
        assert result["exitCode"] == 0
        assert "exec-through-stack" in result["output"]

    def test_unscheduled_pod_log_409(self, cluster):
        from kubernetes_tpu.server.api import APIError

        api, client, kubelet, runtime = cluster
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {"name": "floating", "namespace": "default"},
                "spec": {"containers": [{"name": "c", "image": "x"}]},
            },
            namespace="default",
        )
        with pytest.raises(APIError) as e:
            client.pod_logs("floating")
        assert e.value.code == 409


class TestServiceEnv:
    def test_from_services_reference_format(self):
        from kubernetes_tpu.kubelet.envvars import from_services
        from kubernetes_tpu.models.objects import (
            ObjectMeta,
            Service,
            ServicePort,
            ServiceSpec,
        )

        svc = Service(
            metadata=ObjectMeta(name="redis-master", namespace="default"),
            spec=ServiceSpec(
                cluster_ip="10.0.0.11",
                ports=[ServicePort(name="redis", port=6379, protocol="TCP")],
            ),
        )
        env = from_services([svc])
        # Exact reference names (envvars_test.go shapes).
        assert env["REDIS_MASTER_SERVICE_HOST"] == "10.0.0.11"
        assert env["REDIS_MASTER_SERVICE_PORT"] == "6379"
        assert env["REDIS_MASTER_SERVICE_PORT_REDIS"] == "6379"
        assert env["REDIS_MASTER_PORT"] == "tcp://10.0.0.11:6379"
        assert env["REDIS_MASTER_PORT_6379_TCP"] == "tcp://10.0.0.11:6379"
        assert env["REDIS_MASTER_PORT_6379_TCP_PROTO"] == "tcp"
        assert env["REDIS_MASTER_PORT_6379_TCP_PORT"] == "6379"
        assert env["REDIS_MASTER_PORT_6379_TCP_ADDR"] == "10.0.0.11"

    def test_headless_services_excluded(self):
        from kubernetes_tpu.kubelet.envvars import from_services
        from kubernetes_tpu.models.objects import (
            ObjectMeta,
            Service,
            ServiceSpec,
        )

        headless = Service(
            metadata=ObjectMeta(name="hl", namespace="default"),
            spec=ServiceSpec(cluster_ip="None"),
        )
        assert from_services([headless]) == {}

    def test_containers_see_service_env(self, cluster):
        """End to end: a real process container observes the service
        discovery variables (kubelet.go makeEnvironmentVariables)."""
        api, client, kubelet, runtime = cluster
        client.create(
            "services",
            {
                "kind": "Service",
                "metadata": {"name": "backend", "namespace": "default"},
                "spec": {
                    "selector": {"app": "backend"},
                    "ports": [{"name": "http", "port": 8080}],
                    "clusterIP": "10.0.0.55",
                },
            },
            namespace="default",
        )
        assert wait_for(
            lambda: runtime.service_env.get("default", {}).get(
                "BACKEND_SERVICE_HOST"
            )
            == "10.0.0.55"
        )
        # Namespaced: a pod in another namespace must NOT see it.
        assert "BACKEND_SERVICE_HOST" not in runtime.service_env.get(
            "other", {}
        )
        _schedule(
            client,
            "envpod",
            ["/bin/sh", "-c", "echo HOST=$BACKEND_SERVICE_HOST "
             "PORT=$BACKEND_SERVICE_PORT VOLS=$KUBERNETES_VOLUMES_DIR; sleep 30"],
        )
        assert wait_for(lambda: _pod_running(client, runtime, "envpod"))
        pod = client.get("pods", "envpod", namespace="default")
        uid = pod.metadata.uid
        assert wait_for(
            lambda: "HOST=10.0.0.55" in runtime.read_logs(uid, "main")
        )
        log = runtime.read_logs(uid, "main")
        assert "PORT=8080" in log
        assert f"pods/{uid}/volumes" in log


class TestKtctlLogsExec:
    def test_ktctl_logs_and_exec_over_http(self, cluster, capsys):
        from kubernetes_tpu.cli.ktctl import main as ktctl_main
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api, client, kubelet, runtime = cluster
        srv = APIHTTPServer(api).start()
        try:
            _schedule(client, "cli1", ["/bin/sh", "-c", "echo cli-log-line; sleep 30"])
            assert wait_for(lambda: _pod_running(client, runtime, "cli1"))
            assert wait_for(
                lambda: "cli-log-line" in client.pod_logs("cli1"), timeout=5
            )
            rc = ktctl_main(["logs", "cli1", "--server", srv.address])
            assert rc == 0
            assert "cli-log-line" in capsys.readouterr().out
            rc = ktctl_main(
                ["exec", "cli1", "--server", srv.address, "--", "/bin/echo", "via-cli"]
            )
            assert rc == 0
            assert "via-cli" in capsys.readouterr().out
        finally:
            srv.stop()


class TestClusterLogAggregator:
    """Logging addon (cluster/addons/fluentd-elasticsearch analog):
    cluster-wide collection through the apiserver log relay, retention
    past pod deletion, substring search."""

    def test_collects_and_searches_across_pods(self, cluster):
        from kubernetes_tpu.addons import ClusterLogAggregator

        api, client, kubelet, runtime = cluster
        _schedule(client, "talker-a", ["/bin/sh", "-c",
                                       "echo uniq-line-alpha; sleep 30"])
        _schedule(client, "talker-b", ["/bin/sh", "-c",
                                       "echo uniq-line-beta; sleep 30"])
        assert wait_for(lambda: _pod_running(client, runtime, "talker-a"))
        assert wait_for(lambda: _pod_running(client, runtime, "talker-b"))
        agg = ClusterLogAggregator(client, poll_interval=0.2).start()
        try:
            assert wait_for(lambda: agg.search("uniq-line-alpha"), timeout=10)
            assert wait_for(lambda: agg.search("uniq-line-beta"), timeout=10)
            hit = agg.search("uniq-line-alpha")[0]
            assert (hit.pod, hit.container) == ("talker-a", "main")
            # Scoped search.
            assert not agg.search("uniq-line-alpha", pod="talker-b")
            # Retention: lines survive the pod's deletion (the whole
            # point of shipping logs off the node).
            client.delete("pods", "talker-a", namespace="default")
            assert agg.search("uniq-line-alpha")
        finally:
            agg.stop()

    def test_incremental_no_duplicates(self, cluster):
        from kubernetes_tpu.addons import ClusterLogAggregator

        api, client, kubelet, runtime = cluster
        _schedule(client, "stepper", ["/bin/sh", "-c",
                                      "echo s1; sleep 0.5; echo s2; sleep 30"])
        assert wait_for(lambda: _pod_running(client, runtime, "stepper"))
        agg = ClusterLogAggregator(client, poll_interval=0.1).start()
        try:
            assert wait_for(
                lambda: agg.search("s2", pod="stepper"), timeout=10
            )
            import time as _t

            _t.sleep(0.5)  # several more polls: offsets must hold
            assert len(agg.search("s1", pod="stepper")) == 1
            assert len(agg.search("s2", pod="stepper")) == 1
        finally:
            agg.stop()


class TestClusterDNSEnv:
    def test_cluster_dns_env_injected(self, runtime):
        """kubelet --cluster-dns surface: containers see the DNS VIP
        (the reference writes resolv.conf; env is the process-runtime
        analog)."""
        runtime.cluster_dns = "10.0.0.10"
        pod = mk_pod("dnsenv", ["/bin/sh", "-c",
                                "echo DNS=$KUBERNETES_CLUSTER_DNS"
                                " DOM=$KUBERNETES_CLUSTER_DOMAIN; sleep 30"])
        runtime.sync_pod(pod)
        assert wait_for(
            lambda: "DNS=10.0.0.10 DOM=cluster.local"
            in runtime.read_logs("dnsenv", "main")
        )


class TestLogsFollow:
    def test_follow_streams_new_lines(self, cluster):
        """ktctl logs -f polls the log subresource and emits only new
        lines (log.go follow)."""
        import io
        import sys as _sys

        from kubernetes_tpu.cli.ktctl import main as ktctl_main

        api, client, kubelet, runtime = cluster
        _schedule(
            client, "flw",
            ["/bin/sh", "-c", "echo first; sleep 1; echo second; sleep 30"],
        )
        assert wait_for(lambda: _pod_running(client, runtime, "flw"))
        assert wait_for(lambda: "first" in client.pod_logs("flw"))
        out = io.StringIO()
        old = _sys.stdout
        _sys.stdout = out
        try:
            rc = ktctl_main(
                ["logs", "flw", "-f", "--follow-rounds", "6"], client=client
            )
        finally:
            _sys.stdout = old
        assert rc == 0
        text = out.getvalue()
        assert "first" in text and "second" in text
        assert text.count("first") == 1  # no re-emission across polls

    def test_follow_ends_when_pod_deleted(self, cluster):
        import io
        import sys as _sys
        import threading

        from kubernetes_tpu.cli.ktctl import main as ktctl_main

        api, client, kubelet, runtime = cluster
        _schedule(client, "gone", ["/bin/sh", "-c", "echo x; sleep 30"])
        assert wait_for(lambda: _pod_running(client, runtime, "gone"))

        def deleter():
            time.sleep(1.0)
            client.delete("pods", "gone", namespace="default")

        t = threading.Thread(target=deleter)
        t.start()
        out = io.StringIO()
        old = _sys.stdout
        _sys.stdout = out
        try:
            rc = ktctl_main(["logs", "gone", "-f"], client=client)
        finally:
            _sys.stdout = old
        t.join()
        assert rc == 0

    def test_follow_unknown_pod_errors(self, cluster):
        from kubernetes_tpu.cli.ktctl import main as ktctl_main

        api, client, kubelet, runtime = cluster
        rc = ktctl_main(["logs", "nosuchpod", "-f"], client=client)
        assert rc == 1  # surfaced like plain logs, not silent success
