"""Cache substrate tests: FIFO, Reflector, Informer (reference:
pkg/client/cache/fifo_test.go, reflector_test.go)."""

import threading
import time

import pytest

from kubernetes_tpu.client import Client, FIFO, Informer, LocalTransport, Reflector
from kubernetes_tpu.client.cache import ThreadSafeStore
from kubernetes_tpu.server import APIServer


def pod_wire(name, ns="default", node=""):
    return {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "containers": [{"name": "c", "image": "nginx"}],
            **({"nodeName": node} if node else {}),
        },
    }


class TestFIFO:
    def test_dedup_returns_latest(self):
        f = FIFO()
        f.add({"metadata": {"name": "a", "namespace": "ns"}, "v": 1})
        f.add({"metadata": {"name": "a", "namespace": "ns"}, "v": 2})
        f.add({"metadata": {"name": "b", "namespace": "ns"}, "v": 1})
        assert f.pop()["v"] == 2
        assert f.pop()["metadata"]["name"] == "b"
        assert f.pop(timeout=0.05) is None

    def test_blocking_pop(self):
        f = FIFO()
        out = []

        def consumer():
            out.append(f.pop(timeout=2))

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        f.add({"metadata": {"name": "x", "namespace": "ns"}})
        t.join()
        assert out[0]["metadata"]["name"] == "x"

    def test_delete_skipped(self):
        f = FIFO()
        f.add({"metadata": {"name": "a", "namespace": "ns"}})
        f.delete({"metadata": {"name": "a", "namespace": "ns"}})
        assert f.pop(timeout=0.05) is None


class TestReflector:
    def test_list_then_watch(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        client.create("pods", pod_wire("pre"))
        store = ThreadSafeStore()
        r = Reflector(client, "pods", store, namespace="default").start()
        try:
            assert r.wait_for_sync()
            assert store.get("default/pre") is not None
            client.create("pods", pod_wire("live"))
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and len(store) < 2:
                time.sleep(0.01)
            assert {k for k in store.keys()} == {"default/pre", "default/live"}
            client.delete("pods", "pre", namespace="default")
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and len(store) > 1:
                time.sleep(0.01)
            assert store.keys() == ["default/live"]
        finally:
            r.stop()

    def test_field_selector_feed_into_fifo(self):
        """The scheduler's unassigned-pod FIFO (factory.go:180-215)."""
        api = APIServer()
        client = Client(LocalTransport(api))
        fifo = FIFO()
        r = Reflector(
            client, "pods", fifo, namespace="", field_selector="spec.nodeName="
        ).start()
        try:
            assert r.wait_for_sync()
            client.create("pods", pod_wire("unassigned"))
            client.create("pods", pod_wire("assigned", node="n1"))
            got = fifo.pop(timeout=2)
            assert got["metadata"]["name"] == "unassigned"
            assert fifo.pop(timeout=0.2) is None
        finally:
            r.stop()


class TestInformer:
    def test_handlers_fire(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        adds, updates, deletes = [], [], []
        inf = Informer(
            client,
            "pods",
            namespace="default",
            on_add=lambda o: adds.append(o["metadata"]["name"]),
            on_update=lambda o: updates.append(o["metadata"]["name"]),
            on_delete=lambda o: deletes.append(o["metadata"]["name"]),
        ).start()
        try:
            assert inf.wait_for_sync()
            client.create("pods", pod_wire("x"))
            client.bind("x", "n1", namespace="default")
            client.delete("pods", "x", namespace="default")
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and not deletes:
                time.sleep(0.01)
            assert adds == ["x"]
            assert updates == ["x"]
            assert deletes == ["x"]
        finally:
            inf.stop()


def obj(name, ns="default", **extra):
    return {"kind": "Pod", "metadata": {"name": name, "namespace": ns}, **extra}


class TestIndexer:
    def test_by_index(self):
        from kubernetes_tpu.client.cache import Indexer

        by_node = lambda o: [o.get("spec", {}).get("nodeName", "")]
        idx = Indexer({"node": by_node})
        idx.add(obj("a", spec={"nodeName": "n1"}))
        idx.add(obj("b", spec={"nodeName": "n1"}))
        idx.add(obj("c", spec={"nodeName": "n2"}))
        assert {o["metadata"]["name"] for o in idx.by_index("node", "n1")} == {"a", "b"}
        assert idx.index_values("node") == ["n1", "n2"]
        # Re-add moves the object between index buckets.
        idx.add(obj("a", spec={"nodeName": "n2"}))
        assert {o["metadata"]["name"] for o in idx.by_index("node", "n2")} == {"a", "c"}
        idx.delete(obj("c"))
        assert {o["metadata"]["name"] for o in idx.by_index("node", "n2")} == {"a"}
        idx.replace([obj("z", spec={"nodeName": "n9"})])
        assert idx.by_index("node", "n1") == []
        assert len(idx.by_index("node", "n9")) == 1


class TestExpirationCache:
    def test_entries_age_out(self):
        import time as _t

        from kubernetes_tpu.client.cache import ExpirationCache

        c = ExpirationCache(ttl=0.15)
        c.add(obj("a"))
        assert c.get("default/a") is not None
        _t.sleep(0.2)
        assert c.get("default/a") is None
        assert c.list() == []

    def test_readd_refreshes(self):
        import time as _t

        from kubernetes_tpu.client.cache import ExpirationCache

        c = ExpirationCache(ttl=0.2)
        c.add(obj("a"))
        _t.sleep(0.12)
        c.add(obj("a"))  # refresh
        _t.sleep(0.12)
        assert c.get("default/a") is not None


class TestUndeltaStore:
    def test_pushes_full_state(self):
        from kubernetes_tpu.client.cache import UndeltaStore

        snaps = []
        s = UndeltaStore(lambda state: snaps.append(
            sorted(o["metadata"]["name"] for o in state)))
        s.add(obj("a"))
        s.add(obj("b"))
        s.delete(obj("a"))
        s.replace([obj("x")])
        assert snaps == [["a"], ["a", "b"], ["b"], ["x"]]


class TestDeltaFIFO:
    def test_deletions_survive_dedup(self):
        """The whole point vs plain FIFO: an add+delete race yields
        BOTH deltas on pop, so the consumer sees the deletion."""
        from kubernetes_tpu.client.cache import DeltaFIFO

        q = DeltaFIFO()
        q.add(obj("a"))
        q.delete(obj("a"))
        deltas = q.pop(timeout=1)
        assert [t for t, _o in deltas] == ["ADDED", "DELETED"]

    def test_add_then_update_types(self):
        from kubernetes_tpu.client.cache import DeltaFIFO

        q = DeltaFIFO()
        q.add(obj("a"))
        assert [t for t, _ in q.pop(timeout=1)] == ["ADDED"]
        q.add(obj("a", spec={"x": 1}))
        assert [t for t, _ in q.pop(timeout=1)] == ["MODIFIED"]

    def test_replace_syncs_and_synthesizes_deletes(self):
        from kubernetes_tpu.client.cache import DeltaFIFO

        q = DeltaFIFO()
        q.add(obj("gone"))
        q.pop(timeout=1)
        q.replace([obj("kept")])
        # Two keys queued: 'gone' (Deleted) and 'kept' (Sync).
        batches = [q.pop(timeout=1), q.pop(timeout=1)]
        types = {d[0][1]["metadata"]["name"]: [t for t, _ in d] for d in batches}
        assert types["gone"] == ["DELETED"]
        assert types["kept"] == ["SYNC"]

    def test_close_unblocks_pop(self):
        import threading as _th

        from kubernetes_tpu.client.cache import DeltaFIFO

        q = DeltaFIFO()
        out = []
        t = _th.Thread(target=lambda: out.append(q.pop()), daemon=True)
        t.start()
        q.close()
        t.join(timeout=5)
        assert out == [None]


def test_reflector_relist_synthesizes_deleted_events():
    """Round-5 review regression: objects deleted while the watch was
    down must surface as DELETED on relist — delta subscribers (the
    incremental scheduler's session) would otherwise carry phantom
    occupancy forever (DeltaFIFO.replace's synthesized-Deleted rule,
    lifted to the Reflector's on_event stream)."""
    import time as _time

    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.client.cache import Informer
    from kubernetes_tpu.server.api import APIServer

    api = APIServer()
    client = Client(LocalTransport(api))
    spec = {"spec": {"containers": [{"name": "c", "image": "x"}]}}
    client.create("pods", obj("stays", **spec), namespace="default")
    client.create("pods", obj("vanishes", **spec), namespace="default")

    events = []

    def _n(o):  # list replay yields typed objects; watch yields dicts
        return o["metadata"]["name"] if isinstance(o, dict) else o.metadata.name

    inf = Informer(
        client,
        "pods",
        on_add=lambda o: events.append(("ADDED", _n(o))),
        on_delete=lambda o: events.append(("DELETED", _n(o))),
    )
    inf.start()
    assert inf.wait_for_sync(10)
    deadline = _time.monotonic() + 5
    while _time.monotonic() < deadline and len(events) < 2:
        _time.sleep(0.02)
    # Simulate a watch outage that misses a delete: stop, delete, and
    # start a FRESH informer sharing the same store (the relist path).
    inf.stop()
    client.delete("pods", "vanishes", namespace="default")
    inf2 = Informer(
        client,
        "pods",
        on_add=lambda o: events.append(("ADDED", _n(o))),
        on_delete=lambda o: events.append(("DELETED", _n(o))),
    )
    inf2.store = inf.store  # carry the stale cache into the relist
    inf2.reflector.store = inf.store
    inf2.start()
    assert inf2.wait_for_sync(10)
    deadline = _time.monotonic() + 5
    while (
        _time.monotonic() < deadline
        and ("DELETED", "vanishes") not in events
    ):
        _time.sleep(0.02)
    inf2.stop()
    assert ("DELETED", "vanishes") in events
    assert [n for n in inf.store.keys()] == ["default/stays"]
