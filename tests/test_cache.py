"""Cache substrate tests: FIFO, Reflector, Informer (reference:
pkg/client/cache/fifo_test.go, reflector_test.go)."""

import threading
import time

import pytest

from kubernetes_tpu.client import Client, FIFO, Informer, LocalTransport, Reflector
from kubernetes_tpu.client.cache import ThreadSafeStore
from kubernetes_tpu.server import APIServer


def pod_wire(name, ns="default", node=""):
    return {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "containers": [{"name": "c", "image": "nginx"}],
            **({"nodeName": node} if node else {}),
        },
    }


class TestFIFO:
    def test_dedup_returns_latest(self):
        f = FIFO()
        f.add({"metadata": {"name": "a", "namespace": "ns"}, "v": 1})
        f.add({"metadata": {"name": "a", "namespace": "ns"}, "v": 2})
        f.add({"metadata": {"name": "b", "namespace": "ns"}, "v": 1})
        assert f.pop()["v"] == 2
        assert f.pop()["metadata"]["name"] == "b"
        assert f.pop(timeout=0.05) is None

    def test_blocking_pop(self):
        f = FIFO()
        out = []

        def consumer():
            out.append(f.pop(timeout=2))

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        f.add({"metadata": {"name": "x", "namespace": "ns"}})
        t.join()
        assert out[0]["metadata"]["name"] == "x"

    def test_delete_skipped(self):
        f = FIFO()
        f.add({"metadata": {"name": "a", "namespace": "ns"}})
        f.delete({"metadata": {"name": "a", "namespace": "ns"}})
        assert f.pop(timeout=0.05) is None


class TestReflector:
    def test_list_then_watch(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        client.create("pods", pod_wire("pre"))
        store = ThreadSafeStore()
        r = Reflector(client, "pods", store, namespace="default").start()
        try:
            assert r.wait_for_sync()
            assert store.get("default/pre") is not None
            client.create("pods", pod_wire("live"))
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and len(store) < 2:
                time.sleep(0.01)
            assert {k for k in store.keys()} == {"default/pre", "default/live"}
            client.delete("pods", "pre", namespace="default")
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and len(store) > 1:
                time.sleep(0.01)
            assert store.keys() == ["default/live"]
        finally:
            r.stop()

    def test_field_selector_feed_into_fifo(self):
        """The scheduler's unassigned-pod FIFO (factory.go:180-215)."""
        api = APIServer()
        client = Client(LocalTransport(api))
        fifo = FIFO()
        r = Reflector(
            client, "pods", fifo, namespace="", field_selector="spec.nodeName="
        ).start()
        try:
            assert r.wait_for_sync()
            client.create("pods", pod_wire("unassigned"))
            client.create("pods", pod_wire("assigned", node="n1"))
            got = fifo.pop(timeout=2)
            assert got["metadata"]["name"] == "unassigned"
            assert fifo.pop(timeout=0.2) is None
        finally:
            r.stop()


class TestInformer:
    def test_handlers_fire(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        adds, updates, deletes = [], [], []
        inf = Informer(
            client,
            "pods",
            namespace="default",
            on_add=lambda o: adds.append(o["metadata"]["name"]),
            on_update=lambda o: updates.append(o["metadata"]["name"]),
            on_delete=lambda o: deletes.append(o["metadata"]["name"]),
        ).start()
        try:
            assert inf.wait_for_sync()
            client.create("pods", pod_wire("x"))
            client.bind("x", "n1", namespace="default")
            client.delete("pods", "x", namespace="default")
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and not deletes:
                time.sleep(0.01)
            assert adds == ["x"]
            assert updates == ["x"]
            assert deletes == ["x"]
        finally:
            inf.stop()
