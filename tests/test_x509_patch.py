"""x509 client-cert authn + JSON-patch/strategic-merge patch types
(VERDICT r2 item 9 — the last §2.4/§2.11 wire deltas):
pkg/apiserver/authn.go:35 (basic/token/x509/SA-JWT) and
pkg/apiserver/resthandler.go:446 (three patch types).
"""

import os
import shutil
import ssl
import subprocess

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.server import APIError, APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer


def pod_wire(name, labels=None):
    return {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": "default", "labels": labels or {}},
        "spec": {
            "containers": [
                {"name": "a", "image": "nginx:1",
                 "env": [{"name": "MODE", "value": "one"}]},
                {"name": "b", "image": "redis:6"},
            ]
        },
    }


class TestPatchTypes:
    @pytest.fixture
    def client(self):
        return Client(LocalTransport(APIServer()))

    def test_json_patch(self, client):
        client.create("pods", pod_wire("jp", labels={"x": "1"}))
        out = client.patch(
            "pods", "jp",
            [
                {"op": "test", "path": "/metadata/labels/x", "value": "1"},
                {"op": "replace", "path": "/spec/containers/0/image",
                 "value": "nginx:2"},
                {"op": "add", "path": "/metadata/labels/y", "value": "2"},
                {"op": "remove", "path": "/metadata/labels/x"},
            ],
            namespace="default", patch_type="json",
        )
        assert out.spec.containers[0].image == "nginx:2"
        assert out.metadata.labels == {"y": "2"}

    def test_json_patch_test_op_conflict(self, client):
        client.create("pods", pod_wire("jt", labels={"x": "1"}))
        with pytest.raises(APIError) as e:
            client.patch(
                "pods", "jt",
                [{"op": "test", "path": "/metadata/labels/x", "value": "9"}],
                namespace="default", patch_type="json",
            )
        assert e.value.code == 409

    def test_json_patch_cannot_rename(self, client):
        """Identity fields are restored whatever the op says."""
        client.create("pods", pod_wire("id1"))
        out = client.patch(
            "pods", "id1",
            [{"op": "replace", "path": "/metadata/name", "value": "evil"}],
            namespace="default", patch_type="json",
        )
        assert out.metadata.name == "id1"

    def test_json_patch_replacing_metadata_with_scalar_is_400(self, client):
        client.create("pods", pod_wire("mm"))
        with pytest.raises(APIError) as e:
            client.patch(
                "pods", "mm",
                [{"op": "replace", "path": "/metadata", "value": "x"}],
                namespace="default", patch_type="json",
            )
        assert e.value.code == 400

    def test_unknown_patch_type_rejected_client_side(self, client):
        with pytest.raises(ValueError):
            client.patch("pods", "x", {}, namespace="default", patch_type="Strategic")

    def test_strategic_merge_containers_by_name(self, client):
        """The signature strategic behavior: patching one container in
        a list updates THAT container instead of replacing the list
        (a merge patch would wipe container 'b')."""
        client.create("pods", pod_wire("sm"))
        out = client.patch(
            "pods", "sm",
            {"spec": {"containers": [{"name": "a", "image": "nginx:9"}]}},
            namespace="default", patch_type="strategic",
        )
        by_name = {c.name: c for c in out.spec.containers}
        assert by_name["a"].image == "nginx:9"
        assert by_name["b"].image == "redis:6"  # untouched

    def test_strategic_merge_delete_directive(self, client):
        client.create("pods", pod_wire("sd"))
        out = client.patch(
            "pods", "sd",
            {"spec": {"containers": [{"name": "b", "$patch": "delete"}]}},
            namespace="default", patch_type="strategic",
        )
        assert [c.name for c in out.spec.containers] == ["a"]

    def test_json_patch_add_missing_parent_is_400(self, client):
        """RFC 6902: 'add' fails when the parent container does not
        exist (evanphx/json-patch, vendored by the reference) — it
        must NOT auto-create intermediate objects."""
        client.create("pods", pod_wire("ap"))
        with pytest.raises(APIError) as e:
            client.patch(
                "pods", "ap",
                [{"op": "add", "path": "/metadata/annotations/k", "value": "v"}],
                namespace="default", patch_type="json",
            )
        assert e.value.code == 400
        # move/copy targets resolve the same way.
        with pytest.raises(APIError) as e:
            client.patch(
                "pods", "ap",
                [{"op": "copy", "from": "/metadata/name",
                  "path": "/metadata/annotations/k"}],
                namespace="default", patch_type="json",
            )
        assert e.value.code == 400

    def test_strategic_merge_ports_by_containerport(self, client):
        """Container ports carry the reference's patchMergeKey
        containerPort even when every element is named: reusing a
        name with a NEW containerPort appends (distinct key value)
        instead of updating the named entry in place."""
        wire = pod_wire("pp")
        wire["spec"]["containers"][0]["ports"] = [
            {"name": "web", "containerPort": 80},
        ]
        client.create("pods", wire)
        out = client.patch(
            "pods", "pp",
            {"spec": {"containers": [{
                "name": "a",
                "ports": [{"name": "web", "containerPort": 8080}],
            }]}},
            namespace="default", patch_type="strategic",
        )
        ports = [
            (p.name, p.container_port)
            for c in out.spec.containers if c.name == "a"
            for p in c.ports
        ]
        assert ("web", 80) in ports and ("web", 8080) in ports

    def test_strategic_merge_node_addresses_by_type(self, client):
        """NodeStatus addresses have NO ip field (NodeAddress is
        type/address) — the shared 'addresses' field name must fall
        through to the type key instead of degrading to whole-list
        replace (round-4 review regression)."""
        client.create("nodes", {"kind": "Node", "metadata": {"name": "na1"}})
        node = client.get("nodes", "na1")
        node.status.addresses = []
        client.patch(
            "nodes", "na1",
            {"status": {"addresses": [
                {"type": "InternalIP", "address": "10.0.0.1"},
                {"type": "Hostname", "address": "na1"},
            ]}},
            patch_type="strategic",
        )
        out = client.patch(
            "nodes", "na1",
            {"status": {"addresses": [
                {"type": "ExternalIP", "address": "34.1.2.3"},
                {"type": "InternalIP", "address": "10.0.0.9"},
            ]}},
            patch_type="strategic",
        )
        got = {(a.type, a.address) for a in out.status.addresses}
        assert got == {
            ("InternalIP", "10.0.0.9"),  # merged by type, updated
            ("Hostname", "na1"),         # untouched entry survives
            ("ExternalIP", "34.1.2.3"),  # appended
        }

    def test_strategic_delete_port_needs_merge_key(self, client):
        """A $patch:delete directive must carry the list's merge key
        (containerPort for container ports); one keyed only by name is
        a 400 — never appended raw into the stored object. With the
        key, the delete lands."""
        wire = pod_wire("pd")
        wire["spec"]["containers"][0]["ports"] = [
            {"name": "web", "containerPort": 80},
            {"name": "adm", "containerPort": 81},
        ]
        client.create("pods", wire)
        with pytest.raises(APIError) as e:
            client.patch(
                "pods", "pd",
                {"spec": {"containers": [{
                    "name": "a",
                    "ports": [{"$patch": "delete", "name": "web"}],
                }]}},
                namespace="default", patch_type="strategic",
            )
        assert e.value.code == 400
        out = client.patch(
            "pods", "pd",
            {"spec": {"containers": [{
                "name": "a",
                "ports": [{"$patch": "delete", "containerPort": 80}],
            }]}},
            namespace="default", patch_type="strategic",
        )
        ports = [
            p.container_port
            for c in out.spec.containers if c.name == "a"
            for p in c.ports
        ]
        assert ports == [81]

    def test_merge_patch_still_replaces_lists(self, client):
        client.create("pods", pod_wire("mp"))
        out = client.patch(
            "pods", "mp",
            {"spec": {"containers": [{"name": "only", "image": "x"}]}},
            namespace="default",
        )
        assert [c.name for c in out.spec.containers] == ["only"]

    def test_patch_types_over_http(self):
        srv = APIHTTPServer(APIServer()).start()
        try:
            client = Client(HTTPTransport(srv.address))
            client.create("pods", pod_wire("h1"))
            out = client.patch(
                "pods", "h1",
                [{"op": "replace", "path": "/spec/containers/1/image",
                  "value": "redis:7"}],
                namespace="default", patch_type="json",
            )
            assert out.spec.containers[1].image == "redis:7"
            out = client.patch(
                "pods", "h1",
                {"spec": {"containers": [{"name": "a", "image": "nginx:3"}]}},
                namespace="default", patch_type="strategic",
            )
            assert {c.name: c.image for c in out.spec.containers} == {
                "a": "nginx:3", "b": "redis:7",
            }
        finally:
            srv.stop()


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    """openssl-generated CA + server cert + client certs."""
    if shutil.which("openssl") is None:
        pytest.skip("openssl not available")
    d = tmp_path_factory.mktemp("pki")

    def run(*args):
        subprocess.run(
            ["openssl", *args], cwd=d, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    run("req", "-x509", "-newkey", "rsa:2048", "-nodes", "-days", "1",
        "-keyout", "ca.key", "-out", "ca.crt", "-subj", "/CN=test-ca")
    # Server cert for 127.0.0.1.
    run("req", "-newkey", "rsa:2048", "-nodes", "-keyout", "server.key",
        "-out", "server.csr", "-subj", "/CN=127.0.0.1",
        "-addext", "subjectAltName=IP:127.0.0.1")
    run("x509", "-req", "-in", "server.csr", "-CA", "ca.crt", "-CAkey",
        "ca.key", "-CAcreateserial", "-days", "1", "-out", "server.crt",
        "-copy_extensions", "copyall")
    # Client cert: CN=alice, O=dev-team.
    run("req", "-newkey", "rsa:2048", "-nodes", "-keyout", "alice.key",
        "-out", "alice.csr", "-subj", "/O=dev-team/CN=alice")
    run("x509", "-req", "-in", "alice.csr", "-CA", "ca.crt", "-CAkey",
        "ca.key", "-CAcreateserial", "-days", "1", "-out", "alice.crt")
    return d


class TestX509:
    def _server(self, pki, authorizer=None):
        return APIHTTPServer(
            APIServer(),
            authorizer=authorizer,
            tls_cert_file=str(pki / "server.crt"),
            tls_key_file=str(pki / "server.key"),
            client_ca_file=str(pki / "ca.crt"),
        ).start()

    def _client(self, srv, pki, with_cert=True):
        ctx = ssl.create_default_context(cafile=str(pki / "ca.crt"))
        if with_cert:
            ctx.load_cert_chain(str(pki / "alice.crt"), str(pki / "alice.key"))
        return Client(HTTPTransport(srv.address, ssl_context=ctx))

    def test_cert_identity_authorized(self, pki):
        from kubernetes_tpu.server.auth import ABACAuthorizer, Policy

        # Policy: only alice may touch pods (everything else denied).
        authorizer = ABACAuthorizer(
            [Policy(user="alice", resource="*", namespace="*")]
        )
        srv = self._server(pki, authorizer=authorizer)
        try:
            assert srv.address.startswith("https://")
            client = self._client(srv, pki, with_cert=True)
            created = client.create("pods", pod_wire("cert-pod"))
            assert created.metadata.name == "cert-pod"
        finally:
            srv.stop()

    def test_no_cert_is_anonymous_and_denied(self, pki):
        from kubernetes_tpu.server.auth import ABACAuthorizer, Policy

        authorizer = ABACAuthorizer(
            [Policy(user="alice", resource="*", namespace="*")]
        )
        srv = self._server(pki, authorizer=authorizer)
        try:
            client = self._client(srv, pki, with_cert=False)
            with pytest.raises(APIError) as e:
                client.create("pods", pod_wire("anon-pod"))
            assert e.value.code == 403
        finally:
            srv.stop()

    def test_peer_cert_parsing(self):
        from kubernetes_tpu.server.auth import X509Authenticator

        user = X509Authenticator().authenticate_peer_cert(
            {
                "subject": (
                    (("organizationName", "dev-team"),),
                    (("commonName", "alice"),),
                )
            }
        )
        assert user.name == "alice"
        assert user.groups == ("dev-team",)
