"""Whole-system integration: apiserver + scheduler + controller manager
+ fake-runtime kubelets in one process.

Reference analog: cmd/integration/integration.go:99 startComponents —
real control plane with two kubelets on FakeDockerClient, asserting
pods get scheduled and run.
"""

import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes_tpu.scheduler.daemon import Scheduler, SchedulerConfig
from kubernetes_tpu.server import APIServer


def wait_until(cond, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def rc_wire(name, replicas, app, cpu="100m", mem="64Mi"):
    return {
        "kind": "ReplicationController",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "replicas": replicas,
            "selector": {"app": app},
            "template": {
                "metadata": {"labels": {"app": app}},
                "spec": {
                    "containers": [
                        {
                            "name": "main",
                            "image": "nginx",
                            "resources": {"limits": {"cpu": cpu, "memory": mem}},
                        }
                    ]
                },
            },
        },
    }


@pytest.fixture
def cluster():
    """Control plane + 2 kubelets, all in-process."""
    api = APIServer()
    client = Client(LocalTransport(api))
    runtimes = {n: FakeRuntime() for n in ("node-1", "node-2")}
    kubelets = [
        Kubelet(
            Client(LocalTransport(api)),
            node_name=name,
            runtime=rt,
            heartbeat_period=0.5,
            sync_period=0.3,
        ).start()
        for name, rt in runtimes.items()
    ]
    sched_cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert sched_cfg.wait_for_sync()
    scheduler = Scheduler(sched_cfg).start()
    manager = ControllerManager(
        Client(LocalTransport(api)),
        node_grace_period=2.0,
        node_eviction_timeout=1.0,
    ).start()
    yield api, client, kubelets, runtimes, scheduler, manager
    manager.stop()
    scheduler.stop()
    for k in kubelets:
        k.stop()


class TestEndToEnd:
    def test_rc_to_running_pods(self, cluster):
        """Create an RC -> pods created -> scheduled -> Running with
        container statuses (the reference's integration.go:405 flow)."""
        api, client, kubelets, runtimes, *_ = cluster
        client.create("replicationcontrollers", rc_wire("web", 6, "web"))

        def all_running():
            pods, _ = client.list("pods", namespace="default")
            return len(pods) == 6 and all(
                p.status.phase == "Running" and p.spec.node_name for p in pods
            )

        assert wait_until(all_running, timeout=15), _dump(client)
        pods, _ = client.list("pods", namespace="default")
        by_node = {}
        for p in pods:
            by_node.setdefault(p.spec.node_name, []).append(p)
            assert p.status.pod_ip
            assert p.status.container_statuses[0].ready
        assert set(by_node) <= {"node-1", "node-2"}
        # Both kubelets actually started containers.
        assert len(by_node) == 2

    def test_scale_up_and_down(self, cluster):
        api, client, *_ = cluster
        client.create("replicationcontrollers", rc_wire("app", 3, "app"))
        assert wait_until(
            lambda: len(client.list("pods", namespace="default")[0]) == 3
        )
        rc = client.get("replicationcontrollers", "app", namespace="default")
        rc.spec.replicas = 5
        client.update("replicationcontrollers", rc, namespace="default")
        assert wait_until(
            lambda: len(client.list("pods", namespace="default")[0]) == 5
        )
        rc = client.get("replicationcontrollers", "app", namespace="default")
        rc.spec.replicas = 1
        client.update("replicationcontrollers", rc, namespace="default")
        assert wait_until(
            lambda: len(client.list("pods", namespace="default")[0]) == 1, timeout=15
        )

    def test_deleted_pod_recreated(self, cluster):
        api, client, *_ = cluster
        client.create("replicationcontrollers", rc_wire("ha", 2, "ha"))
        assert wait_until(
            lambda: len(client.list("pods", namespace="default")[0]) == 2
        )
        victim = client.list("pods", namespace="default")[0][0]
        client.delete("pods", victim.metadata.name, namespace="default")
        assert wait_until(
            lambda: len(client.list("pods", namespace="default")[0]) == 2
            and all(
                p.metadata.name != victim.metadata.name
                for p in client.list("pods", namespace="default")[0]
            )
        )

    def test_endpoints_follow_service(self, cluster):
        api, client, *_ = cluster
        client.create(
            "services",
            {
                "kind": "Service",
                "metadata": {"name": "websvc", "namespace": "default"},
                "spec": {"selector": {"app": "web"}, "ports": [{"port": 80}]},
            },
        )
        client.create("replicationcontrollers", rc_wire("web", 3, "web"))

        def endpoints_ready():
            try:
                ep = client.get("endpoints", "websvc", namespace="default")
            except Exception:
                return False
            return ep.subsets and len(ep.subsets[0].addresses) == 3

        assert wait_until(endpoints_ready, timeout=15), _dump(client)

    def test_node_death_evicts_and_reschedules(self, cluster):
        """Kill a kubelet; its pods must move to the surviving node
        (nodecontroller eviction + RC recreate + scheduler)."""
        api, client, kubelets, runtimes, *_ = cluster
        client.create("replicationcontrollers", rc_wire("mv", 4, "mv"))
        assert wait_until(
            lambda: all(
                p.status.phase == "Running"
                for p in client.list("pods", namespace="default")[0]
            )
            and len(client.list("pods", namespace="default")[0]) == 4,
            timeout=15,
        )
        dead = kubelets[0]
        dead.stop()  # heartbeats cease

        def all_on_survivor():
            pods, _ = client.list("pods", namespace="default")
            return len(pods) == 4 and all(
                p.spec.node_name == "node-2" for p in pods
            )

        assert wait_until(all_on_survivor, timeout=30), _dump(client)

    def test_liveness_probe_restarts_container(self, cluster):
        api, client, kubelets, runtimes, *_ = cluster
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {"name": "flaky", "namespace": "default"},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "x",
                            "livenessProbe": {"exec": {"command": ["check"]}},
                            "resources": {"limits": {"cpu": "50m", "memory": "16Mi"}},
                        }
                    ]
                },
            },
        )
        assert wait_until(
            lambda: client.get("pods", "flaky", namespace="default").status.phase
            == "Running"
        )
        pod = client.get("pods", "flaky", namespace="default")
        node = pod.spec.node_name
        rt = runtimes[node]
        rt.set_probe_result(pod.metadata.uid, "c", False)

        def restarted():
            p = client.get("pods", "flaky", namespace="default")
            cs = p.status.container_statuses
            return cs and cs[0].restart_count >= 1 and p.status.phase == "Running"

        assert wait_until(restarted, timeout=15)


def _dump(client):
    pods, _ = client.list("pods", namespace="default")
    return "; ".join(
        f"{p.metadata.name}@{p.spec.node_name or '-'}:{p.status.phase}" for p in pods
    )


class TestReviewRegressions:
    def test_on_failure_keeps_succeeded_container_done(self, cluster):
        """restartPolicy=OnFailure: exit-0 container stays exited while
        a failed sibling restarts."""
        api, client, kubelets, runtimes, *_ = cluster
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {"name": "mixed", "namespace": "default"},
                "spec": {
                    "restartPolicy": "OnFailure",
                    "containers": [
                        {"name": "done", "image": "x",
                         "resources": {"limits": {"cpu": "50m", "memory": "16Mi"}}},
                        {"name": "flaky", "image": "x",
                         "resources": {"limits": {"cpu": "50m", "memory": "16Mi"}}},
                    ],
                },
            },
        )
        assert wait_until(
            lambda: client.get("pods", "mixed", namespace="default").status.phase
            == "Running"
        )
        pod = client.get("pods", "mixed", namespace="default")
        rt = runtimes[pod.spec.node_name]
        uid = pod.metadata.uid
        rt.fail_container(uid, "done", exit_code=0)  # completed
        rt.fail_container(uid, "flaky", exit_code=1)  # crashed

        def flaky_restarted_done_not():
            p = client.get("pods", "mixed", namespace="default")
            by_name = {c.name: c for c in p.status.container_statuses}
            return (
                by_name.get("flaky") is not None
                and by_name["flaky"].restart_count >= 1
                and by_name.get("done") is not None
                and by_name["done"].restart_count == 0
            )

        assert wait_until(flaky_restarted_done_not, timeout=10)

    def test_endpoints_gc_on_service_delete(self, cluster):
        api, client, *_ = cluster
        client.create(
            "services",
            {
                "kind": "Service",
                "metadata": {"name": "gone", "namespace": "default"},
                "spec": {"selector": {"app": "x"}, "ports": [{"port": 80}]},
            },
        )
        assert wait_until(
            lambda: any(
                e.metadata.name == "gone" for e in client.list("endpoints")[0]
            )
        )
        client.delete("services", "gone", namespace="default")
        assert wait_until(
            lambda: all(
                e.metadata.name != "gone" for e in client.list("endpoints")[0]
            ),
            timeout=10,
        )

    def test_named_target_port_resolved(self, cluster):
        api, client, *_ = cluster
        client.create(
            "services",
            {
                "kind": "Service",
                "metadata": {"name": "named", "namespace": "default"},
                "spec": {
                    "selector": {"app": "np"},
                    "ports": [{"port": 80, "targetPort": "http"}],
                },
            },
        )
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {"name": "np1", "namespace": "default",
                             "labels": {"app": "np"}},
                "spec": {
                    "containers": [
                        {"name": "c", "image": "x",
                         "ports": [{"name": "http", "containerPort": 8080}],
                         "resources": {"limits": {"cpu": "50m", "memory": "16Mi"}}}
                    ]
                },
            },
        )

        def resolved():
            try:
                ep = client.get("endpoints", "named", namespace="default")
            except Exception:
                return False
            return (
                ep.subsets
                and ep.subsets[0].ports[0].port == 8080
            )

        assert wait_until(resolved, timeout=10)

    def test_kubelet_status_writes_are_deduped(self, cluster):
        """A settled pod must not generate a stream of status writes."""
        api, client, *_ = cluster
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {"name": "settle", "namespace": "default"},
                "spec": {"containers": [
                    {"name": "c", "image": "x",
                     "resources": {"limits": {"cpu": "50m", "memory": "16Mi"}}}
                ]},
            },
        )
        assert wait_until(
            lambda: client.get("pods", "settle", namespace="default").status.phase
            == "Running"
        )
        v1 = client.get("pods", "settle", namespace="default").metadata.resource_version
        time.sleep(1.5)  # several sync periods
        v2 = client.get("pods", "settle", namespace="default").metadata.resource_version
        assert v1 == v2, "status writes not deduped"
