"""Daemon launchers, hyperkube dispatch, local-up-cluster, swagger, UI.

Reference: cmd/*/app/server.go flag surfaces, cmd/hyperkube/main.go,
hack/local-up-cluster.sh, pkg/ui + api/swagger-spec."""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.cmd import daemons, hyperkube
from kubernetes_tpu.cmd.localup import LocalCluster, build_parser


def wait_until(cond, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestHyperkube:
    def test_help_lists_servers(self, capsys):
        assert hyperkube.main([]) == 1
        out = capsys.readouterr().out
        for name in ("apiserver", "scheduler", "kubelet", "proxy", "ktctl"):
            assert name in out

    def test_unknown_server(self, capsys):
        assert hyperkube.main(["no-such-daemon"]) == 1

    def test_ktctl_dispatch(self, capsys):
        # Errors cleanly (no server running on a bogus port) but proves
        # dispatch reached ktctl.
        rc = hyperkube.main(
            ["ktctl", "get", "pods", "--server", "http://127.0.0.1:1"]
        )
        assert rc == 1


class TestDaemonFlagParsers:
    def test_all_parsers_have_defaults(self):
        assert daemons.apiserver_parser().parse_args([]).port == 8080
        assert (
            daemons.scheduler_parser().parse_args([]).algorithm_provider
            == "DefaultProvider"
        )
        assert daemons.controller_manager_parser().parse_args([]).server
        args = daemons.kubelet_parser().parse_args(["--node-name", "n1"])
        assert args.node_name == "n1"
        assert daemons.proxy_parser().parse_args([]).bind_address == "127.0.0.1"


class TestHealthServer:
    def test_reference_default_ports(self):
        assert daemons.scheduler_parser().parse_args([]).healthz_port == 10251
        assert (
            daemons.controller_manager_parser().parse_args([]).healthz_port
            == 10252
        )
        assert daemons.proxy_parser().parse_args([]).healthz_port == 10249

    def test_healthz_and_metrics(self):
        """Every daemon mounts /healthz + /metrics on its own port
        (scheduler server.go:105-109); unhealthy checks turn the
        endpoint 500."""
        from kubernetes_tpu.utils import metrics

        # The shared registry may be empty when this file runs alone;
        # give /metrics something real to render.
        metrics.DEFAULT.counter(
            "healthserver_test_total", "health server test counter"
        ).inc()
        state = {"ok": True}
        srv = daemons.HealthServer(
            0, checks=[lambda: (state["ok"], "ok" if state["ok"] else "down")]
        ).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
                assert r.read() == b"ok"
            with urllib.request.urlopen(f"{base}/metrics", timeout=5) as r:
                body = r.read()
                assert b"# HELP" in body and b"# TYPE" in body
            state["ok"] = False
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{base}/healthz", timeout=5)
            assert e.value.code == 500
        finally:
            srv.stop()

    def test_disabled_and_conflict_are_nonfatal(self):
        import argparse

        assert daemons._start_health(argparse.Namespace(healthz_port=-1), []) is None
        # Occupy a port, then ask a "daemon" to bind it: warns, returns None.
        srv = daemons.HealthServer(0).start()
        try:
            taken = srv.port
            assert (
                daemons._start_health(
                    argparse.Namespace(healthz_port=taken), []
                )
                is None
            )
        finally:
            srv.stop()


class TestLocalUpCluster:
    def test_full_cluster_schedules_pods_over_http(self):
        """hack/local-up-cluster.sh analog: one call brings up the
        whole control plane; a pod created over real HTTP gets
        scheduled and runs."""
        args = build_parser().parse_args(["--port", "0", "--nodes", "2"])
        cluster = LocalCluster(args).start()
        try:
            client = Client(HTTPTransport(cluster.http.address))
            client.create(
                "pods",
                {
                    "kind": "Pod",
                    "metadata": {"name": "up1", "namespace": "default"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "image": "x",
                                "resources": {
                                    "limits": {"cpu": "100m", "memory": "64Mi"}
                                },
                            }
                        ]
                    },
                },
                namespace="default",
            )

            def running():
                pod = client.get("pods", "up1", namespace="default")
                return pod.status.phase == "Running" and pod.spec.node_name

            assert wait_until(running)
            nodes, _ = client.list("nodes")
            assert len(nodes) == 2
            # Live componentstatuses (reference: master probes its
            # registered servers on every read).
            comps, _ = client.list("componentstatuses")
            by_name = {c.metadata.name: c for c in comps}
            assert {"etcd-0", "scheduler", "controller-manager"} <= set(by_name)
            for c in by_name.values():
                healthy = [x for x in c.conditions if x.type == "Healthy"]
                assert healthy and healthy[0].status == "True", c.metadata.name
        finally:
            cluster.stop()
        # After stop, the scheduler reports unhealthy (live probe).
        ok, _msg = cluster._scheduler_health()
        assert not ok


class TestExamplesAndTop:
    def test_examples_deploy_and_top_reports(self, capsys):
        """The shipped example manifests deploy through ktctl against a
        live cluster, and `ktctl top nodes` reports real usage."""
        import os

        from kubernetes_tpu.cli.ktctl import main as ktctl_main

        args = build_parser().parse_args(["--port", "0", "--nodes", "2"])
        cluster = LocalCluster(args).start()
        try:
            base = os.path.join(os.path.dirname(__file__), "..", "examples")
            for manifest in ("web-rc.json", "web-service.json"):
                rc = ktctl_main(
                    [
                        "create",
                        "-f",
                        os.path.join(base, manifest),
                        "--server",
                        cluster.http.address,
                    ]
                )
                assert rc == 0
            client = Client(HTTPTransport(cluster.http.address))
            assert wait_until(
                lambda: sum(
                    1
                    for p in client.list(
                        "pods", namespace="default",
                        label_selector="app=web",
                    )[0]
                    if p.status.phase == "Running"
                )
                == 3
            )
            capsys.readouterr()
            rc = ktctl_main(
                ["top", "nodes", "--server", cluster.http.address]
            )
            assert rc == 0
            out = capsys.readouterr().out
            assert "node-0" in out and "node-1" in out
            rc = ktctl_main(
                ["top", "pods", "--server", cluster.http.address]
            )
            assert rc == 0
        finally:
            cluster.stop()


class TestSwaggerAndUI:
    @pytest.fixture
    def server(self):
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        srv = APIHTTPServer(APIServer()).start()
        yield srv
        srv.stop()

    def test_swagger_covers_registry(self, server):
        doc = json.loads(
            urllib.request.urlopen(server.address + "/swagger.json").read()
        )
        assert doc["info"]["title"] == "kubernetes-tpu"
        paths = doc["paths"]
        assert "/api/v1/namespaces/{namespace}/pods" in paths
        assert "/api/v1/nodes" in paths
        assert "/api/v1/namespaces/{namespace}/pods/{name}/log" in paths
        assert "/api/v1/watch/pods" in paths

    def test_ui_renders_with_counts(self, server):
        Client(HTTPTransport(server.address)).create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {"name": "uipod", "namespace": "default"},
                "spec": {"containers": [{"name": "c", "image": "x"}]},
            },
            namespace="default",
        )
        html = urllib.request.urlopen(server.address + "/ui/").read().decode()
        assert "kubernetes-tpu" in html
        assert "pods" in html
        assert "swagger" in html
        # The SPA polls the live API and hash-routes per-resource views.
        assert "setInterval(" in html and "render(" in html
        assert "replicationcontrollers" in html
        # Any /ui subpath serves the app shell (client-side routing).
        sub = urllib.request.urlopen(server.address + "/ui/pods").read().decode()
        assert "setInterval(" in sub
