"""Predicate parity tests — tables mirror the reference's
plugin/pkg/scheduler/algorithm/predicates/predicates_test.go. These are
the oracle for the TPU batch path's >=99% parity requirement."""

import pytest

from kubernetes_tpu.models.objects import (
    AWSElasticBlockStoreVolumeSource,
    Container,
    ContainerPort,
    GCEPersistentDiskVolumeSource,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
    Volume,
)
from kubernetes_tpu.models.quantity import Quantity
from kubernetes_tpu.scheduler import predicates as preds
from kubernetes_tpu.scheduler.types import StaticNodeLister


def resource_pod(*reqs):
    """newResourcePod (predicates_test.go:55-75): containers with LIMITS."""
    containers = [
        Container(
            name=f"c{i}",
            image="x",
            resources=ResourceRequirements(
                limits={
                    "cpu": Quantity.from_milli(cpu),
                    "memory": Quantity.from_int(mem),
                }
            ),
        )
        for i, (cpu, mem) in enumerate(reqs)
    ]
    return Pod(spec=PodSpec(containers=containers))


def make_node(cpu_milli, mem, pods=32, name="machine"):
    """makeResources (predicates_test.go:40-52)."""
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(
            capacity={
                "cpu": Quantity.from_milli(cpu_milli),
                "memory": Quantity.from_int(mem),
                "pods": Quantity.from_int(pods),
            }
        ),
    )


class TestPodFitsResources:
    """predicates_test.go TestPodFitsResources (enough/not-enough pods)."""

    @pytest.mark.parametrize(
        "pod,existing,fits,name",
        [
            (Pod(), [resource_pod((10, 20))], True, "no resources requested always fits"),
            (resource_pod((1, 1)), [resource_pod((10, 20))], False, "too many resources fails"),
            (resource_pod((1, 1)), [resource_pod((5, 5))], True, "both resources fit"),
            (resource_pod((1, 2)), [resource_pod((5, 19))], False, "one resource fits"),
            (resource_pod((5, 1)), [resource_pod((5, 19))], True, "equal edge case"),
        ],
    )
    def test_enough_pod_slots(self, pod, existing, fits, name):
        node = make_node(10, 20, pods=32)
        fit = preds.ResourceFit(StaticNodeLister([node]))
        assert fit(pod, existing, "machine") is fits, name

    @pytest.mark.parametrize(
        "pod,existing,fits,name",
        [
            (Pod(), [resource_pod((10, 20))], False, "no pod slots: zero-request fails"),
            (resource_pod((1, 1)), [resource_pod((5, 5))], False, "no pod slots: fits otherwise"),
            (resource_pod((5, 1)), [resource_pod((5, 19))], False, "no pod slots: equal edge"),
        ],
    )
    def test_not_enough_pod_slots(self, pod, existing, fits, name):
        node = make_node(10, 20, pods=1)
        fit = preds.ResourceFit(StaticNodeLister([node]))
        assert fit(pod, existing, "machine") is fits, name

    def test_zero_capacity_means_unlimited_resource(self):
        """CheckPodsExceedingCapacity: totalMilliCPU == 0 -> cpu always
        fits (predicates.go:123-124)."""
        node = make_node(0, 0, pods=10)
        fit = preds.ResourceFit(StaticNodeLister([node]))
        assert fit(resource_pod((10**9, 10**9)), [], "machine") is True

    def test_overcommitted_node_rejects_everything(self):
        """If ANY pod in the greedy simulation exceeds capacity —
        including a pre-existing one — the node fails for the new pod
        (PodFitsResources checks len(exceeding) > 0, predicates.go:152)."""
        node = make_node(10, 100, pods=32)
        fit = preds.ResourceFit(StaticNodeLister([node]))
        # existing: 8 cpu fits; 5 cpu does NOT (8+5>10) -> node rejects
        # even a tiny new pod.
        existing = [resource_pod((8, 1)), resource_pod((5, 1))]
        assert fit(resource_pod((2, 1)), existing, "machine") is False
        # Without the overflowing existing pod the small pod fits.
        assert fit(resource_pod((2, 1)), [resource_pod((8, 1))], "machine") is True


class TestPodFitsHost:
    """predicates_test.go TestPodFitsHost (:185-218)."""

    @pytest.mark.parametrize(
        "pod_node,node,fits",
        [
            ("", "foo", True),
            ("foo", "foo", True),
            ("bar", "foo", False),
        ],
    )
    def test_table(self, pod_node, node, fits):
        pod = Pod(spec=PodSpec(node_name=pod_node))
        assert preds.pod_fits_host(pod, [], node) is fits


def port_pod(*host_ports):
    return Pod(
        spec=PodSpec(
            containers=[
                Container(
                    name="c",
                    image="x",
                    ports=[ContainerPort(container_port=80, host_port=hp) for hp in host_ports],
                )
            ]
        )
    )


class TestPodFitsPorts:
    """predicates_test.go TestPodFitsPorts (:248-301)."""

    @pytest.mark.parametrize(
        "pod,existing,fits,name",
        [
            (Pod(), [], True, "nothing running"),
            (port_pod(8080), [port_pod(9090)], True, "other port"),
            (port_pod(8080), [port_pod(8080)], False, "same port conflict"),
            (port_pod(8000, 8080), [port_pod(8080)], False, "second port conflicts"),
            (port_pod(8000, 8080), [port_pod(8001, 8080)], False, "dup in existing"),
        ],
    )
    def test_table(self, pod, existing, fits, name):
        assert preds.pod_fits_ports(pod, existing, "machine") is fits, name

    def test_host_port_zero_ignored(self):
        assert preds.pod_fits_ports(port_pod(0), [port_pod(0)], "machine") is True


def gce_pod(pd_name, read_only=False):
    return Pod(
        spec=PodSpec(
            volumes=[
                Volume(
                    name="v",
                    gce_persistent_disk=GCEPersistentDiskVolumeSource(
                        pd_name=pd_name, read_only=read_only
                    ),
                )
            ]
        )
    )


def ebs_pod(volume_id):
    return Pod(
        spec=PodSpec(
            volumes=[
                Volume(
                    name="v",
                    aws_elastic_block_store=AWSElasticBlockStoreVolumeSource(
                        volume_id=volume_id
                    ),
                )
            ]
        )
    )


class TestNoDiskConflict:
    """predicates_test.go TestDiskConflicts/TestAWSDiskConflicts
    (:305-390) + the read-only exemption in isVolumeConflict."""

    def test_gce_conflicts(self):
        assert preds.no_disk_conflict(gce_pod("foo"), [], "m") is True
        assert preds.no_disk_conflict(gce_pod("foo"), [gce_pod("bar")], "m") is True
        assert preds.no_disk_conflict(gce_pod("foo"), [gce_pod("foo")], "m") is False
        assert preds.no_disk_conflict(Pod(), [gce_pod("foo")], "m") is True

    def test_gce_both_read_only_ok(self):
        a, b = gce_pod("foo", read_only=True), gce_pod("foo", read_only=True)
        assert preds.no_disk_conflict(a, [b], "m") is True
        rw = gce_pod("foo", read_only=False)
        assert preds.no_disk_conflict(rw, [b], "m") is False
        assert preds.no_disk_conflict(b, [rw], "m") is False

    def test_ebs_conflicts_even_read_only(self):
        assert preds.no_disk_conflict(ebs_pod("vol1"), [ebs_pod("vol1")], "m") is False
        assert preds.no_disk_conflict(ebs_pod("vol1"), [ebs_pod("vol2")], "m") is True


def selector_pod(selector=None, labels=None):
    return Pod(
        metadata=ObjectMeta(labels=labels or {}),
        spec=PodSpec(node_selector=selector or {}),
    )


def labeled_node(name, labels):
    return Node(metadata=ObjectMeta(name=name, labels=labels))


class TestPodSelectorMatches:
    """predicates_test.go TestPodSelectorMatches (:395-430)."""

    @pytest.mark.parametrize(
        "selector,node_labels,fits",
        [
            ({}, {}, True),
            ({"foo": "bar"}, {"foo": "bar"}, True),
            ({"foo": "bar"}, {"foo": "baz"}, False),
            ({"foo": "bar"}, {}, False),
            ({"foo": "bar", "baz": "qux"}, {"foo": "bar", "baz": "qux", "x": "y"}, True),
            ({"foo": "bar", "baz": "qux"}, {"foo": "bar"}, False),
        ],
    )
    def test_table(self, selector, node_labels, fits):
        node = labeled_node("machine", node_labels)
        pred = preds.NodeSelectorMatches(StaticNodeLister([node]))
        assert pred(selector_pod(selector), [], "machine") is fits


class TestNodeLabelPresence:
    """predicates_test.go TestNodeLabelPresence (:433-500)."""

    @pytest.mark.parametrize(
        "labels,presence,fits",
        [
            (["baz"], True, False),   # label absent, wanted
            (["baz"], False, True),   # label absent, unwanted
            (["foo"], True, True),    # present, wanted
            (["foo"], False, False),  # present, unwanted
            (["foo", "bar"], True, True),
            (["foo", "bar"], False, False),
            (["foo", "baz"], True, False),  # one of them missing
        ],
    )
    def test_table(self, labels, presence, fits):
        node = labeled_node("machine", {"foo": "1", "bar": "2"})
        pred = preds.NodeLabelChecker(StaticNodeLister([node]), labels, presence)
        assert pred(Pod(), [], "machine") is fits


class TestServiceAffinity:
    """predicates_test.go TestServiceAffinity (:503-620, condensed)."""

    def _setup(self):
        from kubernetes_tpu.models.objects import Service, ServiceSpec
        from kubernetes_tpu.scheduler.types import StaticPodLister, StaticServiceLister

        n1 = labeled_node("machine1", {"region": "r1", "zone": "z11"})
        n2 = labeled_node("machine2", {"region": "r1", "zone": "z12"})
        n3 = labeled_node("machine3", {"region": "r2", "zone": "z21"})
        nodes = StaticNodeLister([n1, n2, n3])
        svc = Service(
            metadata=ObjectMeta(name="s1", namespace="default"),
            spec=ServiceSpec(selector={"app": "web"}),
        )
        return nodes, svc, StaticPodLister, StaticServiceLister

    def test_pod_with_selector_labels(self):
        nodes, svc, PL, SL = self._setup()
        pred = preds.ServiceAffinity(PL([]), SL([]), nodes, ["region"])
        pod = selector_pod({"region": "r1"})
        assert pred(pod, [], "machine1") is True
        assert pred(pod, [], "machine3") is False

    def test_affinity_from_service_peer(self):
        nodes, svc, PL, SL = self._setup()
        peer = Pod(
            metadata=ObjectMeta(name="peer", namespace="default", labels={"app": "web"}),
            spec=PodSpec(node_name="machine3"),
        )
        pred = preds.ServiceAffinity(PL([peer]), SL([svc]), nodes, ["region"])
        pod = selector_pod(labels={"app": "web"})
        pod.metadata.namespace = "default"
        # Peer runs in r2 -> only r2 nodes fit.
        assert pred(pod, [], "machine3") is True
        assert pred(pod, [], "machine1") is False

    def test_no_peers_all_fit(self):
        nodes, svc, PL, SL = self._setup()
        pred = preds.ServiceAffinity(PL([]), SL([svc]), nodes, ["region"])
        pod = selector_pod(labels={"app": "web"})
        pod.metadata.namespace = "default"
        assert pred(pod, [], "machine1") is True
        assert pred(pod, [], "machine3") is True
