"""Incremental solver session tests: the device-resident state after
adds/deletes/binds must make the SAME decisions a fresh full solve
makes from the authoritative object state (BASELINE config 5
substrate)."""

import random

import numpy as np
import pytest

from kubernetes_tpu.models.objects import (
    Container,
    ContainerPort,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
    Service,
    ServiceSpec,
)
from kubernetes_tpu.models.quantity import Quantity, parse_quantity
from kubernetes_tpu.ops import RebuildRequired, SolverSession
from kubernetes_tpu.scheduler.batch import schedule_backlog_scalar


def mknode(name, cpu_milli=4000, mem="8Gi", pods=110, labels=None):
    return Node(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        status=NodeStatus(
            capacity={
                "cpu": Quantity.from_milli(cpu_milli),
                "memory": parse_quantity(mem),
                "pods": Quantity.from_int(pods),
            },
            conditions=[NodeCondition(type="Ready", status="True")],
        ),
    )


def mkpod(name, cpu=100, mem="128Mi", labels=None, node_selector=None,
          host_port=0, node_name=""):
    ports = [ContainerPort(container_port=80, host_port=host_port)] if host_port else []
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default", labels=labels or {}),
        spec=PodSpec(
            containers=[
                Container(
                    name="c", image="i", ports=ports,
                    resources=ResourceRequirements(
                        limits={
                            "cpu": Quantity.from_milli(cpu),
                            "memory": parse_quantity(mem),
                        }
                    ),
                )
            ],
            node_selector=node_selector or {},
            node_name=node_name,
        ),
    )


class TestSessionBasics:
    def test_single_tick_matches_scalar_oracle(self):
        nodes = [mknode(f"n{i}", cpu_milli=2000) for i in range(4)]
        pods = [mkpod(f"p{i}", cpu=500) for i in range(10)]
        session = SolverSession(nodes)
        for p in pods:
            session.add_pending(p)
        got = dict(session.solve())
        want = dict(
            zip(
                [f"default/p{i}" for i in range(10)],
                schedule_backlog_scalar(pods, nodes),
            )
        )
        assert got == want  # 4 nodes x 4 cpu slots = 16 >= 10 placed

    def test_capacity_spills_to_unschedulable(self):
        session = SolverSession([mknode("n0", cpu_milli=1000)])
        for i in range(3):
            session.add_pending(mkpod(f"p{i}", cpu=500))
        result = dict(session.solve())
        placed = [k for k, v in result.items() if v]
        assert len(placed) == 2  # 1000m / 500m
        assert result["default/p2"] is None

    def test_occupancy_carries_across_ticks(self):
        session = SolverSession([mknode("n0", cpu_milli=1000)])
        session.add_pending(mkpod("a", cpu=600))
        assert dict(session.solve()) == {"default/a": "n0"}
        session.add_pending(mkpod("b", cpu=600))
        # 600m already committed on device: b can't fit.
        assert dict(session.solve()) == {"default/b": None}

    def test_delete_frees_occupancy(self):
        session = SolverSession([mknode("n0", cpu_milli=1000)])
        session.add_pending(mkpod("a", cpu=600))
        session.solve()
        assert session.delete_assigned("default/a")
        session.add_pending(mkpod("b", cpu=600))
        assert dict(session.solve()) == {"default/b": "n0"}

    def test_delete_frees_host_port(self):
        session = SolverSession([mknode("n0")])
        session.add_pending(mkpod("a", host_port=8080))
        session.solve()
        session.add_pending(mkpod("b", host_port=8080))
        assert dict(session.solve()) == {"default/b": None}  # conflict
        session.delete_assigned("default/a")
        session.add_pending(mkpod("c", host_port=8080))
        assert dict(session.solve()) == {"default/c": "n0"}

    def test_node_upsert_and_remove(self):
        session = SolverSession([mknode("n0", cpu_milli=100)], node_capacity=8)
        session.add_pending(mkpod("a", cpu=500))
        assert dict(session.solve()) == {"default/a": None}
        session.upsert_node(mknode("n1", cpu_milli=4000))
        session.add_pending(mkpod("b", cpu=500))
        assert dict(session.solve()) == {"default/b": "n1"}
        session.remove_node("n1")
        session.add_pending(mkpod("c", cpu=500))
        assert dict(session.solve()) == {"default/c": None}

    def test_pinned_pod_survives_slot_recycling(self):
        """A pod pinned to node A must NOT land on node B when B
        recycles A's slot between add_pending and solve."""
        session = SolverSession([mknode("n0"), mknode("A")], node_capacity=2)
        session.add_pending(mkpod("p", node_name="A"))
        session.remove_node("A")
        session.upsert_node(mknode("B"))  # reuses A's slot
        assert dict(session.solve()) == {"default/p": None}
        # And a pin added BEFORE the node registers resolves at solve.
        session.add_pending(mkpod("q", node_name="C"))
        session.upsert_node(mknode("C"))
        assert dict(session.solve()) == {"default/q": "C"}

    def test_vocab_overflow_raises(self):
        session = SolverSession([mknode("n0")], label_words=1)
        with pytest.raises(RebuildRequired):
            for i in range(40):  # 1 word = 32 label ids
                session.add_pending(
                    mkpod(f"p{i}", node_selector={f"k{i}": "v"})
                )


class TestChurnParity:
    def test_churn_replay_matches_fresh_solves(self):
        """Random create/delete churn: after every tick, the session's
        decisions equal a fresh scalar solve from the surviving object
        state."""
        rng = random.Random(7)
        nodes = [
            mknode(f"n{i}", cpu_milli=rng.choice([2000, 4000]),
                   labels={"zone": f"z{i % 2}"})
            for i in range(6)
        ]
        services = [
            Service(
                metadata=ObjectMeta(name="svc", namespace="default"),
                spec=ServiceSpec(selector={"app": "a"}),
            )
        ]
        session = SolverSession(nodes, services=services)
        live = {}  # key -> (pod, node_name)
        counter = 0
        for tick in range(6):
            batch = []
            for _ in range(rng.randrange(2, 6)):
                counter += 1
                pod = mkpod(
                    f"p{counter}",
                    cpu=rng.choice([200, 400, 800]),
                    labels={"app": "a"} if rng.random() < 0.5 else {},
                    node_selector={"zone": "z0"} if rng.random() < 0.3 else {},
                )
                batch.append(pod)
                session.add_pending(pod)
            # Random deletes of running pods.
            for key in rng.sample(sorted(live), min(2, len(live))):
                session.delete_assigned(key)
                del live[key]
            results = dict(session.solve())
            # Oracle: fresh scalar solve on the same object state.
            assigned_objs = []
            for key, (pod, node_name) in live.items():
                import copy

                placed = copy.deepcopy(pod)
                placed.spec.node_name = node_name
                placed.status.phase = "Running"
                assigned_objs.append(placed)
            want = schedule_backlog_scalar(
                batch, nodes, assigned=assigned_objs, services=services
            )
            for pod, expect in zip(batch, want):
                key = f"default/{pod.metadata.name}"
                assert results[key] == expect, (
                    f"tick {tick}: {key} -> {results[key]} want {expect}"
                )
                if results[key] is not None:
                    live[key] = (pod, results[key])


class TestSessionModes:
    """Wave/Sinkhorn tick solvers over the device-resident session:
    same validity and carry semantics as the scan ticks."""

    @pytest.mark.parametrize("mode", ["wave", "sinkhorn"])
    def test_occupancy_carries_across_ticks(self, mode):
        session = SolverSession([mknode("n0", cpu_milli=1000)], mode=mode)
        session.add_pending(mkpod("a", cpu=600))
        assert dict(session.solve()) == {"default/a": "n0"}
        session.add_pending(mkpod("b", cpu=600))
        assert dict(session.solve()) == {"default/b": None}

    @pytest.mark.parametrize("mode", ["wave", "sinkhorn"])
    def test_delete_then_reuse(self, mode):
        session = SolverSession([mknode("n0", cpu_milli=1000)], mode=mode)
        session.add_pending(mkpod("a", cpu=600))
        session.solve()
        assert session.delete_assigned("default/a")
        session.add_pending(mkpod("b", cpu=600))
        assert dict(session.solve()) == {"default/b": "n0"}

    @pytest.mark.parametrize("mode", ["wave", "sinkhorn"])
    def test_batch_tick_places_everything_that_fits(self, mode):
        nodes = [mknode(f"n{j}", cpu_milli=8000) for j in range(4)]
        session = SolverSession(nodes, mode=mode)
        for i in range(32):
            session.add_pending(mkpod(f"p{i}", cpu=250))
        out = dict(session.solve())
        assert all(v is not None for v in out.values())
        # Host mirror consistent: deleting every pod frees everything.
        for key in list(out):
            assert session.delete_assigned(key)
        session.add_pending(mkpod("post", cpu=7900))
        assert dict(session.solve())["default/post"] is not None

    def test_mode_validation(self):
        with pytest.raises(ValueError):
            SolverSession([mknode("n0")], mode="warp")

    @pytest.mark.parametrize("mode", ["wave", "sinkhorn"])
    def test_host_port_exclusivity_across_ticks(self, mode):
        session = SolverSession([mknode("n0"), mknode("n1")], mode=mode)
        session.add_pending(mkpod("hp1", host_port=8080))
        session.add_pending(mkpod("hp2", host_port=8080))
        session.add_pending(mkpod("hp3", host_port=8080))
        out = dict(session.solve())
        placed = [v for v in out.values() if v is not None]
        assert len(placed) == 2 and len(set(placed)) == 2
