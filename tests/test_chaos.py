"""Chaos plane (ISSUE 15): the deterministic fault registry, its
injection sites, the client resilience they exercise, and the soak
harness's invariant machinery.

Covered here:

- registry mechanics: zero-cost when off, seeded per-site determinism,
  spec parsing, budgets (times/every/after), stats/timeline;
- site behavior end to end: torn WAL write + crash + replay, fsync
  faults surfacing to writers, forced watch drops feeding the
  Reflector's new close-backoff, kubelet heartbeat drops;
- HTTPTransport transient retries (reset/5xx on idempotent verbs,
  fail-fast on POST) driven through the http.request.* sites;
- tools/soak.py: deterministic schedule, and a miniature end-to-end
  run (apiserver crash + replay epoch) with zero invariant violations.
"""

import queue
import threading
import time

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.client.cache import Reflector, ThreadSafeStore
from kubernetes_tpu.server.api import APIError, APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.store.kvstore import KVStore
from kubernetes_tpu.utils import faults

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with a disarmed registry."""
    faults.clear()
    faults.reset_stats(reseed=0)
    yield
    faults.clear()


def wait_until(cond, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def pod_wire(name):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": "x"}]},
    }


# ---------------------------------------------------------------------------
# registry mechanics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_disabled_is_inert(self):
        assert not faults.enabled()
        assert faults.fire(faults.WAL_FSYNC) is False
        # Disabled calls are not even counted (the zero-cost contract).
        assert faults.stats() == {}

    def test_per_site_determinism(self):
        """Same seed -> same firing indices at a site, regardless of
        what other sites did in between (per-site RNG + counters)."""
        def run(interleave: bool):
            faults.clear()
            faults.reset_stats(reseed=99)
            faults.inject(faults.WATCH_DROP, p=0.25, times=6)
            faults.inject(faults.HTTP_DELAY, p=0.5, delay_s=0.0)
            fired = []
            for i in range(60):
                if interleave:
                    faults.fire(faults.HTTP_DELAY)  # consumes ITS rng only
                if faults.fire(faults.WATCH_DROP):
                    fired.append(i)
            return fired

        a = run(interleave=False)
        b = run(interleave=True)
        assert a == b and len(a) == 6

    def test_budget_and_cadence_knobs(self):
        rule = faults.inject(faults.WATCH_DROP, every=3, times=2, after=4)
        fired = [
            i for i in range(1, 20) if faults.fire(faults.WATCH_DROP)
        ]
        # after=4 skips calls 1-4; every=3 on the eligible counter
        # fires at eligible calls 3 and 6 -> absolute calls 7 and 10.
        assert fired == [7, 10]
        assert rule.fired == 2

    def test_spec_roundtrip_and_errors(self):
        faults.configure(
            "seed=5; kvstore.wal.fsync:every=10,times=2 ;"
            "http.request.latency:p=0.5,delay=0.001"
        )
        assert faults.enabled()
        by_site = {r["site"]: r for r in faults.rules()}
        assert by_site["kvstore.wal.fsync"]["every"] == 10
        assert by_site["http.request.latency"]["delay_s"] == 0.001
        faults.configure("")
        assert not faults.enabled()
        with pytest.raises(ValueError, match="unknown fault site"):
            faults.configure("no.such.site:p=1")
        with pytest.raises(ValueError, match="unknown knob"):
            faults.configure("kvstore.wal.fsync:bogus=1")
        with pytest.raises(ValueError, match="ever fire"):
            faults.inject(faults.WAL_FSYNC)
        with pytest.raises(TypeError, match="KT008"):
            faults.inject("kvstore.wal.fsync", every=1)  # ktlint: disable=KT008

    def test_stats_and_timeline(self):
        faults.inject(faults.WATCH_DROP, every=2, times=2)
        for _ in range(5):
            faults.fire(faults.WATCH_DROP)
        st = faults.stats()[faults.WATCH_DROP.name]
        assert st == {"calls": 5, "fired": 2}
        assert faults.timeline() == [
            (faults.WATCH_DROP.name, 2), (faults.WATCH_DROP.name, 4),
        ]
        faults.reset_stats()
        assert faults.timeline() == []

    def test_error_kinds(self):
        faults.inject(faults.WAL_FSYNC, every=1, times=1)
        with pytest.raises(faults.InjectedIOError):
            faults.fire(faults.WAL_FSYNC)
        assert isinstance(faults.InjectedIOError("x"), OSError)
        faults.clear()
        faults.inject(faults.HTTP_5XX, every=1, times=1)
        with pytest.raises(APIError) as ei:
            faults.fire(faults.HTTP_5XX)
        assert ei.value.code == 503
        faults.clear()
        faults.inject(faults.HTTP_RESET, every=1, times=1)
        with pytest.raises(ConnectionResetError):
            faults.fire(faults.HTTP_RESET)


# ---------------------------------------------------------------------------
# kvstore sites: torn write / fsync / snapshot rename + crash()
# ---------------------------------------------------------------------------


class TestKVStoreSites:
    def test_torn_write_is_unacked_and_truncated_on_replay(self, tmp_path):
        store = KVStore(data_dir=str(tmp_path))
        store.create("/registry/pods/default/a", pod_wire("a"))
        faults.inject(faults.WAL_TORN_WRITE, every=1, times=1)
        with pytest.raises(faults.FaultInjected):
            store.create("/registry/pods/default/b", pod_wire("b"))
        faults.clear()
        store.crash()
        recovered = KVStore(data_dir=str(tmp_path))
        try:
            objs, _ = recovered.list("/registry/pods/")
            assert [o["metadata"]["name"] for o in objs] == ["a"]
            # The truncated WAL must accept appends again.
            recovered.create("/registry/pods/default/c", pod_wire("c"))
        finally:
            recovered.close()

    def test_fsync_fault_refuses_the_ack_but_state_recovers(self, tmp_path):
        store = KVStore(data_dir=str(tmp_path))
        faults.inject(faults.WAL_FSYNC, every=1, times=1)
        with pytest.raises(faults.InjectedIOError):
            store.create("/registry/pods/default/x", pod_wire("x"))
        faults.clear()
        # The record was appended+flushed; a later successful write's
        # group commit makes both durable (the documented contract:
        # fsync-before-ack, not fsync-per-record).
        store.create("/registry/pods/default/y", pod_wire("y"))
        store.crash()
        recovered = KVStore(data_dir=str(tmp_path))
        try:
            objs, _ = recovered.list("/registry/pods/")
            assert {o["metadata"]["name"] for o in objs} == {"x", "y"}
        finally:
            recovered.close()

    def test_snapshot_rename_crash_keeps_previous_snapshot(self, tmp_path):
        store = KVStore(data_dir=str(tmp_path), snapshot_every=100000)
        for i in range(8):
            store.create(f"/registry/pods/default/p{i}", pod_wire(f"p{i}"))
        store.snapshot()  # good snapshot at version 8
        store.create("/registry/pods/default/late", pod_wire("late"))
        faults.inject(faults.SNAPSHOT_RENAME, every=1, times=1)
        with pytest.raises(faults.InjectedIOError):
            store.snapshot()
        faults.clear()
        store.crash()
        recovered = KVStore(data_dir=str(tmp_path))
        try:
            objs, _ = recovered.list("/registry/pods/")
            assert len(objs) == 9  # old snapshot + WAL tail, nothing lost
        finally:
            recovered.close()

    def test_crash_refuses_durability_acks_in_flight(self, tmp_path):
        """A writer racing crash() must error out, never hang, and its
        write must not be silently acked as durable."""
        store = KVStore(data_dir=str(tmp_path), serialized_writes=True)
        results: "queue.Queue" = queue.Queue()

        def writer(i):
            try:
                store.create(f"/registry/pods/default/w{i}", pod_wire(f"w{i}"))
                results.put(("ok", i))
            except Exception as e:
                results.put(("err", repr(e)))

        threads = [
            threading.Thread(target=writer, args=(i,), daemon=True)
            for i in range(8)
        ]
        for t in threads:
            t.start()
        store.crash()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive(), "writer hung across crash()"
        outcomes = [results.get(timeout=1) for _ in range(8)]
        # Post-crash, writes must refuse cleanly.
        with pytest.raises(Exception):
            store.create("/registry/pods/default/late", pod_wire("late"))
        # The contract under test: an "ok" is a DURABILITY ack, so
        # every acked write must survive replay (crash() must never
        # advance _synced_seq and silently ack a non-durable write).
        recovered = KVStore(data_dir=str(tmp_path))
        try:
            survived = {
                o["metadata"]["name"]
                for o in recovered.list("/registry/pods/")[0]
            }
            for kind, i in outcomes:
                if kind == "ok":
                    assert f"w{i}" in survived, (
                        f"acked write w{i} lost across crash+replay"
                    )
        finally:
            recovered.close()


# ---------------------------------------------------------------------------
# watch drop site + Reflector close-backoff
# ---------------------------------------------------------------------------


class TestWatchResilience:
    def test_forced_drop_forces_relist_and_converges(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        store = ThreadSafeStore()
        # Every push drops the stream for a while: the reflector must
        # ride its close-backoff + re-list path, then converge once the
        # storm budget is spent.
        faults.inject(faults.WATCH_DROP, every=1, times=6)
        refl = Reflector(client, "pods", store).start()
        try:
            assert refl.wait_for_sync()
            for i in range(5):
                client.create("pods", pod_wire(f"d{i}"))
            assert wait_until(lambda: len(store) == 5, timeout=30), (
                f"store never converged: {len(store)} of 5 "
                f"(drops fired: {faults.stats()})"
            )
        finally:
            refl.stop()

    def test_idle_close_backoff_does_not_tight_loop(self):
        """Consecutive empty watch closes back off instead of
        re-dialing instantly: with every push dropped, the number of
        watch re-establishments in a window stays small."""
        api = APIServer()
        opened = []
        real_watch = api.watch

        def counting_watch(*a, **k):
            opened.append(time.monotonic())
            return real_watch(*a, **k)

        api.watch = counting_watch
        client = Client(LocalTransport(api))
        store = ThreadSafeStore()
        faults.inject(faults.WATCH_DROP, every=1)  # unbounded storm
        refl = Reflector(client, "pods", store).start()
        try:
            assert refl.wait_for_sync()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 1.5:
                client.create("pods", pod_wire(f"s{time.monotonic_ns()}"))
                time.sleep(0.05)
            dials = len([t for t in opened if t >= t0])
            # Tight-looping re-dials hundreds of times in 1.5s; the
            # backoff (50ms doubling to 2s, re-list past 3 closes)
            # keeps it to a handful.
            assert dials <= 20, f"{dials} watch dials in 1.5s"
        finally:
            refl.stop()


# ---------------------------------------------------------------------------
# HTTP transport retries (the client-resilience satellite)
# ---------------------------------------------------------------------------


class TestHTTPRetries:
    @pytest.fixture
    def http_cluster(self):
        api = APIServer()
        srv = APIHTTPServer(api).start()
        yield api, srv
        srv.stop()

    def test_idempotent_get_retries_transient_5xx(self, http_cluster):
        api, srv = http_cluster
        client = Client(HTTPTransport(srv.address))
        client.create("pods", pod_wire("r1"), namespace="default")
        faults.inject(faults.HTTP_5XX, every=1, times=2)
        pod = client.get("pods", "r1", namespace="default")  # 2 injected 503s, then success
        assert pod.metadata.name == "r1"
        assert faults.stats()[faults.HTTP_5XX.name]["fired"] == 2

    def test_retry_budget_is_capped(self, http_cluster):
        api, srv = http_cluster
        client = Client(HTTPTransport(srv.address, max_retries=2))
        faults.inject(faults.HTTP_5XX, every=1)
        with pytest.raises(APIError) as ei:
            client.get("pods", "whatever", namespace="default")
        assert ei.value.code == 503
        # 1 initial + 2 retries, then give up.
        assert faults.stats()[faults.HTTP_5XX.name]["fired"] == 3

    def test_connection_reset_retries_idempotent_only(self, http_cluster):
        api, srv = http_cluster
        client = Client(HTTPTransport(srv.address))
        client.create("pods", pod_wire("r2"), namespace="default")
        faults.inject(faults.HTTP_RESET, every=1, times=1)
        assert client.get("pods", "r2", namespace="default").metadata.name == "r2"
        # POST fails fast: a replayed create could double-apply.
        faults.clear()
        faults.inject(faults.HTTP_RESET, every=1, times=1)
        with pytest.raises(ConnectionError):
            client.create("pods", pod_wire("r3"), namespace="default")

    def test_latency_site_delays_but_succeeds(self, http_cluster):
        api, srv = http_cluster
        client = Client(HTTPTransport(srv.address))
        client.create("pods", pod_wire("r4"), namespace="default")
        faults.inject(faults.HTTP_DELAY, every=1, times=3, delay_s=0.05)
        t0 = time.monotonic()
        assert client.get("pods", "r4", namespace="default").metadata.name == "r4"
        assert time.monotonic() - t0 >= 0.05


# ---------------------------------------------------------------------------
# kubelet heartbeat drop
# ---------------------------------------------------------------------------


class TestKubeletSites:
    def test_heartbeat_drop_skips_beats_without_killing_the_loop(self):
        from kubernetes_tpu.kubelet.agent import Kubelet
        from kubernetes_tpu.kubelet.runtime import FakeRuntime

        api = APIServer()
        kubelet = Kubelet(
            Client(LocalTransport(api)), node_name="hb-n0",
            runtime=FakeRuntime(), heartbeat_period=0.2,
        )
        kubelet.register_node()
        client = Client(LocalTransport(api))

        def beat_stamp():
            node = client.get("nodes", "hb-n0")
            return node.status.conditions[0].last_heartbeat_time

        kubelet._heartbeat()
        before = beat_stamp()
        faults.inject(faults.KUBELET_HEARTBEAT_DROP, every=1)
        time.sleep(1.1)
        kubelet._heartbeat()  # dropped: no write
        assert beat_stamp() == before
        faults.clear()
        time.sleep(1.1)  # now_iso has second granularity
        kubelet._heartbeat()
        assert beat_stamp() != before


# ---------------------------------------------------------------------------
# the soak harness
# ---------------------------------------------------------------------------


@pytest.mark.soak
class TestSoakHarness:
    def test_schedule_is_deterministic(self):
        from tools.soak import EPOCHS, build_schedule

        a = build_schedule(42, n_nodes=200)
        b = build_schedule(42, n_nodes=200)
        assert a == b
        assert [e["epoch"] for e in a] == list(EPOCHS)
        # Every armed rule names a REGISTERED site.
        for entry in a:
            if "rule" in entry:
                assert entry["rule"]["site"] in faults.SITES
        with pytest.raises(ValueError, match="unknown epoch"):
            build_schedule(1, epochs=["nope"])

    def test_mini_soak_apiserver_crash_epoch(self):
        """End-to-end miniature: hollow fleet + incremental daemon +
        an apiserver kill -9 (torn WAL write, crash, replay) — zero
        invariant violations, every wave pod bound, the mirror equal
        to the store across the restart."""
        from tools.soak import run_soak

        artifact = run_soak(
            n_nodes=6, seed=11,
            epochs=["baseline", "apiserver_restart"],
            fsync=False, verbose=False,
        )
        assert artifact["invariant_violations"] == [], artifact
        assert artifact["restarts"]["apiserver"] == 1
        assert artifact["pods_bound"] >= 64  # two 32-pod waves
        assert artifact["bind_p99_s"] is not None
        assert not faults.enabled()  # run_soak leaves the registry off
