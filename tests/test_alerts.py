"""Multi-window multi-burn-rate alert engine (utils/alerts.py).

State-machine unit tests on a private Retention + Registry with
explicit ``now=`` clocks: pending hold-down (for_s must elapse before
firing), flap suppression (a blip shorter than the hold-down lands
back at inactive, never fires), resolve hysteresis (resolve_s of
continuous quiet before resolved — and a re-trip mid-quiet resets the
clock), exactly-one Event per transition, the multi-window AND
condition, the burn multiplier applying to counter_rate rules only,
and the engine's miss/snapshot surfaces.
"""

import dataclasses

import pytest

from kubernetes_tpu.utils import alerts, metrics, timeseries

pytestmark = pytest.mark.health


def _rule(**kw):
    base = dict(
        name="lag_high",
        series="lag_versions",
        threshold=100.0,
        kind="gauge_max",
        windows=(alerts.BurnWindow(long_s=60.0, short_s=20.0, burn=1.0),),
        for_s=10.0,
        resolve_s=15.0,
        severity="page",
    )
    base.update(kw)
    return alerts.AlertRule(**base)


class _Plant:
    """A gauge series driven by hand: set(value, t) samples the
    registry into the retention ring at the given fake time, then
    eval(t) runs one engine pass."""

    def __init__(self, rule=None, clock_scale=1.0):
        self.reg = metrics.Registry()
        self.gauge = self.reg.gauge("lag_versions", "x")
        self.ret = timeseries.Retention()
        self.rule = rule or _rule()
        self.engine = alerts.AlertEngine(
            retention=self.ret, rules=(self.rule,), clock_scale=clock_scale
        )

    def set(self, value, t):
        self.gauge.set(float(value))
        self.ret.sample_now(registry=self.reg, now=t)

    def eval(self, t):
        return self.engine.evaluate(now=t)

    def state(self):
        return self.engine._state[self.rule.name]["state"]


class TestStateMachine:
    def test_pending_hold_down_then_firing(self):
        p = _Plant()
        p.set(10, 0.0)
        p.set(10, 5.0)
        assert p.eval(5.0) == []  # quiet: no state entry transition
        p.set(500, 10.0)
        out = p.eval(10.0)
        assert [t["to"] for t in out] == ["pending"]
        # Hold-down not elapsed: still pending, no new transition.
        p.set(500, 15.0)
        assert p.eval(15.0) == []
        assert p.state() == "pending"
        # for_s=10 elapsed since pending began at t=10.
        p.set(500, 21.0)
        out = p.eval(21.0)
        assert [t["to"] for t in out] == ["firing"]
        assert p.engine.firing() == ["lag_high"]

    def test_flap_suppression_pending_back_to_inactive(self):
        p = _Plant()
        p.set(10, 0.0)
        p.set(500, 5.0)
        assert [t["to"] for t in p.eval(5.0)] == ["pending"]
        # The blip clears before for_s elapses: back to inactive —
        # the hold-down ate the flap, nothing ever fired.
        p.set(10, 8.0)
        p.set(10, 9.0)
        # Shrink the windows' view by moving past them: set enough
        # quiet samples that max-over-window drops under threshold.
        for t in range(10, 75, 5):
            p.set(10, float(t))
        out = p.eval(74.0)
        assert [t["to"] for t in out] == ["inactive"]
        assert p.engine.firing() == []
        assert all(t["to"] != "firing" for t in p.engine.transitions())

    def _fire(self, p):
        p.set(10, 0.0)
        p.set(500, 5.0)
        p.eval(5.0)
        p.set(500, 16.0)
        p.eval(16.0)
        assert p.state() == "firing"

    def test_resolve_hysteresis(self):
        p = _Plant()
        self._fire(p)
        # Quiet from t=20 on. The spike at t=16 leaves the SHORT 20s
        # window after t=36 (the AND condition clears there even
        # though the long window still holds it), so the first quiet
        # eval is t=40 and resolve_s=15 lands resolution at t=55 —
        # every eval before that must stay firing.
        for t in range(20, 120, 5):
            p.set(10, float(t))
            p.eval(float(t))
            if t < 55:
                assert p.state() == "firing", t
        assert p.state() == "resolved"
        assert p.engine.firing() == []

    def test_retrip_during_quiet_resets_resolve_clock(self):
        p = _Plant()
        self._fire(p)
        # Quiet evals; the condition clears at t=38 (spike out of the
        # short window), starting the resolve clock.
        for t in (20.0, 26.0, 32.0, 38.0, 44.0):
            p.set(10, t)
            p.eval(t)
        assert p.state() == "firing"  # 44 - 38 = 6 < resolve_s
        # Re-trip inside the resolve window: clear_since must reset.
        p.set(500, 46.0)
        p.eval(46.0)
        assert p.state() == "firing"
        # Without the reset, the OLD clock (cleared t=38) would have
        # resolved at t=53 — these must all stay firing.
        for t in range(48, 62, 2):
            p.set(10, float(t))
            p.eval(float(t))
            assert p.state() == "firing", t
        # Full quiet: the re-trip leaves the short window after t=66,
        # and a FULL resolve_s later it finally resolves.
        for t in range(62, 120, 2):
            p.set(10, float(t))
            p.eval(float(t))
        assert p.state() == "resolved"
        # Exactly one resolved transition despite two quiet stretches.
        resolved = [
            t for t in p.engine.transitions() if t["to"] == "resolved"
        ]
        assert len(resolved) == 1

    def test_for_s_zero_fires_immediately(self):
        p = _Plant(rule=_rule(for_s=0.0))
        p.set(10, 0.0)
        p.set(500, 5.0)
        out = p.eval(5.0)
        assert [t["to"] for t in out] == ["firing"]

    def test_no_data_is_not_active(self):
        p = _Plant()
        assert p.eval(0.0) == []
        assert p.engine.firing() == []


class TestCondition:
    def test_long_window_alone_does_not_trip(self):
        # Short window quiet + long window hot = recovering incident:
        # must NOT (re-)trip. Drive it directly on the condition.
        p = _Plant(rule=_rule(windows=(
            alerts.BurnWindow(long_s=60.0, short_s=10.0, burn=1.0),
        )))
        p.set(500, 0.0)   # hot sample, old
        p.set(500, 5.0)
        p.set(10, 45.0)   # short window (35..45] sees only quiet
        p.set(10, 44.0)
        active, value, hit = p.engine._condition(p.rule, now=45.0)
        assert not active and hit is None

    def test_burn_multiplier_scales_counter_rate_only(self):
        w = alerts.BurnWindow(long_s=60.0, short_s=20.0, burn=10.0)
        gauge_rule = _rule(threshold=100.0, windows=(w,))
        rate_rule = _rule(
            name="drops", series="d_total", kind="counter_rate",
            threshold=1.0, windows=(w,),
        )
        reg = metrics.Registry()
        g = reg.gauge("lag_versions", "x")
        c = reg.counter("d_total", "x")
        ret = timeseries.Retention()
        eng = alerts.AlertEngine(
            retention=ret, rules=(gauge_rule, rate_rule)
        )
        # Gauge at 150 (> 100): trips with burn=10 untouched (the
        # threshold is NOT multiplied to 1000 for gauge_max).
        g.set(150.0)
        c.inc(5)  # 5/s over 10s? no: 50 increments below
        ret.sample_now(registry=reg, now=0.0)
        g.set(150.0)
        c.inc(50)  # 50 over 10s = 5/s — above 1.0 but BELOW 1.0*10
        ret.sample_now(registry=reg, now=10.0)
        active, _v, hit = eng._condition(gauge_rule, now=10.0)
        assert active and hit["threshold"] == 100.0
        active, _v, _hit = eng._condition(rate_rule, now=10.0)
        assert not active  # 5/s <= burn-scaled 10/s

    def test_any_window_pair_suffices(self):
        # Slow pair trips even when the fast pair sees nothing (its
        # windows hold < 2 samples).
        fast = alerts.BurnWindow(long_s=4.0, short_s=1.0, burn=1.0)
        slow = alerts.BurnWindow(long_s=60.0, short_s=30.0, burn=1.0)
        p = _Plant(rule=_rule(windows=(fast, slow)))
        p.set(500, 0.0)
        p.set(500, 20.0)
        active, _v, hit = p.engine._condition(p.rule, now=40.0)
        assert active and hit["longS"] == 60.0

    def test_worst_label_set_carries_the_rule(self):
        reg = metrics.Registry()
        g = reg.gauge("lag_versions", "x", ("follower",))
        ret = timeseries.Retention()
        rule = _rule()
        eng = alerts.AlertEngine(retention=ret, rules=(rule,))
        g.set(10.0, follower="f1")
        g.set(900.0, follower="f2")
        ret.sample_now(registry=reg, now=0.0)
        ret.sample_now(registry=reg, now=10.0)
        active, value, _hit = eng._condition(rule, now=10.0)
        assert active and value == 900.0


class _EventStub:
    def __init__(self):
        self.calls = []

    def record_event(self, involved, reason="", message="", source=""):
        self.calls.append((involved["metadata"]["name"], reason, message))


class TestEvents:
    def test_exactly_one_event_per_transition(self):
        p = _Plant(rule=_rule(for_s=0.0, resolve_s=10.0))
        stub = _EventStub()
        p.engine.attach_events(stub)
        p.set(10, 0.0)
        p.set(500, 5.0)
        p.eval(5.0)
        # Steady firing: repeated evaluations post nothing new.
        for t in range(6, 12):
            p.set(500, float(t))
            p.eval(float(t))
        assert [c[1] for c in stub.calls] == ["AlertFiring"]
        # Age out + hysteresis: exactly one AlertResolved.
        for t in range(12, 120, 2):
            p.set(10, float(t))
            p.eval(float(t))
        assert [c[1] for c in stub.calls] == ["AlertFiring", "AlertResolved"]
        name, _reason, msg = stub.calls[0]
        assert name == "lag_high"
        assert "inactive -> firing" in msg and "severity page" in msg

    def test_event_poster_exception_never_blocks_the_machine(self):
        p = _Plant(rule=_rule(for_s=0.0))

        class Boom:
            def record_event(self, *a, **kw):
                raise RuntimeError("broadcaster down")

        p.engine.attach_events(Boom())
        p.set(10, 0.0)
        p.set(500, 5.0)
        out = p.eval(5.0)
        assert [t["to"] for t in out] == ["firing"]


class TestEngineSurfaces:
    def test_miss_contract_needs_evals_and_samples(self):
        eng = alerts.AlertEngine(retention=timeseries.Retention())
        assert not eng.sampled  # zero evaluations
        eng.evaluate(now=0.0)
        assert not eng.sampled  # evaluated, but retention never sampled
        p = _Plant()
        p.set(1, 0.0)
        assert not p.engine.sampled
        p.eval(0.0)
        assert p.engine.sampled

    def test_snapshot_shape(self):
        p = _Plant(rule=_rule(for_s=0.0))
        p.set(10, 0.0)
        p.set(500, 5.0)
        p.eval(5.0)
        snap = p.engine.snapshot()
        assert snap["kind"] == "AlertReport"
        assert snap["sampled"] and snap["firing"] == ["lag_high"]
        (row,) = snap["rules"]
        assert row["name"] == "lag_high"
        assert row["state"] == "firing"
        assert row["severity"] == "page"
        assert row["value"] == 500.0
        assert row["trippedWindow"]["longS"] == 60.0
        assert snap["transitions"][-1]["to"] == "firing"

    def test_clock_scale_compresses_everything(self):
        # Scale 0.1: for_s=10 becomes 1s, windows 60/20 become 6/2.
        p = _Plant(clock_scale=0.1)
        p.set(500, 0.0)
        p.set(500, 1.0)
        assert [t["to"] for t in p.eval(1.0)] == ["pending"]
        p.set(500, 2.1)
        assert [t["to"] for t in p.eval(2.1)] == ["firing"]

    def test_configure_resets_state(self):
        p = _Plant(rule=_rule(for_s=0.0))
        p.set(10, 0.0)
        p.set(500, 5.0)
        p.eval(5.0)
        assert p.engine.firing()
        p.engine.configure(rules=(p.rule,))
        assert p.engine.firing() == []
        assert p.engine.transitions() == []
        assert not p.engine.sampled

    def test_transitions_ring_is_bounded(self):
        p = _Plant(rule=_rule(for_s=0.0, resolve_s=0.0))
        eng = p.engine
        # Flip the state by hand through _transition to fill the ring.
        st = {"state": "inactive", "since": 0.0, "clear_since": None}
        for i in range(eng.MAX_TRANSITIONS + 40):
            eng._transition(
                st, p.rule, "firing" if i % 2 == 0 else "resolved",
                float(i), 1.0,
            )
        assert len(eng.transitions()) == eng.MAX_TRANSITIONS


class TestDefaultRules:
    def test_default_rules_cover_the_published_objectives(self):
        names = {r.name for r in alerts.DEFAULT_RULES}
        assert names == {
            "bind_latency_burn",
            "watch_fanout_lag",
            "watch_drop_storm",
            "replication_follower_lag",
            "lease_renew_latency",
            "backlog_pressure",
            "fragmentation_burn",
        }
        for r in alerts.DEFAULT_RULES:
            assert r.windows == (alerts.FAST, alerts.SLOW)
            assert r.for_s > 0 and r.resolve_s > 0
            assert r.kind in ("quantile", "counter_rate", "gauge_max")

    def test_published_burn_windows(self):
        # The SRE-workbook pairs: 1h/5m at 14.4x and 6h/30m at 6x.
        assert (alerts.FAST.long_s, alerts.FAST.short_s) == (3600.0, 300.0)
        assert alerts.FAST.burn == 14.4
        assert (alerts.SLOW.long_s, alerts.SLOW.short_s) == (21600.0, 1800.0)
        assert alerts.SLOW.burn == 6.0

    def test_rules_are_immutable_replace_to_tune(self):
        r = alerts.DEFAULT_RULES[0]
        with pytest.raises(dataclasses.FrozenInstanceError):
            r.threshold = 0.0
        tuned = dataclasses.replace(r, threshold=0.123)
        assert tuned.threshold == 0.123 and tuned.name == r.name
