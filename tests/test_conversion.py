"""Multi-version API: v1beta3 <-> v1 conversion at the HTTP boundary.

Reference: pkg/api/latest/latest.go:32-78 (version negotiation),
pkg/api/v1beta3/conversion.go (host/nodeName, portalIP/clusterIP,
createExternalLoadBalancer/type)."""

import json
import urllib.request

import pytest

from kubernetes_tpu.models import conversion
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer


class TestWireConversion:
    def test_pod_host_to_nodename(self):
        wire = {
            "kind": "Pod",
            "apiVersion": "v1beta3",
            "spec": {"host": "n1", "containers": []},
        }
        out = conversion.to_internal(wire, "v1beta3")
        assert out["spec"]["nodeName"] == "n1"
        assert "host" not in out["spec"]
        assert out["apiVersion"] == "v1"
        back = conversion.from_internal(out, "v1beta3")
        assert back["spec"]["host"] == "n1"
        assert "nodeName" not in back["spec"]

    def test_service_portal_ip_and_lb_bool(self):
        wire = {
            "kind": "Service",
            "apiVersion": "v1beta3",
            "spec": {
                "portalIP": "10.0.0.1",
                "createExternalLoadBalancer": True,
                "publicIPs": ["1.2.3.4"],
            },
        }
        out = conversion.to_internal(wire, "v1beta3")
        assert out["spec"]["clusterIP"] == "10.0.0.1"
        assert out["spec"]["type"] == "LoadBalancer"
        assert out["spec"]["externalIPs"] == ["1.2.3.4"]
        back = conversion.from_internal(out, "v1beta3")
        assert back["spec"]["portalIP"] == "10.0.0.1"
        assert back["spec"]["createExternalLoadBalancer"] is True
        assert back["spec"]["publicIPs"] == ["1.2.3.4"]

    def test_rc_template_host_converts(self):
        wire = {
            "kind": "ReplicationController",
            "spec": {
                "replicas": 1,
                "template": {"spec": {"host": "n2", "containers": []}},
            },
        }
        out = conversion.to_internal(wire, "v1beta3")
        assert out["spec"]["template"]["spec"]["nodeName"] == "n2"

    def test_list_items_convert(self):
        wire = {
            "kind": "PodList",
            "items": [
                {"kind": "Pod", "spec": {"nodeName": "n1"}},
                {"kind": "Pod", "spec": {"nodeName": "n2"}},
            ],
        }
        out = conversion.from_internal(wire, "v1beta3")
        assert [i["spec"]["host"] for i in out["items"]] == ["n1", "n2"]

    def test_unknown_version_rejected(self):
        with pytest.raises(ValueError):
            conversion.to_internal({}, "v1beta9")

    def test_v1_is_identity(self):
        wire = {"kind": "Pod", "spec": {"nodeName": "n1"}}
        assert conversion.to_internal(wire, "v1") is wire
        assert conversion.from_internal(wire, "v1") is wire


class TestHTTPVersionNegotiation:
    @pytest.fixture
    def server(self):
        srv = APIHTTPServer(APIServer()).start()
        yield srv
        srv.stop()

    def _req(self, base, method, path, body=None):
        req = urllib.request.Request(
            base + path,
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"},
        )
        return json.loads(urllib.request.urlopen(req).read())

    def test_api_lists_both_versions(self, server):
        out = self._req(server.address, "GET", "/api")
        assert out["versions"] == ["v1", "v1beta3"]

    def test_create_v1beta3_read_v1(self, server):
        """A legacy client POSTs v1beta3 (spec.host); a modern client
        reads the same pod as v1 (spec.nodeName)."""
        self._req(
            server.address,
            "POST",
            "/api/v1beta3/namespaces/default/pods",
            {
                "kind": "Pod",
                "apiVersion": "v1beta3",
                "metadata": {"name": "legacy"},
                "spec": {"host": "n1", "containers": [{"name": "c", "image": "x"}]},
            },
        )
        v1 = self._req(
            server.address, "GET", "/api/v1/namespaces/default/pods/legacy"
        )
        assert v1["spec"]["nodeName"] == "n1"
        assert "host" not in v1["spec"]

    def test_kindless_v1beta3_body_still_converts(self, server):
        """The API accepts kind-less bodies (kind defaults from the
        path); conversion must still fire via the route's kind hint."""
        self._req(
            server.address,
            "POST",
            "/api/v1beta3/namespaces/default/pods",
            {
                "metadata": {"name": "kindless"},
                "spec": {"host": "n9", "containers": [{"name": "c", "image": "x"}]},
            },
        )
        v1 = self._req(
            server.address, "GET", "/api/v1/namespaces/default/pods/kindless"
        )
        assert v1["spec"]["nodeName"] == "n9"
        assert "host" not in v1["spec"]

    def test_read_v1beta3_of_v1_object(self, server):
        self._req(
            server.address,
            "POST",
            "/api/v1/namespaces/default/services",
            {
                "kind": "Service",
                "metadata": {"name": "svc"},
                "spec": {
                    "clusterIP": "10.0.0.3",
                    "type": "LoadBalancer",
                    "selector": {"a": "b"},
                    "ports": [{"name": "p", "port": 80}],
                },
            },
        )
        beta = self._req(
            server.address, "GET", "/api/v1beta3/namespaces/default/services/svc"
        )
        assert beta["spec"]["portalIP"] == "10.0.0.3"
        assert beta["spec"]["createExternalLoadBalancer"] is True
        assert beta["apiVersion"] == "v1beta3"

    def test_v1beta3_list(self, server):
        self._req(
            server.address,
            "POST",
            "/api/v1/namespaces/default/pods",
            {
                "kind": "Pod",
                "metadata": {"name": "p1"},
                "spec": {
                    "nodeName": "nx",
                    "containers": [{"name": "c", "image": "x"}],
                },
            },
        )
        out = self._req(
            server.address, "GET", "/api/v1beta3/namespaces/default/pods"
        )
        assert out["items"][0]["spec"]["host"] == "nx"

    def test_unknown_version_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            self._req(server.address, "GET", "/api/v2/pods")
        assert e.value.code == 404
