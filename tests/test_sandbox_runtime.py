"""Sandbox runtime: namespace-isolated pods + image store + image GC.

The second real backend behind the kubelet runtime seam — the role
rkt plays for the reference (pkg/kubelet/rkt/rkt.go proves
pkg/kubelet/container/runtime.go:304 supports more than one real
runtime). Assertions here check the ISOLATION is real (PID namespace:
/proc/1 is the pause anchor; UTS: hostname == pod name) and that the
image substrate feeds the kubelet's ImageManager
(pkg/kubelet/image_manager.go analog).
"""

import time

import pytest

from kubernetes_tpu.kubelet.sandbox_runtime import (
    ImageStore,
    SandboxRuntime,
    sandbox_supported,
)
from kubernetes_tpu.kubelet.managers import ImageManager
from kubernetes_tpu.models.objects import Container, ObjectMeta, Pod, PodSpec

needs_sandbox = pytest.mark.skipif(
    not sandbox_supported(), reason="needs root + unshare/nsenter"
)


def mk_pod(name, command, image="app", uid=""):
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default", uid=uid or name),
        spec=PodSpec(
            containers=[Container(name="main", image=image, command=command)]
        ),
    )


def wait_for(cond, timeout=5.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


@pytest.fixture
def runtime(tmp_path):
    rt = SandboxRuntime(str(tmp_path / "kubelet"), node_name="n1")
    yield rt
    for uid in list(rt.list_pods()):
        rt.kill_pod(uid)


@needs_sandbox
class TestIsolation:
    def test_pid_namespace_and_uts_hostname(self, runtime):
        pod = mk_pod("iso-pod", ["sleep", "60"])
        cs = runtime.sync_pod(pod)
        assert wait_for(
            lambda: all(
                c.state == "running" for c in runtime.sync_pod(pod)
            )
        )
        assert cs[0].container_id.startswith("sandbox://")
        # Inside the pod: PID 1 is the pod's own anchor, not the host
        # init — the kernel-enforced proof of a private PID namespace.
        rc, out = runtime.exec_in_container(
            "iso-pod", "main", ["cat", "/proc/1/comm"], pod=pod
        )
        assert rc == 0
        assert out.strip() in ("pause", "python", "python3"), out
        # UTS namespace: the pod sees its own hostname (reference infra-
        # container hostname semantics), the host's is untouched.
        rc, out = runtime.exec_in_container(
            "iso-pod", "main", ["hostname"], pod=pod
        )
        assert rc == 0
        assert out.strip() == "iso-pod"
        import socket

        assert socket.gethostname() != "iso-pod"

    def test_pod_processes_invisible_to_other_pods(self, runtime):
        a = mk_pod("pod-a", ["sleep", "61"])
        b = mk_pod("pod-b", ["sleep", "62"])
        runtime.sync_pod(a)
        runtime.sync_pod(b)
        assert wait_for(
            lambda: all(c.state == "running" for c in runtime.sync_pod(a))
            and all(c.state == "running" for c in runtime.sync_pod(b))
        )
        # pod-a's /proc (private mount of its PID ns) must not show
        # pod-b's sleep 62.
        rc, out = runtime.exec_in_container(
            "pod-a", "main",
            ["sh", "-c", "cat /proc/[0-9]*/cmdline | tr '\\0' ' '"],
            pod=a,
        )
        assert rc == 0
        assert "sleep 61" in out
        assert "sleep 62" not in out

    def test_kill_pod_reaps_the_whole_namespace(self, runtime):
        # A container that double-forks a stray child: PID-namespace
        # teardown must reap it anyway (ns PID 1 death SIGKILLs all).
        pod = mk_pod(
            "spawner",
            ["sh", "-c", "sleep 90 & exec sleep 63"],
        )
        runtime.sync_pod(pod)
        assert wait_for(
            lambda: all(c.state == "running" for c in runtime.sync_pod(pod))
        )
        anchor = runtime._anchors["spawner"]
        inner = runtime._inner_pid(anchor)
        assert inner is not None
        runtime.kill_pod("spawner")
        import subprocess

        def gone():
            out = subprocess.run(
                ["pgrep", "-f", "sleep 9[0]"], capture_output=True, text=True
            )
            return out.returncode != 0

        assert wait_for(gone, timeout=5), "stray child survived kill_pod"

    def test_restart_policy_cycle(self, runtime):
        pod = mk_pod("boom", ["sh", "-c", "exit 3"])
        cs = runtime.sync_pod(pod)
        assert wait_for(
            lambda: all(c.state == "exited" for c in runtime.sync_pod(pod))
        )
        runtime.restart_container("boom", "main")
        cs = runtime.sync_pod(pod)
        assert cs[0].restart_count == 1

    def test_adoption_across_runtime_restart(self, runtime, tmp_path):
        pod = mk_pod("adoptee", ["sleep", "64"])
        runtime.sync_pod(pod)
        assert wait_for(
            lambda: all(c.state == "running" for c in runtime.sync_pod(pod))
        )
        rt2 = SandboxRuntime(str(tmp_path / "kubelet"), node_name="n1")
        try:
            pods = rt2.list_pods()
            assert "adoptee" in pods
            # The adopted pod's namespaces still work for exec.
            rc, out = rt2.exec_in_container(
                "adoptee", "main", ["hostname"], pod=pod
            )
            assert rc == 0 and out.strip() == "adoptee"
        finally:
            rt2.kill_pod("adoptee")


@needs_sandbox
class TestImageSubstrate:
    def test_pull_on_start_and_lru_gc(self, runtime):
        pod = mk_pod("img-pod", ["sleep", "65"], image="registry/web:v1")
        runtime.sync_pod(pod)
        images = {rec["image"] for rec in runtime.images.list_images()}
        assert "registry/web:v1" in images
        assert "pause" in images or len(images) >= 1

    def test_image_manager_evicts_lru_not_in_use(self, tmp_path):
        store = ImageStore(str(tmp_path / "images"))
        store.pull("old:v1")
        time.sleep(0.02)
        store.pull("live:v1")
        time.sleep(0.02)
        store.pull("new:v1")
        used = store.bytes_used()
        # Budget forces eviction of exactly the LRU unused image(s).
        mgr = ImageManager(store, high_bytes=used - 1, low_bytes=used - 1)
        freed = mgr.gc(in_use={"live:v1"})
        assert freed > 0
        remaining = {rec["image"] for rec in store.list_images()}
        assert "live:v1" in remaining  # in-use is never evicted
        assert "old:v1" not in remaining  # LRU went first

    def test_under_high_watermark_is_a_noop(self, tmp_path):
        store = ImageStore(str(tmp_path / "images"))
        store.pull("a:v1")
        mgr = ImageManager(
            store, high_bytes=store.bytes_used() + 1, low_bytes=0
        )
        assert mgr.gc(in_use=set()) == 0
        assert {rec["image"] for rec in store.list_images()} == {"a:v1"}


@needs_sandbox
class TestKubeletIntegration:
    def test_kubelet_runs_pod_on_sandbox_runtime(self, tmp_path):
        """Full seam check: a kubelet over the sandbox runtime takes a
        bound pod to Running with status writeback, and its
        housekeeping has an ImageManager wired."""
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.kubelet.agent import Kubelet
        from kubernetes_tpu.server.api import APIServer

        api = APIServer()
        client = Client(LocalTransport(api))
        kubelet = Kubelet(
            client,
            node_name="sandbox-node",
            runtime=SandboxRuntime(str(tmp_path / "kubelet"), "sandbox-node"),
            root_dir=str(tmp_path / "kubelet"),
        ).start()
        try:
            assert kubelet.image_manager is not None
            wire = {
                "kind": "Pod",
                "metadata": {"name": "sb-pod", "namespace": "default"},
                "spec": {
                    "nodeName": "sandbox-node",
                    "containers": [
                        {
                            "name": "c",
                            "image": "app:v1",
                            "command": ["sleep", "66"],
                        }
                    ],
                },
            }
            client.create("pods", wire)

            def running():
                p = client.get("pods", "sb-pod", namespace="default")
                return p.status.phase == "Running"

            assert wait_for(running, timeout=15)
            p = client.get("pods", "sb-pod", namespace="default")
            assert p.status.container_statuses[0].container_id.startswith(
                "sandbox://"
            )
        finally:
            kubelet.stop()
            for uid in list(kubelet.runtime.list_pods()):
                kubelet.runtime.kill_pod(uid)
