"""ktctl CLI tests (reference analog: hack/test-cmd.sh golden tests)."""

import io
import json
import sys

import pytest

from kubernetes_tpu.cli.ktctl import main
from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.server import APIServer


@pytest.fixture
def env(tmp_path):
    api = APIServer()
    client = Client(LocalTransport(api))
    def run(*argv, expect=0):
        out = io.StringIO()
        old = sys.stdout
        sys.stdout = out
        try:
            rc = main(list(argv), client=client)
        finally:
            sys.stdout = old
        assert rc == expect, out.getvalue()
        return out.getvalue()
    return api, client, run, tmp_path


RC_YAML = """
kind: ReplicationController
metadata:
  name: web
spec:
  replicas: 2
  selector: {app: web}
  template:
    metadata:
      labels: {app: web}
    spec:
      containers:
      - name: main
        image: nginx
        resources:
          limits: {cpu: 100m, memory: 64Mi}
"""


def test_create_get_table(env, tmp_path):
    api, client, run, _ = env
    f = tmp_path / "rc.yaml"
    f.write_text(RC_YAML)
    out = run("create", "-f", str(f))
    assert "replicationcontrollers/web created" in out
    out = run("get", "rc")
    assert "web" in out and "DESIRED" in out
    out = run("get", "rc", "web", "-o", "json")
    assert json.loads(out)["spec"]["replicas"] == 2


def test_apply_update(env, tmp_path):
    api, client, run, _ = env
    f = tmp_path / "rc.yaml"
    f.write_text(RC_YAML)
    run("apply", "-f", str(f))
    f.write_text(RC_YAML.replace("replicas: 2", "replicas: 4"))
    out = run("apply", "-f", str(f))
    assert "configured" in out
    assert client.get("replicationcontrollers", "web").spec.replicas == 4


def test_scale_and_delete(env, tmp_path):
    api, client, run, _ = env
    f = tmp_path / "rc.yaml"
    f.write_text(RC_YAML)
    run("create", "-f", str(f))
    out = run("scale", "rc", "web", "--replicas", "5")
    assert "scaled to 5" in out
    assert client.get("replicationcontrollers", "web").spec.replicas == 5
    run("delete", "rc", "web")
    out = run("get", "rc", "missing", expect=1)


def test_run_expose_describe(env):
    api, client, run, _ = env
    run("run", "app1", "--image", "nginx", "-r", "3")
    rc = client.get("replicationcontrollers", "app1")
    assert rc.spec.replicas == 3
    out = run("expose", "rc", "app1", "--port", "80")
    assert "exposed" in out
    svc = client.get("services", "app1")
    assert svc.spec.selector == {"run": "app1"}
    out = run("describe", "rc", "app1")
    assert "app1" in out and "replicas" in out


def test_label_and_selector_get(env):
    api, client, run, _ = env
    client.create("pods", {
        "kind": "Pod", "metadata": {"name": "p1", "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": "x"}]},
    })
    run("label", "pod", "p1", "tier=web")
    assert client.get("pods", "p1").metadata.labels == {"tier": "web"}
    # Overwrite protection without --overwrite.
    with pytest.raises(SystemExit):
        main(["label", "pod", "p1", "tier=db"], client=client)
    run("label", "pod", "p1", "tier=db", "--overwrite")
    assert client.get("pods", "p1").metadata.labels == {"tier": "db"}
    out = run("get", "pods", "--selector", "tier=db")
    assert "p1" in out
    run("label", "pod", "p1", "tier-")
    assert client.get("pods", "p1").metadata.labels == {}


def test_nodes_and_api_resources(env):
    api, client, run, _ = env
    client.create("nodes", {
        "kind": "Node", "metadata": {"name": "n1"},
        "status": {"capacity": {"cpu": "4", "memory": "8Gi"},
                   "conditions": [{"type": "Ready", "status": "True"}]},
    })
    out = run("get", "nodes")
    assert "n1" in out and "Ready" in out
    out = run("api-resources")
    assert "pods" in out and "replicationcontrollers" in out


def test_yaml_output_roundtrip(env, tmp_path):
    api, client, run, _ = env
    f = tmp_path / "rc.yaml"
    f.write_text(RC_YAML)
    run("create", "-f", str(f))
    out = run("get", "rc", "web", "-o", "yaml")
    import yaml as _yaml

    doc = _yaml.safe_load(out)
    assert doc["spec"]["template"]["spec"]["containers"][0]["image"] == "nginx"
