"""Port-forward tunnel + pod proxy subresource, end to end.

Reference: pkg/kubelet/server.go /portForward, pkg/registry/pod/etcd/
etcd.go:47-49 (proxy + portForward subresources), pkg/client/
portforward + pkg/kubectl/cmd/portforward.go. The streams here are
websocket tunnels: ktctl <-> apiserver <-> kubelet <-> container TCP."""

import socket
import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.client.rest import Client, LocalTransport
from kubernetes_tpu.kubelet.agent import Kubelet
from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer


def wait_for(cond, timeout=10.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def cluster(tmp_path):
    api = APIServer()
    srv = APIHTTPServer(api).start()
    client = Client(LocalTransport(api))
    runtime = ProcessRuntime(str(tmp_path / "kubelet"), node_name="node-1")
    kubelet = Kubelet(
        Client(LocalTransport(api)),
        node_name="node-1",
        runtime=runtime,
        heartbeat_period=0.5,
        sync_period=0.2,
        serve_http=True,
    ).start()
    yield api, srv, client, runtime
    kubelet.stop()
    for uid in list(runtime.list_pods()):
        runtime.kill_pod(uid)
    srv.stop()


def start_web_pod(client, runtime, name, port):
    client.create(
        "pods",
        {
            "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "nodeName": "node-1",
                "containers": [
                    {
                        "name": "web",
                        "image": "httpd",
                        "command": [
                            "python3", "-m", "http.server", str(port),
                            "--bind", "127.0.0.1",
                        ],
                        "ports": [{"containerPort": port}],
                    }
                ],
            },
        },
        namespace="default",
    )

    def serving():
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.2):
                return True
        except OSError:
            return False

    assert wait_for(serving), "web pod never started serving"


class TestPortForward:
    def test_tunnel_through_apiserver(self, cluster):
        from kubernetes_tpu.cli.ktctl import forward_port

        api, srv, client, runtime = cluster
        backend_port = free_port()
        start_web_pod(client, runtime, "webpf", backend_port)

        ready = threading.Event()
        stop = threading.Event()
        t = threading.Thread(
            target=forward_port,
            args=(srv.address, "webpf", 0, backend_port),
            kwargs={"ready_event": ready, "stop_event": stop},
            daemon=True,
        )
        t.start()
        assert ready.wait(5)
        local = ready.port
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{local}/", timeout=10
            ).read()
            # http.server directory listing always mentions itself.
            assert b"Directory listing" in body or b"<html" in body.lower()
            # Second connection through the same forwarder.
            body2 = urllib.request.urlopen(
                f"http://127.0.0.1:{local}/", timeout=10
            ).read()
            assert body2 == body
        finally:
            stop.set()
            t.join(timeout=3)

    def test_forward_to_dead_port_fails_cleanly(self, cluster):
        from kubernetes_tpu.utils import websocket as ws

        api, srv, client, runtime = cluster
        backend_port = free_port()
        start_web_pod(client, runtime, "deadpf", backend_port)
        dead = free_port()
        import urllib.parse as up

        parsed = up.urlparse(srv.address)
        with pytest.raises(ConnectionError):
            ws.WebSocketClient(
                parsed.hostname,
                parsed.port,
                f"/api/v1/namespaces/default/pods/deadpf/portforward"
                f"?port={dead}",
            )


class TestNodeProxy:
    def test_node_proxy_reaches_kubelet_api(self, cluster):
        """GET /nodes/{n}/proxy/stats relays to the node's kubelet
        (reference: apiserver dials node:10250, master.go:497-520)."""
        import json as _json

        api, srv, client, runtime = cluster
        backend_port = free_port()
        start_web_pod(client, runtime, "statpod", backend_port)
        body = urllib.request.urlopen(
            f"{srv.address}/api/v1/nodes/node-1/proxy/stats", timeout=10
        ).read()
        stats = _json.loads(body)
        assert stats["nodeName"] == "node-1"
        healthz = urllib.request.urlopen(
            f"{srv.address}/api/v1/nodes/node-1/proxy/healthz", timeout=10
        ).read()
        assert healthz == b"ok"


class TestPodProxy:
    def test_proxy_get_through_apiserver(self, cluster):
        api, srv, client, runtime = cluster
        backend_port = free_port()
        start_web_pod(client, runtime, "webproxy", backend_port)
        body = urllib.request.urlopen(
            f"{srv.address}/api/v1/namespaces/default/pods/webproxy/proxy/",
            timeout=10,
        ).read()
        assert b"Directory listing" in body or b"<html" in body.lower()

    def test_proxy_with_explicit_port(self, cluster):
        api, srv, client, runtime = cluster
        backend_port = free_port()
        start_web_pod(client, runtime, "webport", backend_port)
        body = urllib.request.urlopen(
            f"{srv.address}/api/v1/namespaces/default/pods/"
            f"webport:{backend_port}/proxy/",
            timeout=10,
        ).read()
        assert b"Directory listing" in body or b"<html" in body.lower()

    def test_proxy_404_passthrough(self, cluster):
        api, srv, client, runtime = cluster
        backend_port = free_port()
        start_web_pod(client, runtime, "web404", backend_port)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"{srv.address}/api/v1/namespaces/default/pods/web404/"
                "proxy/no-such-file",
                timeout=10,
            )
        assert e.value.code == 404
