"""DNS addon + debug endpoints.

Reference: cluster/addons/dns (skydns + kube2sky), pkg/httplog,
net/http/pprof."""

import socket
import struct
import time
import urllib.request

import pytest

from kubernetes_tpu.addons.dns import ClusterDNS, build_response, parse_query
from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer


def dns_query(port, name, timeout=2.0, host="127.0.0.1"):
    """Send one A query with the stdlib only; return resolved IP or
    None (NXDOMAIN)."""
    qname = b"".join(
        bytes([len(p)]) + p.encode() for p in name.strip(".").split(".")
    ) + b"\x00"
    q = struct.pack(">HHHHHH", 0x1234, 0x0100, 1, 0, 0, 0)
    q += qname + struct.pack(">HH", 1, 1)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout)
    try:
        s.sendto(q, (host, port))
        data, _ = s.recvfrom(512)
    finally:
        s.close()
    txid, flags, qd, an, _, _ = struct.unpack(">HHHHHH", data[:12])
    assert txid == 0x1234
    assert flags & 0x8000  # response bit
    if an == 0:
        assert flags & 0x000F == 3  # NXDOMAIN
        return None
    return socket.inet_ntoa(data[-4:])


def service_wire(name, ip, ns="default"):
    return {
        "kind": "Service",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "selector": {"app": name},
            "ports": [{"name": "http", "port": 80}],
            "clusterIP": ip,
        },
    }


class TestClusterDNS:
    @pytest.fixture
    def dns(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        client.create("services", service_wire("web", "10.0.0.10"))
        server = ClusterDNS(Client(LocalTransport(api))).start()
        yield server, client
        server.stop()

    def test_resolves_service_fqdn(self, dns):
        server, client = dns
        assert (
            dns_query(server.port, "web.default.svc.cluster.local")
            == "10.0.0.10"
        )

    def test_resolves_short_form(self, dns):
        server, client = dns
        assert dns_query(server.port, "web.default") == "10.0.0.10"

    def test_nxdomain_for_unknown(self, dns):
        server, client = dns
        assert dns_query(server.port, "nope.default.svc.cluster.local") is None

    def test_tracks_service_churn(self, dns):
        server, client = dns
        client.create("services", service_wire("api", "10.0.0.20"))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if dns_query(server.port, "api.default") == "10.0.0.20":
                break
            time.sleep(0.05)
        assert dns_query(server.port, "api.default") == "10.0.0.20"
        client.delete("services", "api", namespace="default")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if dns_query(server.port, "api.default") is None:
                break
            time.sleep(0.05)
        assert dns_query(server.port, "api.default") is None

    def test_wire_roundtrip_units(self):
        q = struct.pack(">HHHHHH", 7, 0x0100, 1, 0, 0, 0)
        q += b"\x03web\x07default\x00" + struct.pack(">HH", 1, 1)
        parsed = parse_query(q)
        assert parsed is not None
        txid, flags, qname, qtype, question = parsed
        assert (txid, qname, qtype) == (7, "web.default", 1)
        resp = build_response(txid, flags, question, "1.2.3.4")
        assert socket.inet_ntoa(resp[-4:]) == "1.2.3.4"


class TestDebugEndpoints:
    @pytest.fixture
    def server(self):
        srv = APIHTTPServer(APIServer()).start()
        yield srv
        srv.stop()

    def test_request_log_records(self, server):
        urllib.request.urlopen(server.address + "/api/v1/nodes").read()
        body = urllib.request.urlopen(
            server.address + "/debug/requests"
        ).read().decode()
        assert "/api/v1/nodes" in body
        assert "GET" in body

    def test_stack_dump(self, server):
        body = urllib.request.urlopen(
            server.address + "/debug/stacks"
        ).read().decode()
        assert "--- thread" in body
        assert "serve_forever" in body  # the serving thread is visible

    def test_sampling_profile(self, server):
        body = urllib.request.urlopen(
            server.address + "/debug/profile?seconds=0.3"
        ).read().decode()
        assert "sampling profile:" in body
        assert "samples over" in body

    def test_unknown_debug_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(server.address + "/debug/nope")
        assert e.value.code == 404


class TestKubeDNSService:
    """The DNS addon published as the well-known kube-dns service
    (cluster/addons/dns skydns-svc.yaml pins 10.0.0.10): with a
    real-portal kube-proxy, VIP:53/UDP actually answers queries."""

    def test_dns_reachable_at_the_well_known_vip(self):
        from kubernetes_tpu.addons import ClusterDNS
        from kubernetes_tpu.proxy.config import ProxyServer
        from kubernetes_tpu.proxy.portal import LoopbackPortals

        if not LoopbackPortals.supported():
            pytest.skip("needs CAP_NET_ADMIN for real portals")
        api = APIServer()
        client = Client(LocalTransport(api))
        dns = ClusterDNS(client).start()
        proxy = None
        try:
            dns.publish(client)
            svc = api.get("services", "default", "kube-dns")
            assert svc["spec"]["clusterIP"] == "10.0.0.10"
            client.create(
                "services", service_wire("web", "10.0.0.77"),
                namespace="default",
            )
            proxy = ProxyServer(client, real_portals=True).start()

            def resolves():
                try:
                    return (
                        dns_query(
                            53, "web.default.svc.cluster.local",
                            host="10.0.0.10",
                        )
                        == "10.0.0.77"
                    )
                except (OSError, AssertionError):
                    return False

            deadline = time.monotonic() + 10
            ok = False
            while time.monotonic() < deadline and not ok:
                ok = resolves()
                time.sleep(0.2)
            assert ok, "kube-dns VIP never answered"
        finally:
            if proxy is not None:
                proxy.stop()
            dns.stop()

    def test_publish_idempotent(self):
        from kubernetes_tpu.addons import ClusterDNS

        api = APIServer()
        client = Client(LocalTransport(api))
        dns = ClusterDNS(client).start()
        try:
            dns.publish(client)
            dns.publish(client)  # restart: must not conflict
            eps = api.get("endpoints", "default", "kube-dns")
            assert eps["subsets"][0]["ports"][0]["port"] == dns.port
        finally:
            dns.stop()
