"""In-memory time-series retention plane (utils/timeseries.py).

Covers the Retention ring store (bounded per-series rings, windowed
increase/rate/delta/max/avg/quantile queries, counter-reset tolerance,
the miss semantics of a window holding fewer than two samples), the
background Sampler (hook registration/dedup, sweep accounting,
idempotent start, clean stop), and the /debug/timeseries snapshot
payload both bare and with a query attached.

All tests drive private Registry + Retention instances with explicit
``now=`` clocks — nothing here starts the process-global SAMPLER or
pollutes timeseries.DEFAULT (the windowed-SLO fallback in other
modules keys off DEFAULT.sampled).
"""

import threading

import pytest

from kubernetes_tpu.utils import metrics, timeseries

pytestmark = pytest.mark.health


def _counter_reg():
    reg = metrics.Registry()
    c = reg.counter("drops_total", "x", ("resource",))
    return reg, c


class TestRetentionSampling:
    def test_sample_now_retains_all_metric_types(self):
        reg = metrics.Registry()
        reg.counter("c_total", "x").inc(3)
        reg.gauge("g_ratio", "x").set(0.5)
        reg.histogram("h_seconds", "x").observe(0.2)
        ret = timeseries.Retention()
        assert not ret.sampled
        touched = ret.sample_now(registry=reg, now=1.0)
        assert touched == 3
        assert ret.sampled and ret.samples == 1
        assert set(ret.series_names()) == {"c_total", "g_ratio", "h_seconds"}

    def test_summaries_are_skipped(self):
        # Summary reservoirs are not delta-composable across snapshots
        # — the retention plane must not pretend they are.
        reg = metrics.Registry()
        reg.summary("s_seconds", "x").observe(1.0)
        ret = timeseries.Retention()
        assert ret.sample_now(registry=reg, now=1.0) == 0
        assert ret.series_names() == []

    def test_rings_are_bounded(self):
        reg = metrics.Registry()
        g = reg.gauge("g_ratio", "x")
        ret = timeseries.Retention(retain_samples=4)
        for i in range(10):
            g.set(float(i))
            ret.sample_now(registry=reg, now=float(i))
        # Only the newest retain_samples survive: the delta across a
        # huge window sees sample 6 as its oldest point.
        assert ret.delta("g_ratio", 1e9, now=10.0) == 9.0 - 6.0

    def test_label_sets_and_reset(self):
        reg, c = _counter_reg()
        c.inc(resource="pods")
        c.inc(resource="nodes")
        ret = timeseries.Retention()
        ret.sample_now(registry=reg, now=1.0)
        sets = ret.label_sets("drops_total")
        assert {frozenset(d.items()) for d in sets} == {
            frozenset({("resource", "pods")}),
            frozenset({("resource", "nodes")}),
        }
        ret.reset()
        assert not ret.sampled
        assert ret.series_names() == []


class TestWindowedQueries:
    def test_increase_needs_two_samples_and_respects_window(self):
        reg, c = _counter_reg()
        ret = timeseries.Retention()
        c.inc(5, resource="pods")
        ret.sample_now(registry=reg, now=0.0)
        # One sample: no delta to take yet.
        assert ret.increase(
            "drops_total", 60.0, {"resource": "pods"}, now=0.0
        ) is None
        c.inc(7, resource="pods")
        ret.sample_now(registry=reg, now=10.0)
        assert ret.increase(
            "drops_total", 60.0, {"resource": "pods"}, now=10.0
        ) == 7.0
        # A window that excludes the first sample is back to one point.
        assert ret.increase(
            "drops_total", 5.0, {"resource": "pods"}, now=10.0
        ) is None

    def test_increase_tolerates_counter_reset(self):
        # Process restart: the counter restarts from zero. The
        # negative step is dropped, not summed backwards — increase is
        # the sum of positive deltas only (conservative: the remnant
        # counted between the last pre-restart sample and the crash is
        # gone, it never goes negative).
        reg, c = _counter_reg()
        ret = timeseries.Retention()
        c.inc(10, resource="pods")
        ret.sample_now(registry=reg, now=0.0)
        c.inc(2, resource="pods")
        ret.sample_now(registry=reg, now=10.0)
        # Simulate the restart with a fresh registry sharing the name.
        reg2, c2 = _counter_reg()
        c2.inc(3, resource="pods")
        ret.sample_now(registry=reg2, now=20.0)
        c2.inc(4, resource="pods")
        ret.sample_now(registry=reg2, now=30.0)
        assert ret.increase(
            "drops_total", 60.0, {"resource": "pods"}, now=30.0
        ) == 2.0 + 4.0

    def test_rate_uses_observed_span_not_nominal_window(self):
        # 12 increments over 4 observed seconds inside a 60s window:
        # the rate is 3/s, not 0.2/s — a sparse ring must not dilute a
        # burst.
        reg, c = _counter_reg()
        ret = timeseries.Retention()
        c.inc(3, resource="pods")
        ret.sample_now(registry=reg, now=0.0)
        c.inc(12, resource="pods")
        ret.sample_now(registry=reg, now=4.0)
        assert ret.rate(
            "drops_total", 60.0, {"resource": "pods"}, now=4.0
        ) == pytest.approx(3.0)

    def test_gauge_delta_max_avg(self):
        reg = metrics.Registry()
        g = reg.gauge("lag_versions", "x")
        ret = timeseries.Retention()
        for now, v in ((0.0, 10.0), (1.0, 50.0), (2.0, 30.0)):
            g.set(v)
            ret.sample_now(registry=reg, now=now)
        assert ret.delta("lag_versions", 60.0, now=2.0) == 20.0
        assert ret.max_over_time("lag_versions", 60.0, now=2.0) == 50.0
        assert ret.avg_over_time("lag_versions", 60.0, now=2.0) == 30.0
        # Signed: a recovering gauge reports a negative delta.
        assert ret.delta("lag_versions", 1.5, now=2.0) == -20.0
        assert ret.max_over_time("lag_versions", 60.0, now=100.0) is None

    def test_quantile_over_time_is_window_local(self):
        # Old observations outside the window must not drag the
        # windowed quantile: 100 slow obs land between the first two
        # samples, 100 fast ones between the last two — the recovery
        # window's p99 is fast even though lifetime p99 is slow. This
        # is the mechanism behind windowed SLO recovery.
        reg = metrics.Registry()
        h = reg.histogram("lat_seconds", "x")
        ret = timeseries.Retention()
        h.observe(8.0)
        ret.sample_now(registry=reg, now=0.0)
        for _ in range(99):
            h.observe(8.0)
        ret.sample_now(registry=reg, now=10.0)
        slow = ret.quantile_over_time("lat_seconds", 0.99, 60.0, now=10.0)
        assert slow is not None and slow > 5.0
        for _ in range(100):
            h.observe(0.01)
        ret.sample_now(registry=reg, now=20.0)
        fast = ret.quantile_over_time("lat_seconds", 0.99, 12.0, now=20.0)
        assert fast is not None and fast < 0.1
        # Zero new observations inside the window: None (caller
        # decides between no_data and lifetime fallback).
        ret.sample_now(registry=reg, now=30.0)
        assert ret.quantile_over_time(
            "lat_seconds", 0.99, 11.0, now=30.0
        ) is None

    def test_hist_window_counter_reset_uses_last_snapshot(self):
        reg = metrics.Registry()
        h = reg.histogram("lat_seconds", "x")
        ret = timeseries.Retention()
        for _ in range(50):
            h.observe(1.0)
        ret.sample_now(registry=reg, now=0.0)
        # Restarted process: count went backwards; the last snapshot
        # alone IS the since-restart window.
        reg2 = metrics.Registry()
        h2 = reg2.histogram("lat_seconds", "x")
        h2.observe(0.5)
        h2.observe(0.7)
        ret.sample_now(registry=reg2, now=10.0)
        count, _s, buckets = ret.hist_window("lat_seconds", 60.0, now=10.0)
        assert count == 2
        assert sum(buckets) == 2

    def test_unknown_series_and_labels_are_none(self):
        ret = timeseries.Retention()
        assert ret.increase("nope_total", 60.0) is None
        reg, c = _counter_reg()
        c.inc(resource="pods")
        ret.sample_now(registry=reg, now=0.0)
        ret.sample_now(registry=reg, now=1.0)
        assert ret.rate(
            "drops_total", 60.0, {"resource": "nodes"}, now=1.0
        ) is None

    def test_kind_mismatched_queries_are_none_not_crashes(self):
        # A query aimed at the wrong kind answers None: histogram
        # queries on scalar rings and scalar queries on histogram
        # rings must not 500 the /debug endpoints that proxy them.
        reg = metrics.Registry()
        c = reg.counter("mm_total", "x")
        h = reg.histogram("mm_seconds", "x")
        c.inc(5)
        h.observe(1.0)
        ret = timeseries.Retention()
        ret.sample_now(registry=reg, now=0.0)
        c.inc(5)
        h.observe(2.0)
        ret.sample_now(registry=reg, now=10.0)
        # histogram-shaped queries on a scalar (counter) ring
        assert ret.hist_window("mm_total", 60.0, now=10.0) is None
        assert ret.quantile_over_time(
            "mm_total", 0.99, 60.0, now=10.0
        ) is None
        # scalar queries on a histogram ring
        assert ret.increase("mm_seconds", 60.0, now=10.0) is None
        assert ret.rate("mm_seconds", 60.0, now=10.0) is None
        assert ret.delta("mm_seconds", 60.0, now=10.0) is None
        assert ret.max_over_time("mm_seconds", 60.0, now=10.0) is None
        assert ret.avg_over_time("mm_seconds", 60.0, now=10.0) is None
        # the matched queries on the same rings still answer
        assert ret.increase("mm_total", 60.0, now=10.0) == 5.0
        assert ret.hist_window("mm_seconds", 60.0, now=10.0)[0] == 1


class TestSnapshotPayload:
    def _ret(self):
        # The snapshot query path measures against the live monotonic
        # clock (it serves /debug/timeseries), so the samples must sit
        # on that clock, 10s apart, ending "now".
        import time

        t1 = time.monotonic()
        reg = metrics.Registry()
        c = reg.counter("drops_total", "x", ("resource",))
        h = reg.histogram("lat_seconds", "x")
        ret = timeseries.Retention()
        c.inc(2, resource="pods")
        h.observe(0.1)
        ret.sample_now(registry=reg, now=t1 - 10.0)
        c.inc(4, resource="pods")
        h.observe(0.3)
        ret.sample_now(registry=reg, now=t1)
        return ret

    def test_bare_snapshot_lists_series(self):
        snap = self._ret().snapshot()
        assert snap["kind"] == "TimeseriesReport"
        assert snap["sampled"] is True and snap["samples"] == 2
        assert {"drops_total", "lat_seconds"} <= set(snap["series"])
        assert snap["retainSamples"] > 0
        assert "query" not in snap  # bare inventory, no ?series=

    def test_query_snapshot_counter(self):
        snap = self._ret().snapshot(series="drops_total", window_s=60.0)
        q = snap["query"]
        assert q["found"] and q["type"] == "counter"
        assert q["windowS"] == 60.0
        (row,) = q["labelSets"]
        assert row["labels"] == {"resource": "pods"}
        assert row["samplesInWindow"] == 2
        assert row["increase"] == 4.0
        assert row["rate"] == pytest.approx(0.4, rel=0.05)

    def test_query_snapshot_histogram_quantiles(self):
        snap = self._ret().snapshot(series="lat_seconds", window_s=60.0)
        (row,) = snap["query"]["labelSets"]
        assert row["increase"] == 1  # one observation landed in-window
        assert 0 < row["p50"] <= row["p99"]

    def test_query_snapshot_miss(self):
        q = self._ret().snapshot(series="nope_total", window_s=60.0)["query"]
        assert q == {"series": "nope_total", "found": False}


class TestSampler:
    def test_sweep_runs_hooks_and_counts(self):
        ret = timeseries.Retention()
        s = timeseries.Sampler(ret)
        calls = []

        def hook():
            calls.append(1)

        s.add_hook(hook)
        s.add_hook(hook)  # dedup: registering twice runs once
        before = timeseries.SAMPLES.value()
        s.sweep()
        assert calls == [1]
        assert timeseries.SAMPLES.value() == before + 1
        assert ret.sampled

    def test_hook_exception_does_not_kill_the_sweep(self):
        ret = timeseries.Retention()
        s = timeseries.Sampler(ret)
        ran = []
        s.add_hook(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        s.add_hook(lambda: ran.append(1))
        s.sweep()
        assert ran == [1]
        assert ret.sampled  # the sample itself still landed

    def test_start_is_idempotent_and_stop_joins(self):
        ret = timeseries.Retention()
        s = timeseries.Sampler(ret)
        try:
            s.start(interval_s=0.05)
            t1 = s._thread
            s.start(interval_s=0.05)
            assert s._thread is t1  # second start is a no-op
            assert s.running
            assert t1.daemon
        finally:
            s.stop()
        assert not s.running
        alive = [t.name for t in threading.enumerate()]
        assert "kt-timeseries-sampler" not in alive

    def test_stop_without_start_is_noop(self):
        timeseries.Sampler(timeseries.Retention()).stop()
