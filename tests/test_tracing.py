"""End-to-end scheduling traces: Span/Trace mechanics, X-Trace-Id
propagation through the HTTP boundary, the /debug/traces surface,
`ktctl trace`, and the acceptance path — a pod scheduled through the
batch daemon yields one trace with enqueue/lower/upload/solve/
readback/bind steps."""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.scheduler.daemon import BatchScheduler, SchedulerConfig
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.utils import metrics, tracing


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.configure(sample_rate=1.0, log_threshold_s=0.0)
    tracing.DEFAULT_BUFFER.clear()
    yield
    tracing.configure(sample_rate=1.0, log_threshold_s=0.0)
    tracing.DEFAULT_BUFFER.clear()


def pod_wire(name):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {"name": "c", "image": "nginx",
                 "resources": {"limits": {"cpu": "100m", "memory": "64Mi"}}}
            ]
        },
    }


def node_wire(name):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": "4", "memory": "8Gi", "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def span_names(trace_dict):
    names = set()

    def walk(s):
        names.add(s["name"])
        for c in s.get("children", ()):
            walk(c)

    for root in trace_dict["spans"]:
        walk(root)
    return names


class TestSpanMechanics:
    def test_nesting_steps_fields(self):
        with tracing.trace("root", pod="p1") as tr:
            tr.step("marker")
            with tracing.span("child") as sp:
                sp.note(k="v")
                with tracing.span("grandchild"):
                    pass
        d = tracing.DEFAULT_BUFFER.to_dicts(pod="p1")["traces"][0]
        root = d["spans"][0]
        assert root["name"] == "root"
        assert [s["label"] for s in root["steps"]] == ["marker"]
        child = root["children"][0]
        assert child["name"] == "child"
        assert child["fields"] == {"k": "v"}
        assert child["children"][0]["name"] == "grandchild"
        assert d["pods"] == ["p1"]
        assert root["duration_s"] >= 0

    def test_nested_trace_joins_parent(self):
        """A trace() inside an active trace becomes a child span, not a
        second buffer entry (the incremental daemon's scalar fallback
        relies on this)."""
        with tracing.trace("outer", pod="p"):
            with tracing.trace("inner", pod="q"):
                pass
        out = tracing.DEFAULT_BUFFER.to_dicts()["traces"]
        assert len(out) == 1
        assert span_names(out[0]) == {"outer", "inner"}
        assert out[0]["pods"] == ["p", "q"]

    def test_sampling_zero_records_nothing_but_phases_observe(self):
        tracing.configure(sample_rate=0.0)
        before = tracing.PHASE_SECONDS.count(phase="unit_test_phase")
        with tracing.trace("invisible", pod="p"):
            with tracing.phase("unit_test_phase"):
                pass
        assert tracing.DEFAULT_BUFFER.to_dicts()["traces"] == []
        # The in-situ phase histogram observes regardless of sampling.
        assert (
            tracing.PHASE_SECONDS.count(phase="unit_test_phase")
            == before + 1
        )

    def test_explicit_trace_id_bypasses_sampling(self):
        tracing.configure(sample_rate=0.0)
        with tracing.trace("propagated", trace_id="deadbeef01020304"):
            pass
        out = tracing.DEFAULT_BUFFER.to_dicts()["traces"]
        assert [t["traceId"] for t in out] == ["deadbeef01020304"]

    def test_merge_by_trace_id(self):
        with tracing.trace("a", trace_id="cafe0000cafe0000", pod="p"):
            pass
        with tracing.trace("b", trace_id="cafe0000cafe0000"):
            pass
        out = tracing.DEFAULT_BUFFER.to_dicts()["traces"]
        assert len(out) == 1
        assert {s["name"] for s in out[0]["spans"]} == {"a", "b"}

    def test_threshold_logging(self, caplog):
        import logging

        with caplog.at_level(logging.INFO, logger="kubernetes_tpu.trace"):
            with tracing.trace("slowop", pod="p", threshold_s=0.001):
                time.sleep(0.01)
        assert any("over threshold" in r.message for r in caplog.records)
        assert any("slowop" in r.getMessage() for r in caplog.records)

    def test_thread_isolation(self):
        """A fresh thread must not inherit the spawner's trace."""
        import threading

        seen = []
        with tracing.trace("parent"):
            t = threading.Thread(
                target=lambda: seen.append(tracing.current_trace_id())
            )
            t.start()
            t.join()
            assert tracing.current_trace_id() != ""
        assert seen == [""]


class TestHTTPPropagation:
    def test_trace_id_header_joins_apiserver_entry(self):
        api = APIServer()
        http = APIHTTPServer(api).start()
        try:
            client = Client(HTTPTransport(http.address))
            with tracing.trace("client_op", pod="px") as tr:
                client.create("pods", pod_wire("px"))
                tid = tracing.current_trace_id()
                assert tid
        finally:
            http.stop()
        out = tracing.DEFAULT_BUFFER.to_dicts(pod="px")["traces"]
        assert len(out) == 1
        merged = out[0]
        assert merged["traceId"] == tid
        # Two entries under one id: the client's root span and the
        # apiserver's request span (with the pod noted server-side).
        names = span_names(merged)
        assert "client_op" in names
        assert any(n.startswith("POST ") for n in names)

    def test_request_log_carries_trace_id(self):
        """/debug/requests joins /debug/traces: RequestLog entries
        record the request's X-Trace-Id (when the client stamped one)
        and render prints it, so a slow request in the ring can be
        looked up in the trace buffer directly."""
        from kubernetes_tpu.utils import debug

        api = APIServer()
        http = APIHTTPServer(api).start()
        try:
            client = Client(HTTPTransport(http.address))
            with tracing.trace("logged_op", pod="plog"):
                client.create("pods", pod_wire("plog"))
                tid = tracing.current_trace_id()
            assert tid
            # An untraced request records with no id ('-' in render).
            urllib.request.urlopen(
                http.address + "/version", timeout=10
            ).read()
            # The handler records AFTER sending the response, so the
            # client can observe the body before the log entry lands —
            # poll briefly instead of racing it.
            deadline = time.monotonic() + 5.0
            while True:
                text = debug.DEFAULT_REQUEST_LOG.render()
                if "/version" in text or time.monotonic() > deadline:
                    break
                time.sleep(0.02)
        finally:
            http.stop()
        assert "TRACE" in text.splitlines()[0]
        traced = [ln for ln in text.splitlines() if tid in ln]
        assert traced, f"trace id {tid} not in request log:\n{text}"
        assert "POST" in traced[0]
        untraced = [ln for ln in text.splitlines() if "/version" in ln]
        assert untraced and " - " in untraced[0]
        # The id resolves in the trace buffer — the join the log
        # exists for.
        out = tracing.DEFAULT_BUFFER.to_dicts(pod="plog")["traces"]
        assert out and out[0]["traceId"] == tid


SCHED_TIMEOUT = 60.0


class TestSchedulerTraces:
    def _schedule(self, incremental=False):
        from kubernetes_tpu.scheduler.daemon import IncrementalBatchScheduler

        api = APIServer()
        client = Client(LocalTransport(api))
        for j in range(5):
            client.create("nodes", node_wire(f"n{j}"))
        for i in range(8):
            client.create("pods", pod_wire(f"tp{i}"))
        cfg = SchedulerConfig(
            Client(LocalTransport(api)),
            raw_scheduled_cache=incremental,
        ).start()
        assert cfg.wait_for_sync(timeout=SCHED_TIMEOUT)
        sched = (
            IncrementalBatchScheduler(cfg)
            if incremental
            else BatchScheduler(cfg)
        )
        total = 0
        deadline = time.monotonic() + SCHED_TIMEOUT
        while total < 8 and time.monotonic() < deadline:
            total += sched.schedule_batch(timeout=0.5)
        assert total == 8
        assert sched.fallback_count == 0
        cfg.stop()
        return api, client

    def test_batch_trace_has_full_span_tree(self):
        """Acceptance: one trace whose span tree contains enqueue,
        lower, upload, solve, readback, and bind."""
        api, client = self._schedule()
        out = tracing.DEFAULT_BUFFER.to_dicts(pod="tp3")["traces"]
        assert out, "no trace touched pod tp3"
        names = span_names(out[0])
        for required in (
            "enqueue", "lower", "upload", "solve", "readback", "bind"
        ):
            assert required in names, f"missing span {required!r}"
        # The in-process bind request joined the same trace.
        assert "api.bind_bulk" in names
        # /metrics exposes the histogram family with +Inf == _count.
        text = metrics.DEFAULT.render()
        assert "# TYPE scheduler_phase_seconds histogram" in text
        solve_count = tracing.PHASE_SECONDS.count(phase="solve")
        assert solve_count >= 1
        assert (
            f'scheduler_phase_seconds_bucket{{phase="solve",le="+Inf"}} '
            f"{solve_count}" in text
        )

    def test_incremental_trace_has_full_span_tree(self):
        api, client = self._schedule(incremental=True)
        out = tracing.DEFAULT_BUFFER.to_dicts(pod="tp5")["traces"]
        assert out, "no trace touched pod tp5"
        names = span_names(out[0])
        for required in (
            "enqueue", "lower", "upload", "solve", "readback", "bind"
        ):
            assert required in names, f"missing span {required!r}"

    def test_debug_traces_endpoint_and_ktctl(self, capsys):
        from kubernetes_tpu.cli import ktctl

        api, client = self._schedule()
        http = APIHTTPServer(api).start()
        try:
            with urllib.request.urlopen(
                http.address + "/debug/traces?pod=tp2", timeout=10
            ) as resp:
                data = json.loads(resp.read())
        finally:
            http.stop(release_store=False)
        assert data["kind"] == "TraceList"
        assert data["traces"], "endpoint returned no traces for tp2"
        assert "tp2" in data["traces"][0]["pods"]

        # ktctl trace <pod> renders the span tree with durations.
        rc = ktctl.main(["trace", "tp2"], client=client)
        assert rc == 0
        out = capsys.readouterr().out
        assert "TRACE" in out
        for required in ("enqueue", "lower", "solve", "bind"):
            assert required in out
        assert "ms)" in out

        # Unknown pod: clean nonzero exit.
        rc = ktctl.main(["trace", "no-such-pod"], client=client)
        assert rc == 1


class TestTraceMissRendering:
    def test_unknown_pod_exits_nonzero_with_clear_message(self, capsys):
        """`ktctl trace <pod>` with nothing recorded must exit nonzero
        with a 'no trace recorded for pod' message on stderr and dump
        NOTHING on stdout (it used to print an empty tree a script
        piping the output could mistake for data)."""
        from kubernetes_tpu.cli import ktctl

        client = Client(LocalTransport(APIServer()))
        capsys.readouterr()  # drop any prior output
        rc = ktctl.main(["trace", "ghost-pod"], client=client)
        captured = capsys.readouterr()
        assert rc == 1
        assert captured.out == ""
        assert 'no trace recorded for pod "ghost-pod"' in captured.err
