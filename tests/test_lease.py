"""Fencing-lease property tests (utils/lease.py): CAS renew/expire/
steal schedules driven on an injected clock — no sleeping, fully
deterministic per seed.

Properties under test:
- the fencing token is monotonic and bumps exactly on every change of
  effective holder (never on a plain renewal);
- at most one identity's believed token validates at any instant;
- a stale holder — renew CAS lost in flight, or running on a slow
  clock — has its writes refused (LeaseFenceError) after a takeover,
  even while it still believes it leads.

The seeded fault sites LEASE_RENEW_LOST and LEASE_CLOCK_SKEW
(utils/faults.py) drive the two failure seams the module documents."""

import random

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.utils import faults
from kubernetes_tpu.utils.lease import (
    LeaseClient,
    LeaseElector,
    LeaseFenceError,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    faults.reset_stats()
    yield
    faults.clear()
    faults.reset_stats()


def mk_cluster(identities, lease_duration=5.0, clock=None):
    api = APIServer()
    client = Client(LocalTransport(api))
    clock = clock or FakeClock()
    return clock, {
        ident: LeaseClient(
            client, "kt-sched", ident, lease_duration=lease_duration,
            clock=clock,
        )
        for ident in identities
    }


class TestLeaseMechanics:
    def test_first_acquire_creates_with_token_one(self):
        clock, lc = mk_cluster(["a", "b"])
        assert lc["a"].try_acquire() == 1
        # A live lease held by a rival is respected.
        assert lc["b"].try_acquire() is None
        rec = lc["b"].read()
        assert (rec.holder, rec.token) == ("a", 1)

    def test_renewal_keeps_token(self):
        clock, lc = mk_cluster(["a"])
        assert lc["a"].try_acquire() == 1
        clock.advance(2.0)
        assert lc["a"].try_acquire() == 1  # renewal, same epoch
        assert lc["a"].read().token == 1

    def test_expiry_steal_bumps_token(self):
        clock, lc = mk_cluster(["a", "b"])
        assert lc["a"].try_acquire() == 1
        clock.advance(5.1)  # lease expired on the true clock
        assert lc["b"].try_acquire() == 2
        assert lc["a"].held_token() is None  # belief decayed too
        with pytest.raises(LeaseFenceError):
            lc["a"].require(1)

    def test_release_allows_immediate_takeover(self):
        clock, lc = mk_cluster(["a", "b"])
        assert lc["a"].try_acquire() == 1
        lc["a"].release()
        assert lc["b"].try_acquire() == 2  # no expiry wait

    def test_own_lapse_then_reacquire_bumps_token(self):
        """Re-acquisition after this identity's own lease lapsed is a
        NEW fencing epoch — work queued under the old token must
        fence, because a rival may have held in between."""
        clock, lc = mk_cluster(["a"])
        assert lc["a"].try_acquire() == 1
        clock.advance(5.1)
        assert lc["a"].try_acquire() == 2


class TestRenewLostFault:
    def test_holder_believes_through_window_then_fences(self):
        """LEASE_RENEW_LOST: the renew CAS vanishes in flight. The
        holder keeps believing only until the window lapses on its own
        clock — and once a rival steals, the old token is refused."""
        clock, lc = mk_cluster(["a", "b"])
        assert lc["a"].try_acquire() == 1
        rule = faults.inject(faults.LEASE_RENEW_LOST, every=1)
        clock.advance(2.0)
        with pytest.raises(faults.FaultInjected):
            lc["a"].try_acquire()  # renewal lost in flight
        assert rule.fired
        # Belief persists inside the window (never demote early)...
        assert lc["a"].held_token() == 1
        clock.advance(3.2)
        # ...and decays once it lapses (never believe late).
        assert lc["a"].held_token() is None
        faults.clear()
        # The record still says renewed at t0: expired for real now.
        assert lc["b"].try_acquire() == 2
        with pytest.raises(LeaseFenceError):
            lc["a"].require(1)
        assert lc["b"].validate(2)


class TestClockSkewFault:
    def test_slow_clock_belief_outlives_lease_and_fences(self):
        """LEASE_CLOCK_SKEW: the holder's clock starts running slow by
        one lease duration, so it BELIEVES an expired lease is live —
        the exact scenario the fencing token exists for."""
        clock, lc = mk_cluster(["a", "b"])
        assert lc["a"].try_acquire() == 1
        # Arm AFTER the acquisition: the skew hits the running holder.
        rule = faults.inject(faults.LEASE_CLOCK_SKEW, every=1, times=1)
        assert lc["a"].held_token() == 1  # trips the skew on a's clock
        assert rule.fired
        clock.advance(5.1)  # truly expired
        # a still believes: its skewed clock reads inside the window.
        assert lc["a"].held_token() == 1
        # b steals the expired lease regardless of a's belief.
        assert lc["b"].try_acquire() == 2
        assert lc["a"].held_token() == 1  # STILL believes (stale)
        # The store is the fencing authority: a's writes are refused.
        with pytest.raises(LeaseFenceError):
            lc["a"].require(lc["a"].held_token())
        assert lc["b"].validate(2)


class TestLeaseProperties:
    """Randomized renew/expire/steal schedules (seeded): global token
    monotonicity, bump-on-holder-change-only, and at most one
    validated believer at every step."""

    @pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
    def test_schedules(self, seed):
        rng = random.Random(seed)
        idents = ["a", "b", "c"]
        clock, lc = mk_cluster(idents, lease_duration=5.0)
        last_token = 0
        last_holder = None
        for _step in range(120):
            actor = rng.choice(idents)
            action = rng.random()
            if action < 0.55:
                got = lc[actor].try_acquire()
                rec = lc[actor].read()
                if rec is not None:
                    # Global monotonicity.
                    assert rec.token >= last_token
                    if rec.holder != last_holder:
                        # Holder change => strict bump. (The same
                        # holder may ALSO bump — re-acquiring after
                        # its own lapse is a new fencing epoch.)
                        assert rec.token > last_token, (
                            f"seed={seed}: holder {last_holder}->"
                            f"{rec.holder} without a token bump"
                        )
                    last_token, last_holder = rec.token, rec.holder
                if got is not None:
                    assert got == lc[actor].read().token
            elif action < 0.7:
                lc[actor].release()
                rec = lc[actor].read()
                if rec is not None:
                    last_token = rec.token
                    if rec.holder == actor:
                        # Released: renew-time zeroed, holder field
                        # stale until the next steal.
                        last_holder = None
            else:
                clock.advance(rng.uniform(0.2, 3.0))
            # At most ONE identity's believed token validates.
            validated = [
                i
                for i in idents
                if lc[i].validate(lc[i].held_token())
            ]
            assert len(validated) <= 1, (
                f"seed={seed}: two validated holders {validated}"
            )

    @pytest.mark.parametrize("seed", [10, 11])
    def test_schedules_with_renew_lost_storm(self, seed):
        """Same properties with a probabilistic renew-lost fault armed
        — lost renewals may demote holders early but can never create
        two validated believers or a token regression."""
        rng = random.Random(seed)
        idents = ["a", "b"]
        clock, lc = mk_cluster(idents, lease_duration=4.0)
        faults.reset_stats(reseed=seed)
        faults.inject(faults.LEASE_RENEW_LOST, p=0.4)
        last_token = 0
        for _step in range(100):
            actor = rng.choice(idents)
            if rng.random() < 0.6:
                try:
                    lc[actor].try_acquire()
                except faults.FaultInjected:
                    pass
                rec = lc[actor].read()
                if rec is not None:
                    assert rec.token >= last_token
                    last_token = rec.token
            else:
                clock.advance(rng.uniform(0.3, 2.5))
            validated = [
                i
                for i in idents
                if lc[i].validate(lc[i].held_token())
            ]
            assert len(validated) <= 1


class TestLeaseElector:
    def test_single_elector_leads_and_threads_token(self):
        import time as _time

        api = APIServer()
        client = Client(LocalTransport(api))
        lease = LeaseClient(client, "kt-sched", "a", lease_duration=0.6)
        seen = []
        e = LeaseElector(
            lease, renew_period=0.05, retry_period=0.05,
            on_elected=seen.append,
        ).start()
        try:
            deadline = _time.monotonic() + 5
            while _time.monotonic() < deadline and not e.is_leader:
                _time.sleep(0.01)
            assert e.is_leader
            assert seen == [1]
        finally:
            e.stop()
        assert not e.is_leader

    def test_exactly_one_of_many_leads(self):
        import time as _time

        api = APIServer()
        client = Client(LocalTransport(api))
        electors = [
            LeaseElector(
                LeaseClient(
                    client, "kt-sched", f"id{i}", lease_duration=0.6
                ),
                renew_period=0.05,
                retry_period=0.05,
            ).start()
            for i in range(3)
        ]
        try:
            deadline = _time.monotonic() + 5
            while _time.monotonic() < deadline and (
                sum(e.is_leader for e in electors) != 1
            ):
                _time.sleep(0.01)
            assert sum(e.is_leader for e in electors) == 1
            _time.sleep(0.3)  # stable
            assert sum(e.is_leader for e in electors) == 1
        finally:
            for e in electors:
                e.stop()
