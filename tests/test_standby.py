"""Warm-standby scheduler + lease-elected HA (scheduler/standby.py).

The failover contract: the standby's informers run hot and its
SolverSession is prewarmed, so activation is just daemon.start() —
the first tick drains the accumulated watch deltas and binds the
backlog. A deposed leader is killed abruptly (stale fencing token)
and rebuilds a fresh standby."""

import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.client.rest import HTTPTransport
from kubernetes_tpu.scheduler.standby import (
    HAScheduler,
    WarmStandbyScheduler,
)
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer


def wait_until(cond, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def node_wire(name, cpu="8", mem="16Gi"):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": cpu, "memory": mem, "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def pod_wire(name, cpu="100m", mem="64Mi"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "pause",
                    "resources": {"limits": {"cpu": cpu, "memory": mem}},
                }
            ]
        },
    }


def bound_names(client):
    pods, _ = client.list("pods", namespace="default")
    return {p.metadata.name for p in pods if p.spec.node_name}


class TestWarmStandby:
    def test_prewarm_accumulates_deltas_then_activates(self):
        api = APIServer()
        c = Client(LocalTransport(api))
        for i in range(3):
            c.create("nodes", node_wire(f"n{i}"))
        sb = WarmStandbyScheduler(c, sync_timeout=30)
        try:
            sb.prewarm()
            assert sb.warm and not sb.active
            # Deltas arriving while warm queue in the daemon; nothing
            # binds yet (the solve loop is not running).
            c.create("pods", pod_wire("queued"))
            time.sleep(0.3)
            assert bound_names(c) == set()
            # Activation drains the backlog on the first tick.
            sb.activate()
            assert sb.active
            assert wait_until(lambda: "queued" in bound_names(c))
            # Live deltas keep flowing after activation.
            c.create("pods", pod_wire("live"))
            assert wait_until(lambda: "live" in bound_names(c))
        finally:
            sb.stop()

    def test_activate_is_idempotent_and_auto_prewarms(self):
        api = APIServer()
        c = Client(LocalTransport(api))
        c.create("nodes", node_wire("n0"))
        sb = WarmStandbyScheduler(c, sync_timeout=30)
        try:
            d1 = sb.activate()  # cold activate: prewarms internally
            d2 = sb.activate()
            assert d1 is d2
            assert sb.warm and sb.active
        finally:
            sb.stop()


class TestHAScheduler:
    def _cluster(self):
        api = APIServer()
        srv = APIHTTPServer(api).start()

        def client():
            return Client(HTTPTransport(srv.address))

        c = client()
        for i in range(4):
            c.create("nodes", node_wire(f"n{i}"))
        return srv, client, c

    def _ha(self, client_factory, name):
        return HAScheduler(
            client_factory(),
            name,
            lease_duration=0.6,
            renew_period=0.1,
            retry_period=0.1,
            standby_factory=lambda: WarmStandbyScheduler(
                client_factory(), sync_timeout=30
            ),
        )

    def test_failover_activates_warm_standby_fast(self):
        """Kill the scheduler leader; the rival's PREWARMED standby
        takes the lease and its first bind lands — the e2e shape
        behind the failover_to_first_bind_s SLO (the strict 1 s gate
        is bench/check's; tier-1 asserts the path, generously)."""
        srv, client_factory, c = self._cluster()
        ha = []
        try:
            ha = [self._ha(client_factory, n) for n in ("alpha", "beta")]
            for h in ha:
                h.start()
            assert wait_until(
                lambda: sum(h.is_leader for h in ha) == 1, timeout=60
            )
            leader = next(h for h in ha if h.is_leader)
            standby = next(h for h in ha if h is not leader)
            # The standby is warm (informers hot, session prewarmed)
            # while NOT leading.
            assert wait_until(
                lambda: standby.standby is not None and standby.standby.warm
            )
            assert standby.daemon is None
            c.create("pods", pod_wire("before"))
            assert wait_until(lambda: "before" in bound_names(c))
            # Crash the leader: daemon dies AND renewals stop, with no
            # graceful abdication — the lease must expire on its own.
            leader.elector._stop.set()
            leader.standby.kill()
            killed = time.monotonic()
            assert wait_until(lambda: standby.is_leader, timeout=30), (
                "standby never took the lease"
            )
            c.create("pods", pod_wire("after"))
            assert wait_until(
                lambda: "after" in bound_names(c), timeout=30
            ), "standby never bound after takeover"
            # Loose e2e bound: lease expiry (~0.6s) + retry + first
            # tick. The warm path must not pay a LIST or session build.
            assert time.monotonic() - killed < 15.0
            # Fencing epochs advanced across the takeover.
            assert standby.token > 1 or leader.token is None
        finally:
            for h in ha:
                try:
                    h.stop()
                except Exception:
                    pass
            srv.stop()

    def test_deposed_leader_rebuilds_warm_standby(self):
        """A deposed leader kills its daemon and re-enters the
        election warm (fresh standby), ready to take over again."""
        srv, client_factory, c = self._cluster()
        ha = None
        rival = None
        try:
            ha = self._ha(client_factory, "alpha").start()
            assert wait_until(lambda: ha.is_leader, timeout=60)
            first_sb = ha.standby
            # A rival steals the lease while alpha is wedged (simulate
            # by pausing alpha's renewals past the lease window).
            ha.elector._stop.set()
            ha.elector._thread.join(timeout=10)
            rival = self._ha(client_factory, "beta").start()
            assert wait_until(lambda: rival.is_leader, timeout=30)
            # Alpha notices on its next acquire attempt... its elector
            # is stopped, so drive the deposition directly (the
            # callback path the elector thread would take).
            ha._deposed()
            assert ha.token is None
            assert wait_until(
                lambda: ha.standby is not None
                and ha.standby is not first_sb
                and ha.standby.warm
            ), "deposed leader never rebuilt a warm standby"
            assert not ha.standby.active
        finally:
            for h in (ha, rival):
                if h is not None:
                    try:
                        h.stop()
                    except Exception:
                        pass
            srv.stop()
