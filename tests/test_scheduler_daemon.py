"""Scheduler end-to-end: real apiserver + caches + daemon loop
(reference analog: plugin/pkg/scheduler/scheduler_test.go +
test/integration/scheduler_test.go)."""

import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.scheduler.daemon import Scheduler, SchedulerConfig
from kubernetes_tpu.scheduler.generic import FitError, GenericScheduler
from kubernetes_tpu.scheduler.plugins import (
    PluginFactoryArgs,
    build_from_policy,
    default_predicates,
    default_priorities,
)
from kubernetes_tpu.scheduler.types import (
    StaticNodeLister,
    StaticPodLister,
    StaticServiceLister,
)
from kubernetes_tpu.server import APIServer


def pod_wire(name, cpu="100m", mem="100", ns="default"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "nginx",
                    "resources": {"limits": {"cpu": cpu, "memory": mem}},
                }
            ]
        },
    }


def node_wire(name, cpu="4", mem="8Gi", pods="40"):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": cpu, "memory": mem, "pods": pods},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def wait_until(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


class TestGenericScheduler:
    """generic_scheduler_test.go expectations (condensed)."""

    def _args(self, nodes, pods=(), services=()):
        return PluginFactoryArgs(
            pod_lister=StaticPodLister(list(pods)),
            service_lister=StaticServiceLister(list(services)),
            node_lister=StaticNodeLister(nodes),
        )

    def test_picks_least_requested(self):
        from kubernetes_tpu.models.quantity import Quantity
        from tests.test_scheduler_priorities import cpu_mem_pod, make_minion

        nodes = [make_minion("big", 8000, 10**10), make_minion("small", 2000, 10**9)]
        for n in nodes:
            n.status.capacity["pods"] = Quantity.from_int(40)
        args = self._args(nodes)
        sched = GenericScheduler(
            default_predicates(args), default_priorities(args), args.pod_lister
        )
        # 3000m/5000B pod: only "big" passes PodFitsResources... small
        # has 2000m capacity < 3000m. Also scores favor big.
        dest = sched.schedule(cpu_mem_pod(""), args.node_lister)
        assert dest == "big"

    def test_fit_error_carries_predicates(self):
        from tests.test_scheduler_priorities import cpu_mem_pod, make_minion

        nodes = [make_minion("tiny", 100, 100)]
        args = self._args(nodes)
        sched = GenericScheduler(
            default_predicates(args), default_priorities(args), args.pod_lister
        )
        with pytest.raises(FitError) as e:
            sched.schedule(cpu_mem_pod(""), args.node_lister)
        assert "PodFitsResources" in str(e.value)

    def test_policy_file(self):
        from tests.test_scheduler_priorities import make_minion

        policy = {
            "kind": "Policy",
            "predicates": [{"name": "PodFitsResources"}, {"name": "HostName"}],
            "priorities": [
                {"name": "LeastRequestedPriority", "weight": 2},
                {
                    "name": "ZoneSpread",
                    "weight": 1,
                    "argument": {"serviceAntiAffinity": {"label": "zone"}},
                },
            ],
        }
        args = self._args([make_minion("m1", 1000, 1000)])
        predicates, priorities = build_from_policy(policy, args)
        assert set(predicates) == {"PodFitsResources", "HostName"}
        assert len(priorities) == 2
        assert priorities[0].weight == 2


class TestCustomAlgorithmSeam:
    """The algorithm seam is pluggable: any object with
    .schedule(pod, minion_lister) -> host slots into SchedulerConfig,
    exactly how contrib/mesos swaps its own ScheduleAlgorithm into
    scheduler.Config (reference: contrib/mesos/pkg/scheduler/
    scheduler.go:19-20 comment + plugin/pkg/scheduler/algorithm/
    scheduler_interface.go)."""

    def test_custom_algorithm_drives_placement(self):
        class StickyAlgorithm:
            """Places every pod on the lexicographically-last node —
            nothing like the default provider, which proves the daemon
            takes the seam's word for it."""

            def schedule(self, pod, minion_lister):
                nodes = sorted(n.metadata.name for n in minion_lister.list())
                if not nodes:
                    raise RuntimeError("no nodes")
                return nodes[-1]

        api = APIServer()
        client = Client(LocalTransport(api))
        cfg = SchedulerConfig(client).start()
        try:
            assert cfg.wait_for_sync()
            cfg.algorithm = StickyAlgorithm()
            sched = Scheduler(cfg)
            client.create("nodes", node_wire("a-node", cpu="8"))
            client.create("nodes", node_wire("z-node", cpu="1"))
            for i in range(3):
                client.create("pods", pod_wire(f"p{i}"))
            assert wait_until(lambda: len(cfg.pod_queue) >= 3)
            # The node informer is a separate watch thread from the pod
            # reflector: wait for both before scheduling.
            assert wait_until(lambda: len(cfg.node_lister.list()) == 2)
            for _ in range(3):
                assert sched.schedule_one(timeout=1)
            items, _ = client.list("pods", namespace="default")
            assert {p.spec.node_name for p in items} == {"z-node"}
        finally:
            cfg.stop()


class TestSchedulerDaemon:
    def _start(self, api=None, **cfg_kw):
        api = api or APIServer()
        client = Client(LocalTransport(api))
        cfg = SchedulerConfig(client, **cfg_kw).start()
        assert cfg.wait_for_sync()
        sched = Scheduler(cfg)
        return api, client, cfg, sched

    def test_schedules_pending_pod(self):
        api, client, cfg, sched = self._start()
        client.create("nodes", node_wire("n1"))
        client.create("pods", pod_wire("p1"))
        assert wait_until(lambda: len(cfg.pod_queue) > 0)
        assert sched.schedule_one(timeout=1)
        got = client.get("pods", "p1", namespace="default")
        assert got.spec.node_name == "n1"
        cfg.stop()

    def test_spreads_by_least_requested(self):
        api, client, cfg, sched = self._start()
        client.create("nodes", node_wire("n1", cpu="2"))
        client.create("nodes", node_wire("n2", cpu="4"))
        for i in range(4):
            client.create("pods", pod_wire(f"p{i}", cpu="500m"))
        assert wait_until(lambda: len(cfg.pod_queue) >= 4)
        for _ in range(4):
            assert sched.schedule_one(timeout=1)
        placements = {}
        items, _ = client.list("pods", namespace="default")
        for p in items:
            placements.setdefault(p.spec.node_name, 0)
            placements[p.spec.node_name] += 1
        # n2 has double capacity: it should absorb more pods.
        assert placements.get("n2", 0) >= placements.get("n1", 0)
        cfg.stop()

    def test_unschedulable_pod_requeued_with_backoff(self):
        api, client, cfg, sched = self._start()
        client.create("nodes", node_wire("n1", cpu="100m"))
        client.create("pods", pod_wire("huge", cpu="10"))
        assert wait_until(lambda: len(cfg.pod_queue) > 0)
        assert sched.schedule_one(timeout=1)
        got = client.get("pods", "huge", namespace="default")
        assert got.spec.node_name == ""
        # A FailedScheduling event was recorded.
        events, _ = client.list("events", namespace="default")
        assert any(e.reason == "FailedScheduling" for e in events)
        cfg.stop()

    def test_assumed_pod_blocks_capacity(self):
        """After bind, the assumed pod must count against the node
        before the watch confirms it (modeler semantics)."""
        api, client, cfg, sched = self._start()
        client.create("nodes", node_wire("n1", cpu="1", pods="40"))
        client.create("nodes", node_wire("n2", cpu="1", pods="40"))
        client.create("pods", pod_wire("a", cpu="600m"))
        client.create("pods", pod_wire("b", cpu="600m"))
        assert wait_until(lambda: len(cfg.pod_queue) >= 2)
        assert sched.schedule_one(timeout=1)
        assert sched.schedule_one(timeout=1)
        items, _ = client.list("pods", namespace="default")
        hosts = sorted(p.spec.node_name for p in items)
        # 600m + 600m > 1 CPU: they must land on different nodes even if
        # the scheduled-pods watch hasn't caught up.
        assert hosts == ["n1", "n2"]
        cfg.stop()

    def test_daemon_thread_drains_queue(self):
        api, client, cfg, sched = self._start()
        client.create("nodes", node_wire("n1"))
        sched.start()
        for i in range(5):
            client.create("pods", pod_wire(f"d{i}"))
        assert wait_until(
            lambda: all(
                p.spec.node_name == "n1"
                for p in client.list("pods", namespace="default")[0]
            )
            and len(client.list("pods", namespace="default")[0]) == 5,
            timeout=8,
        )
        sched.stop()


class TestDaemonRegressions:
    """Regression tests for review findings."""

    def test_externally_bound_pod_leaves_fifo(self):
        """A pod bound by another actor must produce a synthesized
        DELETED on the filtered watch and leave the scheduler's FIFO."""
        api = APIServer()
        client = Client(LocalTransport(api))
        cfg = SchedulerConfig(client).start()
        assert cfg.wait_for_sync()
        client.create("nodes", node_wire("n1"))
        client.create("pods", pod_wire("stolen"))
        assert wait_until(lambda: len(cfg.pod_queue) == 1)
        # Another actor binds it out from under the scheduler.
        client.bind("stolen", "n1", namespace="default")
        assert wait_until(lambda: len(cfg.pod_queue) == 0)
        cfg.stop()

    def test_deleted_pod_not_requeued_forever(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        cfg = SchedulerConfig(client).start()
        cfg.backoff.initial = 0.05
        assert cfg.wait_for_sync()
        client.create("nodes", node_wire("n1", cpu="100m"))
        client.create("pods", pod_wire("doomed", cpu="10"))
        sched = Scheduler(cfg)
        assert wait_until(lambda: len(cfg.pod_queue) == 1)
        assert sched.schedule_one(timeout=1)  # fails, schedules a requeue
        client.delete("pods", "doomed", namespace="default")
        time.sleep(0.3)  # backoff elapses; re-fetch sees 404 and drops
        assert len(cfg.pod_queue) == 0
        cfg.stop()

    def test_node_deleted_mid_schedule_does_not_crash(self):
        """KeyError from a vanished node is treated as retryable."""
        api = APIServer()
        client = Client(LocalTransport(api))
        cfg = SchedulerConfig(client).start()
        assert cfg.wait_for_sync()
        client.create("nodes", node_wire("n1"))
        client.create("pods", pod_wire("p1"))
        sched = Scheduler(cfg)
        assert wait_until(lambda: len(cfg.pod_queue) == 1)
        # Sabotage: make the node lister's get always fail.
        cfg.node_lister.get = lambda name: (_ for _ in ()).throw(KeyError(name))
        assert sched.schedule_one(timeout=1) is True  # no crash
        cfg.stop()
