"""KV store semantics tests (reference: pkg/tools/etcd_helper*.go)."""

import threading
import time

import pytest

from kubernetes_tpu.store import (
    ADDED,
    AlreadyExistsError,
    CompactedError,
    ConflictError,
    DELETED,
    KVStore,
    MODIFIED,
    NotFoundError,
)


def obj(name, ns="default", **extra):
    return {"kind": "Pod", "metadata": {"name": name, "namespace": ns}, **extra}


def test_create_get_stamps_version():
    s = KVStore()
    created = s.create("/pods/default/a", obj("a"))
    assert created["metadata"]["resourceVersion"] == "1"
    got = s.get("/pods/default/a")
    assert got["metadata"]["name"] == "a"
    with pytest.raises(AlreadyExistsError):
        s.create("/pods/default/a", obj("a"))
    with pytest.raises(NotFoundError):
        s.get("/pods/default/missing")


def test_copies_not_aliased():
    s = KVStore()
    o = obj("a")
    s.create("/k", o)
    o["metadata"]["name"] = "mutated"
    assert s.get("/k")["metadata"]["name"] == "a"
    got = s.get("/k")
    got["metadata"]["name"] = "mutated2"
    assert s.get("/k")["metadata"]["name"] == "a"


def test_cas_set_and_delete():
    s = KVStore()
    created = s.create("/k", obj("a"))
    v = int(created["metadata"]["resourceVersion"])
    s.set("/k", obj("a", spec={"x": 1}), expected_version=v)
    with pytest.raises(ConflictError):
        s.set("/k", obj("a"), expected_version=v)  # stale
    with pytest.raises(ConflictError):
        s.delete("/k", expected_version=v)
    s.delete("/k", expected_version=v + 1)
    with pytest.raises(NotFoundError):
        s.get("/k")


def test_guaranteed_update_retries_on_conflict():
    s = KVStore()
    s.create("/k", obj("a", count=0))
    calls = {"n": 0}

    def bump(cur):
        calls["n"] += 1
        if calls["n"] == 1:
            # Interleave a conflicting write mid-update (another writer).
            s.set("/k", obj("a", count=100))
        cur["count"] = cur.get("count", 0) + 1
        return cur

    out = s.guaranteed_update("/k", bump)
    assert out["count"] == 101  # second attempt saw the interleaved write
    assert calls["n"] == 2


def test_list_prefix_and_version():
    s = KVStore()
    s.create("/pods/default/a", obj("a"))
    s.create("/pods/default/b", obj("b"))
    s.create("/nodes/n1", {"kind": "Node", "metadata": {"name": "n1"}})
    pods, v = s.list("/pods/")
    assert [p["metadata"]["name"] for p in pods] == ["a", "b"]
    assert v == 3


def test_watch_live_events_in_order():
    s = KVStore()
    w = s.watch("/pods/")
    s.create("/pods/default/a", obj("a"))
    s.set("/pods/default/a", obj("a", spec={"nodeName": "n1"}))
    s.delete("/pods/default/a")
    s.create("/nodes/n1", {"kind": "Node", "metadata": {"name": "n1"}})  # filtered
    evs = [w.next(timeout=1) for _ in range(3)]
    assert [e.type for e in evs] == [ADDED, MODIFIED, DELETED]
    assert [e.version for e in evs] == [1, 2, 3]
    assert evs[1].object["spec"]["nodeName"] == "n1"
    assert w.next(timeout=0.05) is None  # node event was filtered by prefix


def test_watch_replay_from_version():
    s = KVStore()
    s.create("/pods/a", obj("a"))
    s.create("/pods/b", obj("b"))
    _, v = s.list("/pods/")
    s.create("/pods/c", obj("c"))
    s.set("/pods/a", obj("a", spec={"x": 1}))
    w = s.watch("/pods/", since=v)
    evs = [w.next(timeout=1) for _ in range(2)]
    assert [(e.type, e.object["metadata"]["name"]) for e in evs] == [
        (ADDED, "c"),
        (MODIFIED, "a"),
    ]
    # live continues after replay
    s.delete("/pods/b")
    ev = w.next(timeout=1)
    assert (ev.type, ev.object["metadata"]["name"]) == (DELETED, "b")


def test_watch_compacted():
    s = KVStore(history_limit=4)
    for i in range(10):
        s.create(f"/pods/p{i}", obj(f"p{i}"))
    with pytest.raises(CompactedError):
        s.watch("/pods/", since=1)


def test_ttl_expiry():
    s = KVStore()
    s.create("/events/e1", {"kind": "Event", "metadata": {"name": "e1"}}, ttl=0.05)
    assert s.get("/events/e1")["metadata"]["name"] == "e1"
    time.sleep(0.08)
    with pytest.raises(NotFoundError):
        s.get("/events/e1")
    # Expiry produced a DELETED event visible to watch replay.
    w = s.watch("/events/", since=1)
    ev = w.next(timeout=1)
    assert ev.type == DELETED


def test_concurrent_guaranteed_updates():
    s = KVStore()
    s.create("/k", obj("a", count=0))

    def worker():
        for _ in range(50):
            s.guaranteed_update(
                "/k", lambda cur: {**cur, "count": cur["count"] + 1}
            )

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert s.get("/k")["count"] == 200


def test_slow_consumer_stream_closed():
    s = KVStore()
    w = s.watch("/pods/", maxsize=2)
    for i in range(5):
        s.create(f"/pods/p{i}", obj(f"p{i}"))
    # Queue overflowed -> stream closed; consumer drains then sees close.
    seen = list(w)
    assert len(seen) <= 3
    assert w.closed


class TestFilteredWatch:
    """Store-level selector filtering with etcd's old/new-aware
    translation (kvstore._filter_event; reference:
    pkg/tools/etcd_helper_watch.go sendModify/sendDelete). The filter
    runs INSIDE the fan-out, so a watcher is never even offered events
    for objects that don't concern it."""

    @staticmethod
    def drain(w, n, timeout=2.0):
        out = []
        deadline = time.time() + timeout
        while len(out) < n and time.time() < deadline:
            ev = w.next(timeout=0.1)
            if ev is not None:
                out.append(ev)
        return out

    @staticmethod
    def unassigned_pred(o):
        return not o.get("spec", {}).get("nodeName")

    def test_modified_out_of_filter_becomes_deleted(self):
        # The scheduler's spec.nodeName=="" watch: binding a pod must
        # surface as DELETED (it left the view), not MODIFIED.
        s = KVStore()
        w = s.watch("/pods/", pred=self.unassigned_pred)
        s.create("/pods/a", obj("a", spec={}))
        s.set("/pods/a", obj("a", spec={"nodeName": "n1"}))
        evs = self.drain(w, 2)
        assert [e.type for e in evs] == [ADDED, DELETED]

    def test_never_matching_object_is_silent(self):
        # A pod born bound: the unassigned watcher sees NOTHING for its
        # whole lifecycle — old/new awareness suppresses the spurious
        # DELETED per status write that the pre-store filter emitted.
        s = KVStore()
        w = s.watch("/pods/", pred=self.unassigned_pred)
        s.create("/pods/b", obj("b", spec={"nodeName": "n1"}))
        s.set("/pods/b", obj("b", spec={"nodeName": "n1"}, status={"p": 1}))
        s.set("/pods/b", obj("b", spec={"nodeName": "n1"}, status={"p": 2}))
        s.delete("/pods/b")
        s.create("/pods/c", obj("c", spec={}))  # sentinel that DOES match
        evs = self.drain(w, 1)
        assert len(evs) == 1 and evs[0].key == "default/c"

    def test_delete_of_matching_object_delivered(self):
        s = KVStore()
        w = s.watch("/pods/", pred=self.unassigned_pred)
        s.create("/pods/d", obj("d", spec={}))
        s.delete("/pods/d")
        evs = self.drain(w, 2)
        assert [e.type for e in evs] == [ADDED, DELETED]

    def test_modified_within_filter_stays_modified(self):
        s = KVStore()
        w = s.watch("/pods/", pred=self.unassigned_pred)
        s.create("/pods/e", obj("e", spec={}))
        s.set("/pods/e", obj("e", spec={}, status={"phase": "Pending"}))
        evs = self.drain(w, 2)
        assert [e.type for e in evs] == [ADDED, MODIFIED]

    def test_replay_degrades_to_spurious_deleted(self):
        # History has no prev state: a replayed non-matching MODIFIED
        # becomes a (harmless) DELETED instead of being dropped.
        s = KVStore()
        s.create("/pods/f", obj("f", spec={"nodeName": "n1"}))
        v = s.version
        s.set("/pods/f", obj("f", spec={"nodeName": "n1"}, status={"x": 1}))
        w = s.watch("/pods/", since=v, pred=self.unassigned_pred)
        evs = self.drain(w, 1)
        assert [e.type for e in evs] == [DELETED]

    def test_replay_then_live_no_duplicates_no_gaps(self):
        # The version floor: replay covers <= registration version;
        # the dispatcher's backlog must not re-deliver, later writes
        # must all arrive.
        s = KVStore()
        s.create("/pods/base", obj("base", spec={"nodeName": "n0"}))
        v0 = s.version
        s.create("/pods/g", obj("g", spec={}))
        w = s.watch("/pods/", since=v0, pred=self.unassigned_pred)
        s.create("/pods/h", obj("h", spec={}))
        evs = self.drain(w, 2)
        assert sorted(e.key for e in evs) == ["default/g", "default/h"]
        assert len({e.version for e in evs}) == 2
