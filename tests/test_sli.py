"""SLI/SLO telemetry plane (utils/sli.py, utils/slo.py).

Covers: the watch-fed lifecycle collector (milestone watermarks, drain
and bound behavior), the slow-consumer watch drop counter + queue-depth
gauge (the previously SILENT drop at store/watch.py), watch fan-out
lag, the declarative SLO engine (verdict ladder, registry evaluation,
the bench objectives), the e2e surface (/debug/slo, `ktctl slo`,
`ktctl top cluster`, the empty-cluster miss contract), and the
overhead guard that lets the collector stay always-on.
"""

import io
import json
import time
from contextlib import redirect_stderr, redirect_stdout

import pytest

from kubernetes_tpu.store import watch as watchmod
from kubernetes_tpu.utils import metrics, sli, slo

pytestmark = pytest.mark.slo


def _pod_wire(name, ns="default", node="", phase=""):
    obj = {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"containers": [{"name": "c", "image": "x"}]},
    }
    if node:
        obj["spec"]["nodeName"] = node
    if phase:
        obj["status"] = {"phase": phase}
    return obj


def _key(name, ns="default"):
    return f"{sli.POD_PREFIX}{ns}/{name}"


class TestLifecycleCollector:
    def test_milestones_observed_in_order(self):
        c = sli.LifecycleSLICollector()
        before = {
            m: sli.STARTUP_LATENCY.count(milestone=m)
            for m in ("decision", "bound", "running")
        }
        c._on_store_event(1, "ADDED", _key("p1"), _pod_wire("p1"), None)
        assert c.tracked_count() == 1
        c.note_decision("default/p1", "bound")
        c._on_store_event(
            2, "MODIFIED", _key("p1"), _pod_wire("p1", node="n0"), None
        )
        c._on_store_event(
            3, "MODIFIED", _key("p1"),
            _pod_wire("p1", node="n0", phase="Running"), None,
        )
        for m in ("decision", "bound", "running"):
            assert (
                sli.STARTUP_LATENCY.count(milestone=m) == before[m] + 1
            ), m
        # Running drains the track.
        assert c.tracked_count() == 0

    def test_milestones_are_first_transition_only(self):
        c = sli.LifecycleSLICollector()
        before = sli.STARTUP_LATENCY.count(milestone="bound")
        c._on_store_event(1, "ADDED", _key("p2"), _pod_wire("p2"), None)
        for v in (2, 3, 4):
            c._on_store_event(
                v, "MODIFIED", _key("p2"), _pod_wire("p2", node="n0"), None
            )
        assert sli.STARTUP_LATENCY.count(milestone="bound") == before + 1
        c.note_decision("default/p2")
        c.note_decision("default/p2")
        # Second decision for a tracked pod is a no-op... and after the
        # first one the flag is set, so exactly one observation landed.

    def test_born_bound_and_foreign_keys_ignored(self):
        c = sli.LifecycleSLICollector()
        c._on_store_event(
            1, "ADDED", _key("static"), _pod_wire("static", node="n0"), None
        )
        c._on_store_event(
            2, "ADDED", "/registry/nodes/n0", {"metadata": {"name": "n0"}},
            None,
        )
        assert c.tracked_count() == 0

    def test_deleted_forgets_and_decision_for_unknown_is_noop(self):
        c = sli.LifecycleSLICollector()
        c._on_store_event(1, "ADDED", _key("p3"), _pod_wire("p3"), None)
        c._on_store_event(2, "DELETED", _key("p3"), _pod_wire("p3"), None)
        assert c.tracked_count() == 0
        before = sli.STARTUP_LATENCY.count(milestone="decision")
        c.note_decision("default/p3")
        assert sli.STARTUP_LATENCY.count(milestone="decision") == before

    def test_tracking_is_bounded_oldest_evicted(self):
        c = sli.LifecycleSLICollector()
        c.MAX_TRACKED = 4
        for i in range(10):
            c._on_store_event(
                i + 1, "ADDED", _key(f"b{i}"), _pod_wire(f"b{i}"), None
            )
        assert c.tracked_count() == 4
        # The survivors are the NEWEST four.
        before = sli.STARTUP_LATENCY.count(milestone="bound")
        c._on_store_event(
            99, "MODIFIED", _key("b9"), _pod_wire("b9", node="n0"), None
        )
        assert sli.STARTUP_LATENCY.count(milestone="bound") == before + 1

    def test_disabled_collector_ignores_events(self):
        c = sli.LifecycleSLICollector()
        c.enabled = False
        c._on_store_event(1, "ADDED", _key("off"), _pod_wire("off"), None)
        assert c.tracked_count() == 0


class TestWatchDropObservability:
    """The silent slow-consumer drop (store/watch.py) is now counted,
    gauged, and logged — the satellite-1 regression tests."""

    def test_full_queue_drops_stream_and_counts(self, caplog):
        before = watchmod.STREAMS_DROPPED.value(resource="widgets")
        s = watchmod.WatchStream(maxsize=2, resource="widgets")
        ok1 = s.push(watchmod.Event("ADDED", {"metadata": {}}, 1))
        ok2 = s.push(watchmod.Event("ADDED", {"metadata": {}}, 2))
        assert ok1 and ok2 and not s.closed
        with caplog.at_level("WARNING", "kubernetes_tpu.store.watch"):
            ok3 = s.push(watchmod.Event("ADDED", {"metadata": {}}, 3))
        assert not ok3
        # The drop site records the (full) queue depth.
        assert watchmod.QUEUE_DEPTH.value(resource="widgets") >= 2
        assert s.closed, "overflow must close (drop) the stream"
        assert (
            watchmod.STREAMS_DROPPED.value(resource="widgets")
            == before + 1
        )
        # The warn log names the resource and the version floor.
        text = "\n".join(r.getMessage() for r in caplog.records)
        assert "widgets" in text and "floor" in text

    def test_kvstore_slow_consumer_drop_end_to_end(self):
        """Fill a maxsize= queue through a real store: the stream must
        close, the counter must increment, and later events must not
        resurrect it."""
        from kubernetes_tpu.store.kvstore import KVStore

        store = KVStore()
        try:
            before = watchmod.STREAMS_DROPPED.value(resource="pods")
            stream = store.watch("/registry/pods/", maxsize=2)
            assert stream.resource == "pods"
            for i in range(8):
                store.create(
                    f"/registry/pods/default/d{i}", _pod_wire(f"d{i}")
                )
            deadline = time.monotonic() + 5.0
            while not stream.closed and time.monotonic() < deadline:
                time.sleep(0.01)
            assert stream.closed, "slow consumer was never dropped"
            assert (
                watchmod.STREAMS_DROPPED.value(resource="pods")
                >= before + 1
            )
        finally:
            store.close()

    def test_resource_of_prefix(self):
        assert watchmod.resource_of_prefix("/registry/pods/") == "pods"
        assert (
            watchmod.resource_of_prefix("/registry/pods/default/") == "pods"
        )
        assert watchmod.resource_of_prefix("/weird/") == "/weird/"


class TestWatchLag:
    def test_lag_observed_and_clamped(self):
        before = sli.WATCH_LAG.count(resource="lagtest")
        sli.observe_watch_lag("lagtest", 5)
        sli.observe_watch_lag("lagtest", -3)  # clock skew clamps to 0
        assert sli.WATCH_LAG.count(resource="lagtest") == before + 2
        assert sli.WATCH_LAG.quantile(0.99, resource="lagtest") <= 8


class TestSLOEngine:
    def test_verdict_ladder(self):
        gate = slo.Objective("g", "s", target=1.0, kind="value_max")
        assert slo.verdict_for_value(gate, 0.5) == "pass"
        assert slo.verdict_for_value(gate, 0.9) == "warn"  # warn band
        assert slo.verdict_for_value(gate, 1.5) == "burn"
        assert slo.verdict_for_value(gate, None) == "no_data"
        assert slo.verdict_for_value(gate, float("nan")) == "no_data"
        warn_only = slo.Objective(
            "w", "s", target=1.0, kind="value_max", severity="warn",
            warn_ratio=0.0,
        )
        assert slo.verdict_for_value(warn_only, 2.0) == "warn"
        assert slo.verdict_for_value(warn_only, 0.9) == "pass"
        floor = slo.Objective("f", "s", target=100.0, kind="value_min")
        assert slo.verdict_for_value(floor, 150.0) == "pass"
        assert slo.verdict_for_value(floor, 50.0) == "burn"

    def test_worst(self):
        assert slo.worst("pass", "warn", "pass") == "warn"
        assert slo.worst("warn", "burn") == "burn"
        assert slo.worst("pass", "no_data") == "no_data"
        assert slo.worst() == "no_data"

    def test_registry_evaluation_quantile_and_counter(self):
        reg = metrics.Registry()
        h = reg.histogram("lat_seconds", "x", ("milestone",))
        for v in (0.1, 0.2, 0.3):
            h.observe(v, milestone="bound")
        h.observe(9.0, milestone="other")  # filtered out by labels
        obj = slo.Objective(
            "lat", "lat_seconds", target=1.0,
            labels=(("milestone", "bound"),),
        )
        e = slo.evaluate_objective(obj, registry=reg)
        assert e["samples"] == 3 and e["verdict"] == "pass"
        assert e["p99"] <= 1.0 and e["p50"] <= 0.5
        c = reg.counter("drops_total", "x", ("resource",))
        cobj = slo.Objective(
            "drops", "drops_total", kind="counter_max", target=0.0
        )
        e = slo.evaluate_objective(cobj, registry=reg)
        # No series yet: zero drops IS a pass, but samples stay 0.
        assert e["verdict"] == "pass" and e["samples"] == 0
        c.inc(resource="pods")
        e = slo.evaluate_objective(cobj, registry=reg)
        assert e["verdict"] == "burn" and e["samples"] == 1

    def test_partial_label_filter_takes_worst_set(self):
        reg = metrics.Registry()
        h = reg.histogram("multi_seconds", "x", ("verb", "resource"))
        h.observe(0.1, verb="GET", resource="pods")
        h.observe(5.0, verb="PUT", resource="pods")
        obj = slo.Objective(
            "m", "multi_seconds", target=1.0,
            labels=(("resource", "pods"),),
        )
        e = slo.evaluate_objective(obj, registry=reg)
        assert e["verdict"] == "burn", e  # the PUT set carries it

    def test_missing_series_is_no_data(self):
        e = slo.evaluate_objective(
            slo.Objective("x", "nope_seconds", target=1.0),
            registry=metrics.Registry(),
        )
        assert e["verdict"] == "no_data" and e["samples"] == 0

    def test_report_overall_ignores_unsampled(self):
        reg = metrics.Registry()
        h = reg.histogram("ok_seconds", "x")
        h.observe(0.01)
        report = slo.evaluate(
            (
                slo.Objective("ok", "ok_seconds", target=1.0),
                slo.Objective("quiet", "quiet_seconds", target=1.0),
            ),
            registry=reg,
        )
        assert report["verdict"] == "pass" and report["sampled"]
        empty = slo.evaluate(
            (slo.Objective("quiet", "quiet_seconds", target=1.0),),
            registry=reg,
        )
        assert empty["verdict"] == "no_data" and not empty["sampled"]

    def test_bench_objectives_are_the_published_definitions(self):
        # 0.1: the always-resident incremental loop's sub-100ms p99
        # pod-to-bind bar (PR 12); CPU CI legs widen it via gate_s.
        assert slo.BENCH_OBJECTIVES["bind_latency_slo"].target == 0.1
        assert slo.BENCH_OBJECTIVES["churn_api_slo"].target == 25000.0
        assert slo.BENCH_OBJECTIVES["pod_crud_slo"].target == 20000.0
        for name in ("churn_api_slo", "pod_crud_slo"):
            assert slo.BENCH_OBJECTIVES[name].severity == "warn"
            assert slo.BENCH_OBJECTIVES[name].kind == "value_min"
        tuned = slo.with_target(
            slo.BENCH_OBJECTIVES["bind_latency_slo"], 2.0
        )
        assert tuned.target == 2.0
        assert slo.verdict_for_value(tuned, 1.5) == "pass"


def _mk_cluster():
    """In-process cluster: apiserver + LocalTransport clients + batch
    scheduler (the check.sh explain-smoke shape)."""
    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.scheduler.daemon import (
        BatchScheduler,
        SchedulerConfig,
    )
    from kubernetes_tpu.server.api import APIServer

    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(2):
        client.create("nodes", {
            "kind": "Node", "metadata": {"name": f"n{j}"},
            "status": {
                "capacity": {"cpu": "8", "memory": "16Gi", "pods": "50"},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        })
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync(timeout=60), "caches never synced"
    return api, client, cfg, BatchScheduler(cfg)


class TestEndToEnd:
    def test_lifecycle_slis_and_slo_surface(self):
        api, client, cfg, sched = _mk_cluster()
        from kubernetes_tpu.cli import ktctl

        n = 4
        base = {
            m: sli.STARTUP_LATENCY.count(milestone=m)
            for m in ("decision", "bound", "running")
        }
        try:
            for i in range(n):
                client.create("pods", _pod_wire(f"e2e-{i}"))
            deadline = time.monotonic() + 60
            bound = 0
            while bound < n and time.monotonic() < deadline:
                sched.schedule_batch(timeout=0.2)
                bound = sum(
                    1
                    for p in client.list("pods", namespace="default")[0]
                    if p.spec.node_name
                )
            assert bound == n, f"only {bound}/{n} bound"
            # Stand-in kubelet: flip each pod Running via the status
            # subresource (the collector reads the watch, not us).
            for i in range(n):
                p = client.get("pods", f"e2e-{i}")
                p.status.phase = "Running"
                client.update_status("pods", p, namespace="default")

            def milestone_counts():
                return {
                    m: sli.STARTUP_LATENCY.count(milestone=m) - base[m]
                    for m in ("decision", "bound", "running")
                }

            deadline = time.monotonic() + 10
            while (
                milestone_counts()["running"] < n
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            got = milestone_counts()
            assert got["bound"] >= n and got["running"] >= n, got
            # The PR-5 join: the flight recorder's decisions stamped
            # the decision milestone for this tick's pods.
            assert got["decision"] >= n, got

            # SLO engine over the live registry.
            report = slo.evaluate()
            objs = {o["name"]: o for o in report["objectives"]}
            assert objs["pod_startup_latency"]["samples"] >= n
            assert objs["pod_startup_latency"]["verdict"] in (
                "pass", "warn", "burn",
            )
            assert objs["pod_bound_latency"]["samples"] >= n
            assert report["sampled"]

            # Device telemetry rode the tick: the compile-cache gauge
            # and transfer counters are live.
            assert sli.XLA_CACHE_ENTRIES.value() >= 1
            assert sli.XLA_COMPILES.value() >= 1
            assert sli.TRANSFER_BYTES.value(direction="h2d") > 0
            assert sli.TRANSFER_BYTES.value(direction="d2h") > 0
            # Informer staleness gauges were set for the daemon's caches.
            staleness = {
                r for (r,) in sli.INFORMER_STALENESS.label_values()
            }
            assert {"nodes", "pods_pending"} <= staleness

            # ktctl slo (LocalTransport: evaluates the local engine).
            out = io.StringIO()
            with redirect_stdout(out):
                rc = ktctl.main(["slo"], client=client)
            assert rc == 0, out.getvalue()
            text = out.getvalue()
            assert "pod_startup_latency" in text and "overall:" in text

            out = io.StringIO()
            with redirect_stdout(out):
                rc = ktctl.main(["slo", "-o", "json"], client=client)
            assert rc == 0
            parsed = json.loads(out.getvalue())
            assert parsed["kind"] == "SLOReport"

            # ktctl top cluster: SLO table + raw telemetry series.
            out = io.StringIO()
            with redirect_stdout(out):
                rc = ktctl.main(["top", "cluster"], client=client)
            assert rc == 0
            text = out.getvalue()
            assert "OBJECTIVE" in text
            assert "solver_xla_compile_cache_entries" in text
        finally:
            cfg.stop()

    def test_http_debug_slo_and_watch_lag(self):
        """The HTTP surface: GET /debug/slo serves the engine's report;
        a real chunked watch over HTTP feeds the fan-out lag series."""
        import urllib.request

        from kubernetes_tpu.client import Client, HTTPTransport
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        srv = APIHTTPServer(api).start()
        try:
            client = Client(HTTPTransport(srv.address))
            lag_before = sum(
                sli.WATCH_LAG.count(resource=r)
                for (r,) in sli.WATCH_LAG.label_values()
            )
            # Cluster-wide unfiltered watch: namespace- or selector-
            # scoped streams are deliberately excluded from the lag
            # SLI (their filtered-out events would read as false lag).
            stream = client.watch("pods")
            for i in range(5):
                client.create(
                    "pods", _pod_wire(f"http-{i}"), namespace="default"
                )
            seen = 0
            deadline = time.monotonic() + 10
            while seen < 5 and time.monotonic() < deadline:
                ev = stream.next(timeout=1.0)
                if ev is not None:
                    seen += 1
            stream.close()
            assert seen == 5
            deadline = time.monotonic() + 5
            while (
                sum(
                    sli.WATCH_LAG.count(resource=r)
                    for (r,) in sli.WATCH_LAG.label_values()
                )
                <= lag_before
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert (
                sum(
                    sli.WATCH_LAG.count(resource=r)
                    for (r,) in sli.WATCH_LAG.label_values()
                )
                > lag_before
            ), "HTTP watch delivery never observed fan-out lag"

            with urllib.request.urlopen(
                srv.address + "/debug/slo", timeout=10
            ) as resp:
                report = json.loads(resp.read())
            assert report["kind"] == "SLOReport"
            names = {o["name"] for o in report["objectives"]}
            assert {
                "pod_startup_latency", "watch_fanout_lag",
                "watch_stream_drops", "solver_compile_churn",
            } <= names
        finally:
            srv.stop()

    def test_ktctl_slo_empty_cluster_miss_contract(self, monkeypatch):
        """`ktctl slo` against a cluster with no SLI samples exits 1
        with 'no SLI samples recorded' and an EMPTY stdout (the ktctl
        trace/explain miss contract)."""
        from kubernetes_tpu.cli import ktctl
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.server.api import APIServer

        # Samples are process-global: evaluate against an EMPTY
        # registry to model the freshly booted cluster (the check.sh
        # smoke proves the same contract in a genuinely fresh process).
        monkeypatch.setattr(
            ktctl,
            "_fetch_slo_report",
            lambda client, args: slo.evaluate(registry=metrics.Registry()),
        )
        api = APIServer()
        client = Client(LocalTransport(api))
        out, err = io.StringIO(), io.StringIO()
        with redirect_stdout(out), redirect_stderr(err):
            rc = ktctl.main(["slo"], client=client)
        assert rc == 1
        assert out.getvalue() == ""
        assert "no SLI samples recorded" in err.getvalue()


class TestOverheadGuard:
    """Observability must be affordable enough to stay always-on: the
    collector + per-tick device telemetry are pinned at <5% of the
    bulk-churn drill's measured per-pod budget (satellite 6)."""

    def test_sli_cost_under_5pct_of_bulk_churn(self):
        from kubernetes_tpu.client import Client, HTTPTransport
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        n_pods, batch = 2000, 500
        # Warm the one-time costs that are NOT per-tick (ops import /
        # first device-stats probe) out of both timed sections — the
        # daemons pay them once per process, not per tick.
        sli.observe_device_telemetry()
        api = APIServer()  # SLI collector attached (always-on)
        api.list("pods", "default")
        srv = APIHTTPServer(api, max_in_flight=800).start()
        try:
            import threading

            client = Client(HTTPTransport(srv.address))
            # The _bulk_churn_figure drill's shape: bulk create + bulk
            # delete over real HTTP, one group commit per batch, a live
            # watch connection consuming every event (the drill's
            # watch-visibility leg), with the collector attached.
            stream = Client(HTTPTransport(srv.address)).watch(
                "pods", namespace="default"
            )
            seen = {"n": 0}

            def consume():
                while seen["n"] < 2 * n_pods:
                    ev = stream.next(timeout=10.0)
                    if ev is None:
                        if stream.closed:
                            return
                        continue
                    seen["n"] += 1

            watcher = threading.Thread(target=consume, daemon=True)
            t0 = time.perf_counter()
            watcher.start()
            for s in range(0, n_pods, batch):
                items = [
                    _pod_wire(f"ov-{i}") for i in range(s, s + batch)
                ]
                res = client.create_bulk(
                    "pods", items, namespace="default"
                )
                assert all(r.get("status") == "Success" for r in res)
            for s in range(0, n_pods, batch):
                client.delete_bulk(
                    "pods",
                    [f"ov-{i}" for i in range(s, s + batch)],
                    namespace="default",
                )
            watcher.join(timeout=30)
            drill_wall = time.perf_counter() - t0
            stream.close()
            assert seen["n"] >= 2 * n_pods, seen
        finally:
            srv.stop()

        # Standalone cost of everything the drill added per event: the
        # SAME 2*n_pods lifecycle events through a fresh collector,
        # plus one device-telemetry sample per batch (the per-tick
        # daemon cost). If this total is <5% of the drill wall, the
        # always-on plane costs <5% of bulk-churn throughput. Best of
        # three repeats: a GC pass landing inside one repeat must not
        # fail the guard (the drill amortizes such noise; a 10ms
        # standalone loop cannot).
        events = []
        for i in range(n_pods):
            events.append(
                ("ADDED", _key(f"ov-{i}"), _pod_wire(f"ov-{i}"))
            )
        for i in range(n_pods):
            events.append(
                ("DELETED", _key(f"ov-{i}"), _pod_wire(f"ov-{i}"))
            )
        sli_cost = float("inf")
        for _repeat in range(3):
            c = sli.LifecycleSLICollector()
            t0 = time.perf_counter()
            for etype, key, obj in events:
                c._on_store_event(1, etype, key, obj, None)
            for _ in range(2 * n_pods // batch):
                sli.observe_device_telemetry()
            sli_cost = min(sli_cost, time.perf_counter() - t0)
        assert sli_cost < 0.05 * drill_wall, (
            f"SLI plane cost {sli_cost:.4f}s is >=5% of the "
            f"{drill_wall:.4f}s bulk-churn drill"
        )
