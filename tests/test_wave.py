"""Wave-commit solver: validity, throughput (>1 pod per device step),
determinism, and sharded-mesh execution.

The wave solver trades decision-order parity for batching (VERDICT r1
#6); what it must NEVER trade is placement VALIDITY — every assignment
is checked here against the snapshot's own predicate semantics."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from kubernetes_tpu.models.columnar import build_snapshot
from kubernetes_tpu.ops import device_snapshot
from kubernetes_tpu.ops.oracle import validate_assignment_numpy
from kubernetes_tpu.ops.solver import solve_assignments
from kubernetes_tpu.ops.wave import solve_waves, wave_assignments
from test_solver_parity import mk_node, mk_pod, random_cluster


# The validity replay now lives in the oracle library (promoted there
# so ops/parity.py can register it as the wave family's NumPy twin —
# KT006); this alias keeps the historical name for test_sinkhorn.
check_validity = validate_assignment_numpy


class TestWaveValidity:
    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_placements_valid_and_count_matches_scan(self, seed):
        pods, nodes, assigned, services = random_cluster(seed)
        snap = build_snapshot(pods, nodes, assigned, services)
        d = device_snapshot(snap)
        scan = solve_assignments(d)
        wave, _ = wave_assignments(d, window=32)
        check_validity(snap, wave)
        # Placement counts track the sequential policy closely. Exact
        # equality is NOT guaranteed on capacity-tight instances:
        # commit order changes which pods fit, in either direction
        # (the wave's randomized ties sometimes pack MORE pods than
        # sequential lowest-index does).
        placed_scan = int((scan >= 0).sum())
        placed_wave = int((wave >= 0).sum())
        slack = max(2, placed_scan // 10)
        assert abs(placed_wave - placed_scan) <= slack, (wave, scan)

    def test_capacity_stress_places_exactly_what_fits(self):
        pods = [mk_pod(f"p{i}", cpu=600, mem_mib=64) for i in range(10)]
        nodes = [mk_node(f"n{j}", cpu=1000) for j in range(3)]
        snap = build_snapshot(pods, nodes)
        d = device_snapshot(snap)
        wave, _ = wave_assignments(d, window=8)
        check_validity(snap, wave)
        assert (wave >= 0).sum() == 3  # one 600m pod per 1000m node

    def test_zero_request_pods_fit_by_count(self):
        pods = [mk_pod(f"z{i}", cpu=0, mem_mib=0) for i in range(5)]
        nodes = [mk_node("n0", pods=2), mk_node("n1", pods=2)]
        snap = build_snapshot(pods, nodes)
        d = device_snapshot(snap)
        wave, _ = wave_assignments(d, window=8)
        check_validity(snap, wave)
        assert (wave >= 0).sum() == 4

    def test_host_port_conflicts_respected(self):
        pods = [mk_pod(f"hp{i}", host_port=8080) for i in range(4)]
        nodes = [mk_node("n0"), mk_node("n1")]
        snap = build_snapshot(pods, nodes)
        d = device_snapshot(snap)
        wave, _ = wave_assignments(d, window=4)
        check_validity(snap, wave)
        assert (wave >= 0).sum() == 2  # one per node, port exclusivity

    def test_deterministic(self):
        pods, nodes, assigned, services = random_cluster(3)
        snap = build_snapshot(pods, nodes, assigned, services)
        d = device_snapshot(snap)
        a1, _ = wave_assignments(d, window=16)
        a2, _ = wave_assignments(d, window=16)
        assert (a1 == a2).all()


class TestWaveThroughput:
    def test_many_pods_per_wave(self):
        """VERDICT r1 #6 'done' criterion: per-step commit count > 1."""
        pods = [
            mk_pod(f"p{i}", cpu=100 + 50 * (i % 4), mem_mib=64)
            for i in range(96)
        ]
        nodes = [mk_node(f"n{j}", cpu=8000, mem_mib=8192) for j in range(24)]
        snap = build_snapshot(pods, nodes)
        d = device_snapshot(snap)
        wave, waves = wave_assignments(d, window=96)
        check_validity(snap, wave)
        assert (wave >= 0).sum() == 96
        assert waves < 96 / 2, waves  # strictly batching, not scanning
        assert 96 / waves > 1.0


class TestWaveOnMesh:
    def test_sharded_matches_single_device(self):
        """8-way node-sharded wave solve must produce the identical
        assignment (integer math + deterministic tie hash)."""
        pods, nodes, assigned, services = random_cluster(5)
        snap = build_snapshot(pods, nodes, assigned, services)
        single = device_snapshot(snap)
        base, _ = wave_assignments(single, window=16)

        devices = np.array(jax.devices()[:8])
        mesh = Mesh(devices, axis_names=("nodes",))
        sharded = device_snapshot(snap, mesh=mesh, pad_to=8)
        with mesh:
            out, _ = solve_waves(sharded.pods, sharded.nodes, window=16)
            out.block_until_ready()
        a = np.asarray(out)[: sharded.n_pods]
        a = np.where(a >= sharded.n_nodes, -1, a)
        assert (a == base).all()


class TestPipelinedModes:
    """solve_backlog_pipelined(mode='wave'|'sinkhorn'): the fast-path
    chunk loop must preserve every placement invariant while chaining
    the donated carry across chunks (bench.py's wall_fast_s path)."""

    @staticmethod
    def _as_indices(out, nodes):
        idx = {n.metadata.name: i for i, n in enumerate(nodes)}
        return np.array(
            [idx[x] if x is not None else -1 for x in out], dtype=np.int64
        )

    @pytest.mark.parametrize("mode", ["wave", "sinkhorn"])
    @pytest.mark.parametrize("seed", [0, 4])
    def test_chunked_placements_valid(self, mode, seed):
        from kubernetes_tpu.ops.pipeline import solve_backlog_pipelined

        pods, nodes, assigned, services = random_cluster(seed)
        out = solve_backlog_pipelined(
            pods, nodes, assigned, services, mode=mode, chunk=8
        )
        snap = build_snapshot(pods, nodes, assigned, services)
        check_validity(snap, self._as_indices(out, nodes))

    @pytest.mark.parametrize("mode", ["wave", "sinkhorn"])
    def test_chunked_matches_capacity_exactly(self, mode):
        from kubernetes_tpu.ops.pipeline import solve_backlog_pipelined

        pods = [mk_pod(f"p{i}", cpu=600, mem_mib=64) for i in range(10)]
        nodes = [mk_node(f"n{j}", cpu=1000) for j in range(3)]
        out = solve_backlog_pipelined(pods, nodes, mode=mode, chunk=4)
        placed = [x for x in out if x is not None]
        assert len(placed) == 3  # one 600m pod per 1000m node, ever
        assert len(set(placed)) == 3

    def test_chunk_boundaries_carry_occupancy(self):
        """A node filled by chunk k must be unavailable to chunk k+1:
        port exclusivity across a 1-pod chunk boundary proves the
        carry actually chains."""
        from kubernetes_tpu.ops.pipeline import solve_backlog_pipelined

        pods = [mk_pod(f"hp{i}", host_port=8080) for i in range(4)]
        nodes = [mk_node("n0"), mk_node("n1")]
        out = solve_backlog_pipelined(pods, nodes, mode="wave", chunk=1)
        placed = [x for x in out if x is not None]
        assert sorted(placed) == ["n0", "n1"]

    def test_unknown_mode_rejected(self):
        from kubernetes_tpu.ops.pipeline import solve_backlog_pipelined

        with pytest.raises(ValueError, match="unknown pipeline mode"):
            solve_backlog_pipelined([], [], mode="hungarian")
