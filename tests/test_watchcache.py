"""The API-plane fast paths (ISSUE 6): watch-cache read path, bulk
write verbs with WAL group commit, encode caching, and the
no-store-scan steady state.

Covers the acceptance criteria:
- LIST from the watch cache equals a LIST from the store under
  concurrent writes (read-your-writes consistency);
- Reflector relist-on-compaction keeps informers converging;
- bulk create commits N objects under ONE fsync, survives WAL replay,
  and emits watch events in version order;
- the daemons/controllers steady state issues NO store-level list
  calls (the soak-tick counter test);
- the kvstore shutdown race fix (serialized writers never strand);
- wire/typed pod validator parity.
"""

import json
import os
import threading
import time

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.store import KVStore
from kubernetes_tpu.store.kvstore import StoreClosedError


def pod_wire(name, ns="default", node="", labels=None):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": {
            "nodeName": node,
            "containers": [
                {
                    "name": "c",
                    "image": "app",
                    "resources": {
                        "limits": {"cpu": "100m", "memory": "64Mi"}
                    },
                }
            ],
        },
    }


def node_wire(name, cpu="8"):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": cpu, "memory": "16Gi", "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


class TestWatchCacheConsistency:
    def test_list_from_cache_equals_store_under_concurrent_writes(self):
        api = APIServer()
        api.list("pods", "default")  # build the cache
        stop = threading.Event()
        errors = []

        def writer(wid):
            try:
                for i in range(400):
                    if stop.is_set():
                        return
                    api.create("pods", "default", pod_wire(f"w{wid}-{i}"))
                    if i % 3 == 0:
                        api.delete("pods", "default", f"w{wid}-{i}")
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(w,), daemon=True)
            for w in range(3)
        ]
        for t in threads:
            t.start()
        # Mid-flight: every LIST must satisfy read-your-writes — the
        # reported resourceVersion is never behind the store version
        # observed BEFORE the call.
        for _ in range(20):
            floor = api.store.version
            out = api.list("pods", "default")
            assert int(out["metadata"]["resourceVersion"]) >= floor
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errors
        # Quiesced: cache content == store content, exactly.
        store_items, store_v = api.store.list("/registry/pods/")
        cache_out = api.list("pods", "default")
        assert int(cache_out["metadata"]["resourceVersion"]) >= store_v
        by_name = lambda objs: {  # noqa: E731
            o["metadata"]["name"]: o["metadata"]["resourceVersion"]
            for o in objs
        }
        assert by_name(cache_out["items"]) == by_name(store_items)

    def test_encoded_list_matches_dict_list_with_selectors(self):
        api = APIServer()
        api.create("pods", "default", pod_wire("a", labels={"app": "x"}))
        api.create("pods", "default", pod_wire("b", labels={"app": "y"}))
        api.create("pods", "default", pod_wire("c", node="n1"))
        for lsel, fsel in (
            ("", ""), ("app=x", ""), ("", "spec.nodeName="), ("app!=x", ""),
        ):
            enc = api.list_response_bytes(
                "pods", "default", label_selector=lsel, field_selector=fsel
            )
            ref = api.list(
                "pods", "default", label_selector=lsel, field_selector=fsel
            )
            got = json.loads(enc)
            assert got["kind"] == "PodList"
            assert [o["metadata"]["name"] for o in got["items"]] == [
                o["metadata"]["name"] for o in ref["items"]
            ], (lsel, fsel)

    def test_encoded_get_and_404_fallback(self):
        api = APIServer()
        api.create("pods", "default", pod_wire("a"))
        enc = api.get_response_bytes("pods", "default", "a")
        assert json.loads(enc)["metadata"]["name"] == "a"
        assert api.get_response_bytes("pods", "default", "nope") is None

    def test_encode_cache_reuses_bytes_per_resource_version(self):
        api = APIServer()
        api.create("pods", "default", pod_wire("a"))
        first = api.list_response_bytes("pods", "default")
        again = api.list_response_bytes("pods", "default")
        assert first == again
        # A write invalidates exactly that object's fragment.
        api.update_status(
            "pods", "default", "a", {"status": {"phase": "Running"}}
        )
        updated = json.loads(api.list_response_bytes("pods", "default"))
        assert updated["items"][0]["status"]["phase"] == "Running"

    def test_cache_serves_ttl_expiry(self):
        store = KVStore()
        api = APIServer(store=store)
        store.create("/registry/events/default/e1", {"kind": "Event",
                     "metadata": {"name": "e1", "namespace": "default"}},
                     ttl=0.05)
        assert len(api.list("events", "default")["items"]) == 1
        time.sleep(0.1)
        # A quiet store: the cache read must still expire the TTL'd
        # object (fresh() pokes expiry) rather than serve it forever.
        assert api.list("events", "default")["items"] == []


class TestReflectorCompaction:
    def test_informer_converges_across_compaction(self):
        from kubernetes_tpu.client.cache import Informer

        # Tiny history ring: churn blows through it so resumed watches
        # raise CompactedError (410) and the Reflector must re-list.
        api = APIServer(store=KVStore(history_limit=32))
        client = Client(LocalTransport(api))
        inf = Informer(client, "pods").start()
        assert inf.wait_for_sync(10)
        for i in range(200):
            api.create("pods", "default", pod_wire(f"c{i}"))
            if i >= 50:
                api.delete("pods", "default", f"c{i - 50}")
        deadline = time.monotonic() + 20
        expected = {f"c{i}" for i in range(150, 200)}
        while time.monotonic() < deadline:
            names = {
                o["metadata"]["name"] if isinstance(o, dict)
                else o.metadata.name
                for o in inf.store.list()
            }
            if names == expected:
                break
            time.sleep(0.05)
        inf.stop()
        assert names == expected


class TestBulkVerbs:
    def test_bulk_create_emits_watch_events_in_input_and_version_order(self):
        api = APIServer()
        stream = api.watch("pods", "default")
        names = [f"p{i}" for i in range(50)]
        res = api.create_bulk(
            "pods", "default", [pod_wire(n) for n in names]
        )
        assert all(r["status"] == "Success" and r["code"] == 201 for r in res)
        seen = []
        versions = []
        deadline = time.monotonic() + 5
        while len(seen) < len(names) and time.monotonic() < deadline:
            ev = stream.next(timeout=0.5)
            if ev is None:
                continue
            assert ev.type == "ADDED"
            seen.append(ev.object["metadata"]["name"])
            versions.append(ev.version)
        stream.close()
        assert seen == names  # input order == version order
        assert versions == sorted(versions)

    def test_bulk_create_partial_failure_is_per_item(self):
        api = APIServer()
        api.create("pods", "default", pod_wire("dup"))
        res = api.create_bulk(
            "pods", "default",
            [pod_wire("ok1"), pod_wire("dup"), {"metadata": {}},
             pod_wire("ok2")],
        )
        assert res[0]["status"] == "Success"
        assert res[1]["code"] == 409
        assert res[2]["code"] == 422
        assert res[3]["status"] == "Success"
        assert len(api.list("pods", "default")["items"]) == 3

    def test_bulk_update_and_delete(self):
        api = APIServer()
        api.create_bulk(
            "pods", "default", [pod_wire(f"u{i}") for i in range(5)]
        )
        items = [pod_wire(f"u{i}", labels={"touched": "yes"}) for i in range(5)]
        res = api.update_bulk("pods", "default", items)
        assert all(r["status"] == "Success" for r in res)
        got = api.get("pods", "default", "u3")
        assert got["metadata"]["labels"] == {"touched": "yes"}
        assert got["metadata"]["uid"]  # carried over from the stored pod
        res = api.delete_bulk(
            "pods", "default", [f"u{i}" for i in range(5)] + ["ghost"]
        )
        assert [r["code"] for r in res] == [200] * 5 + [404]
        assert api.list("pods", "default")["items"] == []

    def test_bulk_create_malformed_item_fails_its_slot_only(self):
        """A non-APIError escaping validation (non-numeric priority,
        non-string label value) must 422 ITS slot, not 500 the batch."""
        api = APIServer()
        bad_prio = pod_wire("badprio")
        bad_prio["spec"]["priority"] = "high"
        bad_label = pod_wire("badlabel")
        bad_label["metadata"]["labels"] = {"k": 7}
        res = api.create_bulk(
            "pods", "default", [pod_wire("ok-a"), bad_prio, bad_label,
                                pod_wire("ok-b")],
        )
        assert res[0]["status"] == "Success"
        assert res[1]["code"] == 422
        assert res[2]["code"] == 422
        assert res[3]["status"] == "Success"
        names = {
            o["metadata"]["name"]
            for o in api.list("pods", "default")["items"]
        }
        assert names == {"ok-a", "ok-b"}

    def test_bulk_update_cas_conflict(self):
        api = APIServer()
        api.create("pods", "default", pod_wire("c1"))
        stale = dict(pod_wire("c1"))
        stale["metadata"]["resourceVersion"] = "1"
        res = api.update_bulk("pods", "default", [stale])
        assert res[0]["code"] == 409

    def test_bulk_http_roundtrip(self):
        api = APIServer()
        srv = APIHTTPServer(api).start()
        try:
            client = Client(HTTPTransport(srv.address))
            res = client.create_bulk(
                "pods", [pod_wire(f"h{i}") for i in range(8)],
                namespace="default",
            )
            assert all(r["status"] == "Success" for r in res)
            items, _ = client.list("pods", namespace="default")
            assert len(items) == 8
            res = client.delete_bulk(
                "pods", [f"h{i}" for i in range(8)], namespace="default"
            )
            assert all(r["status"] == "Success" for r in res)
        finally:
            srv.stop(release_store=False)


class TestGroupCommitDurability:
    def test_bulk_create_is_one_fsync_and_survives_replay(
        self, tmp_path, monkeypatch
    ):
        data_dir = str(tmp_path / "wal")
        store = KVStore(data_dir=data_dir)
        fsyncs = []
        real_fsync = os.fsync

        def counting_fsync(fd):
            fsyncs.append(fd)
            return real_fsync(fd)

        monkeypatch.setattr(os, "fsync", counting_fsync)
        api = APIServer(store=store)
        baseline = len(fsyncs)
        res = api.create_bulk(
            "pods", "default", [pod_wire(f"d{i}") for i in range(64)]
        )
        assert all(r["status"] == "Success" for r in res)
        assert len(fsyncs) - baseline == 1  # ONE group commit for 64 pods
        # Bulk bind: same single-fsync guarantee on the commit path.
        api.create("nodes", "", node_wire("n1"))
        baseline = len(fsyncs)
        out = api.bind_bulk(
            "default",
            [
                {"metadata": {"name": f"d{i}"}, "target": {"name": "n1"}}
                for i in range(64)
            ],
        )
        assert all(r["status"] == "Success" for r in out)
        assert len(fsyncs) - baseline == 1
        monkeypatch.setattr(os, "fsync", real_fsync)
        store.close()
        # WAL replay: a fresh store on the same dir recovers everything.
        re_store = KVStore(data_dir=data_dir)
        try:
            pods, _ = re_store.list("/registry/pods/default/")
            assert len(pods) == 64
            assert all(
                p["spec"]["nodeName"] == "n1" for p in pods
            )
        finally:
            re_store.close()


@pytest.mark.chaos
class TestCrashRecoveryProperty:
    """ISSUE 15: randomized write/snapshot/crash schedules, crashing
    via injected faults at every WAL/snapshot boundary, asserting the
    replayed state equals the pre-crash committed prefix.

    The oracle is exact, not fuzzy, because each fault kind has a
    deterministic durability verdict for the op it kills:

    - ``torn_write``: the record is PARTIAL on disk (no newline) and
      the write unacked — recovery truncates it away, so the op is
      absent (the key keeps its pre-op value);
    - ``wal_fsync``: the record was appended+flushed, only the
      durability ack was refused — in-process (shared page cache) the
      op survives the crash;
    - ``snapshot_rename``: fires AFTER the triggering op's record was
      appended — the op survives on the previous snapshot + full WAL;
    - plain crash between acked ops: every acked op survives.
    """

    KEYS = [f"/registry/pods/default/p{i}" for i in range(10)]

    def _apply_model(self, model, op, key, val):
        if op == "delete":
            model.pop(key, None)
        else:
            model[key] = val

    def _run_schedule(self, base_dir, seed):
        import random

        from kubernetes_tpu.utils import faults

        rng = random.Random(seed)
        data_dir = os.path.join(str(base_dir), f"sched-{seed}")
        store = KVStore(
            data_dir=data_dir,
            snapshot_every=rng.choice([3, 7, 100000]),
        )
        fault_kind = rng.choice(
            ["torn_write", "wal_fsync", "snapshot_rename", "none"]
        )
        n_ops = rng.randrange(20, 45)
        crash_at = rng.randrange(4, n_ops)
        model = {}
        serial = 0
        crashed_op = None  # (op, key, value) the fault interrupted
        try:
            for i in range(n_ops):
                key = rng.choice(self.KEYS)
                if key in model:
                    op = rng.choice(["set", "delete", "snapshot"])
                else:
                    op = "create"
                serial += 1
                val = pod_wire(f"v{serial}", labels={"serial": str(serial)})
                if i == crash_at and fault_kind != "none":
                    site = {
                        "torn_write": faults.WAL_TORN_WRITE,
                        "wal_fsync": faults.WAL_FSYNC,
                        "snapshot_rename": faults.SNAPSHOT_RENAME,
                    }[fault_kind]
                    faults.inject(site, every=1, times=1)
                try:
                    if op == "create":
                        store.create(key, val)
                    elif op == "set":
                        store.set(key, val)
                    elif op == "delete":
                        store.delete(key)
                    else:
                        store.snapshot()
                        continue  # no object mutation to model
                except faults.FaultInjected:
                    crashed_op = (op, key, val)
                    break  # the process "dies" here
                self._apply_model(model, op, key, val)
                if i == crash_at:
                    break  # plain crash after an acked op (or the
                    # armed fault's boundary wasn't crossed: a
                    # snapshot op appends no WAL record)
        finally:
            faults.clear()
            store.crash()
        recovered = KVStore(data_dir=data_dir)
        try:
            # Exact oracle: read back every schedule key and compare
            # against the committed prefix (values carry a serial).
            committed = {
                k: v["metadata"]["labels"]["serial"] for k, v in model.items()
            }
            # The key the fault interrupted gets its own deterministic
            # verdict below; "snapshot" ops touched no key.
            exempt_key = None
            if crashed_op is not None and crashed_op[0] != "snapshot":
                exempt_key = crashed_op[1]
            for k in self.KEYS:
                if k == exempt_key:
                    continue
                if k in committed:
                    obj = recovered.get(k)
                    assert (
                        obj["metadata"]["labels"]["serial"] == committed[k]
                    ), (
                        f"seed {seed} ({fault_kind}): {k} replayed "
                        f"serial {obj['metadata']['labels']['serial']}, "
                        f"committed prefix says {committed[k]}"
                    )
                else:
                    try:
                        recovered.get(k)
                    except Exception:
                        continue  # absent, as committed prefix says
                    raise AssertionError(
                        f"seed {seed} ({fault_kind}): {k} replayed but "
                        "is not in the committed prefix"
                    )
            if crashed_op is not None and crashed_op[0] != "snapshot":
                op, k, val = crashed_op
                want_serial = val["metadata"]["labels"]["serial"]

                def lookup():
                    try:
                        return recovered.get(k)
                    except Exception:
                        return None

                obj = lookup()
                if fault_kind == "torn_write":
                    # Torn record truncated on replay: the key holds
                    # its pre-op committed value (or nothing).
                    if k in committed:
                        assert obj is not None and (
                            obj["metadata"]["labels"]["serial"]
                            == committed[k]
                        ), f"seed {seed}: torn write corrupted {k}"
                    else:
                        assert obj is None or (
                            obj["metadata"]["labels"]["serial"]
                            != want_serial
                        ), f"seed {seed}: torn write survived replay"
                else:
                    # wal_fsync / snapshot_rename fire AFTER the op's
                    # record was appended+flushed: the op survives.
                    if op == "delete":
                        assert obj is None, (
                            f"seed {seed} ({fault_kind}): flushed "
                            "delete lost on replay"
                        )
                    else:
                        assert obj is not None and (
                            obj["metadata"]["labels"]["serial"]
                            == want_serial
                        ), (
                            f"seed {seed} ({fault_kind}): flushed "
                            "record lost on replay"
                        )
            # The version clock recovered intact: a new write bumps
            # PAST everything replayed.
            v_before = recovered.version
            stored = recovered.create(
                "/registry/pods/default/post", pod_wire("post")
            )
            assert int(stored["metadata"]["resourceVersion"]) > v_before
        finally:
            recovered.close()

    def test_randomized_crash_schedules_replay_committed_prefix(
        self, tmp_path
    ):
        for seed in range(12):
            self._run_schedule(tmp_path, seed)


class TestNoStoreScanSteadyState:
    def test_soak_tick_issues_no_store_level_lists(self):
        """The acceptance criterion: controllers, the batch daemon, and
        HTTP LISTs read via the informer/watch-cache path — during a
        steady-state soak tick the kvstore's list() is never called."""
        from kubernetes_tpu.controllers.endpoints import EndpointsController
        from kubernetes_tpu.controllers.gangs import GangController
        from kubernetes_tpu.scheduler.daemon import (
            BatchScheduler,
            SchedulerConfig,
        )

        api = APIServer()
        client = Client(LocalTransport(api))
        for j in range(4):
            client.create("nodes", node_wire(f"n{j}"))
        for i in range(8):
            client.create("pods", pod_wire(f"s{i}"))
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        endpoints = EndpointsController(
            Client(LocalTransport(api)), sync_period=0.2
        ).start()
        gangs = GangController(
            Client(LocalTransport(api)), sync_period=0.2
        ).start()
        sched = None
        try:
            assert cfg.wait_for_sync(timeout=60)
            sched = BatchScheduler(cfg)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                sched.schedule_batch(timeout=0.2)
                pods, _ = client.list("pods", namespace="default")
                if all(p.spec.node_name for p in pods):
                    break
            assert all(p.spec.node_name for p in pods)
            # Steady state reached. Count store-level list calls over a
            # soak window of daemon ticks + controller syncs + client
            # LISTs.
            calls = []
            real_list = api.store.list

            def counting_list(*a, **kw):
                calls.append(a)
                return real_list(*a, **kw)

            api.store.list = counting_list
            try:
                for _ in range(3):
                    sched.schedule_batch(timeout=0.05)
                    client.list("pods", namespace="default")
                    client.list("nodes")
                    time.sleep(0.3)  # several controller sync periods
            finally:
                api.store.list = real_list
            assert calls == [], (
                f"store-level list() hit {len(calls)}x on the steady-"
                f"state path: {calls[:5]}"
            )
        finally:
            gangs.stop()
            endpoints.stop()
            cfg.stop()

    def test_session_path_soak_issues_no_store_level_lists(self):
        """ISSUE 12 extension of the pin: the PIPELINED incremental
        daemon (micro-ticks, commit worker, capacity event-waits) on
        its steady state — binds, retries, and watch-delta session
        upkeep all ride informers; the kvstore's list() is never
        called, even while pods churn through the session."""
        from kubernetes_tpu.scheduler.daemon import (
            IncrementalBatchScheduler,
            SchedulerConfig,
        )

        api = APIServer()
        client = Client(LocalTransport(api))
        for j in range(4):
            client.create("nodes", node_wire(f"n{j}"))
        cfg = SchedulerConfig(
            Client(LocalTransport(api)), raw_scheduled_cache=True
        ).start()
        sched = None
        try:
            assert cfg.wait_for_sync(timeout=60)
            sched = IncrementalBatchScheduler(cfg).start()
            for i in range(8):
                client.create("pods", pod_wire(f"inc{i}"))

            def all_bound():
                pods, _ = client.list("pods", namespace="default")
                return pods and all(p.spec.node_name for p in pods)

            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not all_bound():
                time.sleep(0.2)
            assert all_bound()
            # Steady state: count store-level lists over a churn window
            # (deletes + creates keep the session's delta path and the
            # commit pipeline busy).
            calls = []
            real_list = api.store.list

            def counting_list(*a, **kw):
                calls.append(a)
                return real_list(*a, **kw)

            api.store.list = counting_list
            try:
                for r in range(3):
                    client.delete("pods", f"inc{r}", namespace="default")
                    client.create("pods", pod_wire(f"inc-re{r}"))
                    time.sleep(0.3)
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    pods, _ = client.list("pods", namespace="default")
                    if all(p.spec.node_name for p in pods):
                        break
                    time.sleep(0.2)
            finally:
                api.store.list = real_list
            assert calls == [], (
                f"store-level list() hit {len(calls)}x on the session "
                f"path: {calls[:5]}"
            )
        finally:
            if sched is not None:
                sched.stop()
            cfg.stop()


class TestValidatorParity:
    FIXTURES = [
        (pod_wire("ok"), True),
        ({"kind": "Pod", "metadata": {"name": "x", "namespace": "d"},
          "spec": {}}, False),  # no containers
        ({"kind": "Pod", "metadata": {"name": "Bad_Name!", "namespace": "d"},
          "spec": {"containers": [{"name": "c", "image": "i"}]}}, False),
        ({"kind": "Pod", "metadata": {"name": "x", "namespace": "d"},
          "spec": {"containers": [{"name": "c", "image": ""}]}}, False),
        ({"kind": "Pod", "metadata": {"name": "x", "namespace": "d"},
          "spec": {"containers": [
              {"name": "c", "image": "i"}, {"name": "c", "image": "i"}
          ]}}, False),  # duplicate container name
        ({"kind": "Pod", "metadata": {"name": "x", "namespace": "d"},
          "spec": {"restartPolicy": "Sometimes",
                   "containers": [{"name": "c", "image": "i"}]}}, False),
        ({"kind": "Pod", "metadata": {"name": "x", "namespace": "d"},
          "spec": {"preemptionPolicy": "Nevr",
                   "containers": [{"name": "c", "image": "i"}]}}, False),
        ({"kind": "Pod",
          "metadata": {"name": "x", "namespace": "d",
                       "labels": {"k": "bad value!"}},
          "spec": {"containers": [{"name": "c", "image": "i"}]}}, False),
        ({"kind": "Pod", "metadata": {"name": "x", "namespace": "d"},
          "spec": {"containers": [
              {"name": "c", "image": "i",
               "ports": [{"containerPort": 99999}]}
          ]}}, False),
        ({"kind": "Pod", "metadata": {"name": "x", "namespace": "d"},
          "spec": {"containers": [
              {"name": "c", "image": "i",
               "volumeMounts": [{"name": "ghost", "mountPath": "/x"}]}
          ]}}, False),
    ]

    def test_wire_and_typed_validators_agree(self):
        import copy

        from kubernetes_tpu.models import serde
        from kubernetes_tpu.models.objects import Pod
        from kubernetes_tpu.models.validation import (
            ValidationError,
            validate_pod,
            validate_pod_wire,
        )

        for wire, ok in self.FIXTURES:
            wire = copy.deepcopy(wire)
            typed_ok = wire_ok = True
            try:
                validate_pod(serde.from_wire(Pod, wire))
            except ValidationError:
                typed_ok = False
            try:
                validate_pod_wire(wire)
            except ValidationError:
                wire_ok = False
            assert typed_ok == wire_ok == ok, (wire, typed_ok, wire_ok)


class TestSerializedWriterShutdown:
    def test_close_never_strands_queued_writers(self):
        """ADVICE r5: writers racing close() must error out (or
        succeed), never block forever on ev.wait()."""
        store = KVStore(serialized_writes=True)
        n = 24
        outcomes = []
        barrier = threading.Barrier(n + 1)

        def writer(i):
            barrier.wait()
            try:
                store.create(f"/k{i}", {"v": i})
                outcomes.append("ok")
            except StoreClosedError:
                outcomes.append("closed")
            except Exception as e:
                outcomes.append(type(e).__name__)

        threads = [
            threading.Thread(target=writer, args=(i,), daemon=True)
            for i in range(n)
        ]
        for t in threads:
            t.start()
        barrier.wait()
        store.close()
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads), outcomes
        assert len(outcomes) == n
        # Every outcome is a clean success or a clean closed-store
        # error — nothing hung, nothing exotic.
        assert set(outcomes) <= {"ok", "closed", "StoreError"}, outcomes

    def test_late_writer_after_close_fails_fast(self):
        store = KVStore(serialized_writes=True)
        store.close()
        t0 = time.monotonic()
        with pytest.raises(Exception):
            store.create("/late", {"v": 1})
        assert time.monotonic() - t0 < 2.0


class TestBulkEventsProbe:
    def test_attribute_error_inside_handler_does_not_disable_bulk(self):
        """ADVICE r5: only the hasattr probe (and server-side
        400/404/405) may flip the bulk path off — an AttributeError
        raised INSIDE create_events_bulk is a transient failure."""
        from kubernetes_tpu.client.record import _SinkHandler

        class FlakyClient:
            def __init__(self):
                self.calls = 0

            def create_events_bulk(self, evs):
                self.calls += 1
                if self.calls == 1:
                    raise AttributeError("bug inside the handler")
                return [{"status": "Success"} for _ in evs]

            def create(self, *a, **kw):
                raise AssertionError("bulk path must not be disabled")

        def ev(i):
            return {
                "metadata": {"name": f"e{i}", "namespace": "default"},
                "involvedObject": {"kind": "Pod", "name": f"p{i}",
                                   "namespace": "default", "uid": str(i)},
                "reason": "R", "message": "m",
                "source": {"component": "t"}, "count": 1,
            }

        client = FlakyClient()
        h = _SinkHandler(client)
        h.batch([ev(1), ev(2)])  # AttributeError inside: dropped, NOT disabled
        assert h._bulk_ok is not False
        h.batch([ev(3), ev(4)])  # retried through the bulk path
        assert client.calls == 2

    def test_missing_attribute_disables_bulk_without_calling(self):
        from kubernetes_tpu.client.record import _SinkHandler

        class OldClient:
            def __init__(self):
                self.created = []

            def create(self, resource, ev, namespace=""):
                self.created.append(ev)

        def ev(i):
            return {
                "metadata": {"name": f"e{i}", "namespace": "default"},
                "involvedObject": {"kind": "Pod", "name": f"p{i}",
                                   "namespace": "default", "uid": str(i)},
                "reason": "R", "message": "m",
                "source": {"component": "t"}, "count": 1,
            }

        client = OldClient()
        h = _SinkHandler(client)
        h.batch([ev(1), ev(2)])
        assert h._bulk_ok is False
        assert len(client.created) == 2


class TestCanonicalPodKey:
    def test_empty_namespace_pod_uses_one_key_scheme(self):
        from kubernetes_tpu.models import serde
        from kubernetes_tpu.models.columnar import pod_key
        from kubernetes_tpu.models.objects import Pod, pod_full_key
        from kubernetes_tpu.scheduler.daemon import IncrementalBatchScheduler

        wire = pod_wire("p")
        wire["metadata"]["namespace"] = ""
        pod = serde.from_wire(Pod, wire)
        assert pod_key(pod) == "default/p"
        assert pod_full_key(pod) == "default/p"
        assert IncrementalBatchScheduler._obj_key(pod) == "default/p"
        assert IncrementalBatchScheduler._obj_key(wire) == "default/p"
