"""README drift gate (VERDICT r4 Weak #2): the headline-numbers table
must match what tools/update_readme_bench.py generates from the newest
BENCH_r*.json artifact. If a new artifact lands (or the generator
changes), regenerate with `python tools/update_readme_bench.py`."""

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_readme_bench_table_matches_newest_artifact():
    proc = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "update_readme_bench.py"),
         "--check"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, (
        f"{proc.stdout}{proc.stderr}\n"
        "README.md's bench table has drifted from the newest BENCH "
        "artifact — run `python tools/update_readme_bench.py`."
    )


def test_capacity_row_renders_from_figure_keys():
    """ISSUE 16: artifacts carrying the capacity-plane figure keys get
    a table row with the fragmentation score, slice-alloc rate, and
    the tightest probe shape."""
    from tools import update_readme_bench as urb

    block = urb.render("BENCH_test.json", {
        "fragmentation_score": 0.176471,
        "slice_alloc_success_rate": 0.666667,
        "cluster_headroom_pods": {"slice-1x250m": 4, "slice-4x500m": 0},
    })
    (row,) = [
        line for line in block.splitlines()
        if "Capacity & fragmentation" in line
    ]
    assert "**0.176**" in row, row
    assert "67%" in row, row
    assert "slice-4x500m" in row and "0 pods headroom" in row, row


def test_capacity_row_omitted_when_keys_absent():
    """Pre-ISSUE-16 artifacts must not invent a capacity row (the
    generator's contract: absent keys -> omitted row, never a crash)."""
    from tools import update_readme_bench as urb

    block = urb.render("BENCH_test.json", {"pod_crud_ops_per_sec": 100.0})
    assert "Capacity & fragmentation" not in block
    # Headroom map absent but score present: row renders without the
    # tightest-probe clause rather than crashing on min() of nothing.
    block = urb.render("BENCH_test.json", {"fragmentation_score": 0.5})
    (row,) = [
        line for line in block.splitlines()
        if "Capacity & fragmentation" in line
    ]
    assert "tightest probe" not in row, row


def test_rebalance_row_renders_from_figure_keys():
    """ISSUE 17: artifacts carrying the rebalance-plane figure keys
    get a table row with the before -> after fragmentation scores and
    the move count; absent keys omit the row (pre-ISSUE-17 artifacts
    never invent one)."""
    from tools import update_readme_bench as urb

    block = urb.render("BENCH_test.json", {
        "fragmentation_score_before": 0.076923,
        "fragmentation_score_after": 0.025641,
        "rebalance_moves_executed": 2,
        "rebalance_probe_bound": True,
    })
    (row,) = [
        line for line in block.splitlines()
        if "Rebalancing plane" in line
    ]
    assert "**0.077 → 0.026**" in row, row
    assert "2 moves" in row, row
    assert "post-defrag slice probe bound" in row, row
    block = urb.render("BENCH_test.json", {"pod_crud_ops_per_sec": 100.0})
    assert "Rebalancing plane" not in block


def test_failover_row_renders_from_figure_keys():
    """ISSUE 19: artifacts carrying the HA failover drill keys get a
    table row with the kill-to-first-bind p50/p99 and the SLO verdict;
    absent keys omit the row."""
    from tools import update_readme_bench as urb

    block = urb.render("BENCH_test.json", {
        "failover_to_first_bind_p50_s": 0.0105,
        "failover_to_first_bind_p99_s": 0.0156,
        "failover_rounds": 5,
        "failover_slo_target_s": 1.0,
        "failover_slo": "pass",
    })
    (row,) = [
        line for line in block.splitlines() if "HA failover" in line
    ]
    assert "5 drills" in row, row
    assert "10 / **16 ms**" in row, row
    assert "1 s SLO **pass**" in row, row
    block = urb.render("BENCH_test.json", {"pod_crud_ops_per_sec": 100.0})
    assert "HA failover" not in block
