"""README drift gate (VERDICT r4 Weak #2): the headline-numbers table
must match what tools/update_readme_bench.py generates from the newest
BENCH_r*.json artifact. If a new artifact lands (or the generator
changes), regenerate with `python tools/update_readme_bench.py`."""

import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def test_readme_bench_table_matches_newest_artifact():
    proc = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "update_readme_bench.py"),
         "--check"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, (
        f"{proc.stdout}{proc.stderr}\n"
        "README.md's bench table has drifted from the newest BENCH "
        "artifact — run `python tools/update_readme_bench.py`."
    )
