"""Service cluster-IP / node-port allocation at the apiserver.

Reference semantics: pkg/registry/service/rest.go:68-131 (allocate at
create, respect explicit requests, release on delete), validation's
clusterIP immutability on update, and the restart repair pass
(pkg/registry/service/ipallocator/controller/repair.go).
"""

import pytest

from kubernetes_tpu.server import APIError, APIServer
from kubernetes_tpu.server.allocators import (
    AllocationError,
    IPAllocator,
    PortAllocator,
)
from kubernetes_tpu.store import KVStore


def svc_wire(name, cluster_ip=None, svc_type=None, ports=None, ns="default"):
    spec = {"selector": {"app": name}, "ports": ports or [{"port": 80}]}
    if cluster_ip is not None:
        spec["clusterIP"] = cluster_ip
    if svc_type is not None:
        spec["type"] = svc_type
    return {
        "kind": "Service",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }


class TestIPAllocatorUnit:
    def test_next_excludes_network_and_broadcast(self):
        alloc = IPAllocator("192.168.1.0/30")  # usable: .1, .2
        assert alloc.allocate_next() == "192.168.1.1"
        assert alloc.allocate_next() == "192.168.1.2"
        with pytest.raises(AllocationError):
            alloc.allocate_next()

    def test_explicit_and_release(self):
        alloc = IPAllocator("10.1.0.0/24")
        alloc.allocate("10.1.0.7")
        with pytest.raises(AllocationError):
            alloc.allocate("10.1.0.7")
        alloc.release("10.1.0.7")
        alloc.allocate("10.1.0.7")

    def test_out_of_range_rejected(self):
        alloc = IPAllocator("10.1.0.0/24")
        with pytest.raises(AllocationError):
            alloc.allocate("10.2.0.7")
        with pytest.raises(AllocationError):
            alloc.allocate("not-an-ip")

    def test_port_range(self):
        alloc = PortAllocator(30000, 30001)
        assert alloc.allocate_next() == 30000
        assert alloc.allocate_next() == 30001
        with pytest.raises(AllocationError):
            alloc.allocate_next()
        alloc.release(30000)
        assert alloc.allocate_next() == 30000
        with pytest.raises(AllocationError):
            alloc.allocate(29999)


class TestServiceCreate:
    def test_auto_assigns_distinct_cluster_ips(self):
        api = APIServer()
        a = api.create("services", "default", svc_wire("a"))
        b = api.create("services", "default", svc_wire("b"))
        ips = {a["spec"]["clusterIP"], b["spec"]["clusterIP"]}
        assert len(ips) == 2
        assert all(ip.startswith("10.0.0.") for ip in ips)

    def test_explicit_ip_respected_and_conflicts(self):
        api = APIServer()
        a = api.create("services", "default", svc_wire("a", cluster_ip="10.0.0.42"))
        assert a["spec"]["clusterIP"] == "10.0.0.42"
        with pytest.raises(APIError) as e:
            api.create("services", "default", svc_wire("b", cluster_ip="10.0.0.42"))
        assert e.value.code == 422

    def test_out_of_range_ip_invalid(self):
        api = APIServer()
        with pytest.raises(APIError) as e:
            api.create("services", "default", svc_wire("a", cluster_ip="172.16.0.1"))
        assert e.value.code == 422

    def test_headless_skips_allocation(self):
        api = APIServer()
        a = api.create("services", "default", svc_wire("a", cluster_ip="None"))
        assert a["spec"]["clusterIP"] == "None"
        # Pool untouched: first auto-assign still gets the first IP.
        b = api.create("services", "default", svc_wire("b"))
        assert b["spec"]["clusterIP"] == "10.0.0.1"

    def test_delete_releases_ip(self):
        api = APIServer()
        api.create("services", "default", svc_wire("a", cluster_ip="10.0.0.42"))
        api.delete("services", "default", "a")
        b = api.create("services", "default", svc_wire("b", cluster_ip="10.0.0.42"))
        assert b["spec"]["clusterIP"] == "10.0.0.42"

    def test_duplicate_name_rolls_back_allocation(self):
        api = APIServer()
        api.create("services", "default", svc_wire("a"))
        before = api.service_ips.free
        with pytest.raises(APIError):
            api.create("services", "default", svc_wire("a"))
        assert api.service_ips.free == before

    def test_node_ports_assigned_for_nodeport_type(self):
        api = APIServer()
        svc = api.create(
            "services",
            "default",
            svc_wire("a", svc_type="NodePort", ports=[{"port": 80}, {"port": 443}]),
        )
        nps = [p["nodePort"] for p in svc["spec"]["ports"]]
        assert all(30000 <= p <= 32767 for p in nps)
        assert len(set(nps)) == 2

    def test_explicit_node_port_conflict(self):
        api = APIServer()
        api.create(
            "services",
            "default",
            svc_wire(
                "a", svc_type="NodePort", ports=[{"port": 80, "nodePort": 30080}]
            ),
        )
        with pytest.raises(APIError) as e:
            api.create(
                "services",
                "default",
                svc_wire(
                    "b", svc_type="NodePort", ports=[{"port": 80, "nodePort": 30080}]
                ),
            )
        assert e.value.code == 422

    def test_clusterip_type_does_not_get_node_ports(self):
        api = APIServer()
        svc = api.create("services", "default", svc_wire("a"))
        assert not any(p.get("nodePort") for p in svc["spec"]["ports"])


class TestServiceUpdate:
    def test_cluster_ip_immutable(self):
        api = APIServer()
        svc = api.create("services", "default", svc_wire("a"))
        svc["spec"]["clusterIP"] = "10.0.0.99"
        with pytest.raises(APIError) as e:
            api.update("services", "default", "a", svc)
        assert e.value.code == 422

    def test_omitted_cluster_ip_carries_over(self):
        api = APIServer()
        svc = api.create("services", "default", svc_wire("a"))
        ip = svc["spec"]["clusterIP"]
        svc["spec"].pop("clusterIP")
        out = api.update("services", "default", "a", svc)
        assert out["spec"]["clusterIP"] == ip

    def test_update_without_node_port_carries_allocation_over(self):
        """Re-applying the original manifest (no nodePort field) must
        keep the externally advertised port, not churn it."""
        api = APIServer()
        svc = api.create(
            "services",
            "default",
            svc_wire("a", svc_type="NodePort", ports=[{"port": 80}]),
        )
        np = svc["spec"]["ports"][0]["nodePort"]
        again = svc_wire("a", svc_type="NodePort", ports=[{"port": 80}])
        out = api.update("services", "default", "a", again)
        assert out["spec"]["ports"][0]["nodePort"] == np

    def test_update_carries_by_port_name(self):
        api = APIServer()
        svc = api.create(
            "services",
            "default",
            svc_wire(
                "a",
                svc_type="NodePort",
                ports=[{"name": "web", "port": 80}, {"name": "tls", "port": 443}],
            ),
        )
        by_name = {p["name"]: p["nodePort"] for p in svc["spec"]["ports"]}
        # Reordered, still no explicit nodePorts: each keeps its own.
        again = svc_wire(
            "a",
            svc_type="NodePort",
            ports=[{"name": "tls", "port": 443}, {"name": "web", "port": 80}],
        )
        out = api.update("services", "default", "a", again)
        got = {p["name"]: p["nodePort"] for p in out["spec"]["ports"]}
        assert got == by_name

    def test_type_change_to_clusterip_sheds_node_ports(self):
        """NodePort -> ClusterIP releases the port back to the pool and
        the stored service carries no nodePort."""
        api = APIServer()
        svc = api.create(
            "services",
            "default",
            svc_wire("a", svc_type="NodePort", ports=[{"port": 80}]),
        )
        np = svc["spec"]["ports"][0]["nodePort"]
        out = api.update(
            "services",
            "default",
            "a",
            svc_wire("a", svc_type="ClusterIP", ports=[{"port": 80}]),
        )
        assert not out["spec"]["ports"][0].get("nodePort")
        # Pool released: another service can take the exact port.
        api.create(
            "services",
            "default",
            svc_wire("b", svc_type="NodePort", ports=[{"port": 80, "nodePort": np}]),
        )

    def test_node_port_diff_allocates_and_releases(self):
        api = APIServer()
        svc = api.create(
            "services",
            "default",
            svc_wire(
                "a", svc_type="NodePort", ports=[{"port": 80, "nodePort": 30080}]
            ),
        )
        # Swap the node port: 30080 released, 30090 allocated.
        svc["spec"]["ports"] = [{"port": 80, "nodePort": 30090}]
        api.update("services", "default", "a", svc)
        api.create(
            "services",
            "default",
            svc_wire(
                "b", svc_type="NodePort", ports=[{"port": 80, "nodePort": 30080}]
            ),
        )
        with pytest.raises(APIError):
            api.create(
                "services",
                "default",
                svc_wire(
                    "c", svc_type="NodePort", ports=[{"port": 80, "nodePort": 30090}]
                ),
            )


class TestServicePatch:
    """PATCH must honor the same allocator invariants as update
    (it is not a side door around immutability or the port pool)."""

    def test_patch_cluster_ip_rejected(self):
        api = APIServer()
        api.create("services", "default", svc_wire("a"))
        with pytest.raises(APIError) as e:
            api.patch(
                "services", "default", "a", {"spec": {"clusterIP": "10.0.0.99"}}
            )
        assert e.value.code == 422

    def test_patch_conflicting_node_port_rejected(self):
        api = APIServer()
        api.create(
            "services",
            "default",
            svc_wire("a", svc_type="NodePort", ports=[{"port": 80, "nodePort": 30080}]),
        )
        api.create("services", "default", svc_wire("b"))
        with pytest.raises(APIError) as e:
            api.patch(
                "services",
                "default",
                "b",
                {"spec": {"type": "NodePort",
                          "ports": [{"port": 80, "nodePort": 30080}]}},
            )
        assert e.value.code == 422

    def test_patch_out_of_range_node_port_rejected(self):
        api = APIServer()
        api.create("services", "default", svc_wire("a"))
        with pytest.raises(APIError) as e:
            api.patch(
                "services",
                "default",
                "a",
                {"spec": {"type": "NodePort",
                          "ports": [{"port": 80, "nodePort": 80}]}},
            )
        assert e.value.code == 422

    def test_patch_replacing_ports_carries_node_port(self):
        api = APIServer()
        svc = api.create(
            "services",
            "default",
            svc_wire("a", svc_type="NodePort", ports=[{"name": "web", "port": 80}]),
        )
        np = svc["spec"]["ports"][0]["nodePort"]
        out = api.patch(
            "services",
            "default",
            "a",
            {"spec": {"ports": [{"name": "web", "port": 8080}]}},
        )
        assert out["spec"]["ports"][0]["nodePort"] == np

    def test_patch_cannot_strand_nodeport_service_portless(self):
        api = APIServer()
        api.create(
            "services",
            "default",
            svc_wire("a", svc_type="NodePort", ports=[{"name": "web", "port": 80}]),
        )
        with pytest.raises(APIError) as e:
            api.patch(
                "services",
                "default",
                "a",
                {"spec": {"ports": [{"name": "other", "port": 9090}]}},
            )
        assert e.value.code == 422

    def test_patch_type_to_clusterip_sheds_node_ports(self):
        """PATCH {'spec':{'type':'ClusterIP'}} on a NodePort service:
        the merge keeps the old ports array, but the committed object
        must carry no nodePort and the pool slot must free up."""
        api = APIServer()
        svc = api.create(
            "services",
            "default",
            svc_wire("a", svc_type="NodePort", ports=[{"port": 80}]),
        )
        np = svc["spec"]["ports"][0]["nodePort"]
        out = api.patch(
            "services", "default", "a", {"spec": {"type": "ClusterIP"}}
        )
        assert not out["spec"]["ports"][0].get("nodePort")
        api.create(
            "services",
            "default",
            svc_wire("b", svc_type="NodePort",
                     ports=[{"port": 80, "nodePort": np}]),
        )

    def test_patched_in_node_port_is_tracked(self):
        api = APIServer()
        api.create("services", "default", svc_wire("a"))
        api.patch(
            "services",
            "default",
            "a",
            {"spec": {"type": "NodePort",
                      "ports": [{"port": 80, "nodePort": 30099}]}},
        )
        with pytest.raises(APIError):
            api.create(
                "services",
                "default",
                svc_wire("c", svc_type="NodePort",
                         ports=[{"port": 80, "nodePort": 30099}]),
            )


class TestMasterService:
    def test_publish_creates_service_and_endpoints(self):
        api = APIServer()
        svc = api.publish_master_service("127.0.0.1", 6443)
        assert svc["spec"]["clusterIP"].startswith("10.0.0.")
        assert not svc["spec"].get("selector")
        eps = api.get("endpoints", "default", "kubernetes")
        assert eps["subsets"][0]["addresses"][0]["ip"] == "127.0.0.1"
        assert eps["subsets"][0]["ports"][0]["port"] == 6443

    def test_publish_is_idempotent_and_reconciles(self):
        api = APIServer()
        api.publish_master_service("127.0.0.1", 6443)
        api.publish_master_service("10.9.9.9", 7443)  # master moved
        eps = api.get("endpoints", "default", "kubernetes")
        assert eps["subsets"][0]["addresses"][0]["ip"] == "10.9.9.9"
        assert len(api.list("services", "default")["items"]) == 1
        # The advertised service port follows the master, not just the
        # endpoints.
        svc = api.get("services", "default", "kubernetes")
        assert svc["spec"]["ports"][0]["port"] == 7443

    def test_http_server_publishes_when_enabled(self):
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        srv = APIHTTPServer(api, publish_master=True).start()
        try:
            svc = api.get("services", "default", "kubernetes")
            port = int(srv.address.rsplit(":", 1)[1])
            assert svc["spec"]["ports"][0]["port"] == port
        finally:
            srv.stop()


class TestRepair:
    def test_restart_rebuilds_pools_from_store(self):
        store = KVStore()
        api = APIServer(store=store)
        svc = api.create(
            "services",
            "default",
            svc_wire("a", svc_type="NodePort", ports=[{"port": 80}]),
        )
        ip = svc["spec"]["clusterIP"]
        np = svc["spec"]["ports"][0]["nodePort"]
        # New apiserver over the same store: pools must reflect "a".
        api2 = APIServer(store=store)
        with pytest.raises(APIError):
            api2.create("services", "default", svc_wire("b", cluster_ip=ip))
        with pytest.raises(APIError):
            api2.create(
                "services",
                "default",
                svc_wire(
                    "c", svc_type="NodePort", ports=[{"port": 80, "nodePort": np}]
                ),
            )
