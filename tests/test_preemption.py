"""Priority & preemption subsystem: PriorityClass API + admission,
graceful eviction end-to-end, TPU-solved victim selection, scheduler
integration, and the gang all-or-nothing preemption guard.

The acceptance bar (ISSUE 4): on a full cluster a high-priority pod
binds within two scheduler ticks of victim grace expiry, with
`Preempted` events on victims and `nominatedNodeName` set meanwhile;
pods whose priority does not dominate any victim are never granted a
preemption; scalar and TPU victim selection agree 100% (the randomized
suite lives in test_solver_parity.py).
"""

import time

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.kubelet.agent import Kubelet
from kubernetes_tpu.models.objects import POD_GROUP_LABEL
from kubernetes_tpu.scheduler.daemon import (
    BatchScheduler,
    IncrementalBatchScheduler,
    SchedulerConfig,
)
from kubernetes_tpu.server import APIError, APIServer
from kubernetes_tpu.server.admission import new_from_plugins
from kubernetes_tpu.server.httpserver import APIHTTPServer

pytestmark = pytest.mark.preempt


def pc_wire(name, value, global_default=False, policy=""):
    out = {
        "kind": "PriorityClass",
        "apiVersion": "v1",
        "metadata": {"name": name},
        "value": value,
    }
    if global_default:
        out["globalDefault"] = True
    if policy:
        out["preemptionPolicy"] = policy
    return out


def pod_wire(name, cpu="100m", mem="64Mi", pc="", group="", ns="default",
             node=""):
    labels = {POD_GROUP_LABEL: group} if group else {}
    spec = {
        "containers": [
            {"name": "c", "image": "pause",
             "resources": {"limits": {"cpu": cpu, "memory": mem}}}
        ]
    }
    if pc:
        spec["priorityClassName"] = pc
    if node:
        spec["nodeName"] = node
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, "labels": labels},
        "spec": spec,
    }


def wait_until(cond, timeout=60.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# API resource
# ---------------------------------------------------------------------------


class TestPriorityClassResource:
    def test_crud_and_alias(self):
        client = Client(LocalTransport(APIServer()))
        created = client.create("priorityclasses", pc_wire("high", 1000))
        assert created.value == 1000
        assert created.preemption_policy in ("", "PreemptLowerPriority")
        got = client.get("pc", "high")  # registry alias
        assert got.value == 1000
        items, _ = client.list("priorityclasses")
        assert [c.metadata.name for c in items] == ["high"]
        client.delete("priorityclasses", "high")
        with pytest.raises(APIError):
            client.get("priorityclasses", "high")

    def test_validation(self):
        client = Client(LocalTransport(APIServer()))
        with pytest.raises(APIError) as e:
            client.create("priorityclasses", pc_wire("big", 2 * 10**9))
        assert e.value.code == 422
        with pytest.raises(APIError) as e:
            client.create(
                "priorityclasses", pc_wire("weird", 1, policy="Sometimes")
            )
        assert e.value.code == 422

    def test_pod_preemption_policy_enum_validated(self):
        """A typoed opt-out ('Nevr') must fail validation, not silently
        leave the pod preempt-capable."""
        client = Client(LocalTransport(APIServer()))
        wire = pod_wire("p1")
        wire["spec"]["preemptionPolicy"] = "Nevr"
        with pytest.raises(APIError) as e:
            client.create("pods", wire)
        assert e.value.code == 422
        wire["spec"]["preemptionPolicy"] = "Never"
        wire["spec"]["priority"] = 2 * 10**9  # out of band
        with pytest.raises(APIError) as e:
            client.create("pods", wire)
        assert e.value.code == 422

    def test_ktctl_get_priorityclasses_table(self, capsys):
        from kubernetes_tpu.cli.ktctl import print_table, resolve_resource

        assert resolve_resource("pc") == "priorityclasses"
        client = Client(LocalTransport(APIServer()))
        client.create(
            "priorityclasses",
            pc_wire("high", 1000, global_default=True, policy="Never"),
        )
        objs, _ = client.list("priorityclasses")
        print_table("priorityclasses", objs)
        out = capsys.readouterr().out
        assert "VALUE" in out and "GLOBAL-DEFAULT" in out
        assert "high" in out and "1000" in out and "Never" in out


# ---------------------------------------------------------------------------
# Admission: resolve + freeze
# ---------------------------------------------------------------------------


class TestPriorityAdmission:
    def _api(self):
        api = APIServer()
        api.admission = new_from_plugins(api, ["Priority"])
        return api, Client(LocalTransport(api))

    def test_class_resolves_onto_pod(self):
        api, client = self._api()
        client.create(
            "priorityclasses", pc_wire("high", 500, policy="Never")
        )
        pod = client.create("pods", pod_wire("p1", pc="high"))
        assert pod.spec.priority == 500
        assert pod.spec.preemption_policy == "Never"

    def test_unknown_class_rejected(self):
        api, client = self._api()
        with pytest.raises(APIError) as e:
            client.create("pods", pod_wire("p1", pc="nope"))
        assert e.value.code == 404

    def test_global_default_applies_highest_value(self):
        api, client = self._api()
        client.create("priorityclasses", pc_wire("low", 5, global_default=True))
        client.create("priorityclasses", pc_wire("mid", 50, global_default=True))
        pod = client.create("pods", pod_wire("p1"))
        assert pod.spec.priority == 50
        assert pod.spec.priority_class_name == "mid"

    def test_no_class_means_priority_zero(self):
        api, client = self._api()
        pod = client.create("pods", pod_wire("p1"))
        assert (pod.spec.priority or 0) == 0

    def test_direct_priority_must_match_class(self):
        api, client = self._api()
        client.create("priorityclasses", pc_wire("high", 500))
        wire = pod_wire("p1", pc="high")
        wire["spec"]["priority"] = 7
        with pytest.raises(APIError) as e:
            client.create("pods", wire)
        assert e.value.code == 403

    def test_priority_frozen_on_update(self):
        api, client = self._api()
        client.create("priorityclasses", pc_wire("high", 500))
        client.create("priorityclasses", pc_wire("higher", 900))
        pod = api.create("pods", "default", pod_wire("p1", pc="high"))
        pod["spec"]["priorityClassName"] = "higher"
        pod["spec"]["priority"] = 900
        with pytest.raises(APIError) as e:
            api.update("pods", "default", "p1", pod)
        assert e.value.code == 403
        # Omitting the frozen fields carries them over instead.
        fresh = api.get("pods", "default", "p1")
        fresh["spec"].pop("priority", None)
        fresh["spec"].pop("priorityClassName", None)
        out = api.update("pods", "default", "p1", fresh)
        assert out["spec"]["priority"] == 500
        assert out["spec"]["priorityClassName"] == "high"

    def test_classless_pod_cannot_self_promote_on_update(self):
        """Freeze-bypass regression: a pod stored WITHOUT a priority
        (no class, no default) must not be grantable one by a later
        update/patch — 'frozen at unset' is still frozen."""
        api, client = self._api()
        pod = api.create("pods", "default", pod_wire("p1"))
        assert "priority" not in pod["spec"]
        pod["spec"]["priority"] = 999_999_999
        with pytest.raises(APIError) as e:
            api.update("pods", "default", "p1", pod)
        assert e.value.code == 403
        with pytest.raises(APIError) as e:
            api.patch(
                "pods", "default", "p1", {"spec": {"priority": 12345}}
            )
        assert e.value.code == 403
        client.create("priorityclasses", pc_wire("high", 500))
        with pytest.raises(APIError) as e:
            api.patch(
                "pods", "default", "p1",
                {"spec": {"priorityClassName": "high"}},
            )
        assert e.value.code == 403


# ---------------------------------------------------------------------------
# Graceful eviction
# ---------------------------------------------------------------------------


class TestGracefulDelete:
    def test_unbound_pod_deletes_immediately_despite_grace(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        client.create("pods", pod_wire("p1"))
        client.delete("pods", "p1", namespace="default",
                      grace_period_seconds=30)
        with pytest.raises(APIError):
            client.get("pods", "p1", namespace="default")

    def test_bound_pod_marks_terminating(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        client.create("pods", pod_wire("p1", node="n1"))
        client.delete("pods", "p1", namespace="default",
                      grace_period_seconds=30)
        got = client.get("pods", "p1", namespace="default")
        assert got.metadata.deletion_timestamp
        assert got.metadata.deletion_grace_period_seconds == 30
        # Second graceful delete can only shorten, never extend.
        client.delete("pods", "p1", namespace="default",
                      grace_period_seconds=1)
        ts1 = client.get("pods", "p1", namespace="default")
        assert ts1.metadata.deletion_grace_period_seconds == 1
        client.delete("pods", "p1", namespace="default",
                      grace_period_seconds=600)
        ts2 = client.get("pods", "p1", namespace="default")
        assert (
            ts2.metadata.deletion_timestamp == ts1.metadata.deletion_timestamp
        )
        # Grace 0 force-deletes.
        client.delete("pods", "p1", namespace="default",
                      grace_period_seconds=0)
        with pytest.raises(APIError):
            client.get("pods", "p1", namespace="default")

    def test_eviction_subresource_local(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        client.create("pods", pod_wire("p1", node="n1"))
        client.evict("p1", namespace="default", grace_period_seconds=30)
        got = client.get("pods", "p1", namespace="default")
        assert got.metadata.deletion_timestamp

    def test_kubelet_honors_grace_end_to_end(self):
        """The victim stays Terminating (still present, still bound)
        until grace expiry; watchers see exactly one DELETED."""
        api = APIServer()
        client = Client(LocalTransport(api))
        kl = Kubelet(
            Client(LocalTransport(api)), "n1",
            sync_period=0.2, heartbeat_period=30,
        ).start()
        try:
            client.create("pods", pod_wire("p1", node="n1"))
            assert wait_until(
                lambda: client.get(
                    "pods", "p1", namespace="default"
                ).status.phase == "Running",
                timeout=20,
            )
            stream = client.watch("pods", namespace="default")
            t0 = time.monotonic()
            client.delete("pods", "p1", namespace="default",
                          grace_period_seconds=2)
            got = client.get("pods", "p1", namespace="default")
            assert got.metadata.deletion_timestamp  # Terminating
            # Mid-grace the pod is still there.
            time.sleep(0.8)
            assert client.get("pods", "p1", namespace="default")
            types = []
            deleted_at = None
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                ev = stream.next(timeout=0.5)
                if ev is None:
                    continue
                types.append(ev.type)
                if ev.type == "DELETED":
                    deleted_at = time.monotonic() - t0
                    break
            stream.close()
            assert deleted_at is not None, types
            # ISO stamps truncate to whole seconds: expiry can land up
            # to 1s early but never immediately.
            assert deleted_at >= 0.9, deleted_at
            assert types.count("DELETED") == 1, types
            with pytest.raises(APIError):
                client.get("pods", "p1", namespace="default")
        finally:
            kl.stop()

    def test_http_eviction_and_grace_query(self):
        api = APIServer()
        server = APIHTTPServer(api).start()
        try:
            client = Client(HTTPTransport(server.address))
            client.create("pods", pod_wire("p1", node="n1"))
            client.evict("p1", namespace="default", grace_period_seconds=60)
            got = client.get("pods", "p1", namespace="default")
            assert got.metadata.deletion_timestamp
            client.create("pods", pod_wire("p2", node="n1"))
            client.delete("pods", "p2", namespace="default",
                          grace_period_seconds=60)
            got = client.get("pods", "p2", namespace="default")
            assert got.metadata.deletion_grace_period_seconds == 60
            # Plain DELETE stays immediate (pre-graceful behavior).
            client.delete("pods", "p2", namespace="default")
            with pytest.raises(APIError):
                client.get("pods", "p2", namespace="default")
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# Victim selection (unit; randomized parity in test_solver_parity.py)
# ---------------------------------------------------------------------------


class TestVictimSelection:
    def _mk(self):
        import sys

        sys.path.insert(0, "tests")
        from test_solver_parity import mk_node, mk_pod

        return mk_node, mk_pod

    def test_minimal_prefix_lowest_priority_first(self):
        mk_node, mk_pod = self._mk()
        from kubernetes_tpu.scheduler.batch import preempt_backlog_scalar

        node = mk_node("n0", cpu=1000, mem_mib=8192, pods=10)
        a = mk_pod("a", cpu=400)
        b = mk_pod("b", cpu=400)
        c = mk_pod("c", cpu=200)
        for p, prio in ((a, 10), (b, 5), (c, 20)):
            p.spec.node_name = "n0"
            p.spec.priority = prio
        hi = mk_pod("hi", cpu=500)
        hi.spec.priority = 100
        (dec,) = preempt_backlog_scalar([hi], [node], [a, b, c])
        # b (prio 5) alone frees 400 < 500; b + a frees 800 >= 500.
        assert dec is not None
        assert dec.victims == ("default/b", "default/a")
        assert dec.node == "n0"

    def test_no_domination_never_grants(self):
        mk_node, mk_pod = self._mk()
        from kubernetes_tpu.scheduler.batch import preempt_backlog_scalar

        node = mk_node("n0", cpu=1000, mem_mib=8192, pods=10)
        a = mk_pod("a", cpu=900)
        a.spec.node_name = "n0"
        a.spec.priority = 100
        same = mk_pod("same", cpu=500)
        same.spec.priority = 100  # equal, not dominating
        zero = mk_pod("zero", cpu=500)  # priority 0 cannot preempt
        decs = preempt_backlog_scalar([same, zero], [node], [a])
        assert decs == [None, None]

    def test_never_policy_opts_out(self):
        mk_node, mk_pod = self._mk()
        from kubernetes_tpu.scheduler.batch import preempt_backlog_scalar

        node = mk_node("n0", cpu=1000, mem_mib=8192, pods=10)
        a = mk_pod("a", cpu=900)
        a.spec.node_name = "n0"
        hi = mk_pod("hi", cpu=500)
        hi.spec.priority = 100
        hi.spec.preemption_policy = "Never"
        (dec,) = preempt_backlog_scalar([hi], [node], [a])
        assert dec is None

    def test_fitting_node_is_not_a_preemption_case(self):
        mk_node, mk_pod = self._mk()
        from kubernetes_tpu.scheduler.batch import preempt_backlog_scalar

        empty = mk_node("n0", cpu=4000, mem_mib=8192, pods=10)
        hi = mk_pod("hi", cpu=500)
        hi.spec.priority = 100
        (dec,) = preempt_backlog_scalar([hi], [empty], [])
        assert dec is None  # it fits; preemption has nothing to fix

    def test_terminating_victims_not_chosen_again(self):
        mk_node, mk_pod = self._mk()
        from kubernetes_tpu.scheduler.batch import preempt_backlog_scalar

        node = mk_node("n0", cpu=1000, mem_mib=8192, pods=10)
        a = mk_pod("a", cpu=900)
        a.spec.node_name = "n0"
        a.metadata.deletion_timestamp = "2026-01-01T00:00:00Z"
        hi = mk_pod("hi", cpu=500)
        hi.spec.priority = 100
        (dec,) = preempt_backlog_scalar([hi], [node], [a])
        # The only dominated pod is already terminating: its capacity
        # is promised, evicting it again buys nothing.
        assert dec is None

    def test_node_ranking_prefers_cheapest_victims(self):
        mk_node, mk_pod = self._mk()
        from kubernetes_tpu.scheduler.batch import preempt_backlog_scalar

        n0 = mk_node("n0", cpu=1000, mem_mib=8192, pods=10)
        n1 = mk_node("n1", cpu=1000, mem_mib=8192, pods=10)
        expensive = mk_pod("expensive", cpu=900)
        expensive.spec.node_name = "n0"
        expensive.spec.priority = 50
        cheap = mk_pod("cheap", cpu=900)
        cheap.spec.node_name = "n1"
        cheap.spec.priority = 1
        hi = mk_pod("hi", cpu=500)
        hi.spec.priority = 100
        (dec,) = preempt_backlog_scalar([hi], [n0, n1], [expensive, cheap])
        assert dec.node == "n1" and dec.victims == ("default/cheap",)


# ---------------------------------------------------------------------------
# Gang/preemption interaction guard
# ---------------------------------------------------------------------------


class TestGangPreemptionGuard:
    def _pods(self, specs):
        import sys

        sys.path.insert(0, "tests")
        from test_solver_parity import mk_pod

        pods = []
        for name, group in specs:
            labels = {POD_GROUP_LABEL: group} if group else {}
            p = mk_pod(name, labels=labels)
            pods.append(p)
        return pods

    def test_partial_gang_preemption_dropped(self):
        from kubernetes_tpu.ops.preemption import PreemptionDecision
        from kubernetes_tpu.scheduler.gang import drop_partial_gang_preemptions

        g0, g1 = self._pods([("g0", "gang"), ("g1", "gang")])
        solo = self._pods([("solo", "")])[0]
        unbound = [g0, g1, solo]
        candidates = [g0, g1, solo]
        decisions = [
            PreemptionDecision("default/g0", "n0", ("default/v0",)),
            None,  # g1 infeasible: the gang cannot land whole
            PreemptionDecision("default/solo", "n1", ("default/v1",)),
        ]
        out, dropped = drop_partial_gang_preemptions(
            unbound, candidates, decisions
        )
        assert out[0] is None  # g0's grant dropped with the gang
        assert out[2] is not None  # ungrouped pod unaffected
        assert dropped == ["default/gang"]

    def test_whole_gang_grants_survive(self):
        from kubernetes_tpu.ops.preemption import PreemptionDecision
        from kubernetes_tpu.scheduler.gang import drop_partial_gang_preemptions

        g0, g1 = self._pods([("g0", "gang"), ("g1", "gang")])
        decisions = [
            PreemptionDecision("default/g0", "n0", ("default/v0",)),
            PreemptionDecision("default/g1", "n1", ("default/v1",)),
        ]
        out, dropped = drop_partial_gang_preemptions(
            [g0, g1], [g0, g1], decisions
        )
        assert out == decisions and dropped == []

    def test_backoff_hidden_member_vetoes_via_min_member(self):
        """A gang member sitting in backoff requeue is invisible to the
        tick's unbound set; the declared minMember floor must veto a
        grant the gang still cannot use."""
        from kubernetes_tpu.ops.preemption import PreemptionDecision
        from kubernetes_tpu.scheduler.gang import (
            GangGroup,
            drop_partial_gang_preemptions,
        )

        g0, g1 = self._pods([("g0", "gang"), ("g1", "gang")])
        decisions = [
            PreemptionDecision("default/g0", "n0", ("default/v0",)),
            PreemptionDecision("default/g1", "n1", ("default/v1",)),
        ]
        # Gang of 3, nobody bound: the third member is in backoff, so
        # even a full grant for the two visible members is partial.
        group = GangGroup(
            key="default/gang", name="gang", namespace="default",
            min_member=3, bound=0,
        )
        out, dropped = drop_partial_gang_preemptions(
            [g0, g1], [g0, g1], decisions, groups=[group]
        )
        assert out == [None, None] and dropped == ["default/gang"]
        # One member already bound: 2 grants + 1 bound reach the floor.
        group.bound = 1
        out, dropped = drop_partial_gang_preemptions(
            [g0, g1], [g0, g1], decisions, groups=[group]
        )
        assert out == decisions and dropped == []

    def test_member_outside_candidates_vetoes(self):
        """A gang member excluded from candidacy (e.g. it already
        holds a nomination) only counts when covered; an unbound,
        uncovered member vetoes the whole gang."""
        from kubernetes_tpu.ops.preemption import PreemptionDecision
        from kubernetes_tpu.scheduler.gang import drop_partial_gang_preemptions

        g0, g1 = self._pods([("g0", "gang"), ("g1", "gang")])
        decisions = [PreemptionDecision("default/g0", "n0", ("default/v0",))]
        out, dropped = drop_partial_gang_preemptions(
            [g0, g1], [g0], decisions
        )
        assert out == [None] and dropped == ["default/gang"]
        out, dropped = drop_partial_gang_preemptions(
            [g0, g1], [g0], decisions,
            covered_keys=frozenset({"default/g1"}),
        )
        assert out == decisions and dropped == []


# ---------------------------------------------------------------------------
# Scheduler integration (the acceptance bar)
# ---------------------------------------------------------------------------


def _full_cluster(api):
    """One 1-cpu node (kubelet-backed) filled by two best-effort pods."""
    client = Client(LocalTransport(api))
    client.create("priorityclasses", pc_wire("high", 1000))
    kl = Kubelet(
        Client(LocalTransport(api)), "n1", cpu="1", memory="1Gi",
        max_pods=10, sync_period=0.2, heartbeat_period=30,
    ).start()
    for i in range(2):
        client.create("pods", pod_wire(f"be{i}", cpu="500m", mem="256Mi"))
    return client, kl


@pytest.mark.parametrize(
    "daemon_cls", [BatchScheduler, IncrementalBatchScheduler]
)
def test_high_priority_pod_preempts_and_binds(daemon_cls):
    api = APIServer()
    api.admission = new_from_plugins(api, ["Priority"])
    client, kl = _full_cluster(api)
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    try:
        assert cfg.wait_for_sync(timeout=60)
        sched = daemon_cls(cfg, eviction_grace_seconds=2)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            pods, _ = client.list("pods", namespace="default")
            if pods and all(p.spec.node_name for p in pods):
                break
        pods, _ = client.list("pods", namespace="default")
        assert all(p.spec.node_name == "n1" for p in pods)

        client.create(
            "pods", pod_wire("trainer", cpu="800m", mem="512Mi", pc="high")
        )
        t0 = time.monotonic()
        nominated_seen = False
        grace_expired_at = None
        bound_at = None
        while time.monotonic() - t0 < 40:
            sched.schedule_batch(timeout=0.3)
            tr = client.get("pods", "trainer", namespace="default")
            if tr.status.nominated_node_name == "n1":
                nominated_seen = True
            if grace_expired_at is None:
                try:
                    client.get("pods", "be0", namespace="default")
                    client.get("pods", "be1", namespace="default")
                except APIError:
                    grace_expired_at = time.monotonic()
            if tr.spec.node_name:
                bound_at = time.monotonic()
                break
        assert nominated_seen, "nominatedNodeName never set"
        assert bound_at is not None, "trainer never bound"
        tr = client.get("pods", "trainer", namespace="default")
        assert tr.spec.node_name == "n1"
        # Binds within ~two scheduler ticks of a victim's exit (the
        # loop ticks every ≤0.3s; allow generous scheduling slack).
        if grace_expired_at is not None:
            assert bound_at - grace_expired_at < 5.0

        cfg.client.flush_events()
        events, _ = client.list("events", namespace="default")
        preempted = [e for e in events if e.reason == "Preempted"]
        assert {e.involved_object.name for e in preempted} == {"be0", "be1"}
        assert any("default/trainer" in e.message for e in preempted)
    finally:
        cfg.stop()
        kl.stop()


def test_non_dominating_pod_is_never_granted_preemption():
    """Equal priority everywhere: the cluster stays full, nothing is
    evicted, the pod keeps requeueing with FailedScheduling."""
    api = APIServer()
    api.admission = new_from_plugins(api, ["Priority"])
    client = Client(LocalTransport(api))
    client.create("priorityclasses", pc_wire("high", 1000))
    kl = Kubelet(
        Client(LocalTransport(api)), "n1", cpu="1", memory="1Gi",
        max_pods=10, sync_period=0.2, heartbeat_period=30,
    ).start()
    for i in range(2):
        client.create(
            "pods", pod_wire(f"peer{i}", cpu="500m", mem="256Mi", pc="high")
        )
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    try:
        assert cfg.wait_for_sync(timeout=60)
        sched = BatchScheduler(cfg, eviction_grace_seconds=1)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            pods, _ = client.list("pods", namespace="default")
            if pods and all(p.spec.node_name for p in pods):
                break
        client.create(
            "pods", pod_wire("same-prio", cpu="800m", mem="512Mi", pc="high")
        )
        for _ in range(8):
            sched.schedule_batch(timeout=0.3)
        pods, _ = client.list("pods", namespace="default")
        by_name = {p.metadata.name: p for p in pods}
        assert "peer0" in by_name and "peer1" in by_name  # nobody evicted
        assert not by_name["peer0"].metadata.deletion_timestamp
        assert not by_name["same-prio"].spec.node_name
        assert not by_name["same-prio"].status.nominated_node_name
    finally:
        cfg.stop()
        kl.stop()


def test_gang_preemptor_preempts_whole_gang_or_not_at_all():
    """Regression for the gang guard wired into the daemons: a
    2-member high-priority gang that can only free room for ONE member
    must evict nobody."""
    api = APIServer()
    api.admission = new_from_plugins(api, ["Priority", "PodGroup"])
    client = Client(LocalTransport(api))
    client.create("priorityclasses", pc_wire("high", 1000))
    client.create(
        "podgroups",
        {
            "kind": "PodGroup",
            "apiVersion": "v1",
            "metadata": {"name": "gang", "namespace": "default"},
            "spec": {"minMember": 2},
        },
    )
    kl = Kubelet(
        Client(LocalTransport(api)), "n1", cpu="1", memory="1Gi",
        max_pods=10, sync_period=0.2, heartbeat_period=30,
    ).start()
    # Fill the node: one dominated filler + one same-priority peer.
    client.create("pods", pod_wire("filler", cpu="500m", mem="256Mi"))
    client.create(
        "pods", pod_wire("peer", cpu="500m", mem="256Mi", pc="high")
    )
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    try:
        assert cfg.wait_for_sync(timeout=60)
        sched = BatchScheduler(cfg, eviction_grace_seconds=1)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            pods, _ = client.list("pods", namespace="default")
            if pods and all(p.spec.node_name for p in pods):
                break
        # Two gang members, each 500m: evicting the filler frees room
        # for ONE member only (peer is not dominated) — so the gang
        # guard must drop the grant and the filler must survive.
        for i in range(2):
            client.create(
                "pods",
                pod_wire(f"g{i}", cpu="500m", mem="256Mi", pc="high",
                         group="gang"),
            )
        for _ in range(8):
            sched.schedule_batch(timeout=0.3)
        pods, _ = client.list("pods", namespace="default")
        by_name = {p.metadata.name: p for p in pods}
        assert "filler" in by_name
        assert not by_name["filler"].metadata.deletion_timestamp
        assert not by_name["g0"].spec.node_name
        assert not by_name["g1"].spec.node_name
    finally:
        cfg.stop()
        kl.stop()


def test_failed_evictions_do_not_record_a_nomination(monkeypatch):
    """If every eviction fails transiently, no capacity was freed: the
    preemptor must stay eligible to re-solve next tick instead of being
    frozen behind a dead nomination."""
    api = APIServer()
    api.admission = new_from_plugins(api, ["Priority"])
    client, kl = _full_cluster(api)
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    try:
        assert cfg.wait_for_sync(timeout=60)
        sched = BatchScheduler(cfg, eviction_grace_seconds=1)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.2)
            pods, _ = client.list("pods", namespace="default")
            if pods and all(p.spec.node_name for p in pods):
                break

        def broken_evict(*a, **kw):
            raise APIError(500, "InternalError", "sink is down")

        monkeypatch.setattr(cfg.client, "evict", broken_evict)
        client.create(
            "pods", pod_wire("trainer", cpu="800m", mem="512Mi", pc="high")
        )
        for _ in range(4):
            sched.schedule_batch(timeout=0.3)
        assert sched._nominations == {}
        tr = client.get("pods", "trainer", namespace="default")
        assert not tr.status.nominated_node_name
        be0 = client.get("pods", "be0", namespace="default")
        assert not be0.metadata.deletion_timestamp  # nothing half-evicted
        # Evictions healed: the very next ticks preempt and nominate.
        monkeypatch.undo()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.3)
            tr = client.get("pods", "trainer", namespace="default")
            if tr.spec.node_name:
                break
        assert tr.spec.node_name == "n1"
    finally:
        cfg.stop()
        kl.stop()


def test_priority_orders_the_drained_backlog():
    """Two pods contending for one slot in the same batch: the higher
    priority one wins regardless of arrival order."""
    api = APIServer()
    api.admission = new_from_plugins(api, ["Priority"])
    client = Client(LocalTransport(api))
    client.create("priorityclasses", pc_wire("high", 1000))
    kl = Kubelet(
        Client(LocalTransport(api)), "n1", cpu="1", memory="1Gi",
        max_pods=10, sync_period=0.2, heartbeat_period=30,
    ).start()
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    try:
        assert cfg.wait_for_sync(timeout=60)
        sched = BatchScheduler(cfg)
        # Low-priority first into the queue, high-priority second; only
        # one fits. Both land in one drain (batch window).
        client.create("pods", pod_wire("lo", cpu="800m"))
        client.create("pods", pod_wire("hi", cpu="800m", pc="high"))
        assert wait_until(
            lambda: len(cfg.pod_queue._items) >= 2, timeout=20
        )
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.3)
            hi = client.get("pods", "hi", namespace="default")
            if hi.spec.node_name:
                break
        hi = client.get("pods", "hi", namespace="default")
        lo = client.get("pods", "lo", namespace="default")
        assert hi.spec.node_name == "n1"
        assert not lo.spec.node_name
    finally:
        cfg.stop()
        kl.stop()
