"""Capacity & fragmentation observability plane (ISSUE 16): the
CapacityMonitor (probe assembly, series feeding, trend ring, snapshot
contract), the /debug/capacity HTTP surface, `ktctl top capacity` and
the cluster/nodes capacity rows, the two capacity SLO objectives, the
live daemons' sampling cadence (per resolved tick + idle refresh), and
the <5% always-on overhead guard.

The kernel/oracle bit-exactness itself lives with the other solver
twins in tests/test_solver_parity.py (TestCapacityParity)."""

import io
import json
import threading
import time
from contextlib import redirect_stderr, redirect_stdout

import numpy as np
import pytest

from kubernetes_tpu.utils import capacity as capmod
from kubernetes_tpu.utils import metrics, slo

pytestmark = pytest.mark.capacity


def _pod_wire(name, cpu="100m", mem="64Mi"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "pause",
                    "resources": {"limits": {"cpu": cpu, "memory": mem}},
                }
            ]
        },
    }


def _node_wire(name, cpu="4", mem="8Gi", pods="110"):
    return {
        "kind": "Node",
        "metadata": {"name": name, "labels": {}},
        "status": {
            "capacity": {"cpu": cpu, "memory": mem, "pods": pods},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def _cols(n, cpu_cap=1000.0, mem_cap=1024.0, pods_cap=40.0, cpu_fit=0.0):
    """Minimal occupancy columns: n identical live nodes."""
    ones = np.ones(n, np.float32)
    return {
        "cpu_cap": ones * cpu_cap,
        "mem_cap": ones * mem_cap,
        "pods_cap": ones * pods_cap,
        "cpu_fit": ones * cpu_fit,
        "mem_fit": np.zeros(n, np.float32),
        "pods_used": np.zeros(n, np.float32),
        "over": np.zeros(n, bool),
        "sched": np.ones(n, bool),
    }


class TestProbeSet:
    def test_defaults_are_the_slice_shapes(self):
        m = capmod.CapacityMonitor()
        assert m.probe_set() == list(capmod.DEFAULT_SLICE_SHAPES)

    def test_backlog_quantiles_join_the_probes(self):
        m = capmod.CapacityMonitor()
        m.note_backlog_shapes([(100.0, 64.0)] * 9 + [(900.0, 512.0)])
        probes = {name: (cpu, mem, k) for name, cpu, mem, k in m.probe_set()}
        assert probes["backlog-p50"] == (100.0, 64.0, 1)
        assert probes["backlog-max"] == (900.0, 512.0, 1)
        # p90 interpolates between the two shapes and is ceil'd.
        cpu90 = probes["backlog-p90"][0]
        assert 100.0 < cpu90 <= 900.0 and cpu90 == np.ceil(cpu90)

    def test_configure_replaces_slices(self):
        m = capmod.CapacityMonitor()
        m.configure([("tpu-v4-8", 8000.0, 16384.0, 8)])
        assert m.probe_set() == [("tpu-v4-8", 8000.0, 16384.0, 8)]
        m.reset()
        assert m.probe_set() == list(capmod.DEFAULT_SLICE_SHAPES)

    def test_shape_window_is_bounded(self):
        m = capmod.CapacityMonitor()
        m.note_backlog_shapes([(float(i), 1.0) for i in range(10_000)])
        assert len(m._recent_shapes) == capmod.SHAPE_WINDOW


class TestMonitor:
    def test_cold_snapshot_contract(self):
        m = capmod.CapacityMonitor()
        snap = m.snapshot()
        assert snap["kind"] == "CapacityReport"
        assert snap["sampled"] is False and snap["samples"] == 0
        assert snap["probes"] == [] and snap["trend"] == []

    def test_sample_headroom_math(self):
        """2 empty 1000m nodes, 600m probe: one fits per node (integral
        greedy fit), so headroom 2 and minMember 2 is allocatable."""
        m = capmod.CapacityMonitor()
        m.configure([("g", 600.0, 64.0, 2)])
        body = m.sample(_cols(2), ["a", "b"])
        assert body is not None and body["sampled"]
        (probe,) = body["probes"]
        assert probe["headroom_pods"] == 2 and probe["allocatable"]
        assert body["slice_alloc_success_rate"] == 1.0
        assert body["live_nodes"] == 2
        assert set(body["node_utilization"]) == {"a", "b"}

    def test_full_cluster_is_starved_and_stranded(self):
        m = capmod.CapacityMonitor()
        m.configure([("g", 600.0, 64.0, 1)])
        body = m.sample(
            _cols(3, cpu_fit=900.0),  # 100m free: probe can't fit
            ["a", "b", "c"],
            backlog_depth=4,
            oldest_age_s=2.5,
        )
        (probe,) = body["probes"]
        assert probe["headroom_pods"] == 0 and not probe["allocatable"]
        assert body["fragmentation_score"] == 1.0
        assert body["stranded_node_count"] == 3
        assert len(body["stranded_nodes"]) == 3
        assert body["backlog"] == {
            "depth": 4, "oldest_age_s": 2.5, "pressure": 10.0,
        }

    def test_trend_ring_and_samples_advance(self):
        m = capmod.CapacityMonitor()
        for _ in range(3):
            m.sample(_cols(2), ["a", "b"])
        snap = m.snapshot()
        assert snap["samples"] == 3 and len(snap["trend"]) == 3
        assert m.snapshot()["trend"] == snap["trend"]  # snapshot is a copy

    def test_zero_headroom_counter_gated_on_backlog(self):
        """The starvation counter only moves when pods are actually
        waiting — a full-but-idle cluster is not burning its SLO."""
        m = capmod.CapacityMonitor()
        m.configure([("g", 600.0, 64.0, 1)])
        full = _cols(2, cpu_fit=900.0)
        before = capmod.ZERO_HEADROOM.value()
        m.sample(full, ["a", "b"], backlog_depth=0)
        assert capmod.ZERO_HEADROOM.value() == before
        m.sample(full, ["a", "b"], backlog_depth=1, oldest_age_s=0.5)
        assert capmod.ZERO_HEADROOM.value() == before + 1
        # Headroom available: waiting pods alone don't count either.
        m.sample(_cols(2), ["a", "b"], backlog_depth=1, oldest_age_s=0.5)
        assert capmod.ZERO_HEADROOM.value() == before + 1

    def test_sample_never_raises(self):
        m = capmod.CapacityMonitor()
        assert m.sample({}, []) is None  # missing columns
        assert m.snapshot()["sampled"] is False

    def test_padding_rows_stay_dead(self):
        """np.pad rows (sched=False) must contribute nothing: same
        report for a 3-node cluster and its 128-padded staging."""
        m = capmod.CapacityMonitor()
        body3 = m.sample(_cols(3), ["a", "b", "c"])
        cols = _cols(3)
        padded = {
            k: np.pad(v, (0, 125)) for k, v in cols.items()
        }
        body128 = m.sample(padded, ["a", "b", "c"])
        assert body3["fragmentation_score"] == body128["fragmentation_score"]
        assert body3["probes"] == body128["probes"]
        assert body3["live_nodes"] == body128["live_nodes"] == 3


class TestSLOObjectives:
    def test_objectives_are_registered(self):
        objs = {o.name: o for o in slo.DEFAULT_OBJECTIVES}
        frag = objs["capacity_fragmentation"]
        assert frag.series == "cluster_fragmentation_score"
        assert frag.severity == "warn" and frag.target == 0.5
        zero = objs["capacity_zero_headroom"]
        assert zero.series == "capacity_zero_headroom_ticks_total"
        assert zero.kind == "counter_max" and zero.target == 0.0
        assert zero.severity == "gate"

    def test_fragmentation_warns_not_burns(self):
        reg = metrics.Registry()
        h = reg.histogram(
            "cluster_fragmentation_score", "x",
            buckets=capmod.RATIO_BUCKETS,
        )
        for _ in range(20):
            h.observe(0.9)
        objs = {o.name: o for o in slo.DEFAULT_OBJECTIVES}
        e = slo.evaluate_objective(objs["capacity_fragmentation"], registry=reg)
        assert e["verdict"] == "warn", e

    def test_zero_headroom_burns(self):
        reg = metrics.Registry()
        c = reg.counter("capacity_zero_headroom_ticks_total", "x")
        objs = {o.name: o for o in slo.DEFAULT_OBJECTIVES}
        e = slo.evaluate_objective(objs["capacity_zero_headroom"], registry=reg)
        assert e["verdict"] == "pass", e  # a zero counter passes
        c.inc()
        e = slo.evaluate_objective(objs["capacity_zero_headroom"], registry=reg)
        assert e["verdict"] == "burn", e


class TestHTTPSurface:
    def test_debug_capacity_cold_and_sampled(self, monkeypatch):
        import urllib.error
        import urllib.request

        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        monkeypatch.setattr(capmod, "DEFAULT", capmod.CapacityMonitor())
        api = APIServer()
        srv = APIHTTPServer(api).start()
        try:
            with urllib.request.urlopen(
                srv.address + "/debug/capacity", timeout=10
            ) as resp:
                body = json.loads(resp.read())
            assert body["kind"] == "CapacityReport"
            assert body["sampled"] is False
            capmod.DEFAULT.sample(_cols(2), ["a", "b"])
            with urllib.request.urlopen(
                srv.address + "/debug/capacity", timeout=10
            ) as resp:
                body = json.loads(resp.read())
            assert body["sampled"] and body["samples"] == 1
            assert {p["shape"] for p in body["probes"]} == {
                n for n, _, _, _ in capmod.DEFAULT_SLICE_SHAPES
            }
            # The 404 contract advertises the endpoint.
            try:
                urllib.request.urlopen(
                    srv.address + "/debug/nope", timeout=10
                )
                assert False, "404 expected"
            except urllib.error.HTTPError as e:
                assert "/debug/capacity" in e.read().decode()
        finally:
            srv.stop()


class TestKtctl:
    @staticmethod
    def _run(client, argv):
        from kubernetes_tpu.cli import ktctl

        out, err = io.StringIO(), io.StringIO()
        with redirect_stdout(out), redirect_stderr(err):
            rc = ktctl.main(argv, client=client)
        return rc, out.getvalue(), err.getvalue()

    @pytest.fixture
    def client(self, monkeypatch):
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.server.api import APIServer

        monkeypatch.setattr(capmod, "DEFAULT", capmod.CapacityMonitor())
        return Client(LocalTransport(APIServer()))

    def test_miss_contract(self, client):
        """Cold cluster: exit 1, 'no capacity samples recorded' on
        stderr, EMPTY stdout (the trace/explain/slo miss contract)."""
        rc, out, err = self._run(client, ["top", "capacity"])
        assert rc == 1
        assert out == ""
        assert "no capacity samples recorded" in err

    def test_table_json_yaml(self, client):
        capmod.DEFAULT.note_backlog_shapes([(100.0, 64.0)])
        capmod.DEFAULT.sample(
            _cols(2), ["a", "b"], backlog_depth=2, oldest_age_s=1.0
        )
        rc, out, _ = self._run(client, ["top", "capacity"])
        assert rc == 0
        assert "fragmentation:" in out and "SHAPE" in out
        assert "slice-8x2000m" in out and "backlog-p50" in out
        rc, out, _ = self._run(client, ["top", "capacity", "-o", "json"])
        assert rc == 0
        parsed = json.loads(out)
        assert parsed["kind"] == "CapacityReport" and parsed["sampled"]
        rc, out, _ = self._run(client, ["top", "capacity", "-o", "yaml"])
        assert rc == 0 and "kind: CapacityReport" in out

    def test_top_cluster_capacity_row(self, client):
        capmod.DEFAULT.sample(_cols(2), ["a", "b"])
        rc, out, _ = self._run(client, ["top", "cluster"])
        assert rc == 0
        (row,) = [l for l in out.splitlines() if l.startswith("CAPACITY")]
        assert "fragmentation=" in row and "min-headroom" in row
        # The capacity series also ride the TELEMETRY section.
        assert "cluster_fragmentation_score" in out

    def test_top_nodes_util_column(self, client):
        """`ktctl top nodes` carries UTIL% from the capacity plane's
        per-node view (no second kubelet scrape)."""
        client.create("nodes", _node_wire("n0"))
        cols = _cols(1, cpu_cap=4000.0, cpu_fit=3000.0)
        capmod.DEFAULT.sample(cols, ["n0"])
        rc, out, err = self._run(client, ["top", "nodes"])
        assert rc == 0
        assert "UTIL%" in out.splitlines()[0]
        # 3000/4000 cpu is the binding resource: 75%. No HTTP server
        # here, so the kubelet columns dash out and UTIL% still joins.
        (row,) = [l for l in out.splitlines() if l.startswith("n0")]
        assert "75%" in row


def _mk_cluster():
    """In-process cluster: apiserver + LocalTransport + plain
    BatchScheduler (no session — the cluster_columns sampling path)."""
    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.scheduler.daemon import (
        BatchScheduler,
        SchedulerConfig,
    )
    from kubernetes_tpu.server.api import APIServer

    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(2):
        client.create("nodes", _node_wire(f"n{j}"))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync(timeout=60), "caches never synced"
    return api, client, cfg, BatchScheduler(cfg)


class TestLiveDaemons:
    def test_batch_scheduler_samples_per_tick(self, monkeypatch):
        """The plain BatchScheduler (no session) samples through
        cluster_columns after every resolved tick, noting the tick's
        backlog shapes — so the probe table grows backlog quantiles."""
        monkeypatch.setattr(capmod, "DEFAULT", capmod.CapacityMonitor())
        api, client, cfg, sched = _mk_cluster()
        try:
            for i in range(4):
                client.create("pods", _pod_wire(f"cap-{i}", cpu="250m"))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                sched.schedule_batch(timeout=0.2)
                if capmod.DEFAULT.snapshot().get("sampled"):
                    break
            snap = capmod.DEFAULT.snapshot()
            assert snap["sampled"], "tick never sampled capacity"
            shapes = {p["shape"] for p in snap["probes"]}
            assert "backlog-p50" in shapes and "slice-1x250m" in shapes
            assert snap["live_nodes"] == 2
            # Idle ticks keep the plane fresh past the refresh window.
            first = snap["samples"]
            monkeypatch.setattr(sched, "CAPACITY_IDLE_REFRESH_S", 0.0)
            sched.schedule_batch(timeout=0.01)
            assert capmod.DEFAULT.snapshot()["samples"] > first
        finally:
            cfg.stop()

    def test_incremental_scheduler_samples_from_session(self, monkeypatch):
        """The session-backed daemon samples off the host mirror it
        just solved against, inside its own `capacity` phase span."""
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.scheduler.daemon import (
            IncrementalBatchScheduler,
            SchedulerConfig,
        )
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.utils import tracing

        monkeypatch.setattr(capmod, "DEFAULT", capmod.CapacityMonitor())
        api = APIServer()
        client = Client(LocalTransport(api))
        config = SchedulerConfig(Client(LocalTransport(api))).start()
        assert config.wait_for_sync(timeout=60)
        sched = IncrementalBatchScheduler(config).start()
        try:
            for j in range(2):
                client.create("nodes", _node_wire(f"n{j}"))
            frag_before = capmod.FRAG_SCORE.count()
            for i in range(6):
                client.create("pods", _pod_wire(f"inc-{i}"))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                snap = capmod.DEFAULT.snapshot()
                # The idle refresh may sample the pre-node cluster
                # first; wait for a sample that saw both nodes.
                if snap.get("sampled") and snap.get("live_nodes") == 2:
                    break
                time.sleep(0.05)
            assert snap["sampled"], "micro-tick never sampled capacity"
            assert snap["live_nodes"] == 2
            assert {"n0", "n1"} <= set(snap["node_utilization"])
            # The always-on series moved with the sample.
            assert capmod.FRAG_SCORE.count() > frag_before
            assert capmod.HEADROOM.value(shape="slice-1x250m") >= 0
            # The sample ran inside its own phase span.
            assert tracing.PHASE_SECONDS.count(phase="capacity") >= 1
        finally:
            sched.stop()
            config.stop()


class TestOverheadGuard:
    """Per-tick capacity sampling must stay affordable enough for the
    always-on cadence: <5% of the bulk-churn drill's wall (the same
    bar the SLI collector holds in test_sli.py)."""

    def test_capacity_cost_under_5pct_of_bulk_churn(self):
        from kubernetes_tpu.client import Client, HTTPTransport
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        n_pods, batch = 2000, 500
        # Warm the one-time compile out of both timed sections (the
        # daemons pay it once per process, not per tick).
        m = capmod.CapacityMonitor()
        m.note_backlog_shapes([(100.0, 64.0)] * 8)
        warm_cols = _cols(256)
        assert m.sample(warm_cols, [f"n{j}" for j in range(256)])

        api = APIServer()
        srv = APIHTTPServer(api, max_in_flight=800).start()
        try:
            client = Client(HTTPTransport(srv.address))
            stream = Client(HTTPTransport(srv.address)).watch(
                "pods", namespace="default"
            )
            seen = {"n": 0}

            def consume():
                while seen["n"] < 2 * n_pods:
                    ev = stream.next(timeout=10.0)
                    if ev is None:
                        if stream.closed:
                            return
                        continue
                    seen["n"] += 1

            watcher = threading.Thread(target=consume, daemon=True)
            t0 = time.perf_counter()
            watcher.start()
            for s in range(0, n_pods, batch):
                items = [
                    _pod_wire(f"cap-ov-{i}") for i in range(s, s + batch)
                ]
                res = client.create_bulk("pods", items, namespace="default")
                assert all(r.get("status") == "Success" for r in res)
            for s in range(0, n_pods, batch):
                client.delete_bulk(
                    "pods",
                    [f"cap-ov-{i}" for i in range(s, s + batch)],
                    namespace="default",
                )
            watcher.join(timeout=30)
            drill_wall = time.perf_counter() - t0
            stream.close()
            assert seen["n"] >= 2 * n_pods, seen
        finally:
            srv.stop()

        # Standalone per-tick cost: one capacity sample per drill batch
        # (the daemons sample once per resolved tick), 256-node columns.
        # Best of three repeats: a GC pass landing inside one repeat
        # must not fail the guard.
        names = [f"n{j}" for j in range(256)]
        ticks = 2 * n_pods // batch
        cost = float("inf")
        for _repeat in range(3):
            t0 = time.perf_counter()
            for _ in range(ticks):
                m.note_backlog_shapes([(100.0, 64.0)] * 4)
                m.sample(
                    warm_cols, names, backlog_depth=3, oldest_age_s=0.4
                )
            cost = min(cost, time.perf_counter() - t0)
        assert cost < 0.05 * drill_wall, (
            f"capacity sampling cost {cost:.4f}s is >=5% of the "
            f"{drill_wall:.4f}s bulk-churn drill"
        )
