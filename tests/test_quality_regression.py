"""Decision-quality regression gates for the approximate solvers
(VERDICT r2 item 4): wave/sinkhorn placements are scored against the
greedy oracle via pod-order replay — a change that quietly starts
placing pods on their 5th-best node fails here, not in production.

Scores are a 0-30 scale (three 0-10 priorities). Measured values on
this workload (2k x 200, two seeds): wave mean regret ~0.65-0.73 with
p99 = 2; sinkhorn ~2.5-2.9 with p99 <= 14 (congestion pricing trades
greed for balance — its load stddev is the flip side, benched). The
bounds below carry ~2x headroom over measured, far below a systematic
"always the 5th-best node" regression (which would push mean regret
past 4-5 even for wave).
"""

import numpy as np
import pytest

from __graft_entry__ import _synthetic_objects
from kubernetes_tpu.models.columnar import build_snapshot
from kubernetes_tpu.ops import device_snapshot
from kubernetes_tpu.ops.oracle import assignment_quality, solve_sequential_numpy
from kubernetes_tpu.ops.sinkhorn import sinkhorn_assignments
from kubernetes_tpu.ops.solver import solve_assignments
from kubernetes_tpu.ops.wave import wave_assignments


@pytest.fixture(scope="module")
def problem():
    pods, nodes, services = _synthetic_objects(2000, 200, seed=5)
    snap = build_snapshot(pods, nodes, services=services)
    return snap, device_snapshot(snap)


class TestOracleReplay:
    def test_scan_has_zero_regret(self, problem):
        """The sequential scan IS the greedy policy: replaying its own
        assignment must show zero regret and full greedy match — the
        replay harness's self-test."""
        snap, d = problem
        scan = solve_assignments(d)
        q = assignment_quality(snap, scan)
        assert q["mean_regret"] == 0.0
        assert q["greedy_match"] == 1.0
        assert q["feasible_in_order"] == 1.0

    def test_oracle_matches_device_scan(self, problem):
        snap, d = problem
        seq = solve_sequential_numpy(snap)
        dev = np.asarray(solve_assignments(d))
        assert float((seq == dev).mean()) >= 0.99


class TestWaveQuality:
    def test_regret_bounded(self, problem):
        snap, d = problem
        a, _ = wave_assignments(d)
        a = np.asarray(a)[: d.n_pods]
        q = assignment_quality(snap, a)
        assert q["placed"] == d.n_pods, "wave left pods unplaced"
        assert q["feasible_in_order"] >= 0.99
        assert q["mean_regret"] <= 1.5, q
        assert q["p99_regret"] <= 5, q
        assert q["greedy_match"] >= 0.30, q


class TestSinkhornQuality:
    def test_regret_bounded(self, problem):
        """VERDICT r3 weak #4: sinkhorn's regret collapsed from p99 14
        to ~3 at 10k x 1k by dropping per_node_limit 64 -> 2 — the real
        regret source was the packer committing many same-node pods per
        wave, each blind to the spreading/balance score drift of the
        ones before it (swept: limit 64/16/8/4/2 gives p99 14/11/10/
        7/3 at 10k x 1k). price_cap additionally bounds how far
        congestion pricing can push any pod off its greedy best. At
        THIS small shape (2k x 200) two-per-node commits still cost
        p99 ~8 (200 nodes means every service's peers fit a handful of
        nodes, so one extra same-node commit moves spreading scores
        hard); the headline p99 <= 5 bound is enforced at 10k x 1k
        below and in bench.py's published figures."""
        snap, d = problem
        a, _ = sinkhorn_assignments(d)
        a = np.asarray(a)[: d.n_pods]
        q = assignment_quality(snap, a)
        assert q["placed"] == d.n_pods, "sinkhorn left pods unplaced"
        assert q["feasible_in_order"] >= 0.99
        assert q["mean_regret"] <= 1.5, q
        assert q["p99_regret"] <= 10, q
        assert q["greedy_match"] >= 0.25, q

    @pytest.mark.slow
    def test_regret_at_10kx1k_meets_wave_gate(self):
        """The VERDICT r3 next #8 'done' bar: sinkhorn p99 regret <= 5
        at the 10k x 1k quality shape bench.py publishes."""
        pods, nodes, services = _synthetic_objects(10000, 1000, seed=12)
        snap = build_snapshot(pods, nodes, services=services)
        d = device_snapshot(snap)
        a, _ = sinkhorn_assignments(d)
        q = assignment_quality(snap, np.asarray(a)[: d.n_pods])
        assert q["mean_regret"] <= 1.5, q
        assert q["p99_regret"] <= 5, q


@pytest.mark.slow
class TestSinkhornHotspotRegime:
    """VERDICT r4 #9: the regime where congestion pricing earns its
    keep. On a capacity-tight heterogeneous fleet (50 big nodes every
    pod prefers + 950 small, ~85% CPU-tight) plain waves stampede the
    hot nodes and drain in dribbles; Sinkhorn prices demand to
    capacity and must drain in fewer device steps at no worse mean
    regret. bench.py publishes the same figure (hotspot_*)."""

    def test_sinkhorn_beats_wave_on_hotspot(self):
        import bench

        fig = bench._hotspot_figure()
        assert fig["hotspot_sinkhorn_placed"] == fig["hotspot_pods"]
        assert fig["hotspot_wave_placed"] == fig["hotspot_pods"]
        assert (
            fig["hotspot_sinkhorn_waves"] < fig["hotspot_wave_waves"]
        ), fig
        assert (
            fig["hotspot_sinkhorn_mean_regret"]
            <= fig["hotspot_wave_mean_regret"] + 0.25
        ), fig
