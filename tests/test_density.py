"""Density + load e2e: the reference's cluster-scale pass criteria.

Reference: test/e2e/density.go:108-129 (all pods Running, <=1%
abnormal pod events, gated at 30 pods/node) and test/e2e/load.go
(create/scale/delete many RCs and converge). Run against the full
in-process cluster (LocalCluster — the hack/local-up-cluster analog)."""

import time

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.cmd.localup import LocalCluster, build_parser


def wait_until(cond, timeout=60.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def rc_wire(name, replicas, app, cpu="100m", mem="64Mi"):
    return {
        "kind": "ReplicationController",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "replicas": replicas,
            "selector": {"app": app},
            "template": {
                "metadata": {"labels": {"app": app}},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "pause",
                            # Large enough that LeastRequested's
                            # integer score moves as nodes fill —
                            # sub-10m pods don't shift the score and
                            # legitimately pile onto the tie-break
                            # node, same as the reference scheduler.
                            "resources": {
                                "limits": {"cpu": cpu, "memory": mem}
                            },
                        }
                    ]
                },
            },
        },
    }


@pytest.fixture
def cluster():
    args = build_parser().parse_args(["--port", "0", "--nodes", "4"])
    c = LocalCluster(args).start()
    yield c
    c.stop()


def running_count(client, selector=""):
    pods, _ = client.list("pods", namespace="default", label_selector=selector)
    return sum(1 for p in pods if p.status.phase == "Running")


def abnormal_event_fraction(client, total_pods):
    """density.go:188 pass bar: abnormal (non-routine) pod events must
    stay under 1% of pods."""
    events, _ = client.list("events", namespace="default")
    abnormal = [
        e
        for e in events
        if e.reason
        in ("Failed", "FailedScheduling", "Unhealthy", "ContainerKilled")
    ]
    return len(abnormal) / max(1, total_pods)


class TestDensity:
    def test_density_30_pods_per_node(self, cluster):
        """4 nodes x 30 pods/node = 120 pods, all Running, <=1%
        abnormal events (density.go pass criteria at the gate level)."""
        client = Client(LocalTransport(cluster.api))
        total = 4 * 30
        client.create("replicationcontrollers", rc_wire("dense", total, "dense"))
        assert wait_until(
            lambda: running_count(client, "app=dense") == total, timeout=90
        ), f"only {running_count(client, 'app=dense')}/{total} Running"
        # Spread respected node capacity: no node above its max-pods.
        pods, _ = client.list(
            "pods", namespace="default", label_selector="app=dense"
        )
        per_node = {}
        for p in pods:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(v <= 110 for v in per_node.values())
        assert len(per_node) == 4  # every node carries load
        client.flush_events()
        assert abnormal_event_fraction(client, total) <= 0.01

    def test_density_over_http(self, cluster):
        """Same criteria with the pods created over the real HTTP
        apiserver (the driver surface users touch), then the
        HighLatencyRequests SLO gate: 99% of API calls < 1 s
        (docs/roadmap.md:69, enforced exactly like test/e2e/
        util.go:1286 — from the apiserver's own latency summaries,
        long-running verbs exempt)."""
        from kubernetes_tpu.server.httpserver import high_latency_requests

        client = Client(HTTPTransport(cluster.http.address))
        client.create("replicationcontrollers", rc_wire("htt", 40, "htt"))
        assert wait_until(
            lambda: running_count(client, "app=htt") == 40, timeout=60
        )
        slow = high_latency_requests(threshold=1.0)
        assert not slow, f"API p99 SLO violations: {slow}"


class TestLoad:
    def test_rc_churn_converges(self, cluster):
        """load.go shape: several RCs created, scaled up, scaled down,
        deleted — the system converges to exactly the desired state."""
        client = Client(LocalTransport(cluster.api))
        for i in range(5):
            client.create(
                "replicationcontrollers", rc_wire(f"load-{i}", 4, f"load-{i}")
            )
        assert wait_until(
            lambda: all(
                running_count(client, f"app=load-{i}") == 4 for i in range(5)
            ),
            timeout=60,
        )
        # Scale up evens, scale down odds.
        for i in range(5):
            rc = client.get(
                "replicationcontrollers", f"load-{i}", namespace="default"
            )
            rc.spec.replicas = 8 if i % 2 == 0 else 1
            client.update("replicationcontrollers", rc, namespace="default")
        assert wait_until(
            lambda: all(
                running_count(client, f"app=load-{i}")
                == (8 if i % 2 == 0 else 1)
                for i in range(5)
            ),
            timeout=60,
        )
        # Delete everything; pods drain.
        from kubernetes_tpu.cli.updater import Reaper

        for i in range(5):
            Reaper(client, timeout=30).stop(
                "replicationcontrollers", f"load-{i}", namespace="default"
            )
        assert wait_until(
            lambda: sum(
                running_count(client, f"app=load-{i}") for i in range(5)
            )
            == 0,
            timeout=30,
        )


class TestMaxInFlight:
    """Inbound protection (pkg/apiserver/handlers.go MaxInFlightLimit):
    excess concurrent non-long-running requests get 429; long-running
    requests (watch) bypass the limit entirely."""

    def test_429_beyond_limit_watch_exempt(self):
        import threading

        from kubernetes_tpu.server import APIServer
        from kubernetes_tpu.server.api import APIError
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        slow = threading.Event()
        real_list = api.list

        def slow_list(resource, *a, **kw):
            if resource == "pods":
                slow.wait(timeout=5)
            return real_list(resource, *a, **kw)

        api.list = slow_list
        # The HTTP tier serves LISTs from the watch cache
        # (list_response_bytes); slow that entry point the same way so
        # the in-flight slots actually fill.
        real_enc = api.list_response_bytes

        def slow_enc(resource, *a, **kw):
            if resource == "pods":
                slow.wait(timeout=5)
            return real_enc(resource, *a, **kw)

        api.list_response_bytes = slow_enc
        srv = APIHTTPServer(api, max_in_flight=2).start()
        try:
            client = Client(HTTPTransport(srv.address))
            outcomes = []

            def lister():
                try:
                    client.list("pods", namespace="default")
                    outcomes.append("ok")
                except APIError as e:
                    outcomes.append(e.code)

            threads = [threading.Thread(target=lister) for _ in range(6)]
            for t in threads:
                t.start()
            time.sleep(0.5)  # both slots now held by slow lists
            # Long-running passthrough: a watch opens fine while the
            # server is saturated.
            stream = client.watch("pods", namespace="default")
            assert not stream.closed
            stream.close()
            slow.set()
            for t in threads:
                t.join(timeout=10)
            assert outcomes.count(429) >= 1, outcomes
            assert outcomes.count("ok") >= 2, outcomes
            # Slots were released: the server serves normally again.
            client.list("pods", namespace="default")
        finally:
            api.list = real_list
            srv.stop()


@pytest.mark.slow
class TestDensityAtScale:
    """The reference bar at reference scale (VERDICT r2 item 6):
    >=1k pods over the real HTTP apiserver with >=12 kubelets (fake
    runtime under a real control plane, exactly how cmd/integration
    tests multi-node), batch scheduler, asserting the density.go
    pass criteria: all Running, <=1% abnormal events, API p99 SLO
    clean (test/e2e/density.go:108-129)."""

    def test_density_1k_pods_12_nodes(self):
        from kubernetes_tpu.server.httpserver import (
            high_latency_requests,
            reset_request_latency,
        )

        args = build_parser().parse_args(
            ["--port", "0", "--nodes", "12", "--batch-scheduler"]
        )
        reset_request_latency()
        c = LocalCluster(args).start()
        try:
            client = Client(HTTPTransport(c.http.address))
            total = 1200  # 100 pods/node — over the 30/node gate
            n_rcs = 12
            for i in range(n_rcs):
                # 100 pods/node must FIT the kubelets' registered
                # capacity (4 CPU): 25m each -> 2.5 of 4 cores.
                client.create(
                    "replicationcontrollers",
                    rc_wire(
                        f"dense-{i}", total // n_rcs, f"dense-{i}",
                        cpu="25m", mem="16Mi",
                    ),
                )

            def all_running():
                pods, _ = client.list("pods", namespace="default")
                return sum(1 for p in pods if p.status.phase == "Running")

            assert wait_until(
                lambda: all_running() >= total, timeout=420, interval=1.0
            ), f"only {all_running()}/{total} Running"
            pods, _ = client.list("pods", namespace="default")
            per_node = {}
            for p in pods:
                per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
            assert len(per_node) == 12, "some kubelet carried no pods"
            assert all(v <= 110 for v in per_node.values()), per_node
            client.flush_events()
            assert abnormal_event_fraction(client, total) <= 0.01
            slow = high_latency_requests(threshold=1.0)
            assert not slow, f"API p99 SLO violations: {slow}"
        finally:
            c.stop()


def _density_child(nodes, pods_per_node, kubelet_http, timeout_s):
    """Spawn-process entry: run the reference-goal density drill in a
    FRESH interpreter."""
    TestDensityReferenceGoal()._run(
        nodes, pods_per_node, kubelet_http, timeout_s
    )


def run_isolated_density(nodes, pods_per_node, kubelet_http, timeout_s):
    """Run the density drill in a fresh SPAWNED process (VERDICT r4
    Weak #1): the aggregated slow suite accumulates daemon threads,
    compiled executables, and GC pressure in one interpreter, and on a
    1-core host that contention leaks into this test's p99 SLO gate.
    The reference's e2e runs against a dedicated cluster
    (test/e2e/e2e_test.go); a fresh process is the in-repo equivalent.
    Spawn (not fork): the parent's jax runtime must not be inherited
    mid-flight. Assertion details land on the child's stderr, which
    pytest shows on failure."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    p = ctx.Process(
        target=_density_child,
        args=(nodes, pods_per_node, kubelet_http, timeout_s),
    )
    p.start()
    p.join(timeout=timeout_s + 300)
    if p.is_alive():
        p.terminate()
        p.join(timeout=10)
        raise AssertionError("isolated density run timed out")
    assert p.exitcode == 0, (
        f"isolated density run failed (exit {p.exitcode}); "
        "see child stderr above"
    )


@pytest.mark.slow
class TestDensityReferenceGoal:
    """The reference's v1.0 cluster-size goal: 100 nodes x 30 pods/node
    = 3000 pods (docs/roadmap.md:61-63), pass criteria from
    test/e2e/density.go:108-129 — all pods Running, <=1% abnormal pod
    events — plus the API latency SLO (99% of calls < 1s,
    docs/roadmap.md:69) read from the apiserver's own summaries exactly
    like test/e2e/util.go:1286 HighLatencyRequests.

    Two topologies, scaled to what a single-core CI host can carry:
    - 100 kubelets in-process (cmd/integration's fake-runtime-under-
      real-control-plane pattern) with the client driving pod creation
      and the SLO gate over the real HTTP apiserver;
    - 50 kubelets each talking REAL HTTP (watch fan-out, heartbeats,
      status writeback all cross the wire, one serialized connection
      per kubelet like the Go client's few-multiplexed-connections
      shape).
    """

    @staticmethod
    def _warm_solver(nodes, total):
        """Compile the wave solver's shape buckets for this workload
        BEFORE the SLO-gated phase: XLA CPU compiles take seconds of
        this single core, and a compile landing mid-workload starves
        the HTTP handlers into a bogus p99 breach. The reference's SLO
        is a steady-state serving bar; compilation is one-time."""
        from __graft_entry__ import _synthetic_objects
        from kubernetes_tpu.scheduler.batch import schedule_backlog_wave

        p, n, s = _synthetic_objects(total, nodes, seed=9)
        schedule_backlog_wave(p, n, services=s)

    def _run(self, nodes, pods_per_node, kubelet_http, timeout_s):
        from kubernetes_tpu.server.httpserver import (
            high_latency_requests,
            reset_request_latency,
        )

        self._warm_solver(nodes, nodes * pods_per_node)
        # Fresh SLO window: the process-global latency summary carries
        # every earlier in-process cluster's observations (the gate
        # must judge THIS cluster, like the reference's per-cluster
        # e2e scrape).
        reset_request_latency()
        argv = [
            "--port", "0", "--nodes", str(nodes), "--batch-scheduler",
            "--batch-mode", "wave", "--no-kube-proxy",
        ]
        if kubelet_http:
            argv.append("--kubelet-http")
        c = LocalCluster(build_parser().parse_args(argv)).start()
        try:
            client = Client(HTTPTransport(c.http.address))
            total = nodes * pods_per_node
            n_rcs = max(1, nodes // 10)
            for i in range(n_rcs):
                client.create(
                    "replicationcontrollers",
                    rc_wire(
                        f"dense-{i}", total // n_rcs, f"dense-{i}",
                        cpu="25m", mem="16Mi",
                    ),
                )

            def all_running():
                pods, _ = client.list("pods", namespace="default")
                return sum(1 for p in pods if p.status.phase == "Running")

            assert wait_until(
                lambda: all_running() >= total,
                timeout=timeout_s, interval=1.0,
            ), f"only {all_running()}/{total} Running"
            pods, _ = client.list("pods", namespace="default")
            per_node = {}
            for p in pods:
                per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
            assert len(per_node) == nodes, "some kubelet carried no pods"
            assert all(v <= 110 for v in per_node.values()), per_node
            client.flush_events()
            assert abnormal_event_fraction(client, total) <= 0.01
            slow = high_latency_requests(threshold=1.0)
            assert not slow, f"API p99 SLO violations: {slow}"
        finally:
            c.stop()

    def test_density_3000_pods_100_nodes(self):
        """The headline shape (reference cluster-size goal): measured
        ~25s to all-Running on a 1-core host; 300s is the safety bound.
        Runs in a fresh process so the aggregated suite's residue
        can't breach the SLO gate."""
        run_isolated_density(nodes=100, pods_per_node=30,
                             kubelet_http=False, timeout_s=300)

    def test_density_http_kubelets_50_nodes(self):
        """Full wire topology: 50 kubelets x 30 pods over real HTTP
        (measured ~16s to all-Running; 100 HTTP kubelets exceeds a
        single-core host's thread budget — the in-process variant
        above carries the 100-node shape). Fresh-process isolated."""
        run_isolated_density(nodes=50, pods_per_node=30,
                             kubelet_http=True, timeout_s=300)


def _thousand_node_child(timeout_s, nodes=1000, pods_per_node=30):
    """Spawn entry: the reference's mid-2015 cluster-size goal — 1000
    nodes x 30 pods/node = 30k pods, all Running, <=1% abnormal
    events, API p99 SLO clean (docs/roadmap.md:61-62,
    docs/availability.md:124; pass criteria test/e2e/density.go:
    108-129).

    Lean assembly: kubelets share the in-process transport with LONG
    heartbeat/sync periods (1000 heartbeat threads at the default 5s
    would be pure scheduler thrash on a 1-core host — the reference
    tunes --node-status-update-frequency at scale for the same
    reason); the RC fan-out and the SLO-gated list/create traffic ride
    real HTTP."""
    import sys
    import time as _t

    from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
    from kubernetes_tpu.controllers import ControllerManager
    from kubernetes_tpu.kubelet import FakeRuntime, Kubelet
    from kubernetes_tpu.scheduler.daemon import (
        IncrementalBatchScheduler,
        SchedulerConfig,
    )
    from kubernetes_tpu.server.api import APIServer
    from kubernetes_tpu.server.httpserver import (
        APIHTTPServer,
        high_latency_requests,
        reset_request_latency,
    )

    total = nodes * pods_per_node
    # ~5000 threads contend one GIL here; the default 5 ms switch
    # interval makes every lock handoff cost up to a full quantum
    # (observed as a ~200 writes/s store ceiling with 1400 waiters).
    sys.setswitchinterval(0.0005)
    # Cyclic GC over ~10^7 live objects (30k pods x caches x watch
    # history) costs seconds per gen2 pass and fires constantly at
    # this allocation rate; the drill is a bounded one-shot process,
    # so reference counting alone is the right memory story.
    import gc

    gc.disable()
    from kubernetes_tpu.store.kvstore import KVStore

    # Serialized write-combining store: with thousands of writer
    # threads, per-caller lock acquisition pays a full wake latency
    # per write; one hot applier thread keeps writes flowing.
    api = APIServer(store=KVStore(serialized_writes=True))
    srv = APIHTTPServer(api, max_in_flight=800).start()
    print(f"# apiserver at {srv.address}", flush=True)
    kubelets = []
    t0 = _t.monotonic()
    for i in range(nodes):
        kubelets.append(
            Kubelet(
                Client(LocalTransport(api)),
                node_name=f"node-{i}",
                runtime=FakeRuntime(),
                heartbeat_period=30.0,
                sync_period=15.0,
            ).start()
        )
    print(f"# {nodes} kubelets up in {_t.monotonic() - t0:.1f}s",
          flush=True)
    # Let all 1000 registrations land before the control plane's
    # informers sync (mass startup saturates the single core; creating
    # workloads mid-storm just times out the client).
    deadline = _t.monotonic() + 120
    while _t.monotonic() < deadline:
        if len(api.list("nodes", "")["items"]) >= nodes:
            break
        _t.sleep(1.0)
    n_reg = len(api.list("nodes", "")["items"])
    assert n_reg >= nodes, f"only {n_reg}/{nodes} nodes registered"
    print(f"# all {nodes} nodes registered at "
          f"{_t.monotonic() - t0:.1f}s", flush=True)
    cfg = SchedulerConfig(
        Client(LocalTransport(api)), raw_scheduled_cache=True
    ).start()
    assert cfg.wait_for_sync(120)
    # One fixed tick bucket = ONE compiled executable: a fresh pow2
    # bucket mid-drill stalls binding for a full CPU XLA compile.
    # Scan ticks: on the CPU test backend the wave solver's full-matrix
    # iterations at the 2048-node bucket cost minutes per tick; the
    # sequential scan is linear in the tick's pods and stays seconds.
    sched = IncrementalBatchScheduler(
        cfg, mode="scan", max_batch=1024, pod_bucket=1024
    ).start()
    manager = ControllerManager(
        Client(LocalTransport(api)),
        node_grace_period=120.0,
        node_eviction_timeout=300.0,
    ).start()
    http_client = Client(HTTPTransport(srv.address, timeout=120.0))
    try:
        reset_request_latency()
        n_rcs = 100
        for i in range(n_rcs):
            # CPU sized so EVERY placement moves LeastRequested's
            # integer score (sub-40m pods don't, and the sequential
            # tie-break then piles nodes by index — reference
            # semantics): spread across all 1000 nodes is the point.
            cpu = f"{max(100, 4000 // (pods_per_node * 2))}m"
            http_client.create(
                "replicationcontrollers",
                rc_wire(f"dense-{i}", total // n_rcs, f"dense-{i}",
                        cpu=cpu, mem="16Mi"),
            )

        def running_count_fast():
            # Raw uncopied list: a deep copy of 30k pods per poll would
            # cost more than the cluster under test (read-only refs).
            items = api.list("pods", "default", copy=False)["items"]
            return sum(
                1
                for p in items
                if p.get("status", {}).get("phase") == "Running"
            )

        deadline = _t.monotonic() + timeout_s
        last = -1
        while _t.monotonic() < deadline:
            n = running_count_fast()
            if n >= total:
                break
            if n != last:
                print(f"# running: {n}/{total} "
                      f"({_t.monotonic() - t0:.0f}s)", flush=True)
                last = n
            _t.sleep(3.0)
        n = running_count_fast()
        assert n >= total, f"only {n}/{total} Running"
        # Every node carries load, none over its cap.
        per_node = {}
        for p in api.list("pods", "default")["items"]:
            node = p.get("spec", {}).get("nodeName", "")
            per_node[node] = per_node.get(node, 0) + 1
        assert len(per_node) == nodes, (
            f"only {len(per_node)}/{nodes} nodes carry pods"
        )
        assert all(v <= 110 for v in per_node.values())
        # Abnormal events <= 1% of pods (density.go:188).
        http_client.flush_events()
        assert abnormal_event_fraction(http_client, total) <= 0.01
        # API SLO over the HTTP tier that served the RC fan-out + polls.
        _, _ = http_client.list("pods", namespace="default")
        slow = high_latency_requests(threshold=1.0)
        assert not slow, f"API p99 SLO violations: {slow}"
        print(f"# 1000-node drill: {total} Running in "
              f"{_t.monotonic() - t0:.0f}s", flush=True)
    finally:
        manager.stop()
        sched.stop()
        srv.stop()
        # 1000 kubelets: threads are daemonic; the spawn child exits
        # right after, so skip the ~1000 sequential stop() joins.


@pytest.mark.slow
def test_density_1000_nodes():
    """The 1000-NODE cluster goal (docs/roadmap.md:61-62,
    docs/availability.md:124), fresh-process isolated (same rationale
    as run_isolated_density): 1000 kubelets registering, heartbeating,
    and running pods under one control plane, every node carrying
    load, API SLO clean.

    Pods/node is 5 here, not the 30 the 100-node test carries: on a
    1-CORE CI host ~5000 kubelet threads contend one GIL, and the
    watch dispatcher's fair GIL share caps end-to-end pod throughput
    (observed cliff near ~6k Running pods) — the full 30k-pod shape
    is a host-budget problem, not a design limit
    (KTPU_DRILL_PODS_PER_NODE=30 runs it on a multi-core host). The
    30-pods/node density bar is carried by
    test_density_3000_pods_100_nodes."""
    import multiprocessing as mp
    import os as _os

    ppn = int(_os.environ.get("KTPU_DRILL_PODS_PER_NODE", "5"))
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_thousand_node_child, args=(900, 1000, ppn))
    p.start()
    p.join(timeout=1200)
    if p.is_alive():
        p.terminate()
        p.join(timeout=10)
        raise AssertionError("1000-node drill timed out")
    assert p.exitcode == 0, (
        f"1000-node drill failed (exit {p.exitcode}); see child stderr"
    )


def test_proxy_subpath_is_long_running_exempt():
    """Proxy requests carry subpaths after the verb; they must bypass
    the in-flight limit wherever 'proxy' sits in the path (review
    regression — reference regex matches anywhere)."""
    from kubernetes_tpu.server.httpserver import _request_is_long_running

    assert _request_is_long_running(
        ("nodes", "n1", "proxy", "healthz"), {}
    )
    assert _request_is_long_running(
        ("namespaces", "ns", "pods", "p", "proxy", "metrics"), {}
    )
    assert _request_is_long_running(("watch", "pods"), {})
    assert _request_is_long_running(("namespaces", "d", "pods"), {"watch": "true"})
    assert _request_is_long_running(
        ("namespaces", "d", "pods", "p", "log"), {"follow": "true"}
    )
    assert not _request_is_long_running(
        ("namespaces", "d", "pods", "p", "log"), {}
    )
    assert not _request_is_long_running(("namespaces", "d", "pods"), {})
