"""Density + load e2e: the reference's cluster-scale pass criteria.

Reference: test/e2e/density.go:108-129 (all pods Running, <=1%
abnormal pod events, gated at 30 pods/node) and test/e2e/load.go
(create/scale/delete many RCs and converge). Run against the full
in-process cluster (LocalCluster — the hack/local-up-cluster analog)."""

import time

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.cmd.localup import LocalCluster, build_parser


def wait_until(cond, timeout=60.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def rc_wire(name, replicas, app, cpu="100m", mem="64Mi"):
    return {
        "kind": "ReplicationController",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "replicas": replicas,
            "selector": {"app": app},
            "template": {
                "metadata": {"labels": {"app": app}},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "pause",
                            # Large enough that LeastRequested's
                            # integer score moves as nodes fill —
                            # sub-10m pods don't shift the score and
                            # legitimately pile onto the tie-break
                            # node, same as the reference scheduler.
                            "resources": {
                                "limits": {"cpu": cpu, "memory": mem}
                            },
                        }
                    ]
                },
            },
        },
    }


@pytest.fixture
def cluster():
    args = build_parser().parse_args(["--port", "0", "--nodes", "4"])
    c = LocalCluster(args).start()
    yield c
    c.stop()


def running_count(client, selector=""):
    pods, _ = client.list("pods", namespace="default", label_selector=selector)
    return sum(1 for p in pods if p.status.phase == "Running")


def abnormal_event_fraction(client, total_pods):
    """density.go:188 pass bar: abnormal (non-routine) pod events must
    stay under 1% of pods."""
    events, _ = client.list("events", namespace="default")
    abnormal = [
        e
        for e in events
        if e.reason
        in ("Failed", "FailedScheduling", "Unhealthy", "ContainerKilled")
    ]
    return len(abnormal) / max(1, total_pods)


class TestDensity:
    def test_density_30_pods_per_node(self, cluster):
        """4 nodes x 30 pods/node = 120 pods, all Running, <=1%
        abnormal events (density.go pass criteria at the gate level)."""
        client = Client(LocalTransport(cluster.api))
        total = 4 * 30
        client.create("replicationcontrollers", rc_wire("dense", total, "dense"))
        assert wait_until(
            lambda: running_count(client, "app=dense") == total, timeout=90
        ), f"only {running_count(client, 'app=dense')}/{total} Running"
        # Spread respected node capacity: no node above its max-pods.
        pods, _ = client.list(
            "pods", namespace="default", label_selector="app=dense"
        )
        per_node = {}
        for p in pods:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
        assert all(v <= 110 for v in per_node.values())
        assert len(per_node) == 4  # every node carries load
        client.flush_events()
        assert abnormal_event_fraction(client, total) <= 0.01

    def test_density_over_http(self, cluster):
        """Same criteria with the pods created over the real HTTP
        apiserver (the driver surface users touch), then the
        HighLatencyRequests SLO gate: 99% of API calls < 1 s
        (docs/roadmap.md:69, enforced exactly like test/e2e/
        util.go:1286 — from the apiserver's own latency summaries,
        long-running verbs exempt)."""
        from kubernetes_tpu.server.httpserver import high_latency_requests

        client = Client(HTTPTransport(cluster.http.address))
        client.create("replicationcontrollers", rc_wire("htt", 40, "htt"))
        assert wait_until(
            lambda: running_count(client, "app=htt") == 40, timeout=60
        )
        slow = high_latency_requests(threshold=1.0)
        assert not slow, f"API p99 SLO violations: {slow}"


class TestLoad:
    def test_rc_churn_converges(self, cluster):
        """load.go shape: several RCs created, scaled up, scaled down,
        deleted — the system converges to exactly the desired state."""
        client = Client(LocalTransport(cluster.api))
        for i in range(5):
            client.create(
                "replicationcontrollers", rc_wire(f"load-{i}", 4, f"load-{i}")
            )
        assert wait_until(
            lambda: all(
                running_count(client, f"app=load-{i}") == 4 for i in range(5)
            ),
            timeout=60,
        )
        # Scale up evens, scale down odds.
        for i in range(5):
            rc = client.get(
                "replicationcontrollers", f"load-{i}", namespace="default"
            )
            rc.spec.replicas = 8 if i % 2 == 0 else 1
            client.update("replicationcontrollers", rc, namespace="default")
        assert wait_until(
            lambda: all(
                running_count(client, f"app=load-{i}")
                == (8 if i % 2 == 0 else 1)
                for i in range(5)
            ),
            timeout=60,
        )
        # Delete everything; pods drain.
        from kubernetes_tpu.cli.updater import Reaper

        for i in range(5):
            Reaper(client, timeout=30).stop(
                "replicationcontrollers", f"load-{i}", namespace="default"
            )
        assert wait_until(
            lambda: sum(
                running_count(client, f"app=load-{i}") for i in range(5)
            )
            == 0,
            timeout=30,
        )


class TestMaxInFlight:
    """Inbound protection (pkg/apiserver/handlers.go MaxInFlightLimit):
    excess concurrent non-long-running requests get 429; long-running
    requests (watch) bypass the limit entirely."""

    def test_429_beyond_limit_watch_exempt(self):
        import threading

        from kubernetes_tpu.server import APIServer
        from kubernetes_tpu.server.api import APIError
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        slow = threading.Event()
        real_list = api.list

        def slow_list(resource, *a, **kw):
            if resource == "pods":
                slow.wait(timeout=5)
            return real_list(resource, *a, **kw)

        api.list = slow_list
        srv = APIHTTPServer(api, max_in_flight=2).start()
        try:
            client = Client(HTTPTransport(srv.address))
            outcomes = []

            def lister():
                try:
                    client.list("pods", namespace="default")
                    outcomes.append("ok")
                except APIError as e:
                    outcomes.append(e.code)

            threads = [threading.Thread(target=lister) for _ in range(6)]
            for t in threads:
                t.start()
            time.sleep(0.5)  # both slots now held by slow lists
            # Long-running passthrough: a watch opens fine while the
            # server is saturated.
            stream = client.watch("pods", namespace="default")
            assert not stream.closed
            stream.close()
            slow.set()
            for t in threads:
                t.join(timeout=10)
            assert outcomes.count(429) >= 1, outcomes
            assert outcomes.count("ok") >= 2, outcomes
            # Slots were released: the server serves normally again.
            client.list("pods", namespace="default")
        finally:
            api.list = real_list
            srv.stop()


@pytest.mark.slow
class TestDensityAtScale:
    """The reference bar at reference scale (VERDICT r2 item 6):
    >=1k pods over the real HTTP apiserver with >=12 kubelets (fake
    runtime under a real control plane, exactly how cmd/integration
    tests multi-node), batch scheduler, asserting the density.go
    pass criteria: all Running, <=1% abnormal events, API p99 SLO
    clean (test/e2e/density.go:108-129)."""

    def test_density_1k_pods_12_nodes(self):
        from kubernetes_tpu.server.httpserver import high_latency_requests
        from kubernetes_tpu.utils import metrics as metricspkg

        args = build_parser().parse_args(
            ["--port", "0", "--nodes", "12", "--batch-scheduler"]
        )
        c = LocalCluster(args).start()
        try:
            client = Client(HTTPTransport(c.http.address))
            total = 1200  # 100 pods/node — over the 30/node gate
            n_rcs = 12
            for i in range(n_rcs):
                # 100 pods/node must FIT the kubelets' registered
                # capacity (4 CPU): 25m each -> 2.5 of 4 cores.
                client.create(
                    "replicationcontrollers",
                    rc_wire(
                        f"dense-{i}", total // n_rcs, f"dense-{i}",
                        cpu="25m", mem="16Mi",
                    ),
                )

            def all_running():
                pods, _ = client.list("pods", namespace="default")
                return sum(1 for p in pods if p.status.phase == "Running")

            assert wait_until(
                lambda: all_running() >= total, timeout=420, interval=1.0
            ), f"only {all_running()}/{total} Running"
            pods, _ = client.list("pods", namespace="default")
            per_node = {}
            for p in pods:
                per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
            assert len(per_node) == 12, "some kubelet carried no pods"
            assert all(v <= 110 for v in per_node.values()), per_node
            client.flush_events()
            assert abnormal_event_fraction(client, total) <= 0.01
            slow = high_latency_requests(threshold=1.0)
            assert not slow, f"API p99 SLO violations: {slow}"
        finally:
            c.stop()


@pytest.mark.slow
class TestDensityReferenceGoal:
    """The reference's v1.0 cluster-size goal: 100 nodes x 30 pods/node
    = 3000 pods (docs/roadmap.md:61-63), pass criteria from
    test/e2e/density.go:108-129 — all pods Running, <=1% abnormal pod
    events — plus the API latency SLO (99% of calls < 1s,
    docs/roadmap.md:69) read from the apiserver's own summaries exactly
    like test/e2e/util.go:1286 HighLatencyRequests.

    Two topologies, scaled to what a single-core CI host can carry:
    - 100 kubelets in-process (cmd/integration's fake-runtime-under-
      real-control-plane pattern) with the client driving pod creation
      and the SLO gate over the real HTTP apiserver;
    - 50 kubelets each talking REAL HTTP (watch fan-out, heartbeats,
      status writeback all cross the wire, one serialized connection
      per kubelet like the Go client's few-multiplexed-connections
      shape).
    """

    @staticmethod
    def _warm_solver(nodes, total):
        """Compile the wave solver's shape buckets for this workload
        BEFORE the SLO-gated phase: XLA CPU compiles take seconds of
        this single core, and a compile landing mid-workload starves
        the HTTP handlers into a bogus p99 breach. The reference's SLO
        is a steady-state serving bar; compilation is one-time."""
        from __graft_entry__ import _synthetic_objects
        from kubernetes_tpu.scheduler.batch import schedule_backlog_wave

        p, n, s = _synthetic_objects(total, nodes, seed=9)
        schedule_backlog_wave(p, n, services=s)

    def _run(self, nodes, pods_per_node, kubelet_http, timeout_s):
        from kubernetes_tpu.server.httpserver import high_latency_requests

        self._warm_solver(nodes, nodes * pods_per_node)
        argv = [
            "--port", "0", "--nodes", str(nodes), "--batch-scheduler",
            "--batch-mode", "wave", "--no-kube-proxy",
        ]
        if kubelet_http:
            argv.append("--kubelet-http")
        c = LocalCluster(build_parser().parse_args(argv)).start()
        try:
            client = Client(HTTPTransport(c.http.address))
            total = nodes * pods_per_node
            n_rcs = max(1, nodes // 10)
            for i in range(n_rcs):
                client.create(
                    "replicationcontrollers",
                    rc_wire(
                        f"dense-{i}", total // n_rcs, f"dense-{i}",
                        cpu="25m", mem="16Mi",
                    ),
                )

            def all_running():
                pods, _ = client.list("pods", namespace="default")
                return sum(1 for p in pods if p.status.phase == "Running")

            assert wait_until(
                lambda: all_running() >= total,
                timeout=timeout_s, interval=1.0,
            ), f"only {all_running()}/{total} Running"
            pods, _ = client.list("pods", namespace="default")
            per_node = {}
            for p in pods:
                per_node[p.spec.node_name] = per_node.get(p.spec.node_name, 0) + 1
            assert len(per_node) == nodes, "some kubelet carried no pods"
            assert all(v <= 110 for v in per_node.values()), per_node
            client.flush_events()
            assert abnormal_event_fraction(client, total) <= 0.01
            slow = high_latency_requests(threshold=1.0)
            assert not slow, f"API p99 SLO violations: {slow}"
        finally:
            c.stop()

    def test_density_3000_pods_100_nodes(self):
        """The headline shape (reference cluster-size goal): measured
        ~25s to all-Running on a 1-core host; 300s is the safety bound."""
        self._run(nodes=100, pods_per_node=30, kubelet_http=False,
                  timeout_s=300)

    def test_density_http_kubelets_50_nodes(self):
        """Full wire topology: 50 kubelets x 30 pods over real HTTP
        (measured ~16s to all-Running; 100 HTTP kubelets exceeds a
        single-core host's thread budget — the in-process variant
        above carries the 100-node shape)."""
        self._run(nodes=50, pods_per_node=30, kubelet_http=True,
                  timeout_s=300)


def test_proxy_subpath_is_long_running_exempt():
    """Proxy requests carry subpaths after the verb; they must bypass
    the in-flight limit wherever 'proxy' sits in the path (review
    regression — reference regex matches anywhere)."""
    from kubernetes_tpu.server.httpserver import _request_is_long_running

    assert _request_is_long_running(
        ("nodes", "n1", "proxy", "healthz"), {}
    )
    assert _request_is_long_running(
        ("namespaces", "ns", "pods", "p", "proxy", "metrics"), {}
    )
    assert _request_is_long_running(("watch", "pods"), {})
    assert _request_is_long_running(("namespaces", "d", "pods"), {"watch": "true"})
    assert _request_is_long_running(
        ("namespaces", "d", "pods", "p", "log"), {"follow": "true"}
    )
    assert not _request_is_long_running(
        ("namespaces", "d", "pods", "p", "log"), {}
    )
    assert not _request_is_long_running(("namespaces", "d", "pods"), {})
