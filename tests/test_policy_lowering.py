"""Policy-aware batch lowering: the configured predicate/priority set
(scheduler policy file) must produce the SAME decisions on the device
path as on the scalar path — or route to the scalar path when it can't
lower (round-2 VERDICT item 2 / Weak #1).

Reference semantics under test:
  CheckNodeLabelPresence   predicates.go:226-240
  CheckServiceAffinity     predicates.go:268-335
  ServiceAntiAffinity      spreading.go:105-169
  CalculateNodeLabelPriority  priorities.go:113-138
plus the base five predicates / three priorities with policy-chosen
subsets and weights.
"""

import random

import pytest

from kubernetes_tpu.models.algspec import (
    DEFAULT_SPEC,
    UnloweredPolicyError,
    lower_spec,
    spec_from_policy,
)
from kubernetes_tpu.models.objects import ObjectMeta, Service, ServiceSpec
from kubernetes_tpu.scheduler.batch import (
    parity_report,
    schedule_backlog_scalar,
    schedule_backlog_tpu,
)

from tests.test_solver_parity import mk_node, mk_pod


def mk_svc(name, selector, ns="default"):
    return Service(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=ServiceSpec(selector=selector),
    )


def assert_policy_parity(policy, pending, nodes, assigned=(), services=()):
    spec = spec_from_policy(policy)
    scalar = schedule_backlog_scalar(pending, nodes, assigned, services, spec=spec)
    batch = schedule_backlog_tpu(pending, nodes, assigned, services, spec=spec)
    parity, mismatches = parity_report(scalar, batch)
    assert parity == 1.0, (
        f"parity {parity:.3f}, mismatches at {mismatches[:10]}: "
        + ", ".join(
            f"#{i} scalar={scalar[i]} batch={batch[i]}" for i in mismatches[:5]
        )
    )
    return scalar, batch


BASE_PREDS = [
    {"name": "PodFitsPorts"},
    {"name": "PodFitsResources"},
    {"name": "NoDiskConflict"},
    {"name": "MatchNodeSelector"},
    {"name": "HostName"},
]


class TestSpecPlumbing:
    def test_default_plus_argumented_priority_is_not_default(self):
        """Adding ServiceAntiAffinity on top of the stock set must NOT
        classify as default — the batch path would silently drop the
        configured priority (review regression)."""
        policy = {
            "predicates": BASE_PREDS,
            "priorities": [
                {"name": "LeastRequestedPriority", "weight": 1},
                {"name": "BalancedResourceAllocation", "weight": 1},
                {"name": "ServiceSpreadingPriority", "weight": 1},
                {"name": "aa", "weight": 2,
                 "argument": {"serviceAntiAffinity": {"label": "zone"}}},
            ],
        }
        spec = spec_from_policy(policy)
        assert not spec.is_default()
        nodes = [
            mk_node("n0", labels={"zone": "a"}),
            mk_node("n1", labels={"zone": "b"}),
        ]
        pods = [mk_pod(f"p{i}", labels={"app": "w"}) for i in range(4)]
        assert_policy_parity(
            policy, pods, nodes, services=[mk_svc("w", {"app": "w"})]
        )

    def test_default_policy_is_default_spec(self):
        policy = {
            "kind": "Policy",
            "predicates": BASE_PREDS,
            "priorities": [
                {"name": "LeastRequestedPriority", "weight": 1},
                {"name": "BalancedResourceAllocation", "weight": 1},
                {"name": "ServiceSpreadingPriority", "weight": 1},
            ],
        }
        assert spec_from_policy(policy).is_default()
        assert DEFAULT_SPEC.is_default()

    def test_unknown_kind_raises(self):
        spec = spec_from_policy(
            {"predicates": [{"name": "MyCustomPredicate"}], "priorities": []}
        )
        assert not spec.is_default()
        with pytest.raises(UnloweredPolicyError):
            lower_spec(spec)

    def test_lowered_flags(self):
        spec = spec_from_policy(
            {
                "predicates": [
                    {"name": "PodFitsResources"},
                    {
                        "name": "zone",
                        "argument": {"serviceAffinity": {"labels": ["zone"]}},
                    },
                    {
                        "name": "retiring",
                        "argument": {
                            "labelsPresence": {
                                "labels": ["retiring"], "presence": False,
                            }
                        },
                    },
                ],
                "priorities": [
                    {"name": "LeastRequestedPriority", "weight": 2},
                    {
                        "name": "spread-zone",
                        "weight": 3,
                        "argument": {"serviceAntiAffinity": {"label": "zone"}},
                    },
                    {
                        "name": "prefer-ssd",
                        "weight": 1,
                        "argument": {
                            "labelPreference": {"label": "ssd", "presence": True}
                        },
                    },
                ],
            }
        )
        ls, weights = lower_spec(spec)
        assert ls.resources and not ls.ports and not ls.disk
        assert ls.service_affinity and ls.node_label and ls.static_prio
        assert ls.aa_weights == (3,)
        assert weights == (2, 0, 0)


class TestNodeLabelPresence:
    def test_presence_required(self):
        nodes = [
            mk_node("n0", labels={"zone": "a"}),
            mk_node("n1"),  # lacks the label -> excluded
        ]
        policy = {
            "predicates": BASE_PREDS
            + [{"name": "z", "argument": {"labelsPresence": {"labels": ["zone"], "presence": True}}}],
            "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
        }
        scalar, _ = assert_policy_parity(
            policy, [mk_pod(f"p{i}") for i in range(4)], nodes
        )
        assert set(scalar) == {"n0"}

    def test_absence_required(self):
        nodes = [
            mk_node("n0", labels={"retiring": "2015-06"}),
            mk_node("n1"),
        ]
        policy = {
            "predicates": BASE_PREDS
            + [{"name": "r", "argument": {"labelsPresence": {"labels": ["retiring"], "presence": False}}}],
            "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
        }
        scalar, _ = assert_policy_parity(policy, [mk_pod("p0")], nodes)
        assert scalar == ["n1"]


class TestLabelPreference:
    def test_prefers_labeled_nodes(self):
        nodes = [mk_node("n0"), mk_node("n1", labels={"ssd": "true"})]
        policy = {
            "predicates": BASE_PREDS,
            # Only the label preference scores: labeled node must win.
            "priorities": [
                {"name": "p", "weight": 1,
                 "argument": {"labelPreference": {"label": "ssd", "presence": True}}}
            ],
        }
        scalar, _ = assert_policy_parity(policy, [mk_pod("p0")], nodes)
        assert scalar == ["n1"]

    def test_absence_preference_with_weights(self):
        nodes = [mk_node("n0", labels={"old": "1"}), mk_node("n1")]
        policy = {
            "predicates": BASE_PREDS,
            "priorities": [
                {"name": "LeastRequestedPriority", "weight": 1},
                {"name": "p", "weight": 5,
                 "argument": {"labelPreference": {"label": "old", "presence": False}}},
            ],
        }
        scalar, _ = assert_policy_parity(policy, [mk_pod("p0")], nodes)
        assert scalar == ["n1"]


AFFINITY_POLICY = {
    "predicates": BASE_PREDS
    + [{"name": "za", "argument": {"serviceAffinity": {"labels": ["zone"]}}}],
    "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
}


class TestServiceAffinity:
    def nodes(self):
        return [
            mk_node("n0", labels={"zone": "a"}),
            mk_node("n1", labels={"zone": "a"}),
            mk_node("n2", labels={"zone": "b"}),
            mk_node("n3"),  # unzoned
        ]

    def test_no_peers_no_pin_all_nodes(self):
        """No service peers and no nodeSelector pin: everything fits
        (affinitySelector == Everything())."""
        scalar, _ = assert_policy_parity(
            AFFINITY_POLICY, [mk_pod("p0", labels={"app": "web"})], self.nodes(),
            services=[mk_svc("web", {"app": "web"})],
        )
        assert scalar[0] is not None

    def test_anchor_peer_pins_zone(self):
        """A scheduled peer in zone b forces zone b for new pods."""
        peer = mk_pod("peer", labels={"app": "web"})
        peer.spec.node_name = "n2"  # zone b
        scalar, _ = assert_policy_parity(
            AFFINITY_POLICY,
            [mk_pod(f"p{i}", labels={"app": "web"}) for i in range(3)],
            self.nodes(),
            assigned=[peer],
            services=[mk_svc("web", {"app": "web"})],
        )
        assert set(scalar) == {"n2"}

    def test_node_selector_pin_overrides(self):
        """A pod pinning zone=a via nodeSelector keeps its own pin even
        with a zone-b peer (predicates.go:273-281)."""
        peer = mk_pod("peer", labels={"app": "web"})
        peer.spec.node_name = "n2"
        scalar, _ = assert_policy_parity(
            AFFINITY_POLICY,
            [mk_pod("p0", labels={"app": "web"}, selector={"zone": "a"})],
            self.nodes(),
            assigned=[peer],
            services=[mk_svc("web", {"app": "web"})],
        )
        assert scalar[0] in ("n0", "n1")

    def test_in_backlog_anchor(self):
        """The FIRST placed backlog pod anchors the rest of its service
        (sequential semantics: later pods see earlier placements)."""
        pods = [mk_pod(f"p{i}", labels={"app": "api"}) for i in range(6)]
        scalar, batch = assert_policy_parity(
            AFFINITY_POLICY, pods, self.nodes(),
            services=[mk_svc("api", {"app": "api"})],
        )
        # Wherever the first landed, all zoned placements share its zone
        # value; the scalar==batch assertion above is the real check.
        assert len(set(scalar)) >= 1

    def test_anchor_on_unknown_node_fails_everywhere(self):
        """Peer on a node the cluster no longer knows: the scalar's
        GetNodeInfo error path — pod unschedulable (predicates.go:300)."""
        peer = mk_pod("peer", labels={"app": "web"})
        peer.spec.node_name = "gone-node"
        scalar, _ = assert_policy_parity(
            AFFINITY_POLICY,
            [mk_pod("p0", labels={"app": "web"})],
            self.nodes(),
            assigned=[peer],
            services=[mk_svc("web", {"app": "web"})],
        )
        assert scalar == [None]


class TestServiceAntiAffinity:
    def test_zero_weight_instance_does_not_misalign_columns(self):
        """A weight-0 anti-affinity entry is dropped by lower_spec; the
        zone columns must drop it identically or the weight/column zip
        pairs the wrong label (review regression)."""
        nodes = [
            mk_node("n0", labels={"zone": "a", "rack": "r1"}),
            mk_node("n1", labels={"zone": "a", "rack": "r2"}),
        ]
        policy = {
            "predicates": BASE_PREDS,
            "priorities": [
                {"name": "dead", "weight": 0,
                 "argument": {"serviceAntiAffinity": {"label": "zone"}}},
                {"name": "live", "weight": 2,
                 "argument": {"serviceAntiAffinity": {"label": "rack"}}},
            ],
        }
        pods = [mk_pod(f"p{i}", labels={"app": "web"}) for i in range(4)]
        scalar, _ = assert_policy_parity(
            policy, pods, nodes, services=[mk_svc("web", {"app": "web"})]
        )
        # Rack-spreading alternates racks; zone-spreading would not.
        assert scalar[0] != scalar[1]

    def test_spreads_across_zones(self):
        nodes = [
            mk_node("n0", labels={"zone": "a"}),
            mk_node("n1", labels={"zone": "b"}),
            mk_node("n2"),  # unlabeled: flat 0
        ]
        policy = {
            "predicates": BASE_PREDS,
            "priorities": [
                {"name": "aa", "weight": 1,
                 "argument": {"serviceAntiAffinity": {"label": "zone"}}}
            ],
        }
        pods = [mk_pod(f"p{i}", labels={"app": "web"}) for i in range(4)]
        scalar, batch = assert_policy_parity(
            policy, pods, nodes, services=[mk_svc("web", {"app": "web"})]
        )
        # Zoned nodes beat the unlabeled one; zones alternate under
        # sequential commit. Exact order is checked by parity above.
        assert "n2" not in scalar[:2]


class TestLabelLessAffinity:
    def test_empty_service_affinity_is_noop(self):
        """serviceAffinity with no labels: the scalar's empty affinity
        selector matches everything; the lowering must not demand
        columns that are never built (review regression — this used to
        crash the device path into permanent fallback)."""
        policy = {
            "predicates": BASE_PREDS
            + [{"name": "noop", "argument": {"serviceAffinity": {"labels": []}}}],
            "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
        }
        spec = spec_from_policy(policy)
        ls, _ = lower_spec(spec)
        assert not ls.service_affinity
        scalar, _ = assert_policy_parity(
            policy, [mk_pod("p0")], [mk_node("n0")],
        )
        assert scalar == ["n0"]


class TestPolicySubsets:
    def test_omitting_ports_allows_conflicts(self):
        """A policy WITHOUT PodFitsPorts must not enforce host ports —
        proving the lowering gates each predicate, not just adds new
        ones."""
        policy = {
            "predicates": [{"name": "PodFitsResources"}],
            "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
        }
        pods = [mk_pod("p0", host_port=8080), mk_pod("p1", host_port=8080)]
        nodes = [mk_node("n0")]
        scalar, _ = assert_policy_parity(policy, pods, nodes)
        assert scalar == ["n0", "n0"]  # both land despite the conflict

    def test_weighted_priorities(self):
        policy = {
            "predicates": BASE_PREDS,
            "priorities": [
                {"name": "LeastRequestedPriority", "weight": 3},
                {"name": "BalancedResourceAllocation", "weight": 2},
                {"name": "ServiceSpreadingPriority", "weight": 1},
                {"name": "EqualPriority", "weight": 4},
            ],
        }
        pods = [mk_pod(f"p{i}", cpu=300, mem_mib=256) for i in range(12)]
        nodes = [mk_node(f"n{j}", cpu=2000, mem_mib=2048) for j in range(4)]
        assert_policy_parity(
            policy, pods, nodes,
            services=[mk_svc("s", {"app": "x"})],
        )


class TestFullVocabularyParity:
    """The VERDICT bar: 1k pods x 100 nodes under a policy using every
    reference predicate/priority kind — batch decisions must be
    scalar-identical."""

    POLICY = {
        "kind": "Policy",
        "predicates": BASE_PREDS + [
            {"name": "zone-aff",
             "argument": {"serviceAffinity": {"labels": ["zone"]}}},
            {"name": "has-zone",
             "argument": {"labelsPresence": {"labels": ["zone"], "presence": True}}},
            {"name": "not-retiring",
             "argument": {"labelsPresence": {"labels": ["retiring"], "presence": False}}},
        ],
        "priorities": [
            {"name": "LeastRequestedPriority", "weight": 1},
            {"name": "BalancedResourceAllocation", "weight": 1},
            {"name": "ServiceSpreadingPriority", "weight": 2},
            {"name": "EqualPriority", "weight": 1},
            {"name": "zone-anti",
             "weight": 2,
             "argument": {"serviceAntiAffinity": {"label": "rack"}}},
            {"name": "prefer-ssd",
             "weight": 1,
             "argument": {"labelPreference": {"label": "ssd", "presence": True}}},
        ],
    }

    def build(self, P=1000, N=100, seed=7):
        rng = random.Random(seed)
        nodes = []
        for j in range(N):
            labels = {"zone": f"z{j % 5}", "rack": f"r{j % 10}"}
            if j % 3 == 0:
                labels["ssd"] = "true"
            if j % 17 == 0:
                labels["retiring"] = "soon"
            if j % 11 == 0:
                del labels["zone"]  # fails the labelsPresence check
            nodes.append(
                mk_node(f"n{j}", cpu=8000, mem_mib=16384, pods=64, labels=labels)
            )
        services = [mk_svc(f"svc{k}", {"app": f"app{k}"}) for k in range(8)]
        pods = []
        for i in range(P):
            app = f"app{rng.randrange(10)}"  # some pods match no service
            sel = {}
            if rng.random() < 0.1:
                sel["zone"] = f"z{rng.randrange(5)}"
            pods.append(
                mk_pod(
                    f"p{i}",
                    cpu=rng.choice([100, 250, 500]),
                    mem_mib=rng.choice([64, 128, 256]),
                    labels={"app": app},
                    selector=sel,
                    host_port=8080 if rng.random() < 0.02 else 0,
                )
            )
        # Pre-assigned peers so anchors/zone counts start non-trivial.
        assigned = []
        for k in range(40):
            peer = mk_pod(f"peer{k}", labels={"app": f"app{k % 10}"})
            peer.spec.node_name = f"n{(k * 7) % N}"
            assigned.append(peer)
        return pods, nodes, assigned, services

    @pytest.mark.slow
    def test_1k_x_100_full_vocabulary(self):
        pods, nodes, assigned, services = self.build()
        assert_policy_parity(self.POLICY, pods, nodes, assigned, services)

    def test_200_x_40_full_vocabulary(self):
        """Fast-path version of the same vocabulary (runs in CI)."""
        pods, nodes, assigned, services = self.build(P=200, N=40, seed=11)
        assert_policy_parity(self.POLICY, pods, nodes, assigned, services)
