"""Cluster rolling-upgrade drill (VERDICT r4 #8): under a live
workload with background churn, restart the apiserver (WAL recovery on
the same port), fail over the leader-elected scheduler, and roll every
kubelet (pod adoption) — asserting ZERO workload pod restarts, ZERO
rebinds, and that every watch-fed component resumed.

Reference: test/e2e/cluster_upgrade.go (master upgrade with workload
continuity), test/e2e/restart.go (component restart, pods survive),
test/e2e/reboot.go (node restart, pods recover without rescheduling).
Every component talks REAL HTTP, so the apiserver restart exercises
client reconnection and reflector relist, not in-process shortcuts.
"""

import threading
import time

import pytest

from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes_tpu.scheduler.daemon import BatchScheduler, SchedulerConfig
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.store.kvstore import KVStore
from kubernetes_tpu.utils.leaderelect import HAHotStandby


def wait_until(cond, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def rc_wire(name, replicas, app):
    return {
        "kind": "ReplicationController",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "replicas": replicas,
            "selector": {"app": app},
            "template": {
                "metadata": {"labels": {"app": app}},
                "spec": {
                    "containers": [
                        {
                            "name": "main",
                            "image": "web",
                            "resources": {
                                "limits": {"cpu": "100m", "memory": "64Mi"}
                            },
                        }
                    ]
                },
            },
        },
    }


def pod_wire(name):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": "churn"}]},
    }


def _mk_scheduler(address):
    """Leader-elected batch scheduler over HTTP (hot standby)."""
    client = Client(HTTPTransport(address))

    def factory():
        cfg = SchedulerConfig(client).start()
        cfg.wait_for_sync(20.0)
        return BatchScheduler(cfg).start()

    ha = HAHotStandby(
        client,
        "kube-scheduler",
        identity=f"sched-{id(factory)}",
        factory=factory,
        lease_duration=2.0,
        renew_period=0.4,
        retry_period=0.4,
    )
    return ha.start()


@pytest.mark.slow
def test_rolling_upgrade_zero_disruption(tmp_path):
    data_dir = str(tmp_path / "data")
    server = APIHTTPServer(
        APIServer(store=KVStore(data_dir=data_dir)), port=0
    ).start()
    port = int(server.address.rsplit(":", 1)[1])
    address = server.address

    client = Client(HTTPTransport(address))
    runtimes = {f"node-{i}": FakeRuntime() for i in range(3)}
    kubelets = {
        name: Kubelet(
            Client(HTTPTransport(address)),
            node_name=name,
            runtime=rt,
            heartbeat_period=0.5,
            sync_period=0.3,
        ).start()
        for name, rt in runtimes.items()
    }
    manager = ControllerManager(
        Client(HTTPTransport(address)),
        # Reference-faithful grace periods: a sub-second apiserver
        # restart must not look like node death.
        node_grace_period=40.0,
        node_eviction_timeout=120.0,
    ).start()
    sched_a = _mk_scheduler(address)
    sched_b = _mk_scheduler(address)

    churn_stop = threading.Event()
    churn_bound = []
    churn_errors = [0]

    def churn():
        """Background create/delete through the rolls; errors during
        the apiserver outage are expected and absorbed (clients are
        retried by the next loop iteration)."""
        c = Client(HTTPTransport(address))
        i = 0
        while not churn_stop.is_set():
            name = f"churn-{i}"
            i += 1
            try:
                c.create("pods", pod_wire(name), namespace="default")
                if wait_until(
                    lambda: c.get(
                        "pods", name, namespace="default"
                    ).spec.node_name,
                    timeout=15,
                    interval=0.1,
                ):
                    churn_bound.append(name)
                c.delete("pods", name, namespace="default")
            except Exception:
                churn_errors[0] += 1
            time.sleep(0.05)

    churn_thread = threading.Thread(target=churn, daemon=True)

    try:
        # -- live workload --------------------------------------------
        client.create("replicationcontrollers", rc_wire("web", 9, "web"))

        def running_web():
            pods, _ = client.list(
                "pods", namespace="default", label_selector="app=web"
            )
            return [p for p in pods if p.status.phase == "Running"]

        assert wait_until(lambda: len(running_web()) == 9, timeout=60)
        before = {
            p.metadata.name: p.spec.node_name for p in running_web()
        }
        cids_before = {
            name: {
                c.container_id
                for pod in rt._pods.values()
                for c in pod.values()
            }
            for name, rt in runtimes.items()
        }
        churn_thread.start()
        baseline_bound = len(churn_bound)
        assert wait_until(
            lambda: len(churn_bound) > baseline_bound, timeout=30
        ), "churn did not bind before the rolls began"

        # -- phase 1: apiserver hard restart (WAL recovery, same port) --
        server.stop()  # abandon the store: recovery comes from the WAL
        time.sleep(0.5)
        server2 = APIHTTPServer(
            APIServer(store=KVStore(data_dir=data_dir)),
            port=port,
        ).start()
        assert server2.address == address
        # Watch-fed components resume: a NEW pod binds + runs, which
        # needs scheduler reflector + kubelet informers + RC controller
        # all re-listed against the recovered server.
        client.create("pods", pod_wire("post-restart"), namespace="default")
        assert wait_until(
            lambda: client.get(
                "pods", "post-restart", namespace="default"
            ).spec.node_name,
            timeout=40,
        ), "scheduler did not resume after apiserver restart"
        client.delete("pods", "post-restart", namespace="default")

        # -- phase 2: scheduler failover ------------------------------
        leader = sched_a if sched_a.daemon is not None else sched_b
        standby = sched_b if leader is sched_a else sched_a
        leader.stop()
        client.create("pods", pod_wire("post-failover"), namespace="default")
        assert wait_until(
            lambda: client.get(
                "pods", "post-failover", namespace="default"
            ).spec.node_name,
            timeout=40,
        ), "standby scheduler did not take over"
        client.delete("pods", "post-failover", namespace="default")
        assert standby.daemon is not None

        # -- phase 3: roll every kubelet (pod adoption) ---------------
        for name in list(kubelets):
            kubelets[name].stop()
            kubelets[name] = Kubelet(
                Client(HTTPTransport(address)),
                node_name=name,
                runtime=runtimes[name],  # same machine: same runtime
                heartbeat_period=0.5,
                sync_period=0.3,
            ).start()
            time.sleep(1.0)  # staggered roll, like a real upgrade

        # Rolled kubelets keep reporting: all 9 web pods still Running.
        assert wait_until(lambda: len(running_web()) == 9, timeout=40)

        # -- zero-disruption assertions --------------------------------
        after = {p.metadata.name: p.spec.node_name for p in running_web()}
        assert after == before, "a workload pod was rebound or recreated"
        for name, rt in runtimes.items():
            cids_after = {
                c.container_id
                for pod in rt._pods.values()
                for c in pod.values()
            }
            assert cids_before[name] <= cids_after, (
                f"{name}: a workload container was restarted "
                "(container id changed)"
            )
        for p in running_web():
            for cs in p.status.container_statuses:
                assert (cs.restart_count or 0) == 0
        # Churn kept flowing across all three phases.
        during_rolls = len(churn_bound) - baseline_bound
        assert during_rolls >= 3, (
            f"churn stalled during the rolls (only {during_rolls} bound)"
        )
    finally:
        churn_stop.set()
        churn_thread.join(timeout=10)
        for s in (sched_a, sched_b):
            try:
                s.stop()
            except Exception:
                pass
        manager.stop()
        for k in kubelets.values():
            k.stop()
        try:
            server2.stop()
        except NameError:
            server.stop()
