"""Pod-to-bind latency SLO through the real HTTP control plane.

The reference's serving SLO is 99% of scheduling decisions < 1s
(docs/roadmap.md:66), measured e2e as create -> binding visible to a
watch client (test/e2e/util.go:1286-1301 HighLatencyRequests pattern
applied to the bind path). bench.py's `_api_churn_figure` builds the
whole rig: live apiserver over HTTP, IncrementalBatchScheduler with a
device-resident session, a separate load-generator process driving
paced create/delete churn and timestamping binding visibility.

Since PR 9 the gate's verdict comes from the production SLO engine
(utils/slo.BENCH_OBJECTIVES["bind_latency_slo"]) — bench and
`ktctl slo` share one definition — and the figure embeds the engine's
full slo_report over the drill.

This test runs the same rig at a shape a 1-core CPU CI host sustains
comfortably; the bench publishes the 5k-node figure on TPU hardware.
"""

import pytest

from kubernetes_tpu.utils import slo


@pytest.mark.slow
@pytest.mark.slo
def test_bind_latency_slo_under_churn():
    import bench

    # gate_s=1.0: the reference 99%-in-1s SLO — the right bar for a
    # shared CPU CI host; the 100ms default target
    # (slo.BENCH_OBJECTIVES) is the TPU box's bar, witnessed by the
    # BENCH artifacts.
    fig = bench._api_churn_figure(
        n_nodes=1000, rate=250, duration_s=6.0, creators=2, warmup_s=5.0,
        gate_s=1.0,
    )
    assert fig["bind_latency_unbound"] == 0, fig
    assert fig["bind_latency_p99_s"] < 1.0, fig
    # The figure carries the SLO ENGINE's verdict — recomputing it from
    # the published p99 through the same objective must agree exactly.
    assert fig["bind_latency_slo"] == slo.verdict_for_value(
        slo.with_target(slo.BENCH_OBJECTIVES["bind_latency_slo"], 1.0),
        fig["bind_latency_p99_s"],
    ), fig
    assert fig["bind_latency_slo"] == "pass", fig
    # The engine's own report over the drill rode along: the always-on
    # SLI collector watched every create -> bound transition.
    assert fig["slo_report"]["pod_bound_latency"]["samples"] > 0, fig
    assert fig["slo_report"]["pod_bound_latency"]["verdict"] in (
        "pass", "warn", "burn",
    ), fig
    # The load generator kept pace: achieved churn within 30% of the
    # requested rate (generous: CI hosts share cores).
    assert fig["churn_bound_pods_per_sec"] >= 250 * 0.7, fig
