"""cluster/ composition (kube-up analog) + monitoring addon.

Reference: cluster/kube-up.sh provisioning + cluster/addons/
cluster-monitoring (heapster). The local provider IS the multi-host
composition (same plan, subprocesses instead of ssh), so this e2e is
the closest a single box gets to the real thing: durable apiserver,
HA control-plane pairs, per-node kubelets, published addons.
"""

import json
import os
import time
import urllib.request

import pytest

from kubernetes_tpu.cmd.clusterup import down, load_inventory, plan, up


def wait_until(cond, timeout=60.0, interval=0.3):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def inventory(tmp_path, port, nodes=2, replicas=2, addons=None):
    inv = {
        "master": {
            "host": "127.0.0.1", "port": port,
            "data_dir": str(tmp_path / "master-data"),
        },
        "control_plane_replicas": replicas,
        "batch_scheduler": False,
        "nodes": [{"name": f"cn-{i}", "host": "127.0.0.1"} for i in range(nodes)],
        "runtime": "fake",
        "addons": addons or [],
    }
    path = tmp_path / "inventory.json"
    path.write_text(json.dumps(inv))
    return str(path)


class TestPlan:
    def test_plan_shape(self, tmp_path):
        inv = load_inventory(inventory(tmp_path, 18123, nodes=3, replicas=2,
                                       addons=["dns", "monitoring"]))
        steps = plan(inv)
        roles = [r for _h, r, _a in steps]
        assert roles[0] == "apiserver"
        assert roles.count("controller-manager-0") == 1
        assert "controller-manager-1" in roles and "scheduler-1" in roles
        assert sum(r.startswith("kubelet-") for r in roles) == 3
        assert roles[-1] == "addons"
        # Every control-plane replica runs leader election.
        for _h, r, argv in steps:
            if r.startswith(("controller-manager", "scheduler")):
                assert "--leader-elect" in argv
        # The apiserver is durable.
        api = next(a for _h, r, a in steps if r == "apiserver")
        assert "--data-dir" in api

    def test_ssh_provider_dry_run(self, tmp_path, capsys):
        """--dry-run prints the full per-host plan and starts nothing."""
        inv_path = inventory(tmp_path, 18124)
        from kubernetes_tpu.cmd.clusterup import up_main

        rc = up_main(["-i", inv_path, "--provider", "ssh", "--dry-run"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "apiserver" in out and "kubelet-cn-0" in out


@pytest.mark.slow
class TestLocalClusterUp:
    def test_up_workload_monitoring_down(self, tmp_path):
        from kubernetes_tpu.client import Client, HTTPTransport

        port = 18460
        state = str(tmp_path / "state")
        inv = load_inventory(
            inventory(tmp_path, port, nodes=2, replicas=2,
                      addons=["monitoring"])
        )
        assert up(inv, state) == 0
        try:
            server = f"http://127.0.0.1:{port}"
            client = Client(HTTPTransport(server))
            # Both kubelets register and go Ready.
            assert wait_until(
                lambda: len(client.list("nodes")[0]) == 2, timeout=90
            ), "kubelets never registered"
            # A workload schedules and runs (scheduler leader active).
            client.create(
                "replicationcontrollers",
                {
                    "kind": "ReplicationController",
                    "metadata": {"name": "w", "namespace": "default"},
                    "spec": {
                        "replicas": 4,
                        "selector": {"app": "w"},
                        "template": {
                            "metadata": {"labels": {"app": "w"}},
                            "spec": {"containers": [{"name": "c", "image": "x"}]},
                        },
                    },
                },
            )

            def running():
                pods, _ = client.list("pods", namespace="default")
                return sum(1 for p in pods if p.status.phase == "Running")

            assert wait_until(lambda: running() == 4, timeout=120), (
                f"only {running()}/4 Running"
            )
            # Monitoring addon: published service + live model API.
            assert wait_until(
                lambda: any(
                    s.metadata.name == "monitoring-heapster"
                    for s in client.list("services", namespace="kube-system")[0]
                ),
                timeout=60,
            ), "monitoring service never published"
            eps, _ = client.list("endpoints", namespace="kube-system")
            ep = next(e for e in eps if e.metadata.name == "monitoring-heapster")
            addr = ep.subsets[0].addresses[0].ip
            mport = ep.subsets[0].ports[0].port

            def model_nodes():
                try:
                    d = json.loads(urllib.request.urlopen(
                        f"http://{addr}:{mport}/api/v1/model/nodes", timeout=3
                    ).read())
                    return d.get("items", [])
                except Exception:
                    return []

            assert wait_until(lambda: len(model_nodes()) == 2, timeout=60), (
                "monitor never scraped both nodes"
            )
            node = model_nodes()[0]
            series = json.loads(urllib.request.urlopen(
                f"http://{addr}:{mport}/api/v1/model/nodes/{node}/metrics/pods",
                timeout=3,
            ).read())
            assert series["metrics"], "empty node series"
            assert series["latestTimestamp"]
        finally:
            assert down(state) == 0
        # Everything is gone: the apiserver port refuses connections.
        time.sleep(1)
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=2)


@pytest.mark.slow
class TestSshProviderExecutes:
    """The REMOTE code path (quoting, pidfile daemonization,
    teardown-by-ssh) executed for real — not --dry-run. No sshd on
    this box, so SSH_BASE is swapped for a shim that replays exactly
    what real ssh does with the argv: join the command words with
    spaces and hand the result to a shell on the 'remote' host (here:
    this box) to re-parse. Every quoting decision in
    cmd/clusterup.py's remote branch runs under the same two-level
    shell parsing it would face over a wire (VERDICT r3 next #6)."""

    def test_ssh_up_and_down(self, tmp_path, monkeypatch):
        from kubernetes_tpu.client import Client, HTTPTransport
        from kubernetes_tpu.cmd import clusterup

        shim = tmp_path / "fake-ssh"
        shim.write_text(
            "#!/bin/sh\n"
            "# fake-ssh <host> -- <words...>: real ssh joins the words\n"
            "# with spaces and the remote login shell re-parses them.\n"
            'shift\n[ "$1" = "--" ] && shift\n'
            'exec sh -c "$*"\n'
        )
        shim.chmod(0o755)
        monkeypatch.setattr(clusterup, "SSH_BASE", (str(shim),))

        port = 18470
        # 127.0.1.x are loopback to THIS box but not in the
        # local-host exclusion list, so the remote branch triggers.
        inv = {
            "master": {
                "host": "127.0.1.1", "port": port,
                "data_dir": str(tmp_path / "master-data"),
            },
            "control_plane_replicas": 1,
            "nodes": [{"name": "sn-0", "host": "127.0.1.2"}],
            "runtime": "fake",
            "addons": [],
        }
        inv_path = tmp_path / "inv.json"
        inv_path.write_text(json.dumps(inv))
        state = str(tmp_path / "state")

        assert up(load_inventory(str(inv_path)), state, provider="ssh") == 0
        pids = []
        try:
            st = json.load(open(os.path.join(state, "cluster.json")))
            comps = st["components"]

            def live_pid(info):
                """The REMOTE side writes its pidfile (echo $$ before
                exec) asynchronously — poll until it names a live
                process."""
                try:
                    pid = int(open(info["pidfile"]).read())
                    os.kill(pid, 0)
                    return pid
                except (OSError, ValueError):
                    return None

            # Every component took the remote path and recorded the
            # pidfile the remote side wrote.
            for role, info in comps.items():
                assert info["remote"] is True, role
                assert wait_until(
                    lambda: live_pid(info) is not None, timeout=15
                ), f"{role}: pidfile never named a live process"
                pids.append(live_pid(info))
            server = f"http://127.0.1.1:{port}"
            client = Client(HTTPTransport(server))
            assert wait_until(
                lambda: len(client.list("nodes")[0]) == 1, timeout=90
            ), "kubelet (via ssh shim) never registered"
        finally:
            assert down(state) == 0
        # Teardown went through the ssh kill path: the daemons the
        # pidfiles point at are dead (not just the local ssh clients).
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            alive = []
            for pid in pids:
                try:
                    os.kill(pid, 0)
                    alive.append(pid)
                except ProcessLookupError:
                    pass
            if not alive:
                break
            time.sleep(0.3)
        assert not alive, f"daemons survived kube-down: {alive}"
