"""v1 <-> v1beta3 round-trip fuzz over EVERY registry kind (VERDICT r2
item 8): the conversion layer claims "renames only, everything else is
mechanical" — this property test backs the claim by generating random
fully-populated objects from the typed model and asserting
v1 -> v1beta3 -> v1 is lossless at the wire level (the analog of the
reference's fuzz over generated converters,
pkg/api/serialization_test.go / v1beta3/conversion.go:358-447).
"""

import dataclasses
import random
import string
import typing

import pytest

from kubernetes_tpu.models import conversion, serde
from kubernetes_tpu.models.objects import KINDS
from kubernetes_tpu.models.quantity import Quantity, parse_quantity


def _rand_str(rng):
    return "".join(rng.choices(string.ascii_lowercase, k=rng.randint(1, 8)))


def _rand_value(tp, rng, depth):
    origin = typing.get_origin(tp)
    args = typing.get_args(tp)
    if origin is typing.Union:  # Optional[X]
        inner = [a for a in args if a is not type(None)]
        if rng.random() < 0.4 or depth > 5:
            return None
        return _rand_value(inner[0], rng, depth)
    if origin in (list, typing.List):
        if depth > 5:
            return []
        return [_rand_value(args[0], rng, depth + 1) for _ in range(rng.randint(0, 2))]
    if origin in (dict, typing.Dict):
        if depth > 5:
            return {}
        return {
            _rand_str(rng): _rand_value(args[1], rng, depth + 1)
            for _ in range(rng.randint(0, 2))
        }
    if tp is str:
        return _rand_str(rng)
    if tp is bool:
        return rng.random() < 0.5
    if tp is int:
        return rng.randint(0, 9999)
    if tp is float:
        return float(rng.randint(0, 100))
    if tp is Quantity:
        return parse_quantity(rng.choice(["100m", "2", "64Mi", "1Gi", "500"]))
    if dataclasses.is_dataclass(tp):
        return _rand_instance(tp, rng, depth + 1)
    if tp is typing.Any or tp is object:
        return _rand_str(rng)
    return None


def _rand_instance(cls, rng, depth=0):
    """Random instance of a typed API dataclass, fields filled by type
    hint (bounded depth so recursive specs terminate)."""
    kwargs = {}
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        if f.name in ("kind", "api_version"):
            continue  # set by the caller / serde
        if depth > 6:
            break
        v = _rand_value(hints[f.name], rng, depth)
        if v is not None:
            kwargs[f.name] = v
    try:
        return cls(**kwargs)
    except TypeError:
        return cls()


# Kinds whose wire form the conversion layer must round-trip. Minion is
# an alias of Node; DeleteOptions has no conversions and no metadata.
ROUND_TRIP_KINDS = sorted(set(KINDS) - {"Minion"})


class TestRoundTripFuzz:
    @pytest.mark.parametrize("kind", ROUND_TRIP_KINDS)
    def test_v1_to_v1beta3_to_v1_lossless(self, kind):
        rng = random.Random(hash(kind) & 0xFFFF)
        cls = KINDS[kind]
        for trial in range(25):
            obj = _rand_instance(cls, rng)
            wire = serde.to_wire(obj)
            if not isinstance(wire, dict):
                continue
            wire["kind"] = kind
            wire["apiVersion"] = "v1"
            beta = conversion.from_internal(wire, "v1beta3")
            back = conversion.to_internal(beta, "v1beta3")
            assert back == wire, (
                f"{kind} trial {trial}: round-trip diverged\n"
                f"v1:      {wire}\nv1beta3: {beta}\nback:    {back}"
            )

    @pytest.mark.parametrize("kind", ["Pod", "Service", "ReplicationController"])
    def test_list_round_trip(self, kind):
        rng = random.Random(42)
        cls = KINDS[kind]
        items = []
        for _ in range(4):
            wire = serde.to_wire(_rand_instance(cls, rng))
            wire["kind"] = kind
            wire["apiVersion"] = "v1"
            items.append(wire)
        lst = {"kind": f"{kind}List", "apiVersion": "v1", "items": items}
        beta = conversion.from_internal(lst, "v1beta3")
        back = conversion.to_internal(beta, "v1beta3")
        assert back == lst


class TestSemanticEdges:
    """The named conversions keep their reference quirks."""

    def test_service_type_wins_over_bool(self):
        beta = {
            "kind": "Service", "apiVersion": "v1beta3",
            "spec": {"type": "ClusterIP", "createExternalLoadBalancer": True},
        }
        v1 = conversion.to_internal(beta, "v1beta3")
        assert v1["spec"]["type"] == "ClusterIP"  # type present: bool ignored

    def test_lb_bool_selects_loadbalancer(self):
        beta = {
            "kind": "Service", "apiVersion": "v1beta3",
            "spec": {"createExternalLoadBalancer": True},
        }
        v1 = conversion.to_internal(beta, "v1beta3")
        assert v1["spec"]["type"] == "LoadBalancer"

    def test_legacy_container_capabilities_fold(self):
        """v1beta3 top-level capabilities/privileged fold into
        securityContext on decode (conversion.go:226-256); encode to
        v1beta3 emits only securityContext."""
        beta = {
            "kind": "Pod", "apiVersion": "v1beta3",
            "spec": {
                "host": "n1",
                "containers": [
                    {"name": "c", "image": "x",
                     "capabilities": {"add": ["NET_ADMIN"]},
                     "privileged": True}
                ],
            },
        }
        v1 = conversion.to_internal(beta, "v1beta3")
        c = v1["spec"]["containers"][0]
        assert "capabilities" not in c and "privileged" not in c
        assert c["securityContext"]["capabilities"] == {"add": ["NET_ADMIN"]}
        assert c["securityContext"]["privileged"] is True
        assert v1["spec"]["nodeName"] == "n1"

    def test_status_details_id_name(self):
        v1 = {
            "kind": "Status", "apiVersion": "v1",
            "details": {"name": "p1", "kind": "pods"},
        }
        beta = conversion.from_internal(v1, "v1beta3")
        assert beta["details"]["id"] == "p1" and "name" not in beta["details"]
        assert conversion.to_internal(beta, "v1beta3") == v1
