"""Cloud provider layer tests (reference behaviors:
pkg/cloudprovider/, nodecontroller sync)."""

import jax
import pytest

from kubernetes_tpu.client.rest import Client, LocalTransport
from kubernetes_tpu.cloudprovider import (
    FakeCloudProvider,
    Instance,
    TPUCloudProvider,
    Zone,
    get_provider,
    register_provider,
)
from kubernetes_tpu.cloudprovider.tpu import (
    LABEL_CHIP,
    LABEL_CHIPS,
    LABEL_HOST,
    LABEL_PLATFORM,
)
from kubernetes_tpu.controllers.cloudnodes import (
    LABEL_MANAGED,
    LABEL_ZONE,
    CloudNodeController,
)
from kubernetes_tpu.server.api import APIServer


class TestRegistry:
    def test_builtin_providers_registered(self):
        assert isinstance(get_provider("fake"), FakeCloudProvider)
        assert isinstance(get_provider("tpu"), TPUCloudProvider)

    def test_unknown_provider(self):
        with pytest.raises(KeyError):
            get_provider("no-such-cloud")

    def test_custom_registration(self):
        register_provider("custom", lambda: FakeCloudProvider())
        assert isinstance(get_provider("custom"), FakeCloudProvider)


class TestTPUProvider:
    def test_discovers_hosts_from_devices(self):
        # conftest forces 8 virtual CPU devices in one process = 1 host.
        provider = TPUCloudProvider()
        instances = provider.instances()
        assert len(instances) == 1
        inst = instances[0]
        assert inst.name == "tpu-host-0"
        labels = inst.labels_dict()
        assert labels[LABEL_CHIPS] == str(len(jax.devices()))
        assert labels[LABEL_HOST] == "0"
        assert LABEL_PLATFORM in labels and LABEL_CHIP in labels

    def test_zone_is_slice_scoped(self):
        provider = TPUCloudProvider(slice_name="slice-A")
        zone = provider.zone_of("tpu-host-0")
        assert zone == Zone(failure_domain="slice-A/host-0", region="slice-A")
        assert provider.zone_of("nope") is None
        assert provider.cluster_names() == ["slice-A"]

    def test_multi_host_ring_routes(self):
        class Dev:
            def __init__(self, pid):
                self.process_index = pid
                self.device_kind = "TPU v5e"
                self.platform = "tpu"

        devices = [Dev(p) for p in (0, 0, 1, 1, 2, 2)]
        provider = TPUCloudProvider(devices=devices)
        instances = provider.instances()
        assert [i.name for i in instances] == [
            "tpu-host-0", "tpu-host-1", "tpu-host-2",
        ]
        assert instances[0].instance_type == "tpu-2x-TPU-v5e"
        routes = provider.routes()
        targets = {r.target_instance for r in routes}
        assert targets == {"tpu-host-0", "tpu-host-1", "tpu-host-2"}
        assert len(routes) == 3  # ring with wraparound


class TestCloudNodeController:
    def setup_method(self):
        self.api = APIServer()
        self.client = Client(LocalTransport(self.api))

    def test_registers_and_labels_nodes(self):
        provider = FakeCloudProvider(
            instances=[
                Instance(
                    name="host-a",
                    instance_type="tpu-4x",
                    labels=(("chip", "v5e"),),
                )
            ],
            zones={"host-a": Zone(failure_domain="s0/h0", region="s0")},
        )
        ctl = CloudNodeController(self.client, provider)
        assert ctl.sync_once() == 1
        node = self.client.get("nodes", "host-a")
        assert node.metadata.labels[LABEL_MANAGED] == "cloud"
        assert node.metadata.labels[LABEL_ZONE] == "s0_h0"
        assert node.metadata.labels["chip"] == "v5e"
        assert node.status.conditions[0].status == "Unknown"
        # Second pass: nothing to do.
        assert ctl.sync_once() == 0

    def test_reaps_only_cloud_managed_nodes(self):
        provider = FakeCloudProvider(instances=[Instance(name="host-a")])
        ctl = CloudNodeController(self.client, provider)
        ctl.sync_once()
        # A self-registered (kubelet) node the cloud doesn't know about:
        self.api.create(
            "nodes", "",
            {"kind": "Node", "metadata": {"name": "manual-node"}},
        )
        provider.set_instances([])  # host-a left the slice
        changed = ctl.sync_once()
        assert changed == 1
        names = {n.metadata.name for n in self.client.list("nodes")[0]}
        assert names == {"manual-node"}  # cloud node gone, manual kept

    def test_tpu_provider_end_to_end(self):
        ctl = CloudNodeController(self.client, TPUCloudProvider())
        assert ctl.sync_once() == 1
        node = self.client.get("nodes", "tpu-host-0")
        assert node.metadata.labels[LABEL_MANAGED] == "cloud"
        assert LABEL_CHIPS in node.metadata.labels
