"""Test configuration: force an 8-device virtual CPU platform so
multi-chip sharding paths (Mesh/pjit/shard_map) are exercised without
TPU hardware.

Two subtleties on this machine:
- A sitecustomize imports jax at interpreter start and registers the
  tunneled TPU platform, so JAX_PLATFORMS set here via os.environ is
  too late — jax.config.update('jax_platforms', ...) is the reliable
  override (and insulates tests from TPU-tunnel outages).
- XLA_FLAGS must still be set before the CPU backend initializes,
  which happens at first use, so setting it here works.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (after env setup on purpose)
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: at-scale tests (minutes); run with --runslow"
    )
    config.addinivalue_line(
        "markers",
        "gang: gang-scheduling (PodGroup) tests; tier-1 includes them — "
        "select just these with -m gang",
    )
    config.addinivalue_line(
        "markers",
        "preempt: priority & preemption (PriorityClass/eviction) tests; "
        "tier-1 includes them — select just these with -m preempt",
    )
    config.addinivalue_line(
        "markers",
        "explain: scheduling explainability (flight recorder / explain "
        "readback / ktctl explain) tests; tier-1 includes them — select "
        "just these with -m explain",
    )


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run slow at-scale tests",
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow: needs --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
