"""Test configuration: force an 8-device virtual CPU platform so
multi-chip sharding paths (Mesh/pjit/shard_map) are exercised without
TPU hardware.

Two subtleties on this machine:
- A sitecustomize imports jax at interpreter start and registers the
  tunneled TPU platform, so JAX_PLATFORMS set here via os.environ is
  too late — jax.config.update('jax_platforms', ...) is the reliable
  override (and insulates tests from TPU-tunnel outages).
- XLA_FLAGS must still be set before the CPU backend initializes,
  which happens at first use, so setting it here works.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import threading  # noqa: E402
import time  # noqa: E402

import jax  # noqa: E402  (after env setup on purpose)
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

#: Tier-1 runs these concurrency-heavy modules with the ktsan runtime
#: sanitizer ON (utils/sanitizer.py): their tests construct fresh
#: stores / watch caches / daemons per test, so every hot lock is
#: instrumented, and the teardown guard below fails the test on any
#: lock-order inversion, blocking-call-under-lock, lock held by a dead
#: thread, or leaked non-daemon thread. The empty-findings gate IS the
#: ktsan baseline — and it must stay empty.
KTSAN_MODULES = {
    "test_store",
    "test_watchcache",
    "test_gang",
    "test_preemption",
    "test_ktsan",
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: at-scale tests (minutes); run with --runslow"
    )
    config.addinivalue_line(
        "markers",
        "gang: gang-scheduling (PodGroup) tests; tier-1 includes them — "
        "select just these with -m gang",
    )
    config.addinivalue_line(
        "markers",
        "preempt: priority & preemption (PriorityClass/eviction) tests; "
        "tier-1 includes them — select just these with -m preempt",
    )
    config.addinivalue_line(
        "markers",
        "explain: scheduling explainability (flight recorder / explain "
        "readback / ktctl explain) tests; tier-1 includes them — select "
        "just these with -m explain",
    )
    config.addinivalue_line(
        "markers",
        "slo: SLI/SLO telemetry-plane tests (lifecycle collector, watch "
        "fan-out lag/drops, SLO engine, ktctl slo); tier-1 includes "
        "them — select just these with -m slo",
    )
    config.addinivalue_line(
        "markers",
        "profiler: device-time profiling-plane tests (compile/cost "
        "ledger, duty-cycle/overlap series, ktctl profile, device "
        "traces); tier-1 includes them — select just these with "
        "-m profiler",
    )
    config.addinivalue_line(
        "markers",
        "ktshape: kernel shape/dtype/sharding contract-checker tests "
        "(KT007 fixtures, abstract-eval/jaxpr-walk fixtures, live-tree "
        "contract gate); tier-1 includes them — select just these with "
        "-m ktshape",
    )
    config.addinivalue_line(
        "markers",
        "capacity: capacity & fragmentation observability-plane tests "
        "(capacity kernel twins, monitor, /debug/capacity, ktctl top "
        "capacity, capacity SLO objectives); tier-1 includes them — "
        "select just these with -m capacity",
    )
    config.addinivalue_line(
        "markers",
        "rebalance: continuous-rebalancing plane tests (plan_moves "
        "kernel twins, descheduler move protocol, /debug/rebalance, "
        "ktctl rebalance, rebalance SLO objectives); tier-1 includes "
        "them — select just these with -m rebalance",
    )
    config.addinivalue_line(
        "markers",
        "autoscale: elastic node-pool autoscaler tests (grow on "
        "starvation, cordon-drain-shrink on idle, pool metrics); "
        "tier-1 includes them — select just these with -m autoscale",
    )
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests (utils/faults.py "
        "registry, injection sites, client resilience, crash-recovery "
        "properties); tier-1 includes them — select just these with "
        "-m chaos",
    )
    config.addinivalue_line(
        "markers",
        "soak: hollow-node soak-harness tests (tools/soak.py cluster, "
        "fault epochs, invariant checker); tier-1 includes the small "
        "ones — select with -m soak",
    )
    config.addinivalue_line(
        "markers",
        "ktmesh: static SPMD partitioning-analyzer tests (KT009 "
        "fixtures, sharding contracts / collective inventories / "
        "communication budgets, live-tree mesh gate); tier-1 includes "
        "them — select just these with -m ktmesh",
    )
    config.addinivalue_line(
        "markers",
        "health: cluster health-plane tests (time-series retention, "
        "burn-rate alert engine, /debug/{alerts,timeseries,health}, "
        "ktctl alerts / top health); tier-1 includes them — select "
        "just these with -m health",
    )
    config.addinivalue_line(
        "markers",
        "sanitize: run this test with the ktsan lock sanitizer enabled "
        "(KT_SANITIZE=locks equivalent) and fail it on any sanitizer "
        "finding or leaked non-daemon thread; the concurrency-heavy "
        "modules in conftest.KTSAN_MODULES get this implicitly",
    )


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run slow at-scale tests",
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow: needs --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def host_mesh():
    """Factory for 1-D host-platform meshes over the forced 8-device
    CPU platform, routed through the ONE sanctioned constructor
    (ops.matrices.host_mesh) so tests exercise the same seam sessions
    and the KT_MESH_DEVICES hatch use. Call with n (and optionally the
    axis name); asserts the mesh actually formed — under this conftest
    8 devices are guaranteed, so None means the env setup broke."""
    from kubernetes_tpu.ops import matrices

    def make(n: int, axis: str = "nodes"):
        mesh = matrices.host_mesh(n, axis=axis)
        assert mesh is not None, (
            f"host_mesh({n}) returned None with {len(jax.devices())} "
            "visible devices — the forced 8-device CPU platform did "
            "not take (XLA_FLAGS set after backend init?)"
        )
        return mesh

    return make


@pytest.fixture()
def mesh_subprocess_env():
    """os.environ copy for subprocesses that must see the same forced
    8-device CPU platform as the in-process tests (CLI gates, ktmesh
    subprocess runs). A bare copy is NOT enough on machines where the
    parent inherited different XLA_FLAGS pre-conftest."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    return env


@pytest.fixture(autouse=True)
def _ktsan_guard(request):
    """Per-test ktsan harness: sanitizer-on for KTSAN_MODULES /
    @pytest.mark.sanitize / KT_SANITIZE=locks runs, with a thread
    snapshot so a test that leaks a non-daemon thread (or a lock held
    by a dead thread) fails HERE, not as a hang three modules later.

    Enablement is creation-time: locks built BEFORE enable() (e.g. in
    module-scoped fixtures) stay plain — tests in the sanitized
    modules construct their stores/daemons per test, which is exactly
    what makes per-test enablement effective. The KT_SANITIZE env
    path instruments import-time singletons too."""
    from kubernetes_tpu.utils import sanitizer

    module = request.node.module.__name__.rpartition(".")[2]
    env_on = sanitizer.enabled()
    want = (
        env_on
        or module in KTSAN_MODULES
        or request.node.get_closest_marker("sanitize") is not None
    )
    if not want:
        yield
        return
    sanitizer.enable()
    sanitizer.reset()
    before = {t.ident for t in threading.enumerate()}
    yield

    def fresh_nondaemon():
        return [
            t for t in threading.enumerate()
            if t.ident not in before and t.is_alive() and not t.daemon
        ]

    # Let teardown-stopped workers actually exit before judging.
    deadline = time.monotonic() + 2.0
    while fresh_nondaemon() and time.monotonic() < deadline:
        time.sleep(0.02)
    leaked_threads = [t.name for t in fresh_nondaemon()]
    found = sanitizer.findings()
    dead_held = sanitizer.leaked_locks()
    sanitizer.reset()
    if dead_held:
        # Reported below — forget the dead holders so ONE real leak
        # fails one test instead of cascading into every later one.
        sanitizer.purge_dead_threads()
    if not env_on:
        sanitizer.disable()
    problems = []
    if found:
        problems.append(f"ktsan findings: {found}")
    if dead_held:
        problems.append(f"locks held by dead threads: {dead_held}")
    if leaked_threads:
        problems.append(f"leaked non-daemon threads: {leaked_threads}")
    if problems:
        pytest.fail("ktsan: " + "; ".join(problems))
