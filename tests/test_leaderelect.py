"""Leader election + HA hot standby.

Reference: contrib/pod-master/podmaster.go (etcd-lock hot standby for
scheduler/controller-manager)."""

import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.utils.leaderelect import HAHotStandby, LeaderElector


def wait_until(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def elector(api, name, identity, **kw):
    kw.setdefault("lease_duration", 0.6)
    kw.setdefault("renew_period", 0.1)
    kw.setdefault("retry_period", 0.1)
    return LeaderElector(Client(LocalTransport(api)), name, identity, **kw)


class TestLeaderElector:
    def test_single_candidate_leads(self):
        api = APIServer()
        e = elector(api, "cm", "a").start()
        try:
            assert wait_until(lambda: e.is_leader)
        finally:
            e.stop()

    def test_exactly_one_of_many_leads(self):
        api = APIServer()
        electors = [elector(api, "cm", f"id-{i}").start() for i in range(4)]
        try:
            assert wait_until(
                lambda: sum(e.is_leader for e in electors) == 1
            )
            time.sleep(0.5)  # stable: still exactly one
            assert sum(e.is_leader for e in electors) == 1
        finally:
            for e in electors:
                e.stop()

    def test_takeover_on_leader_death(self):
        api = APIServer()
        a = elector(api, "cm", "a").start()
        assert wait_until(lambda: a.is_leader)
        b = elector(api, "cm", "b").start()
        time.sleep(0.3)
        assert not b.is_leader  # live lease respected
        a.stop()  # stops renewing; lease expires
        try:
            assert wait_until(lambda: b.is_leader, timeout=5)
        finally:
            b.stop()

    def test_distinct_locks_are_independent(self):
        api = APIServer()
        a = elector(api, "scheduler", "a").start()
        b = elector(api, "controller-manager", "b").start()
        try:
            assert wait_until(lambda: a.is_leader and b.is_leader)
        finally:
            a.stop()
            b.stop()


class TestHAHotStandby:
    def test_only_leader_runs_daemon_and_failover(self):
        api = APIServer()

        def factory():
            return ControllerManager(
                Client(LocalTransport(api)), enable_node_lifecycle=False
            ).start()

        ha1 = HAHotStandby(
            Client(LocalTransport(api)), "cm", "one", factory,
            lease_duration=0.6, renew_period=0.1, retry_period=0.1,
        ).start()
        assert wait_until(lambda: ha1.active)
        ha2 = HAHotStandby(
            Client(LocalTransport(api)), "cm", "two", factory,
            lease_duration=0.6, renew_period=0.1, retry_period=0.1,
        ).start()
        time.sleep(0.4)
        assert not ha2.active  # hot standby stays idle
        ha1.stop()
        try:
            assert wait_until(lambda: ha2.active, timeout=5)
            # The promoted manager actually reconciles: create an RC
            # and see pods appear.
            client = Client(LocalTransport(api))
            client.create(
                "replicationcontrollers",
                {
                    "kind": "ReplicationController",
                    "metadata": {"name": "ha-rc", "namespace": "default"},
                    "spec": {
                        "replicas": 2,
                        "selector": {"app": "ha"},
                        "template": {
                            "metadata": {"labels": {"app": "ha"}},
                            "spec": {
                                "containers": [{"name": "c", "image": "x"}]
                            },
                        },
                    },
                },
            )
            assert wait_until(
                lambda: len(
                    client.list("pods", namespace="default")[0]
                )
                == 2
            )
        finally:
            ha2.stop()
        assert not ha2.active
