"""Leader election + HA hot standby.

Reference: contrib/pod-master/podmaster.go (etcd-lock hot standby for
scheduler/controller-manager)."""

import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.utils.leaderelect import HAHotStandby, LeaderElector


def wait_until(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def elector(api, name, identity, **kw):
    kw.setdefault("lease_duration", 0.6)
    kw.setdefault("renew_period", 0.1)
    kw.setdefault("retry_period", 0.1)
    return LeaderElector(Client(LocalTransport(api)), name, identity, **kw)


class TestLeaderElector:
    def test_single_candidate_leads(self):
        api = APIServer()
        e = elector(api, "cm", "a").start()
        try:
            assert wait_until(lambda: e.is_leader)
        finally:
            e.stop()

    def test_exactly_one_of_many_leads(self):
        api = APIServer()
        electors = [elector(api, "cm", f"id-{i}").start() for i in range(4)]
        try:
            assert wait_until(
                lambda: sum(e.is_leader for e in electors) == 1
            )
            time.sleep(0.5)  # stable: still exactly one
            assert sum(e.is_leader for e in electors) == 1
        finally:
            for e in electors:
                e.stop()

    def test_takeover_on_leader_death(self):
        api = APIServer()
        a = elector(api, "cm", "a").start()
        assert wait_until(lambda: a.is_leader)
        b = elector(api, "cm", "b").start()
        time.sleep(0.3)
        assert not b.is_leader  # live lease respected
        a.stop()  # stops renewing; lease expires
        try:
            assert wait_until(lambda: b.is_leader, timeout=5)
        finally:
            b.stop()

    def test_distinct_locks_are_independent(self):
        api = APIServer()
        a = elector(api, "scheduler", "a").start()
        b = elector(api, "controller-manager", "b").start()
        try:
            assert wait_until(lambda: a.is_leader and b.is_leader)
        finally:
            a.stop()
            b.stop()


class TestHAHotStandby:
    def test_only_leader_runs_daemon_and_failover(self):
        api = APIServer()

        def factory():
            return ControllerManager(
                Client(LocalTransport(api)), enable_node_lifecycle=False
            ).start()

        ha1 = HAHotStandby(
            Client(LocalTransport(api)), "cm", "one", factory,
            lease_duration=0.6, renew_period=0.1, retry_period=0.1,
        ).start()
        assert wait_until(lambda: ha1.active)
        ha2 = HAHotStandby(
            Client(LocalTransport(api)), "cm", "two", factory,
            lease_duration=0.6, renew_period=0.1, retry_period=0.1,
        ).start()
        time.sleep(0.4)
        assert not ha2.active  # hot standby stays idle
        ha1.stop()
        try:
            assert wait_until(lambda: ha2.active, timeout=5)
            # The promoted manager actually reconciles: create an RC
            # and see pods appear.
            client = Client(LocalTransport(api))
            client.create(
                "replicationcontrollers",
                {
                    "kind": "ReplicationController",
                    "metadata": {"name": "ha-rc", "namespace": "default"},
                    "spec": {
                        "replicas": 2,
                        "selector": {"app": "ha"},
                        "template": {
                            "metadata": {"labels": {"app": "ha"}},
                            "spec": {
                                "containers": [{"name": "c", "image": "x"}]
                            },
                        },
                    },
                },
            )
            assert wait_until(
                lambda: len(
                    client.list("pods", namespace="default")[0]
                )
                == 2
            )
        finally:
            ha2.stop()
        assert not ha2.active


@pytest.mark.slow
class TestHAFailoverUnderLoad:
    """VERDICT r2 item 7: kill the LEADING batch scheduler mid-backlog;
    the hot standby takes the lease and finishes the backlog with zero
    double-binds — contrib/pod-master's story proven under load, not
    on a toy. The bind CAS (nodeName set iff empty) is what makes dual
    writers safe; 409s are tolerated, rebinds are not."""

    def test_standby_finishes_backlog_no_double_binds(self):
        from kubernetes_tpu.client import HTTPTransport
        from kubernetes_tpu.scheduler.daemon import BatchScheduler, SchedulerConfig
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        srv = APIHTTPServer(api).start()

        def client():
            return Client(HTTPTransport(srv.address))

        c = client()
        for j in range(20):
            c.create(
                "nodes",
                {
                    "kind": "Node",
                    "metadata": {"name": f"n{j}"},
                    "status": {
                        "capacity": {"cpu": "8", "memory": "16Gi", "pods": "110"},
                        "conditions": [{"type": "Ready", "status": "True"}],
                    },
                },
            )
        total = 2000
        for i in range(total):
            c.create(
                "pods",
                {
                    "kind": "Pod",
                    "metadata": {"name": f"p{i:04d}", "namespace": "default"},
                    "spec": {
                        "containers": [
                            {"name": "c", "image": "x",
                             "resources": {"limits": {"cpu": "50m", "memory": "32Mi"}}}
                        ]
                    },
                },
            )
        _, version = c.list("pods", namespace="default")
        stream = c.watch("pods", namespace="default", since=version)

        def factory():
            cfg = SchedulerConfig(client()).start()
            cfg.wait_for_sync(timeout=30)
            # Small batches so the kill lands mid-backlog (10+ cycles).
            return BatchScheduler(cfg, max_batch=200).start()

        ha = [
            HAHotStandby(
                client(), "scheduler", name, factory,
                lease_duration=0.6, renew_period=0.1, retry_period=0.1,
            ).start()
            for name in ("alpha", "beta")
        ]
        try:
            assert wait_until(lambda: sum(h.active for h in ha) == 1, timeout=30)
            leader = next(h for h in ha if h.active)
            standby = next(h for h in ha if h is not leader)

            def bound_count():
                pods, _ = c.list("pods", namespace="default")
                return sum(1 for p in pods if p.spec.node_name)

            # Let the leader get partway through the backlog...
            assert wait_until(
                lambda: 200 <= bound_count() < total, timeout=120
            ), f"leader never got mid-backlog ({bound_count()} bound)"
            # ...then crash it: scheduling stops and renewals stop, with
            # NO graceful abdication — the lease must simply expire.
            if leader.daemon is not None:
                leader.daemon.stop()
            leader.elector._stop.set()

            assert wait_until(lambda: standby.active, timeout=30), (
                "standby never took the lease"
            )
            assert wait_until(
                lambda: bound_count() == total, timeout=300
            ), f"standby stalled: {bound_count()}/{total} bound"

            # Zero double-binds: replay the watch; once a pod carries a
            # nodeName it must never change to a different one.
            bound_to = {}
            while True:
                ev = stream.next(timeout=1.0)
                if ev is None:
                    break
                meta = ev.object.get("metadata", {})
                name = meta.get("name", "")
                node = ev.object.get("spec", {}).get("nodeName", "")
                if not node:
                    continue
                prev = bound_to.get(name)
                assert prev is None or prev == node, (
                    f"pod {name} rebound {prev} -> {node}"
                )
                bound_to[name] = node
            assert len(bound_to) == total
        finally:
            stream.close()
            for h in ha:
                try:
                    h.stop()
                except Exception:
                    pass
            srv.stop()
