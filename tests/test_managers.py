"""Kubelet resource managers: container GC, disk manager, OOM watcher.

Reference: pkg/kubelet/{container_gc,image_manager,disk_manager,
oom_watcher}.go (VERDICT r1 missing #6)."""

import os
import time
from collections import namedtuple

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.kubelet.managers import ContainerGC, DiskManager, OOMWatcher
from kubernetes_tpu.kubelet.runtime import FakeRuntime, RuntimeContainer
from kubernetes_tpu.models.objects import ObjectMeta, Pod
from kubernetes_tpu.server.api import APIServer

FakeStat = namedtuple("FakeStat", "f_frsize f_blocks f_bavail")


class FakeDiskRuntime:
    """Runtime stub exposing only what ContainerGC needs."""

    def __init__(self, live_uids=()):
        self._live = set(live_uids)

    def list_pods(self):
        return {
            uid: [RuntimeContainer(name="c", image="x", container_id="p")]
            for uid in self._live
        }


def make_pod_dir(root, uid, log_bytes=0, age_s=0.0):
    d = os.path.join(root, "pods", uid)
    os.makedirs(d, exist_ok=True)
    if log_bytes:
        with open(os.path.join(d, "main.log"), "wb") as f:
            f.write(b"x" * log_bytes)
    if age_s:
        past = time.time() - age_s
        os.utime(d, (past, past))
    return d


class TestDiskManager:
    def test_usage_and_thresholds(self, tmp_path):
        full = DiskManager(
            str(tmp_path),
            statvfs=lambda p: FakeStat(4096, 1000, 50),  # 95% used
        )
        assert full.usage().used_fraction == pytest.approx(0.95)
        assert full.over_high_threshold()
        assert not full.under_low_threshold()
        empty = DiskManager(
            str(tmp_path), statvfs=lambda p: FakeStat(4096, 1000, 900)
        )
        assert not empty.over_high_threshold()
        assert empty.under_low_threshold()

    def test_statvfs_failure_is_safe(self):
        def boom(p):
            raise OSError("nope")

        dm = DiskManager("/nonexistent", statvfs=boom)
        assert dm.usage().capacity_bytes == 0
        assert not dm.over_high_threshold()


class TestContainerGC:
    def test_dead_pod_dirs_reaped_after_min_age(self, tmp_path):
        root = str(tmp_path)
        make_pod_dir(root, "dead-old", age_s=120)
        make_pod_dir(root, "dead-new")
        live_dir = make_pod_dir(root, "alive", age_s=120)
        gc = ContainerGC(root, FakeDiskRuntime({"alive"}), min_age_s=60)
        stats = gc.gc()
        assert stats["dirs_removed"] == 1
        assert not os.path.exists(os.path.join(root, "pods", "dead-old"))
        assert os.path.exists(os.path.join(root, "pods", "dead-new"))
        assert os.path.exists(live_dir)

    def test_oversized_live_logs_truncated(self, tmp_path):
        root = str(tmp_path)
        d = make_pod_dir(root, "alive", log_bytes=4096)
        gc = ContainerGC(
            root, FakeDiskRuntime({"alive"}), max_log_bytes=1024
        )
        stats = gc.gc()
        assert stats["logs_truncated"] == 1
        size = os.path.getsize(os.path.join(d, "main.log"))
        assert size <= 1024
        with open(os.path.join(d, "main.log"), "rb") as f:
            assert f.read().startswith(b"[log truncated")

    def test_disk_pressure_reclaims_oldest_dead_first(self, tmp_path):
        root = str(tmp_path)
        make_pod_dir(root, "oldest", age_s=300)
        make_pod_dir(root, "newer", age_s=100)
        calls = {"n": 0}

        def statvfs(p):
            # Over high threshold until one dir is removed.
            calls["n"] += 1
            removed = not os.path.exists(os.path.join(root, "pods", "oldest"))
            return FakeStat(4096, 1000, 500 if removed else 20)

        disk = DiskManager(root, statvfs=statvfs)
        gc = ContainerGC(root, FakeDiskRuntime(), min_age_s=1e9, disk=disk)
        stats = gc.gc()
        assert stats["pressure_removed"] == 1
        assert not os.path.exists(os.path.join(root, "pods", "oldest"))
        assert os.path.exists(os.path.join(root, "pods", "newer"))


class TestOOMWatcher:
    def _pod(self, name="victim"):
        return Pod(metadata=ObjectMeta(name=name, namespace="default", uid=name))

    def _killed(self, cid="proc://1"):
        return RuntimeContainer(
            name="main", image="x", container_id=cid,
            state="exited", exit_code=137,
        )

    def test_records_event_once_per_incarnation(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        watcher = OOMWatcher(client, "n1")
        pod = self._pod()
        assert watcher.observe(pod, [self._killed()]) == 1
        assert watcher.observe(pod, [self._killed()]) == 0  # same incarnation
        assert watcher.observe(pod, [self._killed(cid="proc://2")]) == 1
        client.flush_events()
        events, _ = client.list("events", namespace="default")
        kills = [e for e in events if e.reason == "ContainerKilled"]
        assert len(kills) >= 1
        assert "killed" in kills[0].message

    def test_prune_keeps_current_incarnations(self):
        api = APIServer()
        watcher = OOMWatcher(Client(LocalTransport(api)), "n1")
        pod = self._pod()
        killed = self._killed()
        watcher.observe(pod, [killed])
        # Force overflow, then prune against a runtime still tracking
        # the killed incarnation: its key must SURVIVE (no dup events).
        watcher._seen |= {("ghost", f"c{i}", f"id{i}") for i in range(5000)}
        watcher.prune({"victim": [killed]})
        assert ("victim", "main", killed.container_id) in watcher._seen
        assert len(watcher._seen) == 1
        assert watcher.observe(pod, [killed]) == 0  # still deduped

    def test_gc_spares_desired_and_volume_dirs(self, tmp_path):
        root = str(tmp_path)
        make_pod_dir(root, "wanted", age_s=120)
        vol_dir = make_pod_dir(root, "voly")
        os.makedirs(os.path.join(vol_dir, "volumes", "v1"), exist_ok=True)
        with open(os.path.join(vol_dir, "main.log"), "w") as f:
            f.write("x")
        past = time.time() - 120  # age AFTER content creation
        os.utime(vol_dir, (past, past))
        gc = ContainerGC(
            root,
            FakeDiskRuntime(),
            min_age_s=60,
            desired_uids=lambda: {"wanted"},
        )
        stats = gc.gc()
        # Desired pod untouched even with no runtime record (mount
        # retry case); volume-holding dir keeps its volumes, loses only
        # runtime artifacts.
        assert os.path.exists(os.path.join(root, "pods", "wanted"))
        assert os.path.exists(os.path.join(vol_dir, "volumes", "v1"))
        assert not os.path.exists(os.path.join(vol_dir, "main.log"))
        assert stats["dirs_removed"] == 0

    def test_normal_exits_ignored(self):
        api = APIServer()
        watcher = OOMWatcher(Client(LocalTransport(api)), "n1")
        ok = RuntimeContainer(
            name="main", image="x", container_id="p", state="exited", exit_code=0
        )
        running = RuntimeContainer(
            name="side", image="x", container_id="q", state="running"
        )
        assert watcher.observe(self._pod(), [ok, running]) == 0


def test_sync_pool_elastic_survives_wedged_workers():
    """Round-4 review regression: two wedged syncs must not starve the
    node's other pods — transient workers spawn when all are busy and
    retire when idle (the reference's per-pod-worker isolation on a
    thread budget, pod_workers.go:91-123)."""
    import threading
    import time

    from kubernetes_tpu.kubelet.agent import _SyncPool

    unblock = threading.Event()
    synced = []

    def sync_fn(pod):
        if pod == "wedge":
            unblock.wait(timeout=10)
        else:
            synced.append(pod)

    pool = _SyncPool(sync_fn, workers=2, max_workers=8)
    try:
        pool.update("a", "wedge")
        pool.update("b", "wedge")
        time.sleep(0.3)  # both base workers now wedged
        pool.update("c", "ok")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and "ok" not in synced:
            time.sleep(0.02)
        assert "ok" in synced, "third pod starved behind wedged workers"
        # Transient workers retire once idle (bounded thread growth).
        unblock.set()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and pool._nworkers > 2:
            time.sleep(0.1)
        assert pool._nworkers <= 2
    finally:
        unblock.set()
        pool.stop()


def test_sync_pool_never_overlaps_one_pod():
    """Round-5 advisor regression: forget() (pod deleted) followed by
    update() (pod recreated) leaves two queue tokens for one key; a
    worker claiming the second token while the first sync is still
    running must NOT start a concurrent sync for the same pod."""
    import threading
    import time

    from kubernetes_tpu.kubelet.agent import _SyncPool

    release = threading.Event()
    in_flight = {}
    overlaps = []
    lock = threading.Lock()

    def sync_fn(pod):
        key, slow = pod
        with lock:
            if in_flight.get(key):
                overlaps.append(key)
            in_flight[key] = True
        if slow:
            release.wait(timeout=10)
        with lock:
            in_flight[key] = False

    # No workers yet: stage the duplicate-token state deterministically.
    pool = _SyncPool(sync_fn, workers=0, max_workers=0)
    try:
        pool.update("p", ("p", True))  # token 1
        pool.forget("p")  # pod deleted: pending dropped, token 1 orphaned
        pool.update("p", ("p", True))  # pod recreated: token 2
        with pool._lock:
            pool._spawn_locked(transient=False)  # worker A: claims token 1,
        time.sleep(0.3)  # ...pops the pending spec, blocks in sync
        pool.update("p", ("p", False))  # key running -> pending only
        with pool._lock:
            pool._spawn_locked(transient=False)  # worker B: claims token 2
        time.sleep(0.3)  # pre-fix B would now sync "p" concurrently
        release.set()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and in_flight.get("p", True):
            time.sleep(0.02)
        assert not overlaps, f"concurrent syncs for one pod: {overlaps}"
        assert in_flight.get("p") is False  # the recreated pod did sync
    finally:
        release.set()
        pool.stop()


def test_serde_decode_never_aliases_source_dict():
    """Round-5 advisor regression: Any-typed leaves (ContainerStatus.
    state) must be deep-copied at decode — watch events share one
    object across all watchers, so an aliased leaf mutated by one
    informer consumer would corrupt every other's view."""
    from kubernetes_tpu.models.objects import ContainerStatus
    from kubernetes_tpu.models.serde import from_wire

    wire = {
        "name": "main",
        "state": {"running": {"startedAt": "2026-01-01T00:00:00Z"}},
    }
    st = from_wire(ContainerStatus, wire)
    assert st.state == wire["state"]
    assert st.state is not wire["state"]
    st.state["running"]["startedAt"] = "mutated"
    assert wire["state"]["running"]["startedAt"] == "2026-01-01T00:00:00Z"


def test_image_gc_units_consistent():
    """Round-5 advisor regression: remove() must report freed bytes in
    the same unit bytes_used() counts (manifest-declared), so the GC
    watermark math `used - freed` tracks the store's own metric."""
    import tempfile

    from kubernetes_tpu.kubelet.managers import ImageManager
    from kubernetes_tpu.kubelet.sandbox_runtime import ImageStore

    with tempfile.TemporaryDirectory() as d:
        store = ImageStore(d)
        for i in range(6):
            store.pull(f"img-{i}")
        used = store.bytes_used()
        sizes = {rec["image"]: rec["bytes"] for rec in store.list_images()}
        freed = store.remove("img-0")
        assert freed == sizes["img-0"]
        assert store.bytes_used() == used - freed
        # And the manager's stop condition lands where the store agrees.
        mgr = ImageManager(store, high_bytes=0, low_bytes=0)
        total_freed = mgr.gc(in_use=set())
        assert store.bytes_used() == 0
        assert total_freed == used - freed
