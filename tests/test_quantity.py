"""Quantity parsing/formatting parity (reference: pkg/api/resource/)."""

import pytest

from kubernetes_tpu.models.quantity import Quantity, parse_quantity


@pytest.mark.parametrize(
    "s,milli",
    [
        ("0", 0),
        ("100m", 100),
        ("1", 1000),
        ("2", 2000),
        ("250m", 250),
        ("1.5", 1500),
        ("0.1", 100),
        ("1k", 1_000_000),
        ("1M", 1_000_000_000),
        ("1Ki", 1024 * 1000),
        ("1Mi", 1024**2 * 1000),
        ("64Mi", 64 * 1024**2 * 1000),
        ("1Gi", 1024**3 * 1000),
        ("1.5Gi", 1536 * 1024**2 * 1000),
        ("-1", -1000),
        ("+1", 1000),
    ],
)
def test_parse(s, milli):
    assert parse_quantity(s).milli == milli


def test_milli_and_value():
    q = parse_quantity("2500m")
    assert q.milli_value() == 2500
    assert q.value() == 3  # rounds up like the reference's Value()
    assert parse_quantity("2").value() == 2
    assert parse_quantity("64Mi").value() == 64 * 1024**2


def test_roundtrip_strings():
    for s in ["100m", "2", "64Mi", "1Gi", "500m", "4", "10k", "128Ki"]:
        assert str(parse_quantity(s)) == s


def test_arithmetic_and_compare():
    a, b = parse_quantity("1"), parse_quantity("500m")
    assert (a + b).milli == 1500
    assert (a - b).milli == 500
    assert b < a
    assert parse_quantity("1024Mi") == parse_quantity("1Gi")


def test_invalid():
    for bad in ["", "abc", "1Q", "--1", "1..5"]:
        with pytest.raises(ValueError):
            parse_quantity(bad)


def test_from_int():
    assert Quantity.from_int(4).milli_value() == 4000
    assert Quantity.from_milli(250).milli_value() == 250
