"""Pallas scan kernel: bit-parity with the XLA scan + dispatch rules.

The kernel (ops/pallas_scan.py) must make EXACTLY the decisions the XLA
lax.scan makes — it carries the sequential-parity referee's wall on
TPU. On this CPU test platform the kernel runs in pallas interpret
mode; the real-TPU lowering is exercised by bench.py and was verified
bit-identical at the full 50k x 5k shape."""

import numpy as np
import pytest

from kubernetes_tpu.models.columnar import build_snapshot
from kubernetes_tpu.ops import device_snapshot
from kubernetes_tpu.ops.pallas_scan import (
    pallas_eligible,
    solve_with_state_pallas,
)
from kubernetes_tpu.ops.solver import DEFAULT_WEIGHTS, _solve_with_state_xla
from kubernetes_tpu.models.algspec import DEFAULT_LOWERED

from tests.test_solver_parity import random_cluster


def _both(pending, nodes, assigned=(), services=()):
    snap = build_snapshot(pending, nodes, assigned_pods=assigned, services=services)
    d = device_snapshot(snap)
    # XLA path donates nodes: give it its own copies.
    import jax

    nodes_copy = {k: jax.numpy.array(v) for k, v in d.nodes.items()}
    ref, ref_state = _solve_with_state_xla(
        d.pods, nodes_copy, DEFAULT_WEIGHTS, DEFAULT_LOWERED
    )
    got, got_state = solve_with_state_pallas(
        d.pods, d.nodes, DEFAULT_WEIGHTS, interpret=True
    )
    return np.asarray(ref), ref_state, np.asarray(got), got_state


class TestBitParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_random_cluster_decisions_identical(self, seed):
        pods, nodes, assigned, services = random_cluster(seed)
        ref, _, got, _ = _both(pods, nodes, assigned, services)
        assert (ref == got).all(), (
            f"seed {seed}: {int((ref != got).sum())}/{len(ref)} decisions differ"
        )

    def test_final_state_matches_for_chunk_chaining(self):
        """The pipeline chains the carry across chunks: the kernel's
        post-commit state must equal the XLA scan's, field by field."""
        pods, nodes, assigned, services = random_cluster(3)
        _, ref_state, _, got_state = _both(pods, nodes, assigned, services)
        for key in (
            "cpu_fit", "mem_fit", "cpu_used", "mem_used", "pods_used",
            "uport", "uvol_any", "uvol_rw", "svc_counts",
        ):
            assert np.array_equal(
                np.asarray(ref_state[key]), np.asarray(got_state[key])
            ), key

    def test_chunked_equals_monolithic(self):
        """Two pallas calls chained through the carry == one call (the
        exact contract solve_backlog_pipelined relies on)."""
        pods, nodes, assigned, services = random_cluster(5)
        snap = build_snapshot(
            pods, nodes, assigned_pods=assigned, services=services
        )
        d = device_snapshot(snap)
        whole, _ = solve_with_state_pallas(d.pods, d.nodes, interpret=True)
        P = snap.pods.count
        if P < 2:
            pytest.skip("need >=2 pods to chunk")
        cut = P // 2
        import jax.numpy as jnp

        def slice_pods(lo, hi):
            out = {}
            for k, v in d.pods.items():
                sl = v[lo:hi]
                # re-bucket to the 128 floor the kernel expects
                pad = 128 - sl.shape[0] % 128 if sl.shape[0] % 128 else 0
                if pad:
                    fill = -2 if k == "pinned" else (-1 if k in ("svc", "svc_ids") else 0)
                    width = [(0, pad)] + [(0, 0)] * (sl.ndim - 1)
                    sl = jnp.pad(sl, width, constant_values=fill)
                out[k] = sl
            return out

        a1, state = solve_with_state_pallas(
            slice_pods(0, cut), d.nodes, interpret=True
        )
        a2, _ = solve_with_state_pallas(
            slice_pods(cut, P), state, interpret=True
        )
        chained = np.concatenate([np.asarray(a1)[:cut], np.asarray(a2)[: P - cut]])
        assert (np.asarray(whole)[:P] == chained).all()


class TestDispatch:
    def test_not_eligible_on_cpu_platform(self):
        pods, nodes, assigned, services = random_cluster(0)
        snap = build_snapshot(
            pods, nodes, assigned_pods=assigned, services=services
        )
        d = device_snapshot(snap)
        # conftest forces the CPU platform: the real kernel must not
        # engage; solver.solve falls back to the XLA scan.
        assert not pallas_eligible(d.pods, d.nodes, DEFAULT_LOWERED)

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("KTPU_PALLAS", "off")
        pods, nodes, assigned, services = random_cluster(0)
        snap = build_snapshot(
            pods, nodes, assigned_pods=assigned, services=services
        )
        d = device_snapshot(snap)
        assert not pallas_eligible(d.pods, d.nodes, DEFAULT_LOWERED)


class TestServiceAxisPadding:
    """Regression (round-4 review): SolverSession carries UNPADDED
    service axes — S=1 with no services (the churn bench shape), or any
    S not a multiple of 8 — and the kernel's 8-row banded access must
    pad rather than crash (S<8) or clamp into a neighbor service's
    counts (S%8 != 0)."""

    @pytest.mark.parametrize("n_services", [0, 1, 3, 12])
    def test_odd_service_axis_matches_xla(self, n_services):
        from kubernetes_tpu.models.objects import (
            ObjectMeta,
            Service,
            ServiceSpec,
        )
        from tests.test_solver_parity import mk_node, mk_pod

        services = [
            Service(
                metadata=ObjectMeta(name=f"s{i}", namespace="default"),
                spec=ServiceSpec(selector={"app": f"a{i}"}),
            )
            for i in range(n_services)
        ]
        nodes = [mk_node(f"n{j}") for j in range(5)]
        pods = [
            mk_pod(
                f"p{i}", cpu=100, mem_mib=64,
                labels={"app": f"a{i % max(1, n_services)}"},
            )
            for i in range(20)
        ]
        ref, _, got, got_state = _both(pods, nodes, services=services)
        assert (ref == got).all()
        # The returned carry keeps the caller's (N, S) schema exactly.
        snap = build_snapshot(pods, nodes, services=services)
        d = device_snapshot(snap)
        assert (
            np.asarray(got_state["svc_counts"]).shape
            == np.asarray(d.nodes["svc_counts"]).shape
        )

    def test_vmem_guard_rejects_oversized_shapes(self):
        from kubernetes_tpu.ops.pallas_scan import (
            VMEM_BUDGET_BYTES,
            _vmem_bytes,
        )

        # The review's counter-example: ~3072 nodes x ~1536 services
        # needs >16MB for the counts carry alone — must be rejected.
        assert _vmem_bytes(3072, 1536, 2, 2, 2) > VMEM_BUDGET_BYTES
        # The bench's 50k x 5k shape (N=5120, S=512) must be admitted.
        assert _vmem_bytes(5120, 512, 2, 2, 2) <= VMEM_BUDGET_BYTES


def test_multiword_bitsets_match_xla():
    """Port vocabularies past 64 entries need 3+ u32 words — the
    kernel's static per-word loops must agree with the XLA scan across
    the word boundary (each pod claims a distinct hostPort; a second
    same-port pod must avoid the first's node)."""
    from tests.test_solver_parity import mk_node, mk_pod

    nodes = [mk_node(f"n{j}", pods=200) for j in range(4)]
    pods = []
    for i in range(70):  # 70 distinct ports -> 3 words, bucketed to 4
        pods.append(mk_pod(f"p{i}", cpu=10, mem_mib=8, host_port=7000 + i))
    for i in range(8):  # conflicts: same ports again
        pods.append(mk_pod(f"q{i}", cpu=10, mem_mib=8, host_port=7000 + i))
    ref, _, got, _ = _both(pods, nodes)
    assert (ref == got).all()
