"""ktsan: the lock-order/deadlock sanitizer, both halves.

Runtime (utils/sanitizer.py): every detector is proven to FIRE on a
deliberate violation — a lock-order inversion, a blocking call under a
lock, an Event.wait without timeout, a jit-dispatch hook under a lock,
a lock held by a dead thread — and to stay quiet on the sanctioned
shapes (io_gate locks, allow_blocking grants, RLock re-entry).

Static (tools/ktlint/lockgraph.py + KT006): fixture trees prove the
interprocedural detectors fire (inversion cycle, ``*_locked`` caller
without the lock, ``*_locked`` re-acquire, unregistered jitted
kernel), pragmas suppress with a reason, runtime/static graphs merge
on node names — and the LIVE tree is gated clean (the acceptance
criterion: zero cycles, zero contract violations).

Plus the satellites that ride the same machinery: the kernel/oracle
registry resolves at runtime, _scatter_rows has NumPy parity with its
registered twin, and the recompilation sentinel pins the pow2
bucketing contract by counting actual XLA compiles.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from kubernetes_tpu.utils import sanitizer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.ktlint import lockgraph  # noqa: E402
from tools.ktlint.rules_parity import (  # noqa: E402
    OracleTwinRule,
    jitted_kernels,
    resolve_oracle,
)
import ast  # noqa: E402
import pathlib  # noqa: E402

from tools.ktlint.framework import FileContext  # noqa: E402


# -- runtime: lock-order graph -----------------------------------------


class TestRuntimeLockOrder:
    def test_inversion_is_a_finding(self):
        a = sanitizer.lock("fxrt.a")
        b = sanitizer.lock("fxrt.b")
        with a:
            with b:
                pass

        def reversed_order():
            with b:
                with a:
                    pass

        t = threading.Thread(target=reversed_order)
        t.start()
        t.join()
        kinds = [f["kind"] for f in sanitizer.findings()]
        assert "lock-order-cycle" in kinds, sanitizer.findings()
        cyc = next(
            f for f in sanitizer.findings()
            if f["kind"] == "lock-order-cycle"
        )
        assert set(cyc["cycle"]) >= {"fxrt.a", "fxrt.b"}
        sanitizer.reset()

    def test_consistent_order_is_clean(self):
        a = sanitizer.lock("fxrt.c1")
        b = sanitizer.lock("fxrt.c2")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert sanitizer.findings() == []

    def test_sibling_instances_same_name_not_an_edge(self):
        # Two stores' kvstore.lock taken nested must not self-cycle.
        s1 = sanitizer.lock("fxrt.sib")
        s2 = sanitizer.lock("fxrt.sib")
        with s1:
            with s2:
                pass
        assert sanitizer.findings() == []
        assert not any(
            e["from"] == e["to"] == "fxrt.sib" for e in sanitizer.edges()
        )

    def test_rlock_reentry_is_not_an_edge(self):
        r = sanitizer.rlock("fxrt.re")
        with r:
            with r:
                assert r._is_owned()
        assert sanitizer.findings() == []


# -- runtime: blocking under a lock ------------------------------------


class TestRuntimeBlocking:
    def test_fsync_under_lock_fires(self, tmp_path):
        lk = sanitizer.lock("fxrt.fs")
        f = open(tmp_path / "x", "w")
        f.write("x")
        f.flush()
        with lk:
            os.fsync(f.fileno())
        f.close()
        found = [
            f for f in sanitizer.findings()
            if f["kind"] == "blocking-under-lock" and f["op"] == "fsync"
        ]
        assert found and "fxrt.fs" in found[0]["locks"]
        sanitizer.reset()

    def test_io_gate_lock_is_exempt(self, tmp_path):
        gate = sanitizer.lock("fxrt.gate", io_gate=True)
        f = open(tmp_path / "x", "w")
        f.write("x")
        f.flush()
        with gate:
            os.fsync(f.fileno())
        f.close()
        assert sanitizer.findings() == []

    def test_allow_blocking_grant(self, tmp_path):
        lk = sanitizer.lock("fxrt.grant")
        f = open(tmp_path / "x", "w")
        f.write("x")
        f.flush()
        with lk:
            with sanitizer.allow_blocking("fixture: documented exception"):
                os.fsync(f.fileno())
        f.close()
        assert sanitizer.findings() == []

    def test_event_wait_no_timeout_under_lock_fires(self):
        lk = sanitizer.lock("fxrt.evw")
        ev = threading.Event()
        ev.set()  # wait() returns immediately; the CALL is the finding
        with lk:
            ev.wait()
        assert any(
            f["op"] == "event-wait-no-timeout" for f in sanitizer.findings()
        ), sanitizer.findings()
        sanitizer.reset()

    def test_event_wait_with_timeout_is_fine(self):
        lk = sanitizer.lock("fxrt.evt")
        ev = threading.Event()
        with lk:
            ev.wait(timeout=0.001)
        assert sanitizer.findings() == []

    def test_jit_dispatch_hook_under_lock_fires(self):
        lk = sanitizer.lock("fxrt.jit")
        sanitizer.check_blocking("jit-dispatch", "free")  # no lock: quiet
        assert sanitizer.findings() == []
        with lk:
            sanitizer.check_blocking("jit-dispatch", "under lock")
        assert any(
            f["op"] == "jit-dispatch" for f in sanitizer.findings()
        )
        sanitizer.reset()

    def test_blocking_only_observes_sanitized_locks(self, tmp_path):
        # A plain threading.Lock is invisible — adoption via the
        # factory is what opts a component in.
        plain = threading.Lock()
        f = open(tmp_path / "x", "w")
        f.write("x")
        f.flush()
        with plain:
            os.fsync(f.fileno())
        f.close()
        assert sanitizer.findings() == []


# -- runtime: leaks -----------------------------------------------------


class TestRuntimeLeaks:
    def test_lock_held_by_dead_thread_is_leaked(self):
        lk = sanitizer.lock("fxrt.leak")

        def die_holding():
            lk.acquire()

        t = threading.Thread(target=die_holding)
        t.start()
        t.join()
        leaks = sanitizer.leaked_locks()
        assert ("fxrt.leak" in [name for _t, name in leaks]), leaks
        # Clean up so the conftest guard doesn't (rightly) fail us.
        sanitizer.purge_dead_threads()
        lk._inner.release() if hasattr(lk, "_inner") else None
        assert sanitizer.leaked_locks() == []

    def test_held_locks_snapshot(self):
        lk = sanitizer.lock("fxrt.held")
        with lk:
            assert ("fxrt.held" in [n for _t, n in sanitizer.held_locks()])
        assert "fxrt.held" not in [n for _t, n in sanitizer.held_locks()]


# -- runtime: factory cost when off ------------------------------------


def test_factory_returns_plain_locks_when_off():
    # The guard fixture enabled the sanitizer for this module; flip it
    # off around the assertion (enable() restores instrumented mode).
    sanitizer.disable()
    try:
        lk = sanitizer.lock("noop")
        rk = sanitizer.rlock("noop")
        assert type(lk) is type(threading.Lock())
        assert isinstance(rk, type(threading.RLock()))
    finally:
        sanitizer.enable()


# -- static: fixtures ---------------------------------------------------


INVERSION_SRC = """
from kubernetes_tpu.utils import sanitizer

class B:
    def __init__(self):
        self._lock = sanitizer.lock("fx.b")

class A:
    def __init__(self):
        self._lock = sanitizer.lock("fx.a")
        self._b = B()

    def ab(self):
        with self._lock:
            with self._b._lock:
                pass

class C:
    def __init__(self):
        self._a = A()
        self._b = B()

    def ba(self):
        with self._b._lock:
            with self._a._lock:
                pass
"""

LOCKED_SRC = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def _bump_locked(self):
        self._n += 1

    def good(self):
        with self._lock:
            self._bump_locked()

    def bad(self):
        self._bump_locked()
"""

REACQUIRE_SRC = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def _oops_locked(self):
        with self._lock:
            pass
"""

CLEAN_SRC = """
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._aux = threading.Lock()
        self._n = 0

    def _bump_locked(self):
        self._n += 1

    def work(self):
        with self._lock:
            with self._aux:
                self._bump_locked()
"""


def _analyze_src(tmp_path, src, name="fx.py", runtime=None):
    p = tmp_path / name
    p.write_text(src)
    return lockgraph.analyze([p], runtime=runtime)


class TestStaticLockGraph:
    def test_deliberate_inversion_is_a_cycle(self, tmp_path):
        rep = _analyze_src(tmp_path, INVERSION_SRC)
        assert rep.cycles, rep.render()
        assert set(rep.cycles[0]["nodes"]) == {"fx.a", "fx.b"}
        assert rep.exit_code == 1

    def test_locked_caller_without_lock_fires(self, tmp_path):
        rep = _analyze_src(tmp_path, LOCKED_SRC)
        assert [v.rule for v in rep.violations] == ["KTSAN02"]
        assert "bad" not in rep.violations[0].message  # message names callee
        assert "_bump_locked" in rep.violations[0].message

    def test_locked_caller_pragma_suppresses(self, tmp_path):
        src = LOCKED_SRC.replace(
            "        self._bump_locked()\n"
            "\n"
            "    def bad(self):\n"
            "        self._bump_locked()",
            "        self._bump_locked()\n"
            "\n"
            "    def bad(self):\n"
            "        self._bump_locked()  # ktlint: disable=KTSAN02",
        )
        rep = _analyze_src(tmp_path, src)
        assert rep.violations == [] and rep.suppressed == 1

    def test_locked_body_reacquire_fires(self, tmp_path):
        rep = _analyze_src(tmp_path, REACQUIRE_SRC)
        assert [v.rule for v in rep.violations] == ["KTSAN03"]

    def test_clean_nesting_passes_and_extracts_edges(self, tmp_path):
        rep = _analyze_src(tmp_path, CLEAN_SRC)
        assert rep.violations == [] and rep.cycles == []
        pairs = {(e.src, e.dst) for e in rep.edges}
        assert ("fx.S._lock", "fx.S._aux") in pairs

    def test_init_is_exempt(self, tmp_path):
        src = LOCKED_SRC.replace(
            "    def bad(self):\n        self._bump_locked()",
            "",
        ) + (
            "\n"
            "class T(S):\n"
            "    def __init__(self):\n"
            "        super().__init__()\n"
            "        self._bump_locked()\n"
        )
        rep = _analyze_src(tmp_path, src)
        assert rep.violations == []

    def test_runtime_graph_merges_into_cycle(self, tmp_path):
        # Static half of the cycle from the fixture, runtime half from
        # a sanitizer report: only together do they close the loop.
        src = INVERSION_SRC.replace(
            "    def ba(self):\n"
            "        with self._b._lock:\n"
            "            with self._a._lock:\n"
            "                pass\n",
            "    def ba(self):\n"
            "        pass\n",
        )
        rep = _analyze_src(tmp_path, src)
        assert rep.cycles == []
        runtime = {
            "edges": [
                {"from": "fx.b", "to": "fx.a", "count": 3,
                 "site": "observed in test run"}
            ],
            "findings": [],
        }
        rep2 = _analyze_src(tmp_path, src, runtime=runtime)
        assert rep2.cycles and set(rep2.cycles[0]["nodes"]) == {
            "fx.a", "fx.b"
        }

    def test_runtime_findings_fail_the_gate(self, tmp_path):
        rep = _analyze_src(
            tmp_path, CLEAN_SRC,
            runtime={"edges": [], "findings": [
                {"kind": "blocking-under-lock", "op": "fsync",
                 "locks": ["x"]}
            ]},
        )
        assert rep.exit_code == 1


# -- static: KT006 ------------------------------------------------------


def _ops_ctx(src, relpath):
    tree = ast.parse(src)
    return FileContext(
        pathlib.Path("/nonexistent"), relpath, tree, src.splitlines()
    )


class TestKT006:
    def test_unregistered_kernel_fires(self):
        src = (
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, static_argnames=('n',))\n"
            "def brand_new_kernel(x, n):\n"
            "    return x\n"
        )
        ctx = _ops_ctx(src, "kubernetes_tpu/ops/fake.py")
        findings = OracleTwinRule().check(ctx)
        assert [f.rule for f in findings] == ["KT006"]
        assert "fake.brand_new_kernel" in findings[0].message

    def test_nested_jit_is_found(self):
        src = (
            "import functools\n"
            "import jax\n"
            "def factory():\n"
            "    @jax.jit\n"
            "    def kernel(x):\n"
            "        return x\n"
            "    return kernel\n"
        )
        keys = [k for k, _l in jitted_kernels(ast.parse(src), "fake")]
        assert keys == ["fake.factory.kernel"]

    def test_stale_registry_key_fires(self):
        rule = OracleTwinRule()
        ctx = _ops_ctx("ORACLE_TWINS = {}\n", "kubernetes_tpu/ops/parity.py")
        findings = rule._check_registry(
            ctx,
            {"solver.kernel_that_never_existed": {
                "oracle": "ops.oracle.solve_sequential_numpy",
                "suite": "tests/test_solver_parity.py"}},
            {"solver.kernel_that_never_existed": 1},
        )
        assert findings and "stale" in findings[0].message

    def test_unresolvable_oracle_fires(self):
        rule = OracleTwinRule()
        ctx = _ops_ctx("ORACLE_TWINS = {}\n", "kubernetes_tpu/ops/parity.py")
        findings = rule._check_registry(
            ctx,
            {"solver._solve_xla": {
                "oracle": "ops.oracle.no_such_twin",
                "suite": "tests/test_solver_parity.py"}},
            {"solver._solve_xla": 1},
        )
        assert findings and "does not resolve" in findings[0].message

    def test_oracle_resolution_helper(self):
        assert resolve_oracle("ops.oracle.solve_sequential_numpy")
        assert resolve_oracle("scheduler.gang.member_counts_host")
        assert resolve_oracle("ops.oracle.nope_nope") is None

    def test_registry_resolves_at_runtime(self):
        """Static strings stay honest: every oracle imports, every
        kernel key's module + top-level symbol exist."""
        import importlib

        from kubernetes_tpu.ops.parity import ORACLE_TWINS

        assert ORACLE_TWINS, "registry must not be empty"
        for key, entry in ORACLE_TWINS.items():
            mod_name, rest = key.split(".", 1)
            mod = importlib.import_module(f"kubernetes_tpu.ops.{mod_name}")
            top = rest.split(".", 1)[0]
            assert hasattr(mod, top), f"{key}: {top} missing in ops/{mod_name}"
            omod_path, ofunc = entry["oracle"].rsplit(".", 1)
            omod = importlib.import_module(
                f"kubernetes_tpu.{omod_path}"
                if not omod_path.startswith("tests") else omod_path
            )
            assert callable(getattr(omod, ofunc)), entry["oracle"]
            assert os.path.exists(os.path.join(ROOT, entry["suite"]))


# -- live-tree gates (the acceptance criterion) -------------------------


class TestLiveTree:
    def test_lock_graph_clean_on_live_tree(self):
        """Zero lock-order cycles, zero interprocedural *_locked
        violations on kubernetes_tpu/ — ktsan's static baseline is
        EMPTY and must stay empty (pragma with a reason, or fix)."""
        rep = lockgraph.analyze()
        assert rep.cycles == [], rep.render()
        assert rep.violations == [], rep.render()
        # It audited real code: locks inventoried, edges extracted,
        # and the one documented pragma grant is visible.
        assert len(rep.locks) >= 20
        assert rep.edges, "no ordering edges extracted?"
        assert rep.suppressed >= 1

    def test_lock_graph_cli(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.ktlint", "--lock-graph",
             "--format=json"],
            capture_output=True, text=True, timeout=120, cwd=ROOT,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        data = json.loads(proc.stdout)
        assert data["cycles"] == [] and data["violations"] == []
        assert data["counts"]["KTSAN01"] == 0

    def test_kt006_clean_on_live_tree(self):
        from tools import ktlint

        rep = ktlint.lint(select=["KT006"], baseline_path=None)
        assert rep.findings == [], [f.render() for f in rep.findings]


# -- scatter twin parity ------------------------------------------------


def test_scatter_rows_parity():
    import jax.numpy as jnp

    from kubernetes_tpu.ops.incremental import _scatter_rows
    from kubernetes_tpu.ops.oracle import scatter_rows_numpy

    rng = np.random.default_rng(0)
    host = {
        "a": rng.standard_normal((16, 4)).astype(np.float32),
        "b": rng.integers(0, 100, size=16).astype(np.int32),
    }
    idx = np.array([3, 7, 11], np.int32)
    rows = {
        "a": rng.standard_normal((3, 4)).astype(np.float32),
        "b": rng.integers(0, 100, size=3).astype(np.int32),
    }
    want = scatter_rows_numpy(host, idx, rows)
    got = _scatter_rows(
        {k: jnp.asarray(v) for k, v in host.items()},
        jnp.asarray(idx),
        {k: jnp.asarray(v) for k, v in rows.items()},
    )
    for k in host:
        np.testing.assert_array_equal(np.asarray(got[k]), want[k])


# -- recompilation sentinel ---------------------------------------------


class TestRecompilationSentinel:
    def test_bounded_compiles_across_randomized_backlogs(self):
        """The pow2/static-bucketing contract, asserted where it
        bites: N randomized backlog shapes must funnel into a handful
        of padded shapes, and the solver must compile AT MOST once per
        padded shape (jit cache-size delta). A bucketing regression
        (padding by exact size, a dtype wobble, a non-static arg)
        fails this immediately instead of as a mystery slowdown."""
        import random

        import jax

        from kubernetes_tpu.models.columnar import build_snapshot
        from kubernetes_tpu.ops import device_snapshot, solve_assignments
        from kubernetes_tpu.ops.solver import _solve_xla
        from test_solver_parity import mk_node, mk_pod

        jax.clear_caches()
        assert _solve_xla._cache_size() == 0
        rng = random.Random(0xA11CE)
        padded_shapes = set()
        runs = 0
        for _ in range(10):
            P = rng.randint(1, 600)
            N = rng.randint(1, 40)
            pods = [
                mk_pod(f"p{i}", cpu=rng.choice([50, 100, 250]))
                for i in range(P)
            ]
            nodes = [mk_node(f"n{j}") for j in range(N)]
            snap = build_snapshot(pods, nodes)
            d = device_snapshot(snap)
            out = solve_assignments(d)
            assert len(out) == P
            padded_shapes.add(
                (d.pods["cpu"].shape[0], d.nodes["cpu_cap"].shape[0])
            )
            runs += 1
        # Bucketing must coalesce: 10 random shapes, few padded ones.
        assert len(padded_shapes) < runs
        assert len(padded_shapes) <= 4  # pow2 buckets for P<=600, N<=40
        compiles = _solve_xla._cache_size()
        assert compiles <= len(padded_shapes), (
            f"{compiles} compiles for {len(padded_shapes)} padded shapes "
            f"({sorted(padded_shapes)}) — shape bucketing regressed"
        )
