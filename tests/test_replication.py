"""HA control plane: WAL-shipping replication (store/replication.py),
follower promotion, stateless apiserver fan-out with write forwarding,
client endpoint rotation, and Reflector watch resume.

The raft-lite contract under test: a write acked to a client is
durable on a quorum, and a follower promoted at ANY instant exposes
exactly the committed prefix — byte-identical WAL, never a torn or
unacked record."""

import json
import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport, Reflector
from kubernetes_tpu.client.cache import ThreadSafeStore
from kubernetes_tpu.client.rest import HTTPTransport
from kubernetes_tpu.server.api import APIError, APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.store.kvstore import KVStore
from kubernetes_tpu.store.replication import (
    FollowerReplica,
    HTTPLink,
    LocalLink,
    ReplicationError,
    ReplicationHub,
)


def wait_until(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def pod_wire(name, ns="default"):
    return {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"containers": [{"name": "c", "image": "nginx"}]},
    }


class PartitionableLink(LocalLink):
    """LocalLink with a partition switch (the shipper sees a dead
    link; the follower simply stops receiving)."""

    def __init__(self, replica, name="follower"):
        super().__init__(replica, name)
        self.partitioned = False

    def append(self, lines, commit):
        if self.partitioned:
            raise ConnectionError(f"{self.name}: partitioned")
        return super().append(lines, commit)


def _wal_bytes(store):
    with open(store._wal_path, "rb") as f:
        return f.read()


class TestWALShipping:
    def test_quorum_ack_and_follower_convergence(self):
        leader = KVStore()
        hub = ReplicationHub(leader).attach()
        api = APIServer(store=leader)
        api.replication = hub
        f1, f2 = FollowerReplica(name="f1"), FollowerReplica(name="f2")
        hub.add_follower(LocalLink(f1, "f1"))
        hub.add_follower(LocalLink(f2, "f2"))
        c = Client(LocalTransport(api))
        for i in range(20):
            c.create("pods", pod_wire(f"p{i}"))  # acks only at quorum
        # Acked writes are quorum-committed by definition of the gate.
        assert hub.commit_index == leader.version
        # Both followers converge to the full log and apply the
        # committed prefix into their live mirrors.
        assert wait_until(
            lambda: f1.store.journaled_version == leader.version
            and f2.store.journaled_version == leader.version
        )
        assert wait_until(
            lambda: f1.store.version == leader.version
            and f2.store.version == leader.version
        )
        st = hub.status()
        assert st["role"] == "leader"
        assert {f["name"] for f in st["followers"]} == {"f1", "f2"}
        assert all(f["alive"] for f in st["followers"])
        hub.stop()

    def test_single_node_cluster_acks_alone(self):
        """No followers: local fsync IS quorum (majority of 1)."""
        leader = KVStore()
        ReplicationHub(leader).attach()
        api = APIServer(store=leader)
        c = Client(LocalTransport(api))
        c.create("pods", pod_wire("solo"))
        assert c.get("pods", "solo", namespace="default") is not None

    def test_one_dead_follower_does_not_block_acks(self):
        """3-replica cluster (leader + 2): majority is 2, so one
        partitioned follower lags alone while writes keep acking."""
        leader = KVStore()
        hub = ReplicationHub(leader, ack_timeout_s=5.0).attach()
        api = APIServer(store=leader)
        f1, f2 = FollowerReplica(name="f1"), FollowerReplica(name="f2")
        l1 = PartitionableLink(f1, "f1")
        hub.add_follower(l1)
        hub.add_follower(LocalLink(f2, "f2"))
        l1.partitioned = True
        c = Client(LocalTransport(api))
        for i in range(5):
            c.create("pods", pod_wire(f"p{i}"))
        assert hub.commit_index == leader.version
        # Heal the partition: the lagging follower catches up.
        l1.partitioned = False
        assert wait_until(
            lambda: f1.store.version == leader.version
        )
        hub.stop()

    def test_lost_quorum_refuses_to_ack(self):
        """2-replica cluster (leader + 1): majority is 2. With the
        only follower partitioned the write journals locally but the
        ack times out — exactly a raft leader losing its quorum."""
        leader = KVStore()
        hub = ReplicationHub(leader, ack_timeout_s=0.4).attach()
        api = APIServer(store=leader)
        f1 = FollowerReplica(name="f1")
        link = PartitionableLink(f1, "f1")
        hub.add_follower(link)
        link.partitioned = True
        c = Client(LocalTransport(api))
        with pytest.raises(ReplicationError):
            c.create("pods", pod_wire("unacked"))
        hub.stop()


class TestPromotion:
    def test_promoted_follower_byte_identical_committed_prefix(
        self, tmp_path
    ):
        """The acceptance oracle: after leader crash, the promoted
        follower's WAL is byte-identical to the committed prefix of
        the leader's WAL, and the promoted store serves every acked
        write (snapshot rotation disabled so the WAL holds the full
        history on both sides)."""
        leader = KVStore(
            data_dir=str(tmp_path / "leader"), snapshot_every=10**9
        )
        hub = ReplicationHub(leader).attach()
        f1 = FollowerReplica(
            store=KVStore(
                data_dir=str(tmp_path / "f1"), snapshot_every=10**9
            ),
            name="f1",
        )
        # Follower joins BEFORE the first write so every record ships
        # as a WAL line (a late joiner bootstraps from dump_state and
        # only the post-join suffix is byte-comparable).
        hub.add_follower(LocalLink(f1, "f1"))
        api = APIServer(store=leader)
        c = Client(LocalTransport(api))
        for i in range(30):
            c.create("pods", pod_wire(f"p{i}"))
        acked_version = leader.version
        assert wait_until(
            lambda: f1.store.journaled_version == acked_version
        )
        leader_wal = _wal_bytes(leader)
        leader.crash()
        promoted = f1.promote()
        # Byte-identical committed prefix: every acked record, no
        # torn tail.
        follower_wal = _wal_bytes(promoted)
        assert follower_wal == leader_wal[: len(follower_wal)]
        assert promoted.version == acked_version
        # The promoted store serves every acked write...
        new_api = APIServer(store=promoted)
        nc = Client(LocalTransport(new_api))
        pods, _ = nc.list("pods", namespace="default")
        assert {p.metadata.name for p in pods} >= {
            f"p{i}" for i in range(30)
        }
        # ...and is writable (a new leader).
        nc.create("pods", pod_wire("after-failover"))
        assert nc.get("pods", "after-failover", namespace="default")

    def test_unacked_write_never_exposed_after_promote(self, tmp_path):
        """A write that journals on the leader but never reaches
        quorum is NOT acked — and a follower promoted afterwards must
        not expose it (the torn-record half of the oracle)."""
        leader = KVStore(
            data_dir=str(tmp_path / "leader"), snapshot_every=10**9
        )
        hub = ReplicationHub(leader, ack_timeout_s=0.4).attach()
        f1 = FollowerReplica(
            store=KVStore(
                data_dir=str(tmp_path / "f1"), snapshot_every=10**9
            ),
            name="f1",
        )
        link = PartitionableLink(f1, "f1")
        hub.add_follower(link)
        api = APIServer(store=leader)
        c = Client(LocalTransport(api))
        for i in range(10):
            c.create("pods", pod_wire(f"acked{i}"))
        acked_version = leader.version
        assert wait_until(
            lambda: f1.store.journaled_version == acked_version
        )
        link.partitioned = True
        with pytest.raises(ReplicationError):
            c.create("pods", pod_wire("torn"))
        assert leader.version > acked_version  # journaled locally...
        promoted = f1.promote()
        assert promoted.version == acked_version  # ...but never here
        leader_wal = _wal_bytes(leader)
        follower_wal = _wal_bytes(promoted)
        assert follower_wal == leader_wal[: len(follower_wal)]
        assert len(follower_wal) < len(leader_wal)
        nc = Client(LocalTransport(APIServer(store=promoted)))
        with pytest.raises(APIError):
            nc.get("pods", "torn", namespace="default")

    def test_promoted_follower_rejects_stale_leader(self):
        """A stale leader shipping into a promoted follower gets a
        hard refusal, not a silent divergence."""
        f1 = FollowerReplica(name="f1")
        f1.promote()
        with pytest.raises(ReplicationError):
            f1.append([], 5)
        assert f1.status()["role"] == "leader"


class TestHTTPPlane:
    """N stateless apiservers over the replication plane: reads fan
    out on every replica's watch cache, writes forward to the leader,
    /replication rides the same HTTP plane, /healthz reports the
    replication subcheck."""

    def _cluster(self):
        leader_store = KVStore()
        leader_api = APIServer(store=leader_store)
        leader_http = APIHTTPServer(leader_api).start()
        hub = ReplicationHub(leader_store).attach()
        leader_api.replication = hub
        followers = []
        for name in ("f1", "f2"):
            rep = FollowerReplica(name=name)
            api = APIServer(store=rep.store)
            api.replication = rep
            api.leader_url = leader_http.address
            http = APIHTTPServer(api).start()
            hub.add_follower(HTTPLink(http.address, name=name))
            followers.append((rep, api, http))
        return leader_store, leader_api, leader_http, hub, followers

    def test_forwarded_write_and_fanout_read(self):
        _store, _api, leader_http, hub, followers = self._cluster()
        f1_http = followers[0][2]
        try:
            # Write through a FOLLOWER endpoint: forwarded to the
            # leader, acked at quorum, then readable from the same
            # follower's own watch cache.
            c = Client(HTTPTransport(f1_http.address))
            c.create("pods", pod_wire("fwd"))
            assert wait_until(
                lambda: any(
                    p.metadata.name == "fwd"
                    for p in c.list("pods", namespace="default")[0]
                )
            )
            # Writes through the leader replicate out to followers.
            lc = Client(HTTPTransport(leader_http.address))
            lc.create("pods", pod_wire("direct"))
            assert wait_until(
                lambda: any(
                    p.metadata.name == "direct"
                    for p in c.list("pods", namespace="default")[0]
                )
            )
        finally:
            hub.stop()
            leader_http.stop()
            for _, _, http in followers:
                http.stop()

    def test_forwarded_write_shares_one_trace_id(self):
        """A write through a follower is ONE operation: the follower's
        request-log entry and the leader's carry the SAME trace id —
        minted on the follower when the client sent no X-Trace-Id, and
        reused verbatim when it did (before the fix, an unstamped
        forwarded mutation appeared as two unrelated requests at
        /debug/requests)."""
        import urllib.request

        from kubernetes_tpu.utils import debug

        _store, _api, leader_http, hub, followers = self._cluster()
        f1_http = followers[0][2]
        try:
            c = Client(HTTPTransport(f1_http.address))
            c.create("pods", pod_wire("traced"))
            posts = [
                e for e in list(debug.DEFAULT_REQUEST_LOG._ring)
                if e[1] == "POST" and e[2].endswith("/pods")
            ]
            # The leader's hop logs first (it responds before the
            # follower's own finally runs), then the follower's.
            assert len(posts) >= 2
            tids = {e[5] for e in posts[-2:]}
            assert len(tids) == 1, posts[-2:]
            assert tids.pop(), "trace id was never minted on the hop"
            # A client-stamped id is reused verbatim across both hops.
            req = urllib.request.Request(
                f1_http.address + "/api/v1/namespaces/default/pods",
                data=json.dumps(pod_wire("traced2")).encode(),
                headers={
                    "Content-Type": "application/json",
                    "X-Trace-Id": "trace-fwd-regress",
                },
                method="POST",
            )
            urllib.request.urlopen(req, timeout=10).read()
            stamped = [
                e for e in list(debug.DEFAULT_REQUEST_LOG._ring)
                if e[5] == "trace-fwd-regress"
            ]
            assert len(stamped) == 2  # follower hop + leader hop
        finally:
            hub.stop()
            leader_http.stop()
            for _, _, http in followers:
                http.stop()

    def test_healthz_replication_subcheck(self):
        import urllib.request

        _store, _api, leader_http, hub, followers = self._cluster()
        try:
            h = json.loads(
                urllib.request.urlopen(
                    leader_http.address + "/healthz"
                ).read()
            )
            rep = h["checks"]["replication"]
            assert rep["status"] == "ok"
            assert rep["role"] == "leader"
            assert set(rep["followerLag"]) == {"f1", "f2"}
            fh = json.loads(
                urllib.request.urlopen(
                    followers[0][2].address + "/healthz"
                ).read()
            )
            assert fh["checks"]["replication"]["role"] == "follower"
            st = json.loads(
                urllib.request.urlopen(
                    followers[0][2].address + "/replication/status"
                ).read()
            )
            assert st["role"] == "follower"
            assert "journaled" in st
        finally:
            hub.stop()
            leader_http.stop()
            for _, _, http in followers:
                http.stop()


class TestEndpointRotation:
    def test_client_rotates_on_dead_endpoint(self):
        """Two stateless apiservers over ONE store; killing the one
        the client is pinned to rotates reads to the survivor inside
        the retry loop — no caller-visible failure."""
        store = KVStore()
        api = APIServer(store=store)
        s1 = APIHTTPServer(api).start()
        s2 = APIHTTPServer(api).start()
        try:
            from urllib.parse import urlparse

            t = HTTPTransport([s1.address, s2.address])
            c = Client(t)
            c.create("pods", pod_wire("p0"))
            u1, u2 = urlparse(s1.address), urlparse(s2.address)
            assert (t.host, t.port) == (u1.hostname, u1.port)
            s1.stop(release_store=False)
            got = c.get("pods", "p0", namespace="default")
            assert got.metadata.name == "p0"
            assert (t.host, t.port) == (u2.hostname, u2.port)
        finally:
            for s in (s1, s2):
                try:
                    s.stop()
                except Exception:
                    pass

    def test_transport_accepts_single_url_string(self):
        t = HTTPTransport("http://127.0.0.1:1")
        assert t.endpoints == [("127.0.0.1", 1)]
        with pytest.raises(ValueError):
            HTTPTransport([])


class TestWatchResume:
    def test_resume_skips_full_relist_after_rotation(self):
        """The satellite regression: a Reflector whose endpoint dies
        mid-watch rotates and RESUMES the watch from its last
        resourceVersion — list_count stays 1 and later events still
        arrive."""
        store = KVStore()
        api = APIServer(store=store)
        s1 = APIHTTPServer(api).start()
        s2 = APIHTTPServer(api).start()
        refl = None
        try:
            c = Client(HTTPTransport([s1.address, s2.address]))
            c.create("pods", pod_wire("pre"))
            cache = ThreadSafeStore()
            refl = Reflector(c, "pods", cache, namespace="default").start()
            assert refl.wait_for_sync(10)
            assert refl.list_count == 1
            s1.stop(release_store=False)  # kill the watched endpoint
            wc = Client(HTTPTransport(s2.address))
            wc.create("pods", pod_wire("post-rotation"))
            assert wait_until(
                lambda: cache.get("default/post-rotation") is not None
            ), "event after rotation never arrived"
            assert refl.list_count == 1, (
                "rotation must resume the watch, not re-LIST"
            )
        finally:
            if refl is not None:
                refl.stop()
            for s in (s1, s2):
                try:
                    s.stop()
                except Exception:
                    pass

    def test_compacted_resume_falls_back_to_relist(self):
        """When the resume version has been compacted out of watch
        history the server answers 410 Gone and the Reflector falls
        back to a full re-LIST, converging anyway. Driven at the
        cycle seam (one _list_and_watch call per cycle) so the
        outage window is deterministic."""
        api = APIServer(store=KVStore(history_limit=4))
        c = Client(LocalTransport(api))
        c.create("pods", pod_wire("pre"))
        cache = ThreadSafeStore()
        refl = Reflector(c, "pods", cache, namespace="default")
        refl._list()
        assert refl.list_count == 1
        # A prior cycle reached its watch phase, then the transport
        # failed (endpoint rotation): the next cycle tries to resume.
        refl._resume_watch = True
        # Meanwhile the cluster churns far past the history window.
        for i in range(40):
            c.create("pods", pod_wire(f"burst{i}"))
        # The resume attempt 410s and demands a fresh cycle with a
        # full LIST (no list happened in THIS cycle).
        assert refl._list_and_watch() is True
        assert refl.list_count == 1
        assert refl._resume_watch is False
        # The fresh cycle re-LISTs and converges (stop is set so the
        # cycle ends after its list half instead of blocking in the
        # watch loop).
        refl._stop.set()
        refl._list_and_watch()
        assert refl.list_count == 2
        assert cache.get("default/burst39") is not None
