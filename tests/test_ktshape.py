"""ktshape (tools/ktlint/ktshape.py + kubernetes_tpu/ops/contracts.py):
the kernel shape/dtype/sharding contract checker.

Three layers, mirroring the ktlint/ktsan test conventions:

- KT007 AST fixtures: violate / pass / pragma per check (host
  round-trips in trace-time helpers, unbucketed device dims,
  dtype-unpinned literal arrays);
- abstract-interpretation fixtures driven through check_kernel: a
  dtype-drifted kernel caught by eval_shape, a weak-literal kernel
  caught by the jaxpr walk (the before/after shape of the wave.py
  sweep fix), and a fake `pod_axis: shardable` kernel with a cross-pod
  segment_sum caught by the coupling classifier;
- live-tree gates: every ORACLE_TWINS kernel is contracted (and vice
  versa), `python -m tools.ktlint --kernel-contracts` exits 0 with
  zero findings, the checker performs ZERO kernel executions, and the
  ledger's observed staged-shape signatures join back against the
  contracts (the /debug/kernels CONTRACT column).
"""

import json
import pathlib
import subprocess
import sys
import textwrap

import numpy as np
import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))  # tools/ is a repo-root namespace package

from tools import ktlint  # noqa: E402
from tools.ktlint import ktshape  # noqa: E402
from tools.ktlint.framework import run as lint_run  # noqa: E402

pytestmark = pytest.mark.ktshape


def lint_src(tmp_path, source, relname="ops/x.py"):
    """Lint one fixture file with KT007 only; returns the Report."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return lint_run([path], ktlint.rules_by_id(["KT007"]), baseline=None)


# -- KT007: host round-trips in trace-time helpers ---------------------


class TestKT007TracedHelpers:
    def test_detects_sync_in_reachable_helper(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax
            import numpy as np

            def _helper(x):
                y = np.asarray(x)
                return y.item()

            @jax.jit
            def kernel(x):
                return _helper(x) + 1
            """,
        )
        msgs = "\n".join(f.message for f in rep.findings)
        assert "np.asarray" in msgs
        assert ".item()" in msgs
        assert "trace-time helper of jitted kernel()" in msgs

    def test_callback_reference_joins_the_closure(self, tmp_path):
        # A helper passed BY NAME (never called directly) is still
        # traced — the wave family's `choose` callbacks ride this way.
        rep = lint_src(
            tmp_path,
            """\
            import jax

            def _choose(x):
                return int(x)

            def _loop(x, choose):
                return choose(x)

            @jax.jit
            def kernel(x):
                return _loop(x, _choose)
            """,
        )
        assert len(rep.findings) == 1
        assert "int(x)" in rep.findings[0].message

    def test_unreachable_host_helper_passes(self, tmp_path):
        # Host-side wrappers AROUND the kernel may sync freely.
        rep = lint_src(
            tmp_path,
            """\
            import jax
            import numpy as np

            @jax.jit
            def kernel(x):
                return x * 2

            def wrapper(x):
                return np.asarray(kernel(x)).item()
            """,
        )
        assert rep.findings == []

    def test_out_of_scope_dir_ignored(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax

            def _helper(x):
                return float(x)

            @jax.jit
            def kernel(x):
                return _helper(x)
            """,
            relname="models/x.py",
        )
        assert rep.findings == []

    def test_pragma_suppresses(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax

            def _helper(x):
                return float(x)  # ktlint: disable=KT007

            @jax.jit
            def kernel(x):
                return _helper(x)
            """,
        )
        assert rep.findings == []
        assert len(rep.suppressed) == 1


# -- KT007: unbucketed device dims -------------------------------------


class TestKT007UnbucketedDims:
    def test_detects_len_and_count_dims(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax.numpy as jnp

            def stage(backlog, cols):
                a = jnp.zeros(len(backlog))
                b = jnp.full(cols.count, -1.0)
                c = jnp.arange(len(backlog))
                return a, b, c
            """,
        )
        msgs = "\n".join(f.message for f in rep.findings)
        assert len(rep.findings) == 3
        assert "len(...)" in msgs
        assert ".count" in msgs
        assert "pow2_bucket" in msgs

    def test_shape_keyword_is_scanned_too(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax.numpy as jnp

            def stage(backlog):
                return jnp.zeros(shape=(len(backlog), 4))
            """,
        )
        assert len(rep.findings) == 1
        assert "len(...)" in rep.findings[0].message

    def test_bucketed_dims_pass(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax.numpy as jnp
            from kubernetes_tpu.ops.matrices import pow2_bucket

            def stage(backlog, arr):
                a = jnp.zeros(pow2_bucket(len(backlog)))
                b = jnp.zeros(arr.shape[0])
                c = jnp.zeros((128, 8), dtype=jnp.float32)
                return a, b, c
            """,
        )
        assert rep.findings == []

    def test_pragma_suppresses(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax.numpy as jnp

            def stage(backlog):
                return jnp.zeros(len(backlog))  # ktlint: disable=KT007
            """,
        )
        assert rep.findings == []
        assert len(rep.suppressed) == 1


# -- KT007: dtype-unpinned literal arrays ------------------------------


class TestKT007UntypedArrays:
    def test_detects_bare_array_and_literal_asarray(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax.numpy as jnp

            A = jnp.array([1, 2, 3])
            B = jnp.asarray([1.0, 2.0])
            """,
        )
        assert len(rep.findings) == 2
        msgs = "\n".join(f.message for f in rep.findings)
        assert "without dtype=" in msgs

    def test_pinned_and_array_sourced_pass(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax.numpy as jnp

            def f(host_arr):
                a = jnp.array([1, 2, 3], dtype=jnp.int32)
                b = jnp.asarray(host_arr)  # dtype rides the array
                c = jnp.asarray(host_arr, dtype=jnp.float32)
                return a, b, c
            """,
        )
        assert rep.findings == []

    def test_pragma_suppresses(self, tmp_path):
        rep = lint_src(
            tmp_path,
            """\
            import jax.numpy as jnp

            A = jnp.array([1, 2, 3])  # ktlint: disable=KT007
            """,
        )
        assert rep.findings == []
        assert len(rep.suppressed) == 1


# -- contracts: signature matching -------------------------------------


class TestSignatures:
    def test_leaf_signature_format(self):
        from kubernetes_tpu.ops import contracts

        assert contracts.leaf_signature(np.zeros((4, 2), np.uint32)) == (
            "u32[4,2]"
        )
        assert contracts.leaf_signature(np.zeros((), np.float32)) == "f32[]"
        assert contracts.leaf_signature(7) == "7"

    def test_gang_signature_match_and_lattice_drift(self):
        from kubernetes_tpu.ops import contracts

        ok, detail = contracts.match_signature(
            "matrices.gang_member_counts", "b8[16],i32[16],8"
        )
        assert ok, detail
        ok, detail = contracts.match_signature(
            "matrices.gang_member_counts", "b8[24],i32[24],8"
        )
        assert not ok and "off its bucket lattice" in detail

    def test_dtype_drift_is_a_mismatch(self):
        from kubernetes_tpu.ops import contracts

        ok, detail = contracts.match_signature(
            "matrices.gang_member_counts", "f32[16],i32[16],8"
        )
        assert not ok and "observed" in detail

    def test_solver_signature_roundtrip_with_optional_leaf(self):
        # A signature generated FROM the contract matches it, and an
        # optional policy leaf (aff_pin) may ride along or not.
        from kubernetes_tpu.ops import contracts

        c = contracts.CONTRACTS["solver._solve_xla"]
        bindings = dict(c.samples[0])
        args, kwargs = contracts.abstract_args(c, bindings)
        sig = contracts.shape_signature(args, kwargs)
        ok, detail = contracts.match_signature("solver._solve_xla", sig)
        assert ok, detail
        import jax

        args[0]["aff_pin"] = jax.ShapeDtypeStruct(
            (bindings["P"], 3), np.int32
        )
        sig2 = contracts.shape_signature(args, kwargs)
        ok, detail = contracts.match_signature("solver._solve_xla", sig2)
        assert ok, detail

    def test_verdict_strings(self):
        from kubernetes_tpu.ops import contracts

        assert contracts.contract_verdict("nope.kernel", "") == (
            "uncontracted"
        )
        assert contracts.contract_verdict(
            "matrices.gang_member_counts", "b8[16],i32[16],8"
        ) == "ok"
        assert contracts.contract_verdict(
            "matrices.gang_member_counts", "b8[24],i32[24],8"
        ).startswith("mismatch")


# -- abstract-interpretation fixtures ----------------------------------


def _fixture_contract(results, pod_axis="shardable", dims=("P",),
                      dtype="f32"):
    from kubernetes_tpu.ops import contracts

    return contracts.Contract(
        kernel="fixture.k",
        args=(("x", contracts.ArraySpec(tuple(dims), dtype)),),
        results=results,
        pod_dim="P",
        pod_axis=pod_axis,
        samples=({"P": 128},),
    )


class TestAbstractEval:
    def test_dtype_drifted_kernel_is_caught(self):
        from kubernetes_tpu.ops import contracts
        from kubernetes_tpu.ops.ledger import traced_jit

        @traced_jit
        def k(x):
            return x * 2.0  # f32, but the contract (oracle) says i32

        findings = ktshape.check_kernel(
            "fixture.k", k,
            _fixture_contract(contracts.ArraySpec(("P",), "i32")),
        )
        assert any(
            f.check == "abstract-eval" and "drifted" in f.message
            for f in findings
        ), findings

    def test_shape_drift_is_caught(self):
        from kubernetes_tpu.ops import contracts
        from kubernetes_tpu.ops.ledger import traced_jit

        @traced_jit
        def k(x):
            return x[: x.shape[0] // 2]

        findings = ktshape.check_kernel(
            "fixture.k", k,
            _fixture_contract(contracts.ArraySpec(("P",), "f32")),
        )
        assert any(f.check == "abstract-eval" for f in findings), findings

    def test_weak_literal_materialization_caught_and_fix_clean(self):
        # The before/after shape of the wave.py sweep fix: bare int
        # literals in a branch-select materialize a weak i32[P].
        import jax.numpy as jnp

        from kubernetes_tpu.ops import contracts
        from kubernetes_tpu.ops.ledger import traced_jit

        @traced_jit
        def before(x):
            return x + jnp.where(x > 0, -1, -2)

        @traced_jit
        def after(x):
            return x + jnp.where(x > 0, jnp.int32(-1), jnp.int32(-2))

        spec = _fixture_contract(contracts.ArraySpec(("P",), "f32"))
        findings = ktshape.check_kernel("fixture.k", before, spec)
        assert any(f.check == "weak-type" for f in findings), findings
        assert ktshape.check_kernel("fixture.k", after, spec) == []

    def test_fake_shardable_segment_sum_caught(self):
        import jax

        from kubernetes_tpu.ops import contracts
        from kubernetes_tpu.ops.ledger import traced_jit

        @traced_jit(static_argnames=("num_groups",))
        def fake(placed, gids, num_groups):
            return jax.ops.segment_sum(
                placed.astype("int32"),
                jax.numpy.clip(gids, 0, num_groups - 1),
                num_segments=num_groups,
            )

        c = contracts.Contract(
            kernel="fixture.fake",
            args=(
                ("placed", contracts.ArraySpec(("PG",), "b8")),
                ("gids", contracts.ArraySpec(("PG",), "i32")),
            ),
            results=contracts.ArraySpec(("G",), "i32"),
            pod_dim="PG",
            pod_axis="shardable",  # a lie: segment_sum couples pods
            samples=({"PG": 8, "G": 8},),
            kwargs=(("num_groups", contracts.DimRef("G")),),
        )
        findings = ktshape.check_kernel("fixture.fake", fake, c)
        assert any(
            f.check == "pod-axis" and "declared shardable" in f.message
            for f in findings
        ), findings

    def test_honest_shardable_passes_and_stale_reduces_flagged(self):
        from kubernetes_tpu.ops import contracts
        from kubernetes_tpu.ops.ledger import traced_jit

        @traced_jit
        def k(x):
            return x * 2

        spec_ok = _fixture_contract(contracts.ArraySpec(("P",), "f32"))
        assert ktshape.check_kernel("fixture.k", k, spec_ok) == []
        spec_stale = _fixture_contract(
            contracts.ArraySpec(("P",), "f32"), pod_axis="reduces"
        )
        findings = ktshape.check_kernel("fixture.k", k, spec_stale)
        assert any(
            f.check == "pod-axis" and "tighten" in f.message
            for f in findings
        ), findings

    def test_off_lattice_sample_rejected(self):
        from kubernetes_tpu.ops import contracts
        from kubernetes_tpu.ops.ledger import traced_jit

        @traced_jit
        def k(x):
            return x * 2

        c = contracts.Contract(
            kernel="fixture.k",
            args=(("x", contracts.ArraySpec(("P",), "f32")),),
            results=contracts.ArraySpec(("P",), "f32"),
            pod_dim="P",
            pod_axis="shardable",
            samples=({"P": 100},),  # 100 is not a pow2 bucket
        )
        findings = ktshape.check_kernel("fixture.k", k, c)
        assert any(
            f.check == "completeness" and "lattice" in f.message
            for f in findings
        ), findings


# -- live-tree gates ----------------------------------------------------


class TestLiveTree:
    def test_registry_completeness_both_ways(self):
        from kubernetes_tpu.ops import contracts
        from kubernetes_tpu.ops.parity import ORACLE_TWINS

        assert set(contracts.CONTRACTS) == set(ORACLE_TWINS)
        for key, c in contracts.CONTRACTS.items():
            assert c.kernel == key
            assert c.pod_axis in contracts.POD_AXIS_KINDS

    def test_completeness_findings_on_registry_drift(self):
        from kubernetes_tpu.ops import contracts

        stale = contracts.Contract(
            kernel="solver._gone_kernel",
            args=(("x", contracts.ArraySpec(("P",), "f32")),),
            results=contracts.ArraySpec(("P",), "f32"),
            pod_dim="P",
            pod_axis="shardable",
            samples=({"P": 128},),
        )
        contracts.CONTRACTS["solver._gone_kernel"] = stale
        missing = contracts.CONTRACTS.pop("solver.explain_rows")
        try:
            rep = ktshape.analyze(kernels=[])
            checks = {
                (f.kernel, f.check) for f in rep.findings
            }
            assert ("solver._gone_kernel", "completeness") in checks
            assert ("solver.explain_rows", "completeness") in checks
        finally:
            del contracts.CONTRACTS["solver._gone_kernel"]
            contracts.CONTRACTS["solver.explain_rows"] = missing

    def test_live_tree_gate_zero_findings(self):
        """ACCEPTANCE: the CLI gate — every registered kernel
        contracted and clean, the go/no-go list names explain_rows,
        every 'reduces' kernel backed by real coupling evidence."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.ktlint", "--kernel-contracts",
             "--format=json"],
            capture_output=True, text=True, timeout=300, cwd=str(ROOT),
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        data = json.loads(proc.stdout)
        from kubernetes_tpu.ops.parity import ORACLE_TWINS

        assert data["findings"] == []
        assert data["errors"] == []
        assert data["kernels_checked"] == len(ORACLE_TWINS)
        assert "solver.explain_rows" in data["shardable"]
        for row in data["kernels"]:
            if row["pod_axis"] == "reduces":
                assert row["coupling_evidence"] > 0, row
            assert row["weak_intermediates"] == 0, row

    def test_cli_rejects_paths_and_unknown_kernel_keys(self):
        """`--kernel-contracts <path>` must error (rc 2), not silently
        filter the gate to zero kernels and exit green."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.ktlint", "--kernel-contracts",
             "kubernetes_tpu/ops/"],
            capture_output=True, text=True, timeout=120, cwd=str(ROOT),
        )
        assert proc.returncode == 2
        assert "kernel keys" in proc.stderr
        proc = subprocess.run(
            [sys.executable, "-m", "tools.ktlint", "--kernel-contracts",
             "solver.explain_rows"],
            capture_output=True, text=True, timeout=300, cwd=str(ROOT),
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr

    def test_checker_performs_zero_kernel_executions(self):
        """The no-device-execution guard: abstract eval only — the jit
        dispatch caches and the compile ledger's call counts must not
        move across a full analyze()."""
        from kubernetes_tpu.ops import contracts, ledger

        kernels = {
            key: contracts.resolve_kernel(key)
            for key in contracts.registry_keys()
        }
        cache_before = {k: fn._cache_size() for k, fn in kernels.items()}
        calls_before = {
            r["kernel"]: r["calls"] for r in ledger.DEFAULT.rows()
        }
        rep = ktshape.analyze()
        assert rep.exit_code == 0, rep.render()
        for key, fn in kernels.items():
            assert fn._cache_size() == cache_before[key], (
                f"{key} compiled during the contract check"
            )
        calls_after = {
            r["kernel"]: r["calls"] for r in ledger.DEFAULT.rows()
        }
        assert calls_after == calls_before


# -- ledger join (observed vs declared) --------------------------------


def _dispatch_on_and_off_lattice():
    """Two real gang_member_counts dispatches into the process ledger:
    one on the pow2 lattice, one deliberately off it (pod axis 24)."""
    import jax.numpy as jnp

    from kubernetes_tpu.ops import matrices

    matrices.gang_member_counts(
        jnp.asarray(np.zeros(16, bool)),
        jnp.asarray(np.full(16, -1, np.int32)),
        num_groups=8,
    )
    matrices.gang_member_counts(
        jnp.asarray(np.zeros(24, bool)),
        jnp.asarray(np.full(24, -1, np.int32)),
        num_groups=8,
    )


class TestLedgerJoin:
    def test_ledger_rows_carry_contract_verdicts(self):
        from kubernetes_tpu.ops import ledger

        _dispatch_on_and_off_lattice()
        rows = {r["kernel"]: r for r in ledger.DEFAULT.rows()}
        shapes = {
            s["signature"]: s["contract"]
            for s in rows["matrices.gang_member_counts"]["shapes"]
        }
        assert shapes["b8[16],i32[16],8"] == "ok"
        assert shapes["b8[24],i32[24],8"].startswith("mismatch")
        assert "PG=24" in shapes["b8[24],i32[24],8"]

    def test_ktctl_profile_kernels_renders_contract_column(self, capsys):
        from kubernetes_tpu.cli import ktctl
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.server.api import APIServer

        _dispatch_on_and_off_lattice()
        rc = ktctl.main(
            ["profile", "kernels"],
            client=Client(LocalTransport(APIServer())),
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "CONTRACT" in out
        # The off-lattice dispatch surfaces as a MISMATCH row with the
        # drifted dim spelled out below the table.
        assert "MISMATCH" in out
        assert "off its bucket lattice" in out


# -- the pow2 lattice helpers (satellite: explicit edge coverage) ------


class TestBucketLattice:
    def test_pow2_bucket_edges(self):
        from kubernetes_tpu.ops.matrices import pow2_bucket

        assert pow2_bucket(0) == 128  # empty staging keeps the floor
        assert pow2_bucket(1) == 128
        assert pow2_bucket(127) == 128
        assert pow2_bucket(128) == 128  # exact bucket is not inflated
        assert pow2_bucket(129) == 256
        assert pow2_bucket(8192) == 8192

    def test_pow2_bucket_minimum_clamp(self):
        from kubernetes_tpu.ops.matrices import pow2_bucket

        assert pow2_bucket(0, minimum=8) == 8
        assert pow2_bucket(3, minimum=8) == 8
        assert pow2_bucket(8, minimum=8) == 8
        assert pow2_bucket(9, minimum=8) == 16
        assert pow2_bucket(7, minimum=1) == 8
        assert pow2_bucket(1, minimum=1) == 1

    def test_pod_axis_bucket_edges(self):
        from kubernetes_tpu.ops.matrices import _pod_axis_bucket

        assert _pod_axis_bucket(0, 128) == 128
        assert _pod_axis_bucket(1, 128) == 128
        assert _pod_axis_bucket(8191, 128) == 8192
        assert _pod_axis_bucket(8192, 128) == 8192  # pow2 band edge
        # Past the pow2 band: 1024-multiples, exact multiples kept.
        assert _pod_axis_bucket(8193, 128) == 9216
        assert _pod_axis_bucket(9216, 128) == 9216
        assert _pod_axis_bucket(9217, 128) == 10240

    def test_lattice_validators_agree_with_the_helpers(self):
        # Every bucket the helpers can emit sits on the declared
        # lattice (the contract checker and the staging layer must
        # agree about what "bucketed" means).
        from kubernetes_tpu.ops import contracts
        from kubernetes_tpu.ops.matrices import _pod_axis_bucket, pow2_bucket

        for n in (0, 1, 127, 128, 500, 8192, 8193, 20000):
            assert contracts.dim_ok("P", _pod_axis_bucket(n, 128)), n
        for n in (0, 1, 7, 8, 9, 1000):
            assert contracts.dim_ok("PG", pow2_bucket(max(n, 1), 8)), n
            assert contracts.dim_ok("V", pow2_bucket(max(n, 1), 8)), n
            assert contracts.dim_ok("R", pow2_bucket(max(n, 1), 8)), n
