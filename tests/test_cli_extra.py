"""ktctl parity-tier commands added after the operational tier:
version, api-versions, cluster-info, namespace, update, proxy, config.

Reference: pkg/kubectl/cmd/{version,apiversions,clusterinfo,namespace,
update,proxy}.go and pkg/kubectl/cmd/config/.
"""

import io
import json
import sys
import urllib.request

import pytest

from kubernetes_tpu.cli.ktctl import main
from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.client.kubeconfig import load_kubeconfig
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer


def run_main(*argv, client=None, expect=0):
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        rc = main(list(argv), client=client)
    finally:
        sys.stdout = old
    assert rc == expect, out.getvalue()
    return out.getvalue()


@pytest.fixture
def http_env():
    api = APIServer()
    srv = APIHTTPServer(api).start()
    client = Client(HTTPTransport(srv.address))
    yield api, srv, client
    srv.stop()


class TestConfigCommands:
    def test_build_and_use_config(self, tmp_path):
        cfg = str(tmp_path / "config")
        run_main("config", "--kubeconfig", cfg, "set-cluster", "prod",
                 "--server-url", "http://10.1.2.3:8080")
        run_main("config", "--kubeconfig", cfg, "set-credentials", "alice",
                 "--token", "sekrit")
        run_main("config", "--kubeconfig", cfg, "set-context", "prod-ctx",
                 "--cluster", "prod", "--user", "alice",
                 "--ctx-namespace", "team1")
        run_main("config", "--kubeconfig", cfg, "use-context", "prod-ctx")
        resolved = load_kubeconfig(cfg)
        assert resolved.server == "http://10.1.2.3:8080"
        assert resolved.token == "sekrit"
        assert resolved.namespace == "team1"
        assert resolved.context == "prod-ctx"

    def test_use_context_unknown_fails(self, tmp_path):
        cfg = str(tmp_path / "config")
        out = io.StringIO()
        old = sys.stderr
        sys.stderr = out
        try:
            rc = main(["config", "--kubeconfig", cfg, "use-context", "nope"])
        finally:
            sys.stderr = old
        assert rc == 1
        assert "no context exists" in out.getvalue()

    def test_view_and_set_unset(self, tmp_path):
        cfg = str(tmp_path / "config")
        run_main("config", "--kubeconfig", cfg, "set", "current-context", "x")
        view = run_main("config", "--kubeconfig", cfg, "view")
        assert json.loads(view)["current-context"] == "x"
        run_main("config", "--kubeconfig", cfg, "unset", "current-context")
        view = run_main("config", "--kubeconfig", cfg, "view")
        assert "current-context" not in json.loads(view)

    def test_set_cluster_merges(self, tmp_path):
        cfg = str(tmp_path / "config")
        run_main("config", "--kubeconfig", cfg, "set-cluster", "prod",
                 "--server-url", "http://a:1")
        run_main("config", "--kubeconfig", cfg, "set-cluster", "prod",
                 "--server-url", "http://b:2")
        view = json.loads(run_main("config", "--kubeconfig", cfg, "view"))
        assert len(view["clusters"]) == 1
        assert view["clusters"][0]["cluster"]["server"] == "http://b:2"


class TestNamespaceCommand:
    def test_get_and_set(self, tmp_path):
        cfg = str(tmp_path / "config")
        run_main("config", "--kubeconfig", cfg, "set-context", "ctx", "--cluster", "c")
        run_main("config", "--kubeconfig", cfg, "use-context", "ctx")
        out = run_main("namespace", "--kubeconfig", cfg)
        assert out.strip() == "default"
        run_main("namespace", "--kubeconfig", cfg, "team2")
        out = run_main("namespace", "--kubeconfig", cfg)
        assert out.strip() == "team2"
        assert load_kubeconfig(cfg).namespace == "team2"


class TestUpdateCommand:
    RC = {
        "kind": "ReplicationController",
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {
            "replicas": 2,
            "selector": {"app": "web"},
            "template": {
                "metadata": {"labels": {"app": "web"}},
                "spec": {"containers": [{"name": "c", "image": "nginx"}]},
            },
        },
    }

    def test_replace_from_file(self, tmp_path):
        api = APIServer()
        client = Client(LocalTransport(api))
        client.create("replicationcontrollers", self.RC, namespace="default")
        changed = json.loads(json.dumps(self.RC))
        changed["spec"]["replicas"] = 5
        f = tmp_path / "rc.json"
        f.write_text(json.dumps(changed))
        out = run_main("update", "-f", str(f), client=client)
        assert "updated" in out
        got = client.get("replicationcontrollers", "web", namespace="default")
        assert got.spec.replicas == 5

    def test_merge_patch(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        client.create("replicationcontrollers", self.RC, namespace="default")
        run_main(
            "update", "rc", "web", "--patch",
            json.dumps({"spec": {"replicas": 7}}), client=client,
        )
        got = client.get("replicationcontrollers", "web", namespace="default")
        assert got.spec.replicas == 7

    def test_requires_exactly_one_mode(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        with pytest.raises(SystemExit):
            main(["update", "rc", "web"], client=client)


class TestServerInfoCommands:
    def test_version(self, http_env):
        api, srv, client = http_env
        out = run_main("version", "--server", srv.address, client=client)
        assert "Client Version:" in out and "Server Version:" in out

    def test_api_versions(self, http_env):
        api, srv, client = http_env
        out = run_main("api-versions", "--server", srv.address, client=client)
        assert "v1" in out

    def test_cluster_info(self, http_env):
        api, srv, client = http_env
        api.create(
            "services",
            "default",
            {
                "kind": "Service",
                "metadata": {
                    "name": "dns",
                    "labels": {"kubernetes.io/cluster-service": "true"},
                },
                "spec": {"selector": {"k": "v"}, "ports": [{"port": 53}]},
            },
        )
        out = run_main("cluster-info", "--server", srv.address, client=client)
        assert f"Kubernetes master is running at {srv.address}" in out
        assert "dns is running at" in out


class TestProxyCommand:
    def test_relays_api_requests_with_credentials(self, http_env):
        from kubernetes_tpu.cli.ktctl import _ProxyServer

        api, srv, client = http_env
        api.create(
            "pods",
            "default",
            {
                "kind": "Pod",
                "metadata": {"name": "p1"},
                "spec": {"containers": [{"name": "c", "image": "nginx"}]},
            },
        )
        proxy = _ProxyServer(srv.address, {}, port=0).serve_background()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{proxy.port}/api/v1/namespaces/default/pods/p1",
                timeout=5,
            ) as resp:
                body = json.loads(resp.read())
            assert body["metadata"]["name"] == "p1"
            # Non-API paths are refused.
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{proxy.port}/etc/passwd", timeout=5
                )
            assert e.value.code == 404
        finally:
            proxy.stop()


class TestGetWatch:
    """`ktctl get -w` (reference get.go:79-143 WatchLoop)."""

    def test_watch_streams_changes(self):
        import threading

        api = APIServer()
        client = Client(LocalTransport(api))
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {"name": "w0"},
                "spec": {"containers": [{"name": "c", "image": "x"}]},
            },
            namespace="default",
        )

        def later():
            import time

            time.sleep(0.3)
            for name in ("w1", "w2"):
                client.create(
                    "pods",
                    {
                        "kind": "Pod",
                        "metadata": {"name": name},
                        "spec": {"containers": [{"name": "c", "image": "x"}]},
                    },
                    namespace="default",
                )

        t = threading.Thread(target=later)
        t.start()
        out = run_main(
            "get", "pods", "-w", "--watch-events", "2", "-o", "name",
            client=client,
        )
        t.join()
        # Initial list (w0) + the two watched creations.
        assert "pods/w0" in out
        assert "pods/w1" in out and "pods/w2" in out

    def test_watch_only_skips_initial_list(self):
        import threading

        api = APIServer()
        client = Client(LocalTransport(api))
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {"name": "pre"},
                "spec": {"containers": [{"name": "c", "image": "x"}]},
            },
            namespace="default",
        )

        def later():
            import time

            time.sleep(0.3)
            client.create(
                "pods",
                {
                    "kind": "Pod",
                    "metadata": {"name": "post"},
                    "spec": {"containers": [{"name": "c", "image": "x"}]},
                },
                namespace="default",
            )

        t = threading.Thread(target=later)
        t.start()
        out = run_main(
            "get", "pods", "--watch-only", "--watch-events", "1",
            "-o", "name", client=client,
        )
        t.join()
        assert "pods/post" in out
        assert "pods/pre" not in out


class TestBuilderInputs:
    """Resource-builder surface: directories visit every manifest
    (builder.go:77-126); selector-based delete (delete.go)."""

    def test_create_from_directory(self, tmp_path):
        api = APIServer()
        client = Client(LocalTransport(api))
        d = tmp_path / "manifests"
        d.mkdir()
        for i in range(2):
            (d / f"pod{i}.json").write_text(json.dumps({
                "kind": "Pod",
                "metadata": {"name": f"dirpod{i}"},
                "spec": {"containers": [{"name": "c", "image": "x"}]},
            }))
        (d / "notes.txt").write_text("ignored")
        out = run_main("create", "-f", str(d), client=client)
        assert "pods/dirpod0 created" in out and "pods/dirpod1 created" in out

    def test_empty_directory_errors(self, tmp_path):
        api = APIServer()
        client = Client(LocalTransport(api))
        d = tmp_path / "empty"
        d.mkdir()
        with pytest.raises(SystemExit):
            main(["create", "-f", str(d)], client=client)

    def test_delete_by_selector(self):
        api = APIServer()
        client = Client(LocalTransport(api))
        for i in range(3):
            client.create("pods", {
                "kind": "Pod",
                "metadata": {"name": f"victim{i}",
                             "labels": {"app": "doomed"}},
                "spec": {"containers": [{"name": "c", "image": "x"}]},
            }, namespace="default")
        client.create("pods", {
            "kind": "Pod",
            "metadata": {"name": "keeper", "labels": {"app": "safe"}},
            "spec": {"containers": [{"name": "c", "image": "x"}]},
        }, namespace="default")
        out = run_main("delete", "pods", "-l", "app=doomed", client=client)
        assert out.count("deleted") == 3
        pods, _ = client.list("pods", namespace="default")
        assert [p.metadata.name for p in pods] == ["keeper"]
