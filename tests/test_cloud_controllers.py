"""ServiceController (provider LBs) + RouteController (pod CIDRs).

Reference: pkg/cloudprovider/servicecontroller/servicecontroller.go and
routecontroller/routecontroller.go (VERDICT r1 #8)."""

import time
from types import SimpleNamespace

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.cloudprovider.fake import FakeCloudProvider
from kubernetes_tpu.cloudprovider.tpu import TPUCloudProvider
from kubernetes_tpu.controllers.routes import RouteController
from kubernetes_tpu.controllers.servicelb import ServiceController
from kubernetes_tpu.server import APIServer


def wait_until(cond, timeout=6.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def node_wire(name, ready=True, pod_cidr=""):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "spec": {"podCIDR": pod_cidr},
        "status": {
            "conditions": [
                {"type": "Ready", "status": "True" if ready else "False"}
            ]
        },
    }


def lb_name(name, ns="default"):
    svc = SimpleNamespace(metadata=SimpleNamespace(namespace=ns, name=name))
    return ServiceController._lb_name(svc)


def lb_service_wire(name, svc_type="LoadBalancer"):
    return {
        "kind": "Service",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "selector": {"app": name},
            "ports": [{"name": "http", "port": 80}],
            "type": svc_type,
        },
    }


@pytest.fixture
def api_client():
    api = APIServer()
    return api, Client(LocalTransport(api))


class TestServiceController:
    def test_loadbalancer_service_gets_provider_ingress(self, api_client):
        api, client = api_client
        provider = FakeCloudProvider()
        client.create("nodes", node_wire("n1"))
        client.create("nodes", node_wire("n2"))
        client.create("nodes", node_wire("sick", ready=False))
        ctrl = ServiceController(
            Client(LocalTransport(api)), provider, sync_period=0.1
        ).start()
        try:
            client.create(
                "services", lb_service_wire("web"), namespace="default"
            )
            assert wait_until(
                lambda: (
                    client.get("services", "web", namespace="default").status
                    or {}
                )
                .get("loadBalancer", {})
                .get("ingress")
            )
            svc = client.get("services", "web", namespace="default")
            assert svc.status["loadBalancer"]["ingress"] == [
                {"ip": f"lb-{lb_name('web')}"}
            ]
            # Only READY nodes back the LB.
            assert provider.load_balancer().balancers[lb_name("web")] == [
                "n1",
                "n2",
            ]
        finally:
            ctrl.stop()

    def test_node_churn_updates_lb_hosts(self, api_client):
        api, client = api_client
        provider = FakeCloudProvider()
        client.create("nodes", node_wire("n1"))
        ctrl = ServiceController(
            Client(LocalTransport(api)), provider, sync_period=0.1
        ).start()
        try:
            client.create(
                "services", lb_service_wire("web"), namespace="default"
            )
            assert wait_until(
                lambda: provider.load_balancer().balancers.get(lb_name("web"))
                == ["n1"]
            )
            client.create("nodes", node_wire("n2"))
            assert wait_until(
                lambda: provider.load_balancer().balancers.get(lb_name("web"))
                == ["n1", "n2"]
            )
        finally:
            ctrl.stop()

    def test_clusterip_service_ignored_and_teardown_on_delete(self, api_client):
        api, client = api_client
        provider = FakeCloudProvider()
        ctrl = ServiceController(
            Client(LocalTransport(api)), provider, sync_period=0.1
        ).start()
        try:
            client.create(
                "services",
                lb_service_wire("plain", svc_type="ClusterIP"),
                namespace="default",
            )
            client.create(
                "services", lb_service_wire("lb"), namespace="default"
            )
            assert wait_until(
                lambda: lb_name("lb") in provider.load_balancer().balancers
            )
            assert lb_name("plain") not in provider.load_balancer().balancers
            client.delete("services", "lb", namespace="default")
            assert wait_until(
                lambda: lb_name("lb") not in provider.load_balancer().balancers
            )
        finally:
            ctrl.stop()

    def test_type_change_clears_ingress_and_lb(self, api_client):
        """Switching type LoadBalancer -> ClusterIP must tear down the
        provider LB AND clear the published ingress."""
        api, client = api_client
        provider = FakeCloudProvider()
        ctrl = ServiceController(
            Client(LocalTransport(api)), provider, sync_period=0.1
        ).start()
        try:
            client.create(
                "services", lb_service_wire("flip"), namespace="default"
            )
            assert wait_until(
                lambda: lb_name("flip") in provider.load_balancer().balancers
            )
            svc = client.get("services", "flip", namespace="default")
            svc.spec.type = "ClusterIP"
            client.update("services", svc, namespace="default")
            assert wait_until(
                lambda: lb_name("flip")
                not in provider.load_balancer().balancers
            )
            assert wait_until(
                lambda: not (
                    client.get("services", "flip", namespace="default").status
                    or {}
                ).get("loadBalancer", {})
            )
        finally:
            ctrl.stop()

    def test_tpu_provider_fabric_ingress(self, api_client):
        """The TPU fabric provider's LB surface: a LoadBalancer service
        gets a slice-edge ingress backed by TPU hosts."""
        api, client = api_client

        class Dev:
            process_index = 0
            device_kind = "tpu-v5e"
            platform = "tpu"
            coords = (0, 0, 0)

        provider = TPUCloudProvider(devices=[Dev()])
        client.create("nodes", node_wire("tpu-host-0"))
        ctrl = ServiceController(
            Client(LocalTransport(api)), provider, sync_period=0.1
        ).start()
        try:
            client.create(
                "services", lb_service_wire("inference"), namespace="default"
            )
            assert wait_until(
                lambda: provider.load_balancer().balancers.get(
                    lb_name("inference")
                )
                == ["tpu-host-0"]
            )
        finally:
            ctrl.stop()


class TestRouteController:
    def test_routes_follow_pod_cidrs(self, api_client):
        api, client = api_client
        provider = FakeCloudProvider()
        client.create("nodes", node_wire("n1", pod_cidr="10.244.1.0/24"))
        client.create("nodes", node_wire("n2", pod_cidr="10.244.2.0/24"))
        client.create("nodes", node_wire("nocidr"))
        ctrl = RouteController(
            Client(LocalTransport(api)), provider, sync_period=0.1
        ).start()
        try:
            assert wait_until(
                lambda: {r.name for r in provider.routes()}
                == {"podcidr-n1", "podcidr-n2"}
            )
            by_name = {r.name: r for r in provider.routes()}
            assert by_name["podcidr-n1"].destination_cidr == "10.244.1.0/24"
            assert by_name["podcidr-n1"].target_instance == "n1"
            # Node deletion removes its route.
            client.delete("nodes", "n2")
            assert wait_until(
                lambda: {r.name for r in provider.routes()} == {"podcidr-n1"}
            )
        finally:
            ctrl.stop()

    def test_cidr_move_recreates_route(self, api_client):
        api, client = api_client
        provider = FakeCloudProvider()
        client.create("nodes", node_wire("n1", pod_cidr="10.244.1.0/24"))
        ctrl = RouteController(
            Client(LocalTransport(api)), provider, sync_period=0.1
        ).start()
        try:
            assert wait_until(
                lambda: any(
                    r.destination_cidr == "10.244.1.0/24"
                    for r in provider.routes()
                )
            )
            node = client.get("nodes", "n1")
            node.spec.pod_cidr = "10.244.9.0/24"
            client.update("nodes", node)
            assert wait_until(
                lambda: any(
                    r.destination_cidr == "10.244.9.0/24"
                    for r in provider.routes()
                )
            )
        finally:
            ctrl.stop()

    def test_ici_base_routes_untouched(self, api_client):
        """The TPU provider's discovered ICI ring is not managed state:
        the controller must never delete it."""
        api, client = api_client

        class Dev:
            def __init__(self, pid):
                self.process_index = pid
                self.device_kind = "tpu-v5e"
                self.platform = "tpu"
                self.coords = (pid, 0, 0)

        provider = TPUCloudProvider(devices=[Dev(0), Dev(1)])
        base = {r.name for r in provider.routes()}
        assert base  # ici ring exists
        ctrl = RouteController(
            Client(LocalTransport(api)), provider, sync_period=0.1
        ).start()
        try:
            client.create(
                "nodes", node_wire("tpu-host-0", pod_cidr="10.244.0.0/24")
            )
            assert wait_until(
                lambda: "podcidr-tpu-host-0"
                in {r.name for r in provider.routes()}
            )
            assert base <= {r.name for r in provider.routes()}
        finally:
            ctrl.stop()
