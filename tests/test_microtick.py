"""Micro-tick cadence + pipelined dispatch correctness (ISSUE 12).

The always-resident incremental loop's contracts:

- wake-on-arrival: a lone pod on an idle cluster binds without waiting
  any drain period (the event-driven drain replaces the fixed window);
- coalescing under burst still respects max_batch;
- commit/solve overlap loses no decision or SLI milestone and never
  reorders ticks (the commit worker is one FIFO thread);
- capacity-freed pods re-solve the tick the capacity appears (backoff
  event-waits, epoch sampled at solve time);
- the session pre-warm compiles every pod bucket up front so a fresh
  bucket never stalls a live tick;
- SolverSession.solve_async keeps host and device state consistent
  while deltas land mid-flight;
- a daemon killed between solve dispatch and commit (ISSUE 15 chaos
  plane) restarts into a fresh session with no double-bind and its
  nomination state recovered by re-solving.
"""

import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Node, Pod
from kubernetes_tpu.scheduler.daemon import (
    IncrementalBatchScheduler,
    SchedulerConfig,
)
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.utils import faults, flightrecorder, sli


def kill_daemon(sched, cfg) -> None:
    """Abrupt daemon death: IncrementalBatchScheduler.kill() (the one
    canonical crash shape, shared with tools/soak.py) + informer
    teardown — no commit flush, exactly what a crashed process would
    (not) do."""
    sched.kill()
    cfg.stop()


def wait_until(cond, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def node_wire(name, cpu="4", mem="8Gi"):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": cpu, "memory": mem, "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def pod_wire(name, cpu="100m", mem="64Mi"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "pause",
                    "resources": {"limits": {"cpu": cpu, "memory": mem}},
                }
            ]
        },
    }


@pytest.fixture
def api():
    return APIServer()


@pytest.fixture
def client(api):
    return Client(LocalTransport(api))


def bound_node(client, name):
    return client.get("pods", name, namespace="default").spec.node_name


class TestMicroTickCadence:
    def test_wake_on_arrival_binds_without_drain_period(self, api, client):
        """A lone pod binds the moment its watch event lands — never
        after a drain period. The daemon runs with a pathological 5s
        batch_window: the fixed-period drain would eat it; the
        event-driven micro-tick must not."""
        client.create("nodes", node_wire("n0"))
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        sched = IncrementalBatchScheduler(
            cfg, batch_window=5.0, coalesce_min=64, prewarm_buckets=128
        )
        try:
            # Pre-warm OUTSIDE the measured window (compiles are paid
            # at build, which is the feature under test's other half).
            sched.prewarm()
            sched.start()
            t0 = time.monotonic()
            client.create("pods", pod_wire("solo"), namespace="default")
            assert wait_until(
                lambda: bound_node(client, "solo"), timeout=4.0
            ), "micro-tick did not fire on arrival"
            assert time.monotonic() - t0 < 4.0  # << the 5s window
        finally:
            sched.stop()

    def test_burst_coalescing_respects_max_batch(self, api, client):
        """An instantaneous burst larger than max_batch drains at most
        max_batch per tick; the rest stays queued for the next tick."""
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        sched = IncrementalBatchScheduler(cfg, max_batch=8, batch_window=0.2)
        try:
            for i in range(20):
                cfg.pod_queue.add(
                    serde.from_wire(Pod, pod_wire(f"burst-{i}"))
                )
            batch = sched._drain(timeout=1.0)
            assert len(batch) == 8
            batch2 = sched._drain(timeout=1.0)
            assert len(batch2) == 8
            assert len(sched._drain(timeout=1.0)) == 4
        finally:
            sched.stop()

    def test_commit_overlap_keeps_milestones_ordered_and_complete(
        self, api, client
    ):
        """With commits riding the worker thread (overlapping the next
        solve), every pod still gets its flight-recorder decision, the
        SLI decision/bound milestones all land, and SolveRecords stay
        in strictly increasing tick order."""
        n = 30
        for j in range(4):
            client.create("nodes", node_wire(f"n{j}"))
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        dec_before = sli.STARTUP_LATENCY.count(milestone="decision")
        bnd_before = sli.STARTUP_LATENCY.count(milestone="bound")
        sched = IncrementalBatchScheduler(cfg).start()
        try:
            # Several waves so ticks genuinely overlap commits.
            for w in range(3):
                for i in range(n // 3):
                    client.create(
                        "pods", pod_wire(f"ov-{w}-{i}"), namespace="default"
                    )
                time.sleep(0.05)
            names = [f"ov-{w}-{i}" for w in range(3) for i in range(n // 3)]
            assert wait_until(
                lambda: all(bound_node(client, x) for x in names)
            )
            # Flight recorder: one decision per pod, outcome bound.
            for x in names:
                ds = flightrecorder.DEFAULT.decisions(
                    pod=f"default/{x}", limit=1
                )["decisions"]
                assert ds, f"no decision recorded for {x}"
                assert ds[0]["outcome"] == "bound"
            # SLI milestones: decision + bound landed for every pod
            # (counts are process-global; compare against the snapshot).
            assert wait_until(
                lambda: sli.STARTUP_LATENCY.count(milestone="bound")
                - bnd_before >= n
            )
            assert (
                sli.STARTUP_LATENCY.count(milestone="decision") - dec_before
                >= n
            )
            # SolveRecords in tick order (single FIFO commit worker);
            # solves() lists newest first.
            ticks = [
                r["tick"]
                for r in flightrecorder.DEFAULT.solves(limit=256)["solves"]
                if r.get("incremental")
            ]
            assert ticks == sorted(ticks, reverse=True)
        finally:
            sched.stop()

    def test_bound_verdict_tables_attach_after_quiet(self, api, client):
        """The pipelined daemon defers bound-pod explain tables off the
        latency path; once the loop quiets, the commit worker attaches
        them to the SAME Decision records readers see."""
        client.create("nodes", node_wire("n0"))
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        sched = IncrementalBatchScheduler(cfg).start()
        try:
            client.create("pods", pod_wire("tbl"), namespace="default")
            assert wait_until(lambda: bound_node(client, "tbl"))

            def has_table():
                ds = flightrecorder.DEFAULT.decisions(
                    pod="default/tbl", limit=1
                )["decisions"]
                return bool(ds and ds[0].get("nodes"))

            # Quiet threshold + worker poll: well under a few seconds.
            assert wait_until(has_table, timeout=10.0), (
                "deferred bound-pod verdict table never attached"
            )
            ds = flightrecorder.DEFAULT.decisions(
                pod="default/tbl", limit=1
            )["decisions"]
            winner = next(v for v in ds[0]["nodes"] if v["ok"])
            assert winner["score"] == sum(winner["components"].values())
        finally:
            sched.stop()

    def test_capacity_freed_releases_backoff_immediately(self, api, client):
        """A pod stuck behind a full node re-solves the tick the
        blocking pod's DELETED lands — not after the grown backoff
        (scheduler/daemon.py retry event-waits + solve-time epoch)."""
        client.create("nodes", node_wire("solo", cpu="1"))
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        sched = IncrementalBatchScheduler(cfg).start()
        try:
            client.create(
                "pods", pod_wire("hog", cpu="900m"), namespace="default"
            )
            assert wait_until(lambda: bound_node(client, "hog"))
            client.create(
                "pods", pod_wire("waiter", cpu="900m"), namespace="default"
            )
            # Let the waiter fail a few solves so its backoff grows
            # past the release window we assert below.
            time.sleep(2.5)
            assert not bound_node(client, "waiter")
            t0 = time.monotonic()
            client.delete("pods", "hog", namespace="default")
            assert wait_until(
                lambda: bound_node(client, "waiter") == "solo", timeout=3.0
            ), "capacity event did not release the backoff"
            assert time.monotonic() - t0 < 3.0
        finally:
            sched.stop()


class TestSessionPipeline:
    def _session(self, n_nodes=4):
        from kubernetes_tpu.ops import SolverSession

        nodes = [
            serde.from_wire(Node, node_wire(f"n{j}")) for j in range(n_nodes)
        ]
        return SolverSession(nodes)

    def test_prewarm_covers_fresh_buckets(self):
        """After prewarm(max_pod_bucket=256), a first-ever 256-bucket
        tick compiles NOTHING (the cache sentinel the PR-7 test and
        the solver_xla_compiles_total gauge watch)."""
        from kubernetes_tpu.ops.solver import _solve_with_state_xla

        session = self._session()
        session.prewarm(max_pod_bucket=256, max_scatter_width=8)
        before = int(_solve_with_state_xla._cache_size())
        for i in range(130):  # pow2 bucket: 256 (fresh for this session)
            session.add_pending(
                serde.from_wire(Pod, pod_wire(f"warm-{i}", cpu="10m"))
            )
        out = session.solve()
        assert len(out) == 130
        assert int(_solve_with_state_xla._cache_size()) == before, (
            "a pre-warmed bucket still compiled on the live tick"
        )

    def test_solve_async_overlaps_deltas_consistently(self):
        """Deltas applied while a solve is IN FLIGHT (node upsert, a
        foreign delete, next tick's staging) converge to the same
        host/device state as the synchronous path: row recomputes miss
        the in-flight commits, result() re-applies them."""
        session = self._session()
        for i in range(6):
            session.add_pending(
                serde.from_wire(Pod, pod_wire(f"a{i}"))
            )
        handle = session.solve_async()
        assert not handle.done()
        # Mid-flight: next tick's staging plus a node row recompute.
        session.add_pending(serde.from_wire(Pod, pod_wire("late")))
        session.upsert_node(
            serde.from_wire(Node, node_wire("n1"))  # dirty row mid-flight
        )
        first = handle.result()
        assert len(first) == 6 and all(d for _k, d in first)
        second = session.solve()
        assert [k for k, _d in second] == ["default/late"]
        # Host mirror bookkeeping exactly matches the commit map.
        tracked = sum(len(l) for l in session._assigned)
        assert tracked == len(session._pod_node) == 7
        # A second solve_async with nothing pending flushes cleanly.
        assert session.solve_async().result() == []

    def test_solve_async_auto_resolves_previous_tick(self):
        """Back-to-back solve_async calls: the second resolves the
        first before dispatching (donated carry + dirty flush need
        it), so results are never lost or reordered."""
        session = self._session()
        session.add_pending(serde.from_wire(Pod, pod_wire("p0")))
        h1 = session.solve_async()
        session.add_pending(serde.from_wire(Pod, pod_wire("p1")))
        h2 = session.solve_async()
        assert h1.done(), "second dispatch must resolve the first tick"
        assert [k for k, _ in h1.result()] == ["default/p0"]
        assert [k for k, _ in h2.result()] == ["default/p1"]


@pytest.mark.chaos
class TestDaemonRestartInvariants:
    """ISSUE 15: kill the incremental daemon between solve dispatch and
    commit (the scheduler.commit.crash chaos site), restart it, and
    assert the recovery contracts — no double-bind, nominations
    recovered by re-solving."""

    @pytest.fixture(autouse=True)
    def _disarm(self):
        faults.clear()
        faults.reset_stats(reseed=0)
        yield
        faults.clear()

    def test_commit_crash_restart_binds_once(self, api, client):
        for j in range(4):
            client.create("nodes", node_wire(f"n{j}"))
        v0 = api.store.version
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        sched = IncrementalBatchScheduler(cfg).start()
        killed = False
        try:
            # Warm-up commit lands clean; the NEXT commit job dies.
            client.create("pods", pod_wire("warm"), namespace="default")
            assert wait_until(lambda: bound_node(client, "warm"))
            rule = faults.inject(faults.SCHED_COMMIT_CRASH, every=1, times=1)
            names = [f"crash-{i}" for i in range(6)]
            for n in names:
                client.create("pods", pod_wire(n), namespace="default")
            assert wait_until(lambda: rule.fired > 0, timeout=30), (
                "commit crash never fired"
            )
            faults.clear()
            # The daemon "died" mid-commit: its session still charges
            # pods that never bound. Kill it abruptly and restart.
            kill_daemon(sched, cfg)
            killed = True
            cfg = SchedulerConfig(
                Client(LocalTransport(api)), raw_scheduled_cache=True
            ).start()
            assert cfg.wait_for_sync()
            sched = IncrementalBatchScheduler(cfg).start()
            killed = False
            assert wait_until(
                lambda: all(bound_node(client, n) for n in names),
                timeout=60,
            ), "restarted daemon never drained the crashed tick's pods"
            # No double-bind: replay the full watch history — each pod
            # must carry exactly ONE distinct non-empty nodeName, ever.
            nodes_seen = {}
            stream = client.watch("pods", namespace="default", since=v0)
            while True:
                ev = stream.next(timeout=0.5)
                if ev is None:
                    break
                obj = ev.object
                name = obj.get("metadata", {}).get("name", "")
                node = obj.get("spec", {}).get("nodeName", "")
                if node:
                    nodes_seen.setdefault(name, set()).add(node)
            stream.close()
            for n in names + ["warm"]:
                assert len(nodes_seen.get(n, set())) == 1, (
                    f"{n} observed bound to {nodes_seen.get(n)}"
                )
        finally:
            if not killed:
                sched.stop()

    def test_nomination_recovered_across_restart(self, api):
        """Kill the daemon right after it nominates a preemptor (its
        in-memory nomination table dies with it); the fresh daemon must
        still get the preemptor bound — recovery is re-solving, not
        remembering."""
        from kubernetes_tpu.kubelet.agent import Kubelet
        from kubernetes_tpu.kubelet.runtime import FakeRuntime

        client = Client(LocalTransport(api))
        client.create("nodes", node_wire("solo", cpu="1"))
        kl = Kubelet(
            Client(LocalTransport(api)), "solo", cpu="1",
            sync_period=0.2, heartbeat_period=30, runtime=FakeRuntime(),
        ).start()
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        sched = IncrementalBatchScheduler(
            cfg, eviction_grace_seconds=1
        ).start()
        killed = False
        try:
            hog = pod_wire("hog", cpu="900m")
            client.create("pods", hog, namespace="default")
            assert wait_until(lambda: bound_node(client, "hog"))
            hi = pod_wire("hi-prio", cpu="900m")
            hi["spec"]["priority"] = 100
            client.create("pods", hi, namespace="default")

            def nominated():
                p = client.get("pods", "hi-prio", namespace="default")
                return p.status.nominated_node_name == "solo"

            assert wait_until(nominated, timeout=30), (
                "preemptor never nominated"
            )
            kill_daemon(sched, cfg)
            killed = True
            cfg = SchedulerConfig(
                Client(LocalTransport(api)), raw_scheduled_cache=True
            ).start()
            assert cfg.wait_for_sync()
            sched = IncrementalBatchScheduler(
                cfg, eviction_grace_seconds=1
            ).start()
            killed = False
            assert wait_until(
                lambda: bound_node(client, "hi-prio") == "solo", timeout=60
            ), "nominated preemptor never bound after daemon restart"
        finally:
            if not killed:
                sched.stop()
            kl.stop()
