"""Volume plugin framework tests (reference behaviors:
pkg/volume/*/..._test.go, pkg/util/mount)."""

import base64
import os
import subprocess

import pytest

from kubernetes_tpu.client.rest import Client, LocalTransport
from kubernetes_tpu.models.objects import (
    EmptyDirVolumeSource,
    GitRepoVolumeSource,
    HostPathVolumeSource,
    NFSVolumeSource,
    ObjectMeta,
    PersistentVolumeClaimVolumeSource,
    Pod,
    PodSpec,
    SecretVolumeSource,
    Volume,
)
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.volumes import FakeMounter, VolumeHost, VolumePluginManager


def mkpod(name="p1", uid="uid-1", volumes=()):
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default", uid=uid),
        spec=PodSpec(volumes=list(volumes)),
    )


@pytest.fixture
def host(tmp_path):
    api = APIServer()
    client = Client(LocalTransport(api))
    h = VolumeHost(root_dir=str(tmp_path), client=client, mounter=FakeMounter())
    h.api = api  # for tests to seed objects
    return h


@pytest.fixture
def mgr(host):
    return VolumePluginManager(host)


class TestEmptyDir:
    def test_setup_teardown(self, mgr):
        pod = mkpod(volumes=[Volume(name="scratch", empty_dir=EmptyDirVolumeSource())])
        paths = mgr.mount_pod_volumes(pod)
        assert os.path.isdir(paths["scratch"])
        assert "empty-dir" in paths["scratch"]
        mgr.teardown_pod_volumes("uid-1")
        assert not os.path.exists(paths["scratch"])

    def test_idempotent_setup(self, mgr):
        pod = mkpod(volumes=[Volume(name="s", empty_dir=EmptyDirVolumeSource())])
        p1 = mgr.mount_pod_volumes(pod)["s"]
        open(os.path.join(p1, "data.txt"), "w").write("keep")
        p2 = mgr.mount_pod_volumes(pod)["s"]
        assert p1 == p2
        assert os.path.exists(os.path.join(p2, "data.txt"))


class TestHostPath:
    def test_exposes_existing_path(self, mgr, tmp_path):
        target = tmp_path / "data"
        target.mkdir()
        pod = mkpod(
            volumes=[Volume(name="h", host_path=HostPathVolumeSource(path=str(target)))]
        )
        paths = mgr.mount_pod_volumes(pod)
        assert paths["h"] == str(target)
        # Teardown must NOT delete a host path.
        mgr.teardown_pod_volumes("uid-1")
        assert target.is_dir()


class TestSecret:
    def test_writes_decoded_keys(self, mgr, host):
        host.api.create(
            "secrets",
            "default",
            {
                "kind": "Secret",
                "metadata": {"name": "creds"},
                "data": {"user": base64.b64encode(b"alice").decode()},
            },
        )
        pod = mkpod(
            volumes=[Volume(name="sec", secret=SecretVolumeSource(secret_name="creds"))]
        )
        paths = mgr.mount_pod_volumes(pod)
        assert open(os.path.join(paths["sec"], "user"), "rb").read() == b"alice"

    def test_missing_secret_fails_setup(self, mgr):
        pod = mkpod(
            volumes=[Volume(name="sec", secret=SecretVolumeSource(secret_name="nope"))]
        )
        with pytest.raises(Exception):
            mgr.mount_pod_volumes(pod)


class TestGitRepo:
    def test_clones_local_repo(self, mgr, tmp_path):
        src = tmp_path / "srcrepo"
        src.mkdir()
        subprocess.run(["git", "init", "-q"], cwd=src, check=True)
        (src / "hello.txt").write_text("world")
        subprocess.run(["git", "add", "."], cwd=src, check=True)
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             "commit", "-q", "-m", "init"],
            cwd=src, check=True,
        )
        pod = mkpod(
            volumes=[Volume(name="code", git_repo=GitRepoVolumeSource(repository=str(src)))]
        )
        paths = mgr.mount_pod_volumes(pod)
        assert open(os.path.join(paths["code"], "hello.txt")).read() == "world"


class TestNetworkVolumes:
    def test_nfs_mounts_through_mounter(self, mgr, host):
        pod = mkpod(
            volumes=[
                Volume(
                    name="share",
                    nfs=NFSVolumeSource(server="fs1", path="/exports", read_only=True),
                )
            ]
        )
        paths = mgr.mount_pod_volumes(pod)
        mounts = host.mounter.list()
        assert len(mounts) == 1
        assert mounts[0].device == "fs1:/exports"
        assert mounts[0].fstype == "nfs"
        assert "ro" in mounts[0].opts
        assert mounts[0].path == paths["share"]
        # Teardown unmounts before removing the dir.
        mgr.teardown_pod_volumes("uid-1")
        assert host.mounter.list() == []
        assert ("unmount", paths["share"]) in host.mounter.log

    def test_mount_is_idempotent(self, mgr, host):
        pod = mkpod(volumes=[Volume(name="share", nfs=NFSVolumeSource(server="a", path="/x"))])
        mgr.mount_pod_volumes(pod)
        mgr.mount_pod_volumes(pod)
        assert len(host.mounter.list()) == 1


class TestPersistentClaim:
    def test_delegates_to_bound_pv(self, mgr, host, tmp_path):
        data = tmp_path / "pvdata"
        data.mkdir()
        host.api.create(
            "persistentvolumes",
            "",
            {
                "kind": "PersistentVolume",
                "metadata": {"name": "pv1"},
                "spec": {
                    "capacity": {"storage": "1Gi"},
                    "accessModes": ["ReadWriteOnce"],
                    "persistentVolumeSource": {"hostPath": {"path": str(data)}},
                },
            },
        )
        host.api.create(
            "persistentvolumeclaims",
            "default",
            {
                "kind": "PersistentVolumeClaim",
                "metadata": {"name": "claim1"},
                "spec": {"volumeName": "pv1", "accessModes": ["ReadWriteOnce"],
                 "resources": {"requests": {"storage": "1Gi"}}},
            },
        )
        pod = mkpod(
            volumes=[
                Volume(
                    name="store",
                    persistent_volume_claim=PersistentVolumeClaimVolumeSource(
                        claim_name="claim1"
                    ),
                )
            ]
        )
        paths = mgr.mount_pod_volumes(pod)
        assert paths["store"] == str(data)

    def test_read_only_claim_forces_ro_mount(self, mgr, host):
        host.api.create(
            "persistentvolumes",
            "",
            {
                "kind": "PersistentVolume",
                "metadata": {"name": "pvnfs"},
                "spec": {
                    "capacity": {"storage": "1Gi"},
                    "accessModes": ["ReadOnlyMany"],
                    "persistentVolumeSource": {
                        "nfs": {"server": "fs1", "path": "/exports"}
                    },
                },
            },
        )
        host.api.create(
            "persistentvolumeclaims",
            "default",
            {
                "kind": "PersistentVolumeClaim",
                "metadata": {"name": "roclaim"},
                "spec": {"volumeName": "pvnfs", "accessModes": ["ReadOnlyMany"],
                 "resources": {"requests": {"storage": "1Gi"}}},
            },
        )
        pod = mkpod(
            volumes=[
                Volume(
                    name="store",
                    persistent_volume_claim=PersistentVolumeClaimVolumeSource(
                        claim_name="roclaim", read_only=True
                    ),
                )
            ]
        )
        paths = mgr.mount_pod_volumes(pod)
        (mount,) = host.mounter.list()
        assert mount.path == paths["store"]
        assert "ro" in mount.opts  # claim read_only overrides PV source

    def test_git_repo_rejects_option_injection(self, mgr):
        pod = mkpod(
            volumes=[
                Volume(
                    name="code",
                    git_repo=GitRepoVolumeSource(
                        repository="--upload-pack=touch /tmp/pwned"
                    ),
                )
            ]
        )
        with pytest.raises(ValueError):
            mgr.mount_pod_volumes(pod)

    def test_unbound_claim_fails(self, mgr, host):
        host.api.create(
            "persistentvolumeclaims",
            "default",
            {
                "kind": "PersistentVolumeClaim",
                "metadata": {"name": "pending"},
                "spec": {"accessModes": ["ReadWriteOnce"],
                 "resources": {"requests": {"storage": "1Gi"}}},
            },
        )
        pod = mkpod(
            volumes=[
                Volume(
                    name="store",
                    persistent_volume_claim=PersistentVolumeClaimVolumeSource(
                        claim_name="pending"
                    ),
                )
            ]
        )
        with pytest.raises(Exception):
            mgr.mount_pod_volumes(pod)


class TestOrphanDiskGC:
    def test_restart_orphans_swept_from_disk(self, tmp_path):
        """Volume dirs for pods the RUNTIME has forgotten (kubelet
        restart) must still be GC'd: the orphan sweep unions runtime
        pods with on-disk volume state."""
        import time

        from kubernetes_tpu.kubelet.agent import Kubelet
        from kubernetes_tpu.models.objects import EmptyDirVolumeSource

        api = APIServer()
        client = Client(LocalTransport(api))
        # Simulate a pre-restart leftover: volumes on disk, no runtime
        # record, no apiserver pod.
        h = VolumeHost(root_dir=str(tmp_path), client=client)
        mgr = VolumePluginManager(h)
        ghost = mkpod(name="ghost", uid="ghost-uid",
                      volumes=[Volume(name="s", empty_dir=EmptyDirVolumeSource())])
        mgr.mount_pod_volumes(ghost)
        leftover = os.path.join(str(tmp_path), "pods", "ghost-uid")
        assert os.path.isdir(leftover)
        kubelet = Kubelet(client, "n1", root_dir=str(tmp_path),
                          heartbeat_period=0.5, sync_period=0.1).start()
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and os.path.exists(leftover):
                time.sleep(0.05)
            assert not os.path.exists(leftover)
        finally:
            kubelet.stop()


class TestKubeletIntegration:
    def test_volumes_mounted_and_cleaned(self, tmp_path):
        import time

        from kubernetes_tpu.kubelet.agent import Kubelet
        from kubernetes_tpu.models import serde

        api = APIServer()
        client = Client(LocalTransport(api))
        kubelet = Kubelet(
            client, "n1", root_dir=str(tmp_path), heartbeat_period=0.5,
            sync_period=0.2,
        ).start()
        try:
            pod = mkpod(
                name="volpod", uid="",
                volumes=[Volume(name="scratch", empty_dir=EmptyDirVolumeSource())],
            )
            pod.spec.containers = []
            wire = serde.to_wire(pod)
            wire["spec"]["containers"] = [{"name": "c", "image": "busybox"}]
            wire["spec"]["nodeName"] = "n1"
            created = client.create("pods", wire)
            uid = created.metadata.uid
            voldir = os.path.join(str(tmp_path), "pods", uid, "volumes")
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and not os.path.isdir(voldir):
                time.sleep(0.05)
            assert os.path.isdir(voldir)
            client.delete("pods", "volpod", namespace="default")
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and os.path.exists(voldir):
                time.sleep(0.05)
            assert not os.path.exists(voldir)
        finally:
            kubelet.stop()
