"""Tests for the satellite controllers: NamespaceManager,
ResourceQuotaManager, ServiceAccounts/Token controllers, PV claim
binder.

Reference behaviors: pkg/namespace/, pkg/resourcequota/,
pkg/serviceaccount/, pkg/volumeclaimbinder/."""

import base64

import pytest

from kubernetes_tpu.client.rest import Client, LocalTransport
from kubernetes_tpu.controllers.namespace import NamespaceManager
from kubernetes_tpu.controllers.resourcequota import ResourceQuotaManager
from kubernetes_tpu.controllers.serviceaccounts import (
    ServiceAccountsController,
    TokenController,
)
from kubernetes_tpu.controllers.pvrecycler import PersistentVolumeRecycler
from kubernetes_tpu.controllers.volumeclaimbinder import (
    PersistentVolumeClaimBinder,
)
from kubernetes_tpu.server.api import APIError, APIServer
from kubernetes_tpu.server.auth import ServiceAccountTokenManager


@pytest.fixture
def api():
    return APIServer()


@pytest.fixture
def client(api):
    return Client(LocalTransport(api))


def mkpod(name, ns="default", cpu=None):
    spec = {"containers": [{"name": "c", "image": "i"}]}
    if cpu:
        spec["containers"][0]["resources"] = {"limits": {"cpu": cpu}}
    return {"kind": "Pod", "metadata": {"name": name, "namespace": ns}, "spec": spec}


class TestNamespaceManager:
    def test_two_phase_delete(self, api, client):
        api.create("namespaces", "", {"metadata": {"name": "team"}})
        api.create("pods", "team", mkpod("p1", "team"))
        api.create("secrets", "team", {"kind": "Secret", "metadata": {"name": "s1"}})
        # DELETE marks Terminating (finalizer defaulting) instead of removing.
        api.delete("namespaces", "", "team")
        ns = api.get("namespaces", "", "team")
        assert ns["status"]["phase"] == "Terminating"
        assert ns["metadata"]["deletionTimestamp"]
        # Controller purges content, finalizes, deletes.
        mgr = NamespaceManager(client)
        assert mgr.sync_once() == 1
        with pytest.raises(APIError):
            api.get("namespaces", "", "team")
        assert api.list("pods", "team")["items"] == []
        assert api.list("secrets", "team")["items"] == []

    def test_active_namespaces_untouched(self, api, client):
        api.create("namespaces", "", {"metadata": {"name": "keep"}})
        api.create("pods", "keep", mkpod("p1", "keep"))
        NamespaceManager(client).sync_once()
        assert api.get("namespaces", "", "keep")
        assert len(api.list("pods", "keep")["items"]) == 1

    def test_no_finalizer_deletes_immediately(self, api):
        api.create("namespaces", "", {"metadata": {"name": "plain"}})
        api.finalize_namespace("plain", {"spec": {"finalizers": []}})
        api.delete("namespaces", "", "plain")
        with pytest.raises(APIError):
            api.get("namespaces", "", "plain")


class TestResourceQuotaManager:
    def test_recomputes_drifted_usage(self, api, client):
        api.create(
            "resourcequotas",
            "default",
            {
                "kind": "ResourceQuota",
                "metadata": {"name": "q"},
                "spec": {"hard": {"pods": "10", "cpu": "4"}},
            },
        )
        api.create("pods", "default", mkpod("a", cpu="500m"))
        api.create("pods", "default", mkpod("b", cpu="250m"))
        mgr = ResourceQuotaManager(client)
        assert mgr.sync_once() == 1
        q = api.get("resourcequotas", "default", "q")
        assert q["status"]["used"]["pods"] == "2"
        assert q["status"]["used"]["cpu"] == "750m"
        # Second pass: no drift, no write.
        assert mgr.sync_once() == 0


class TestServiceAccountControllers:
    def test_default_sa_created(self, api, client):
        api.create("namespaces", "", {"metadata": {"name": "apps"}})
        ctl = ServiceAccountsController(client)
        created = ctl.sync_once()
        assert created >= 2  # default + apps
        assert api.get("serviceaccounts", "apps", "default")
        assert api.get("serviceaccounts", "default", "default")
        # Idempotent.
        assert ctl.sync_once() == 0

    def test_token_minted_and_verifiable(self, api, client):
        ServiceAccountsController(client).sync_once()
        mgr = ServiceAccountTokenManager(b"test-key")
        tc = TokenController(client, mgr)
        minted = tc.sync_once()
        assert minted >= 1
        secret = api.get("secrets", "default", "default-token")
        assert secret["type"] == "kubernetes.io/service-account-token"
        token = base64.b64decode(secret["data"]["token"]).decode()
        info = mgr.authenticate_token(token)
        assert info.name == "system:serviceaccount:default:default"
        # SA references the secret; second sync is a no-op.
        sa = api.get("serviceaccounts", "default", "default")
        assert any(s["name"] == "default-token" for s in sa["secrets"])
        assert tc.sync_once() == 0


def mkpv(name, storage, modes=("ReadWriteOnce",), reclaim="Retain"):
    return {
        "kind": "PersistentVolume",
        "metadata": {"name": name},
        "spec": {
            "capacity": {"storage": storage},
            "accessModes": list(modes),
            "persistentVolumeSource": {"hostPath": {"path": f"/tmp/{name}"}},
            "persistentVolumeReclaimPolicy": reclaim,
        },
    }


def mkpvc(name, storage, modes=("ReadWriteOnce",), ns="default"):
    return {
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "accessModes": list(modes),
            "resources": {"requests": {"storage": storage}},
        },
    }


class TestPVClaimBinder:
    def test_smallest_sufficient_binding(self, api, client):
        api.create("persistentvolumes", "", mkpv("small", "1Gi"))
        api.create("persistentvolumes", "", mkpv("big", "100Gi"))
        api.create("persistentvolumeclaims", "default", mkpvc("c1", "500Mi"))
        binder = PersistentVolumeClaimBinder(client)
        assert binder.sync_once() == 1
        pvc = api.get("persistentvolumeclaims", "default", "c1")
        assert pvc["spec"]["volumeName"] == "small"
        assert pvc["status"]["phase"] == "Bound"
        pv = api.get("persistentvolumes", "", "small")
        assert pv["status"]["phase"] == "Bound"
        assert pv["spec"]["claimRef"]["name"] == "c1"
        big = api.get("persistentvolumes", "", "big")
        assert big["status"]["phase"] == "Available"

    def test_too_small_not_bound(self, api, client):
        api.create("persistentvolumes", "", mkpv("tiny", "100Mi"))
        api.create("persistentvolumeclaims", "default", mkpvc("c1", "5Gi"))
        assert PersistentVolumeClaimBinder(client).sync_once() == 0
        pvc = api.get("persistentvolumeclaims", "default", "c1")
        assert not pvc["spec"].get("volumeName")

    def test_access_mode_mismatch(self, api, client):
        api.create("persistentvolumes", "", mkpv("rwo", "10Gi", modes=("ReadWriteOnce",)))
        api.create(
            "persistentvolumeclaims",
            "default",
            mkpvc("c1", "1Gi", modes=("ReadWriteMany",)),
        )
        assert PersistentVolumeClaimBinder(client).sync_once() == 0

    def test_release_on_claim_delete_retain(self, api, client):
        api.create("persistentvolumes", "", mkpv("v", "10Gi"))
        api.create("persistentvolumeclaims", "default", mkpvc("c1", "1Gi"))
        binder = PersistentVolumeClaimBinder(client)
        binder.sync_once()
        api.delete("persistentvolumeclaims", "default", "c1")
        binder.sync_once()
        pv = api.get("persistentvolumes", "", "v")
        assert pv["status"]["phase"] == "Released"

    def test_release_recycle_goes_released_until_scrubbed(self, api, client):
        """Recycle no longer short-circuits to Available in the binder:
        the volume waits Released for the recycler's scrub (returning
        it dirty would hand old data to the next claim)."""
        api.create("persistentvolumes", "", mkpv("v", "10Gi", reclaim="Recycle"))
        api.create("persistentvolumeclaims", "default", mkpvc("c1", "1Gi"))
        binder = PersistentVolumeClaimBinder(client)
        binder.sync_once()
        api.delete("persistentvolumeclaims", "default", "c1")
        binder.sync_once()
        pv = api.get("persistentvolumes", "", "v")
        assert pv["status"]["phase"] == "Released"


class TestPVRecycler:
    """persistent_volume_recycler.go analog: Released+Recycle -> scrub
    (real deletion on the host_path substrate) -> Available -> a new
    claim binds the same volume."""

    def _pv_at(self, path, reclaim="Recycle"):
        pv = mkpv("rv", "10Gi", reclaim=reclaim)
        pv["spec"]["persistentVolumeSource"]["hostPath"]["path"] = str(path)
        return pv

    def test_recycle_scrubs_and_repools(self, api, client, tmp_path):
        voldir = tmp_path / "vol"
        voldir.mkdir()
        (voldir / "old-tenant-data.txt").write_text("secret")
        (voldir / "sub").mkdir()
        (voldir / "sub" / "f").write_text("x")
        api.create("persistentvolumes", "", self._pv_at(voldir))
        api.create("persistentvolumeclaims", "default", mkpvc("c1", "1Gi"))
        binder = PersistentVolumeClaimBinder(client)
        recycler = PersistentVolumeRecycler(client)
        binder.sync_once()
        assert api.get("persistentvolumes", "", "rv")["status"]["phase"] == "Bound"

        api.delete("persistentvolumeclaims", "default", "c1")
        binder.sync_once()  # Bound -> Released
        assert recycler.sync_once() == 1
        pv = api.get("persistentvolumes", "", "rv")
        assert pv["status"]["phase"] == "Available"
        assert not pv["spec"].get("claimRef")
        # The scrub really deleted the old tenant's files; the
        # directory itself (the volume) survives.
        assert voldir.is_dir()
        assert list(voldir.iterdir()) == []

        # A later claim binds the SAME volume (the e2e bar in VERDICT
        # r3 missing #2).
        api.create("persistentvolumeclaims", "default", mkpvc("c2", "1Gi"))
        assert binder.sync_once() == 1
        assert (
            api.get("persistentvolumeclaims", "default", "c2")["spec"]["volumeName"]
            == "rv"
        )

    def test_retain_stays_released(self, api, client, tmp_path):
        voldir = tmp_path / "vol"
        voldir.mkdir()
        (voldir / "keep.txt").write_text("kept")
        api.create("persistentvolumes", "", self._pv_at(voldir, reclaim="Retain"))
        api.create("persistentvolumeclaims", "default", mkpvc("c1", "1Gi"))
        binder = PersistentVolumeClaimBinder(client)
        binder.sync_once()
        api.delete("persistentvolumeclaims", "default", "c1")
        binder.sync_once()
        assert PersistentVolumeRecycler(client).sync_once() == 0
        assert api.get("persistentvolumes", "", "rv")["status"]["phase"] == "Released"
        assert (voldir / "keep.txt").read_text() == "kept"  # untouched

    def test_unrecyclable_source_goes_failed(self, api, client):
        pv = mkpv("nfsvol", "10Gi", reclaim="Recycle")
        pv["spec"]["persistentVolumeSource"] = {
            "nfs": {"server": "fileserver", "path": "/exports/a"}
        }
        api.create("persistentvolumes", "", pv)
        api.create("persistentvolumeclaims", "default", mkpvc("c1", "1Gi"))
        binder = PersistentVolumeClaimBinder(client)
        binder.sync_once()
        api.delete("persistentvolumeclaims", "default", "c1")
        binder.sync_once()
        assert PersistentVolumeRecycler(client).sync_once() == 0
        pv = api.get("persistentvolumes", "", "nfsvol")
        assert pv["status"]["phase"] == "Failed"
        assert "no recyclable" in pv["status"]["message"]

    def test_missing_scrub_dir_goes_failed(self, api, client, tmp_path):
        api.create(
            "persistentvolumes", "", self._pv_at(tmp_path / "never-created")
        )
        api.create("persistentvolumeclaims", "default", mkpvc("c1", "1Gi"))
        binder = PersistentVolumeClaimBinder(client)
        binder.sync_once()
        api.delete("persistentvolumeclaims", "default", "c1")
        binder.sync_once()
        assert PersistentVolumeRecycler(client).sync_once() == 0
        pv = api.get("persistentvolumes", "", "rv")
        assert pv["status"]["phase"] == "Failed"
        assert "not a directory" in pv["status"]["message"]


class TestReviewRegressions:
    def test_rejected_create_leaves_quota_status(self, api):
        """A failed store write must not inflate status.used."""
        from kubernetes_tpu.server import admission as adm

        api.admission = adm.new_from_plugins(api, ["ResourceQuota"])
        api.create(
            "resourcequotas",
            "default",
            {
                "kind": "ResourceQuota",
                "metadata": {"name": "q"},
                "spec": {"hard": {"pods": "5"}},
            },
        )
        api.create("pods", "default", mkpod("a"))
        with pytest.raises(APIError):  # duplicate name -> 409 post-admission
            api.create("pods", "default", mkpod("a"))
        q = api.get("resourcequotas", "default", "q")
        assert q["status"]["used"]["pods"] == "1"

    def test_foreign_finalizer_blocks_deletion(self, api, client):
        api.create(
            "namespaces",
            "",
            {
                "metadata": {"name": "guarded"},
                "spec": {"finalizers": ["kubernetes", "example.com/cleanup"]},
            },
        )
        api.delete("namespaces", "", "guarded")
        NamespaceManager(client).sync_once()
        ns = api.get("namespaces", "", "guarded")
        assert ns["spec"]["finalizers"] == ["example.com/cleanup"]
        assert ns["status"]["phase"] == "Terminating"
        # Once the foreign owner removes its finalizer, deletion completes.
        api.finalize_namespace("guarded", {"spec": {"finalizers": []}})
        NamespaceManager(client).sync_once()
        with pytest.raises(APIError):
            api.get("namespaces", "", "guarded")

    def test_finalize_authorized_as_namespaces(self):
        """PUT /namespaces/{name}/finalize authorizes as resource
        'namespaces', not 'finalize'."""
        import json as _json
        import urllib.request

        from kubernetes_tpu.server import auth as authpkg
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api2 = APIServer()
        authn = authpkg.UnionAuthenticator(
            tokens=[
                authpkg.TokenAuthenticator(
                    {"ctl": authpkg.UserInfo(name="controller")}
                )
            ]
        )
        authz = authpkg.ABACAuthorizer(
            [authpkg.Policy(user="controller", resource="namespaces")]
        )
        srv = APIHTTPServer(api2, authenticator=authn, authorizer=authz).start()
        try:
            api2.create("namespaces", "", {"metadata": {"name": "x"}})
            body = _json.dumps(
                {"spec": {"finalizers": []}}
            ).encode()
            r = urllib.request.Request(
                srv.address + "/api/v1/namespaces/x/finalize",
                data=body,
                method="PUT",
                headers={"Authorization": "Bearer ctl"},
            )
            with urllib.request.urlopen(r) as resp:
                assert resp.status == 200
        finally:
            srv.stop()
