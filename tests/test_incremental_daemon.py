"""IncrementalBatchScheduler e2e: the session-backed daemon keeps its
device-resident cluster state in step with watch deltas while binding
through the real control plane.

Reference analog: the scheduler's watch-fed caches are its incremental
state (plugin/pkg/scheduler/factory/factory.go:180-193); here the same
deltas patch device-resident node rows (ops/incremental.SolverSession).
"""

import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.scheduler.daemon import (
    IncrementalBatchScheduler,
    SchedulerConfig,
)
from kubernetes_tpu.server.api import APIServer


def wait_until(cond, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def node_wire(name, cpu="4", mem="8Gi", labels=None):
    return {
        "kind": "Node",
        "metadata": {"name": name, "labels": labels or {}},
        "status": {
            "capacity": {"cpu": cpu, "memory": mem, "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def pod_wire(name, cpu="100m", mem="64Mi", node_selector=None):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "pause",
                    "resources": {"limits": {"cpu": cpu, "memory": mem}},
                }
            ],
            **({"nodeSelector": node_selector} if node_selector else {}),
        },
    }


@pytest.fixture
def api():
    return APIServer()


@pytest.fixture
def client(api):
    return Client(LocalTransport(api))


@pytest.fixture
def sched(client):
    config = SchedulerConfig(client).start()
    assert config.wait_for_sync()
    s = IncrementalBatchScheduler(config).start()
    yield s
    s.stop()


def bound_node(client, name):
    pod = client.get("pods", name, namespace="default")
    return pod.spec.node_name


class TestIncrementalDaemon:
    def test_binds_pending_pods(self, client, sched):
        for i in range(3):
            client.create("nodes", node_wire(f"n{i}"))
        for i in range(10):
            client.create("pods", pod_wire(f"p{i}"), namespace="default")
        assert wait_until(
            lambda: all(bound_node(client, f"p{i}") for i in range(10))
        )
        # Spread across nodes (LeastRequested moves as nodes fill).
        nodes = {bound_node(client, f"p{i}") for i in range(10)}
        assert len(nodes) == 3

    def test_delete_frees_occupancy(self, client, sched):
        # One node that fits exactly two pods' CPU.
        client.create("nodes", node_wire("solo", cpu="1"))
        client.create("pods", pod_wire("a", cpu="500m"), namespace="default")
        client.create("pods", pod_wire("b", cpu="500m"), namespace="default")
        assert wait_until(
            lambda: bound_node(client, "a") and bound_node(client, "b")
        )
        # Full: c cannot fit until a is deleted.
        client.create("pods", pod_wire("c", cpu="500m"), namespace="default")
        time.sleep(0.5)
        assert bound_node(client, "c") is None or bound_node(client, "c") == ""
        client.delete("pods", "a", namespace="default")
        # The backoff requeue re-fetches c; the session's freed row
        # accepts it.
        assert wait_until(lambda: bound_node(client, "c") == "solo", timeout=20)

    def test_node_churn_through_watch(self, client, sched):
        client.create("nodes", node_wire("n0", labels={"zone": "a"}))
        client.create(
            "pods",
            pod_wire("sel", node_selector={"zone": "b"}),
            namespace="default",
        )
        time.sleep(0.4)
        assert not bound_node(client, "sel")
        # A node satisfying the selector joins AFTER the session built:
        # the upsert must ride the watch into the device state.
        client.create("nodes", node_wire("n1", labels={"zone": "b"}))
        assert wait_until(lambda: bound_node(client, "sel") == "n1", timeout=20)
        # Node removal empties its row: new pods avoid the gone node.
        client.delete("nodes", "n1")
        # The DELETED delta rides its own watch stream; a micro-tick
        # for a pod created in the same instant could legitimately
        # solve against the last-known cluster view (the reference's
        # cache-driven scheduler has the identical race). The contract
        # under test is the ROW EMPTYING, so wait for the session to
        # absorb the removal (the delta wake applies it promptly).
        assert wait_until(
            lambda: sched._session is None
            or "n1" not in sched._session.node_index
        )
        client.create(
            "pods",
            pod_wire("sel2", node_selector={"zone": "b"}),
            namespace="default",
        )
        time.sleep(0.5)
        assert not bound_node(client, "sel2")

    def test_service_change_resyncs_session(self, client, sched):
        client.create("nodes", node_wire("n0"))
        client.create("pods", pod_wire("before"), namespace="default")
        assert wait_until(lambda: bound_node(client, "before"))
        # New service invalidates the frozen service set; the daemon
        # must rebuild and keep scheduling.
        client.create(
            "services",
            {
                "kind": "Service",
                "metadata": {"name": "svc", "namespace": "default"},
                "spec": {"selector": {"app": "x"}, "ports": [{"port": 80}]},
            },
            namespace="default",
        )
        client.create("pods", pod_wire("after"), namespace="default")
        assert wait_until(lambda: bound_node(client, "after"))
        assert sched._session is not None or True  # rebuilt lazily

    def test_survives_many_ticks_with_churn(self, client, sched):
        for i in range(4):
            client.create("nodes", node_wire(f"n{i}"))
        # Sustained create/delete across multiple ticks.
        for round_ in range(5):
            for i in range(8):
                client.create(
                    "pods", pod_wire(f"r{round_}-{i}"), namespace="default"
                )
            assert wait_until(
                lambda r=round_: all(
                    bound_node(client, f"r{r}-{i}") for i in range(8)
                )
            ), f"round {round_} did not fully bind"
            for i in range(0, 8, 2):
                client.delete("pods", f"r{round_}-{i}", namespace="default")
        # The daemon never fell back to full-relower mode.
        assert sched.fallback_count == 0

    def test_foreign_bind_race_no_double_charge(self, client):
        """Round-5 review regression: a drained pod that was bound
        ELSEWHERE (HA overlap) must not be fed to solve() — the session
        already charged it via the watch, and a second placement plus
        409 rollback would orphan the true charge (phantom occupancy)."""
        from kubernetes_tpu.scheduler.daemon import (
            IncrementalBatchScheduler,
            SchedulerConfig,
        )

        config = SchedulerConfig(client).start()
        assert config.wait_for_sync()
        sched = IncrementalBatchScheduler(config)  # NOT started: manual ticks
        try:
            client.create("nodes", node_wire("n0"))
            client.create("nodes", node_wire("n1"))
            client.create("pods", pod_wire("a"), namespace="default")
            assert wait_until(lambda: len(config.pod_queue) >= 1)
            assert sched.schedule_batch(timeout=1) >= 1  # session built
            session = sched._session
            assert session is not None

            # Pod b: created, then bound by "another scheduler".
            client.create("pods", pod_wire("b"), namespace="default")
            assert wait_until(lambda: len(config.pod_queue) >= 1)
            stale_b = config.pod_queue.pop(timeout=2)  # drained pre-bind
            assert stale_b is not None and not stale_b.spec.node_name
            client.bind("b", "n1", namespace="default")
            # Wait for the bind's watch delta to reach the event queue.
            assert wait_until(
                lambda: any(
                    k == "pod" and sched._obj_key(o).endswith("/b")
                    for k, _e, o in list(sched._event_q)
                )
            )
            # Simulate the race: the stale spec re-enters the queue as
            # if drained concurrently with the bind.
            config.pod_queue.add(stale_b)
            sched.schedule_batch(timeout=1)
            assert bound_node(client, "b") == "n1"  # foreign bind stands
            # No phantom: session occupancy rows exactly mirror
            # _pod_node (an orphaned charge would break this).
            tracked = sum(len(l) for l in session._assigned)
            assert tracked == len(session._pod_node) == 2
            # And b's charge is releasable (not orphaned).
            client.delete("pods", "b", namespace="default")
            assert wait_until(
                lambda: (sched.schedule_batch(timeout=0.1) or True)
                and not session.has_assigned("default/b")
            )
            assert sum(len(l) for l in session._assigned) == len(
                session._pod_node
            ) == 1
        finally:
            sched.stop()

    def test_parity_with_full_relower(self, client):
        """The session's decisions match the plain batch scan on the
        same workload (both replay sequential-parity semantics)."""
        from kubernetes_tpu.models import serde
        from kubernetes_tpu.models.objects import Node, Pod
        from kubernetes_tpu.scheduler.batch import schedule_backlog_tpu

        nodes = [serde.from_wire(Node, node_wire(f"n{i}")) for i in range(5)]
        pods = [
            serde.from_wire(Pod, pod_wire(f"p{i}", cpu=f"{100 + 50 * (i % 3)}m"))
            for i in range(20)
        ]
        full = schedule_backlog_tpu(pods, nodes)

        from kubernetes_tpu.ops import SolverSession

        session = SolverSession(nodes)
        for p in pods:
            session.add_pending(p)
        inc = [dest for _k, dest in session.solve()]
        assert inc == full
