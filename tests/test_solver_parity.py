"""TPU solver vs scalar oracle parity — the core correctness bar for
the batch path (BASELINE.md: >=99% decision parity; these small cases
must be exact)."""

import random

import pytest

from kubernetes_tpu.models.objects import (
    Container,
    ContainerPort,
    GCEPersistentDiskVolumeSource,
    Node,
    NodeCondition,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
    Service,
    ServiceSpec,
    Volume,
)
from kubernetes_tpu.models.quantity import Quantity, parse_quantity
from kubernetes_tpu.scheduler.batch import (
    parity_report,
    schedule_backlog_scalar,
    schedule_backlog_tpu,
)

MIB = 1024**2


def mk_pod(
    name,
    cpu=100,
    mem_mib=64,
    selector=None,
    host_port=0,
    pd=None,
    pinned="",
    labels=None,
    ns="default",
):
    vols = []
    if pd:
        vols.append(
            Volume(name="v", gce_persistent_disk=GCEPersistentDiskVolumeSource(pd_name=pd))
        )
    ports = [ContainerPort(container_port=80, host_port=host_port)] if host_port else []
    limits = {}
    if cpu:
        limits["cpu"] = Quantity.from_milli(cpu)
    if mem_mib:
        limits["memory"] = parse_quantity(f"{mem_mib}Mi")
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=labels or {}),
        spec=PodSpec(
            containers=[
                Container(
                    name="c", image="x", ports=ports,
                    resources=ResourceRequirements(limits=limits),
                )
            ],
            volumes=vols,
            node_selector=selector or {},
            node_name=pinned,
        ),
    )


def mk_node(name, cpu=4000, mem_mib=8192, pods=40, labels=None, ready=True):
    return Node(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        status=NodeStatus(
            capacity={
                "cpu": Quantity.from_milli(cpu),
                "memory": parse_quantity(f"{mem_mib}Mi"),
                "pods": Quantity.from_int(pods),
            },
            conditions=[NodeCondition(type="Ready", status="True" if ready else "False")],
        ),
    )


def assert_parity(pending, nodes, assigned=(), services=(), min_parity=1.0):
    scalar = schedule_backlog_scalar(pending, nodes, assigned, services)
    batch = schedule_backlog_tpu(pending, nodes, assigned, services)
    parity, mismatches = parity_report(scalar, batch)
    assert parity >= min_parity, (
        f"parity {parity:.3f}, mismatches at {mismatches[:10]}: "
        + ", ".join(
            f"#{i} scalar={scalar[i]} batch={batch[i]}" for i in mismatches[:5]
        )
    )
    return scalar, batch


class TestExactParity:
    def test_empty_cluster(self):
        scalar, batch = assert_parity([mk_pod("p0")], [])
        assert scalar == [None]

    def test_single_pod_single_node(self):
        scalar, batch = assert_parity([mk_pod("p0")], [mk_node("n0")])
        assert scalar == ["n0"]

    def test_sequential_spreading(self):
        """Identical pods must spread the same way in both paths (each
        placement changes the next pod's scores)."""
        pods = [mk_pod(f"p{i}", cpu=500, mem_mib=512) for i in range(8)]
        nodes = [mk_node(f"n{j}", cpu=2000, mem_mib=4096) for j in range(3)]
        assert_parity(pods, nodes)

    def test_capacity_exhaustion(self):
        pods = [mk_pod(f"p{i}", cpu=600, mem_mib=64) for i in range(5)]
        nodes = [mk_node("n0", cpu=1000, mem_mib=8192, pods=40)]
        scalar, batch = assert_parity(pods, nodes)
        assert scalar[0] == "n0" and scalar[1] is None  # 600+600 > 1000

    def test_pod_count_capacity(self):
        pods = [mk_pod(f"p{i}", cpu=10, mem_mib=1) for i in range(4)]
        nodes = [mk_node("n0", pods=2), mk_node("n1", pods=2)]
        scalar, batch = assert_parity(pods, nodes)
        assert scalar.count(None) == 0

    def test_zero_request_pods(self):
        pods = [mk_pod(f"p{i}", cpu=0, mem_mib=0) for i in range(3)]
        nodes = [mk_node("n0", pods=2), mk_node("n1", pods=1)]
        assert_parity(pods, nodes)

    def test_node_selector(self):
        pods = [
            mk_pod("ssd1", selector={"disk": "ssd"}),
            mk_pod("hdd1", selector={"disk": "hdd"}),
            mk_pod("any1"),
            mk_pod("impossible", selector={"disk": "tape"}),
        ]
        nodes = [
            mk_node("n-ssd", labels={"disk": "ssd"}),
            mk_node("n-hdd", labels={"disk": "hdd"}),
        ]
        scalar, batch = assert_parity(pods, nodes)
        assert scalar[0] == "n-ssd" and scalar[1] == "n-hdd"
        assert scalar[3] is None

    def test_host_ports(self):
        pods = [mk_pod(f"hp{i}", host_port=8080) for i in range(3)]
        nodes = [mk_node("n0"), mk_node("n1")]
        scalar, batch = assert_parity(pods, nodes)
        assert scalar[2] is None  # only 2 nodes can hold port 8080

    def test_volumes_exclusive(self):
        pods = [mk_pod("v1", pd="disk-a"), mk_pod("v2", pd="disk-a")]
        nodes = [mk_node("n0"), mk_node("n1")]
        scalar, batch = assert_parity(pods, nodes)
        assert set(scalar) == {"n0", "n1"}

    def test_pinned_host(self):
        pods = [mk_pod("pin", pinned="n1"), mk_pod("ghost", pinned="nope")]
        nodes = [mk_node("n0"), mk_node("n1")]
        scalar, batch = assert_parity(pods, nodes)
        assert scalar == ["n1", None]

    def test_not_ready_node_excluded(self):
        pods = [mk_pod("p0")]
        nodes = [mk_node("dead", cpu=64000, ready=False), mk_node("n1")]
        scalar, batch = assert_parity(pods, nodes)
        assert scalar == ["n1"]

    def test_existing_occupancy(self):
        assigned = [mk_pod("a0", cpu=3000, mem_mib=4096)]
        assigned[0].spec.node_name = "n0"
        pods = [mk_pod("p0", cpu=500, mem_mib=512)]
        nodes = [mk_node("n0"), mk_node("n1")]
        scalar, batch = assert_parity(pods, nodes, assigned=assigned)
        assert scalar == ["n1"]  # n0 is loaded

    def test_overcommitted_node_rejected(self):
        """A node whose existing pods overflow greedy capacity rejects
        all new pods (predicates.go:152) — but still scores."""
        assigned = [
            mk_pod("a0", cpu=3000, mem_mib=64),
            mk_pod("a1", cpu=3000, mem_mib=64),
            mk_pod("a2", cpu=3000, mem_mib=64),  # 9000m > 4000m
        ]
        for a in assigned:
            a.spec.node_name = "n0"
        pods = [mk_pod("p0", cpu=100, mem_mib=64)]
        nodes = [mk_node("n0"), mk_node("n1")]
        scalar, batch = assert_parity(pods, nodes, assigned=assigned)
        assert scalar == ["n1"]

    def test_service_spreading(self):
        svc = Service(
            metadata=ObjectMeta(name="web", namespace="default"),
            spec=ServiceSpec(selector={"app": "web"}),
        )
        assigned = [
            mk_pod("a0", labels={"app": "web"}),
            mk_pod("a1", labels={"app": "web"}),
        ]
        assigned[0].spec.node_name = "n0"
        assigned[1].spec.node_name = "n0"
        pods = [mk_pod(f"w{i}", labels={"app": "web"}) for i in range(4)]
        nodes = [mk_node("n0"), mk_node("n1"), mk_node("n2")]
        assert_parity(pods, nodes, assigned=assigned, services=[svc])


def random_cluster(seed):
    """Shared fuzz-cluster generator: (pending, nodes, assigned,
    services). Used by both the scalar-parity fuzz here and the
    sharded-mesh parity fuzz in test_multichip.py, so both suites
    always sample the same input space."""
    rng = random.Random(seed)
    n_nodes = rng.randint(1, 12)
    n_pods = rng.randint(1, 40)
    zones = ["a", "b", "c"]
    nodes = [
        mk_node(
            f"n{j}",
            cpu=rng.choice([1000, 2000, 4000, 8000]),
            mem_mib=rng.choice([1024, 4096, 8192]),
            pods=rng.choice([3, 10, 40]),
            labels={"zone": rng.choice(zones)} if rng.random() < 0.7 else {},
            ready=rng.random() > 0.1,
        )
        for j in range(n_nodes)
    ]
    svc = Service(
        metadata=ObjectMeta(name="web", namespace="default"),
        spec=ServiceSpec(selector={"app": "web"}),
    )
    assigned = []
    for i in range(rng.randint(0, 10)):
        a = mk_pod(
            f"a{i}",
            cpu=rng.choice([0, 100, 500, 1000]),
            mem_mib=rng.choice([0, 64, 512, 1024]),
            labels={"app": "web"} if rng.random() < 0.5 else {},
        )
        a.spec.node_name = rng.choice(nodes).metadata.name
        assigned.append(a)
    pods = [
        mk_pod(
            f"p{i}",
            cpu=rng.choice([0, 50, 100, 500, 1500]),
            mem_mib=rng.choice([0, 16, 128, 1024]),
            selector={"zone": rng.choice(zones)} if rng.random() < 0.3 else None,
            host_port=rng.choice([0, 0, 0, 8080, 9090]),
            labels={"app": "web"} if rng.random() < 0.4 else {},
        )
        for i in range(n_pods)
    ]
    return pods, nodes, assigned, [svc]


class TestRandomizedParity:
    """Fuzz parity across random clusters. The sequential-parity solver
    should match the oracle exactly on Mi-granular inputs."""

    @pytest.mark.parametrize("seed", range(6))
    def test_random_cluster(self, seed):
        pods, nodes, assigned, services = random_cluster(seed)
        assert_parity(pods, nodes, assigned=assigned, services=services)


class TestSequentialNumpyOracle:
    """The NumPy sequential oracle (ops.oracle) is the at-scale parity
    yardstick; its equivalence to the scalar object-graph oracle is
    established here, on the same fuzz space."""

    @staticmethod
    def _oracle_names(pending, nodes, assigned=(), services=()):
        from kubernetes_tpu.models.columnar import build_snapshot
        from kubernetes_tpu.ops.oracle import solve_sequential_numpy

        snap = build_snapshot(pending, nodes, assigned, services)
        seq = solve_sequential_numpy(snap)
        return [snap.nodes.names[i] if i >= 0 else None for i in seq]

    @pytest.mark.parametrize("seed", range(6))
    def test_matches_scalar_oracle_fuzz(self, seed):
        pods, nodes, assigned, services = random_cluster(seed)
        scalar = schedule_backlog_scalar(pods, nodes, assigned, services)
        seq = self._oracle_names(pods, nodes, assigned, services)
        parity, mismatches = parity_report(scalar, seq)
        assert parity == 1.0, f"mismatches at {mismatches[:10]}"

    @pytest.mark.slow
    def test_scalar_parity_config2(self):
        """BASELINE config 2 (1k x 100): full scalar-vs-numpy and
        scalar-vs-device parity, asserted >= 0.99 (VERDICT r1 #3)."""
        from __graft_entry__ import _synthetic_objects

        pods, nodes, services = _synthetic_objects(1000, 100, seed=21)
        scalar = schedule_backlog_scalar(pods, nodes, services=services)
        seq = self._oracle_names(pods, nodes, services=services)
        batch = schedule_backlog_tpu(pods, nodes, services=services)
        p_seq, _ = parity_report(scalar, seq)
        p_dev, _ = parity_report(scalar, batch)
        assert p_seq >= 0.99 and p_dev >= 0.99, (p_seq, p_dev)

    @pytest.mark.slow
    def test_device_parity_config3_10k(self):
        """BASELINE config 3 scale (10k x 1k): device vs sequential
        oracle >= 0.99 (VERDICT r1 #3: parity evidence at >=10k pods)."""
        import numpy as np

        from __graft_entry__ import _synthetic_objects
        from kubernetes_tpu.models.columnar import build_snapshot
        from kubernetes_tpu.ops import device_snapshot
        from kubernetes_tpu.ops.oracle import solve_sequential_numpy
        from kubernetes_tpu.ops.solver import solve_assignments

        pods, nodes, services = _synthetic_objects(10000, 1000, seed=22)
        snap = build_snapshot(pods, nodes, services=services)
        seq = solve_sequential_numpy(snap)
        dev = np.asarray(solve_assignments(device_snapshot(snap)))
        parity = float((seq == dev).mean())
        assert parity >= 0.99, parity


class TestPipelinedBacklog:
    """solve_backlog_pipelined must be bit-identical to the monolithic
    TPU path: chunking changes staging, never decisions."""

    @pytest.mark.parametrize("seed", range(4))
    def test_matches_monolithic_fuzz(self, seed):
        from kubernetes_tpu.ops.pipeline import solve_backlog_pipelined

        pods, nodes, assigned, services = random_cluster(seed)
        mono = schedule_backlog_tpu(pods, nodes, assigned, services)
        pipe = solve_backlog_pipelined(
            pods, nodes, assigned, services, chunk=8
        )
        assert mono == pipe

    def test_cross_chunk_state_carries(self):
        """Placements in chunk k must constrain chunk k+1 (capacity)."""
        from kubernetes_tpu.ops.pipeline import solve_backlog_pipelined

        pods = [mk_pod(f"p{i}", cpu=600, mem_mib=64) for i in range(4)]
        nodes = [mk_node("n0", cpu=1000), mk_node("n1", cpu=1000)]
        out = solve_backlog_pipelined(pods, nodes, chunk=2)
        assert out[:2] in (["n0", "n1"], ["n1", "n0"])
        assert out[2:] == [None, None]


def attach_gangs(pods, rng):
    """Attach PodGroups to a backlog fixture: consecutive chunks become
    gangs (some chunks stay ungrouped) with randomized minMember, so
    the acceptance loop exercises accept, reject, and release paths.
    Deterministic per rng seed. Returns the partitioned GangGroups."""
    from kubernetes_tpu.models.objects import POD_GROUP_LABEL
    from kubernetes_tpu.scheduler.gang import partition_backlog

    min_members = {}
    gi = i = 0
    while i < len(pods):
        chunk = pods[i : i + rng.randint(1, 4)]
        i += len(chunk)
        if rng.random() < 0.3:
            continue  # ungrouped chunk: rides along per-pod
        name = f"g{gi}"
        gi += 1
        for p in chunk:
            p.metadata.labels[POD_GROUP_LABEL] = name
        min_members[name] = rng.randint(1, len(chunk) + 1)
    return partition_backlog(
        pods, min_member_of=lambda ns, n: min_members.get(n)
    )


@pytest.mark.gang
class TestGangParity:
    """Every backlog fixture also runs with gangs attached: the scalar
    and TPU paths must agree on the accepted-group set AND on every
    destination (the acceptance loop re-solves, so group rejection must
    not perturb decision parity)."""

    @staticmethod
    def _both(pods, nodes, assigned=(), services=(), groups=()):
        from kubernetes_tpu.scheduler.batch import (
            schedule_backlog_gang_scalar,
            schedule_backlog_gang_tpu,
        )

        ds, acc_s, rej_s = schedule_backlog_gang_scalar(
            pods, nodes, assigned, services, groups=groups
        )
        dt, acc_t, rej_t = schedule_backlog_gang_tpu(
            pods, nodes, assigned, services, groups=groups
        )
        assert {g.key for g in acc_s} == {g.key for g in acc_t}
        assert {g.key for g in rej_s} == {g.key for g in rej_t}
        parity, mismatches = parity_report(ds, dt)
        assert parity == 1.0, f"mismatches at {mismatches[:10]}"
        return ds, acc_s, rej_s

    @pytest.mark.parametrize("seed", range(6))
    def test_random_cluster_with_gangs(self, seed):
        pods, nodes, assigned, services = random_cluster(seed)
        groups = attach_gangs(pods, random.Random(seed + 1000))
        self._both(pods, nodes, assigned, services, groups)

    def test_rejected_gang_zeroes_all_members(self):
        from kubernetes_tpu.models.objects import POD_GROUP_LABEL
        from kubernetes_tpu.scheduler.gang import partition_backlog

        pods = [mk_pod(f"p{i}", cpu=600) for i in range(3)]
        for p in pods:
            p.metadata.labels[POD_GROUP_LABEL] = "g0"
        nodes = [mk_node("n0", cpu=1000)]  # fits 1 of 3; minMember 3
        groups = partition_backlog(pods, min_member_of=lambda ns, n: 3)
        ds, accepted, rejected = self._both(pods, nodes, groups=groups)
        assert ds == [None, None, None]
        assert [g.key for g in rejected] == ["default/g0"]


class TestPreemptionParity:
    """Scalar and TPU victim selection must pick IDENTICAL victim sets
    (and nodes, and preemptor ordering effects) on randomized clusters
    — the preemption analog of the backlog decision-parity bar."""

    @staticmethod
    def _random_preemption_problem(seed):
        rng = random.Random(seed)
        N = rng.randint(1, 8)
        nodes = [
            mk_node(
                f"n{j}",
                cpu=rng.choice([1000, 2000, 4000]),
                mem_mib=rng.choice([1024, 2048, 4096]),
                pods=rng.randint(2, 8),
                labels={"zone": rng.choice(["a", "b"])},
                ready=rng.random() > 0.1,
            )
            for j in range(N)
        ]
        assigned = []
        for i in range(rng.randint(0, 24)):
            p = mk_pod(
                f"a{i}",
                cpu=rng.choice([0, 100, 300, 500, 900]),
                mem_mib=rng.choice([0, 64, 256, 512]),
            )
            p.spec.node_name = f"n{rng.randrange(N)}"
            p.spec.priority = rng.choice([0, 0, 5, 10, 50, 100])
            if rng.random() < 0.1:
                p.metadata.deletion_timestamp = "2026-01-01T00:00:00Z"
            if rng.random() < 0.1:
                p.status.phase = rng.choice(["Succeeded", "Failed"])
            assigned.append(p)
        preemptors = []
        for i in range(rng.randint(1, 5)):
            p = mk_pod(
                f"p{i}",
                cpu=rng.choice([200, 600, 1200, 2500]),
                mem_mib=rng.choice([128, 512, 1024]),
                selector={"zone": rng.choice(["a", "b"])}
                if rng.random() < 0.3
                else None,
            )
            p.spec.priority = rng.choice([0, 20, 60, 200])
            if rng.random() < 0.15:
                p.spec.preemption_policy = "Never"
            preemptors.append(p)
        return preemptors, nodes, assigned

    @pytest.mark.parametrize("seed", range(12))
    def test_victim_set_parity_random_clusters(self, seed):
        from kubernetes_tpu.scheduler.batch import (
            preempt_backlog_scalar,
            preempt_backlog_tpu,
        )

        preemptors, nodes, assigned = self._random_preemption_problem(seed)
        scalar = preempt_backlog_scalar(preemptors, nodes, assigned)
        device = preempt_backlog_tpu(preemptors, nodes, assigned)
        for i, (a, b) in enumerate(zip(scalar, device)):
            ka = (a.key, a.node, a.victims) if a else None
            kb = (b.key, b.node, b.victims) if b else None
            assert ka == kb, f"preemptor #{i}: scalar={ka} device={kb}"

    def test_dominated_only_victims(self):
        """The mask is strict: priority ties are not victims, on both
        paths."""
        from kubernetes_tpu.scheduler.batch import (
            preempt_backlog_scalar,
            preempt_backlog_tpu,
        )

        node = mk_node("n0", cpu=1000)
        a = mk_pod("a", cpu=900)
        a.spec.node_name = "n0"
        a.spec.priority = 100
        hi = mk_pod("hi", cpu=500)
        hi.spec.priority = 100
        for fn in (preempt_backlog_scalar, preempt_backlog_tpu):
            assert fn([hi], [node], [a]) == [None]


@pytest.mark.explain
class TestExplainParity:
    """Device explain readback vs the NumPy scalar predicate twin:
    per-node predicate-failure bits AND the component-score
    decomposition must match 100% (the acceptance bar for the
    flight-recorder surface) — exercised on raw randomized clusters
    and on the states the daemons actually explain: bound pods
    (pre-solve occupancy), infeasible pods (post-solve occupancy), and
    preemption-nominated pods."""

    @staticmethod
    def _assert_parity(pending, nodes, assigned=(), services=()):
        import numpy as np

        from kubernetes_tpu.models.columnar import build_snapshot
        from kubernetes_tpu.ops.oracle import explain_bits_numpy
        from kubernetes_tpu.ops.pipeline import explain_matrix

        names, bits, comps = explain_matrix(
            pending, nodes, assigned, services
        )
        snap = build_snapshot(
            pending, nodes, assigned_pods=assigned, services=services
        )
        tbits, tlr, tbra, tspread = explain_bits_numpy(snap)
        mism = int((bits != tbits).sum())
        assert mism == 0, f"{mism} predicate-bit mismatches"
        assert (comps["leastRequested"] == tlr).all()
        assert (comps["balanced"] == tbra).all()
        assert (comps["spreading"] == tspread).all()
        return names, np.asarray(bits)

    @pytest.mark.parametrize("seed", range(6))
    def test_random_cluster_bit_parity(self, seed):
        pods, nodes, assigned, services = random_cluster(seed)
        self._assert_parity(pods, nodes, assigned, services)

    @pytest.mark.parametrize("seed", range(3))
    def test_bound_and_infeasible_pod_states(self, seed):
        """The daemon's two explain states: bound pods against the
        pre-solve occupancy, unbound pods against the post-solve
        occupancy — where, occupancy only growing, every node must
        show at least one failing predicate for every unbound pod."""
        import copy

        pods, nodes, assigned, services = random_cluster(seed)
        dests = schedule_backlog_tpu(pods, nodes, assigned, services)
        bound = [p for p, d in zip(pods, dests) if d is not None]
        unbound = [p for p, d in zip(pods, dests) if d is None]
        if bound:
            self._assert_parity(bound, nodes, assigned, services)
        if unbound:
            placed = []
            for p, d in zip(pods, dests):
                if d is not None:
                    q = copy.deepcopy(p)
                    q.spec.node_name = d
                    placed.append(q)
            _, bits = self._assert_parity(
                unbound, nodes, list(assigned) + placed, services
            )
            assert (bits != 0).all(), (
                "an unbound pod showed a feasible node in the "
                "post-solve state"
            )

    @pytest.mark.parametrize("seed", range(6))
    def test_preemption_nominated_pods(self, seed):
        """Preemptors granted a nomination explain with the same 100%
        bit parity as everyone else (and their verdicts evaluate
        against the cluster state the victim selection saw)."""
        from kubernetes_tpu.scheduler.batch import preempt_backlog_tpu

        preemptors, nodes, assigned = (
            TestPreemptionParity._random_preemption_problem(seed)
        )
        decisions = preempt_backlog_tpu(preemptors, nodes, assigned)
        nominated = [
            p for p, d in zip(preemptors, decisions) if d is not None
        ]
        if not nominated:
            pytest.skip("no nomination granted for this seed")
        self._assert_parity(nominated, nodes, assigned)


class TestSpreadingParityRegressions:
    """Review findings: overlapping service selectors and terminal-phase
    pods must not diverge from the scalar oracle."""

    def test_overlapping_service_selectors(self):
        svc_a = Service(
            metadata=ObjectMeta(name="svc-a", namespace="default"),
            spec=ServiceSpec(selector={"a": "1"}),
        )
        svc_b = Service(
            metadata=ObjectMeta(name="svc-b", namespace="default"),
            spec=ServiceSpec(selector={"b": "1"}),
        )
        # Assigned pod matches BOTH services; its own first match is
        # svc-a, but it must still count against svc-b's spreading.
        both = mk_pod("both", labels={"a": "1", "b": "1"})
        both.spec.node_name = "n0"
        pods = [mk_pod(f"b{i}", labels={"b": "1"}) for i in range(3)]
        nodes = [mk_node("n0"), mk_node("n1"), mk_node("n2")]
        assert_parity(pods, nodes, assigned=[both], services=[svc_a, svc_b])

    def test_terminal_phase_pod_still_counts_for_spreading(self):
        svc = Service(
            metadata=ObjectMeta(name="web", namespace="default"),
            spec=ServiceSpec(selector={"app": "web"}),
        )
        done = mk_pod("done", labels={"app": "web"})
        done.spec.node_name = "n0"
        done.status.phase = "Succeeded"  # free resources, still spreads
        pods = [mk_pod(f"w{i}", labels={"app": "web"}) for i in range(3)]
        nodes = [mk_node("n0"), mk_node("n1")]
        scalar, batch = assert_parity(pods, nodes, assigned=[done], services=[svc])

    def test_terminal_phase_pod_frees_occupancy(self):
        """...but its resources do NOT count (filterNonRunningPods)."""
        done = mk_pod("done", cpu=3900, mem_mib=64)
        done.spec.node_name = "n0"
        done.status.phase = "Failed"
        pods = [mk_pod("p0", cpu=3000, mem_mib=64)]
        nodes = [mk_node("n0", cpu=4000)]
        scalar, batch = assert_parity(pods, nodes, assigned=[done])
        assert scalar == ["n0"]  # failed pod's cpu is released


def random_capacity_args(seed):
    """Random occupancy-column + probe-shape inputs for the capacity
    kernel twins — the raw f32/i32/b8 arrays both sides consume, over
    the same cap/fit value space the column builders emit (integral
    milli-cpu and MiB columns, masked/overcommitted nodes, dead
    probes, zero-request probes)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 300))
    q = int(rng.integers(1, 12))
    cpu_cap = rng.choice([0.0, 1000.0, 2000.0, 4000.0, 8000.0], n).astype(
        np.float32
    )
    mem_cap = rng.choice([0.0, 1024.0, 4096.0, 8192.0], n).astype(np.float32)
    pods_cap = rng.choice([0.0, 3.0, 10.0, 40.0, 110.0], n).astype(np.float32)
    cpu_fit = np.floor(cpu_cap * rng.random(n) * 1.2).astype(np.float32)
    mem_fit = np.floor(mem_cap * rng.random(n) * 1.2).astype(np.float32)
    pods_used = np.floor(pods_cap * rng.random(n)).astype(np.float32)
    over = rng.random(n) < 0.1
    sched = rng.random(n) > 0.15
    probe_cpu = rng.choice(
        [0.0, 50.0, 100.0, 250.0, 500.0, 2000.0], q
    ).astype(np.float32)
    probe_mem = rng.choice([0.0, 16.0, 64.0, 256.0, 2048.0], q).astype(
        np.float32
    )
    probe_min = rng.integers(1, 9, q).astype(np.int32)
    probe_live = rng.random(q) > 0.2
    return (
        cpu_cap, mem_cap, pods_cap, cpu_fit, mem_fit, pods_used, over,
        sched, probe_cpu, probe_mem, probe_min, probe_live,
    )


@pytest.mark.capacity
class TestCapacityParity:
    """ops/capacity.capacity_report vs ops.oracle.capacity_report_numpy:
    BIT-EXACT on every leaf (np.array_equal, no tolerance) — the
    kernel's cross-node/cross-probe reductions are int32-quantized
    precisely so reduction order cannot split the twins."""

    @staticmethod
    def _assert_bit_exact(args):
        import numpy as np

        from kubernetes_tpu.ops.capacity import capacity_report
        from kubernetes_tpu.ops.oracle import capacity_report_numpy

        dev = capacity_report(*args)
        ora = capacity_report_numpy(*args)
        assert len(dev) == len(ora) == 11
        for i, (d, o) in enumerate(zip(dev, ora)):
            d, o = np.asarray(d), np.asarray(o)
            assert d.shape == o.shape, f"leaf {i}: {d.shape} != {o.shape}"
            assert d.dtype == o.dtype, f"leaf {i}: {d.dtype} != {o.dtype}"
            assert np.array_equal(d, o), f"leaf {i} diverged"
        return ora

    @pytest.mark.parametrize("seed", range(10))
    def test_random_columns_bit_exact(self, seed):
        self._assert_bit_exact(random_capacity_args(seed))

    @pytest.mark.parametrize("seed", range(4))
    def test_cluster_columns_bit_exact(self, seed):
        """The watch-cache column builder (utils/capacity.py
        cluster_columns) feeding both twins on randomized object-graph
        clusters — the plain BatchScheduler's whole sampling path."""
        import numpy as np

        from kubernetes_tpu.utils.capacity import cluster_columns

        pods, nodes, assigned, services = random_cluster(seed)
        for p, d in zip(pods, schedule_backlog_tpu(pods, nodes, assigned)):
            if d is not None:
                p.spec.node_name = d
        cols, names = cluster_columns(nodes, list(assigned) + list(pods))
        probe_cpu = np.asarray([100.0, 500.0, 2000.0, 0.0], np.float32)
        probe_mem = np.asarray([64.0, 512.0, 2048.0, 0.0], np.float32)
        probe_min = np.asarray([1, 4, 8, 1], np.int32)
        probe_live = np.asarray([True, True, True, False])
        self._assert_bit_exact(
            (
                cols["cpu_cap"], cols["mem_cap"], cols["pods_cap"],
                cols["cpu_fit"], cols["mem_fit"], cols["pods_used"],
                cols["over"], cols["sched"],
                probe_cpu, probe_mem, probe_min, probe_live,
            )
        )

    def test_terminating_and_terminal_pods_release_columns(self):
        """cluster_columns frees Terminating and terminal-phase pods'
        charges — their capacity is (about to be) free, so the probes
        must see it (filterNonRunningPods semantics)."""
        from kubernetes_tpu.utils.capacity import cluster_columns

        a = mk_pod("a0", cpu=3900, mem_mib=64)
        a.spec.node_name = "n0"
        cols, _ = cluster_columns([mk_node("n0", cpu=4000)], [a])
        assert cols["cpu_fit"][0] == 3900
        a.metadata.deletion_timestamp = "2026-01-01T00:00:00Z"
        cols, _ = cluster_columns([mk_node("n0", cpu=4000)], [a])
        assert cols["cpu_fit"][0] == 0
        a.metadata.deletion_timestamp = None
        a.status.phase = "Succeeded"
        cols, _ = cluster_columns([mk_node("n0", cpu=4000)], [a])
        assert cols["cpu_fit"][0] == 0

    def test_gang_probe_allocatability(self):
        """A probe's minMember is the gang acceptance bound: headroom
        below it reads not-allocatable even when single pods still
        fit (all-or-nothing, same rule as the gang solver)."""
        import numpy as np

        ones = np.ones(2, np.float32)
        zeros = np.zeros(2, np.float32)
        args = (
            ones * 1000.0, ones * 1024.0, ones * 40.0,  # caps
            zeros, zeros, zeros,  # nothing charged
            np.zeros(2, bool), np.ones(2, bool),  # all live
            np.asarray([600.0, 600.0], np.float32),
            np.asarray([64.0, 64.0], np.float32),
            np.asarray([2, 3], np.int32),  # gang bounds
            np.ones(2, bool),
        )
        out = self._assert_bit_exact(args)
        headroom, slice_ok = out[4], out[6]
        assert list(headroom) == [2, 2]  # one 600m pod per 1000m node
        assert list(slice_ok) == [True, False]  # minMember 2 ok, 3 not


def random_rebalance_args(seed):
    """Random occupancy + movable-worklist + probe inputs for the
    defrag-plan kernel twins: the capacity value space plus a pod axis
    (requests in column units, current placement indices including
    invalid/-1 rows, dead padding, forced drains) and a move budget."""
    import numpy as np

    (
        cpu_cap, mem_cap, pods_cap, cpu_fit, mem_fit, pods_used, over,
        sched, probe_cpu, probe_mem, probe_min, probe_live,
    ) = random_capacity_args(seed)
    rng = np.random.default_rng(seed + 7919)
    n = cpu_cap.shape[0]
    d = int(rng.integers(1, 80))
    pod_cpu = rng.choice([0.0, 50.0, 100.0, 250.0, 600.0, 2000.0], d).astype(
        np.float32
    )
    pod_mem = rng.choice([0.0, 16.0, 64.0, 512.0, 2048.0], d).astype(
        np.float32
    )
    pod_node = rng.integers(-2, n + 2, d).astype(np.int32)
    pod_live = rng.random(d) > 0.2
    pod_force = rng.random(d) < 0.15
    move_budget = np.int32(rng.integers(0, d + 4))
    return (
        cpu_cap, mem_cap, pods_cap, cpu_fit, mem_fit, pods_used, over,
        sched, pod_cpu, pod_mem, pod_node, pod_live, pod_force,
        probe_cpu, probe_mem, probe_min, probe_live, move_budget,
    )


@pytest.mark.rebalance
class TestRebalanceParity:
    """ops/rebalance.plan_moves vs ops.oracle.plan_moves_numpy:
    BIT-EXACT on every leaf (np.array_equal, no tolerance) — the
    defrag scan's gains and scores are int32-quantized and its best-fit
    argmin takes the first minimum on both sides, so reduction order
    and tie-breaks cannot split the twins."""

    @staticmethod
    def _assert_bit_exact(args):
        import numpy as np

        from kubernetes_tpu.ops.oracle import plan_moves_numpy
        from kubernetes_tpu.ops.rebalance import plan_moves

        dev = plan_moves(*args)
        ora = plan_moves_numpy(*args)
        assert len(dev) == len(ora) == 6
        for i, (d, o) in enumerate(zip(dev, ora)):
            d, o = np.asarray(d), np.asarray(o)
            assert d.shape == o.shape, f"leaf {i}: {d.shape} != {o.shape}"
            assert d.dtype == o.dtype, f"leaf {i}: {d.dtype} != {o.dtype}"
            assert np.array_equal(d, o), f"leaf {i} diverged"
        return ora

    @pytest.mark.parametrize("seed", range(10))
    def test_random_worklists_bit_exact(self, seed):
        self._assert_bit_exact(random_rebalance_args(seed))

    def test_consolidation_moves_and_scores(self):
        """The canonical defrag shape: three 500m pods spread over
        three 1000m nodes leave 500m shards a 700m probe cannot use;
        pairing two pods up frees a whole node and both twins agree
        the score drops."""
        import numpy as np

        ones = np.ones(4, np.float32)
        args = (
            ones * 1000.0, ones * 1024.0, ones * 40.0,
            np.asarray([500.0, 500.0, 500.0, 0.0], np.float32),
            np.asarray([64.0, 64.0, 64.0, 0.0], np.float32),
            np.asarray([1.0, 1.0, 1.0, 0.0], np.float32),
            np.zeros(4, bool), np.ones(4, bool),
            np.asarray([500.0] * 3 + [0.0], np.float32),
            np.asarray([64.0] * 3 + [0.0], np.float32),
            np.asarray([0, 1, 2, -1], np.int32),
            np.asarray([True, True, True, False]),
            np.zeros(4, bool),
            np.asarray([700.0], np.float32),
            np.asarray([256.0], np.float32),
            np.asarray([1], np.int32),
            np.asarray([True]),
            np.int32(8),
        )
        out = self._assert_bit_exact(args)
        dest, moved, gain, n_moves, before, after = out
        assert int(n_moves) >= 1
        assert bool(np.any(moved))
        assert float(after) < float(before)
        assert all(int(g) > 0 for g, m in zip(gain, moved) if m)

    def test_budget_zero_plans_nothing(self):
        import numpy as np

        args = list(random_rebalance_args(3))
        args[-1] = np.int32(0)
        out = self._assert_bit_exact(tuple(args))
        assert int(out[3]) == 0 and not bool(np.any(out[1]))
        # Scores still measure: an all-frozen plan is a score probe.
        assert float(out[4]) == float(out[5])
