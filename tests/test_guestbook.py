"""The guestbook example, end to end: the manifests in
examples/guestbook/ must actually work on a real cluster — RCs create
pods, the scheduler places them, the process runtime runs them, env
injection carries the redis service address into the frontend, and the
apiserver's service proxy reaches it.

Reference analog: examples/guestbook/ (the canonical walkthrough) +
test/e2e/kubectl.go's guestbook validation.
"""

import json
import os
import time
import urllib.parse
import urllib.request

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.cmd.localup import LocalCluster, build_parser

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples", "guestbook")


def wait_until(cond, timeout=60.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def load(name):
    with open(os.path.join(EXAMPLES, name)) as f:
        return json.load(f)


@pytest.mark.slow
def test_guestbook_end_to_end():
    from kubernetes_tpu.proxy.portal import LoopbackPortals

    if not LoopbackPortals.supported():
        pytest.skip(
            "needs CAP_NET_ADMIN: the frontend dials the redis VIP, "
            "which is only routable through a real loopback portal"
        )
    args = build_parser().parse_args(
        ["--port", "0", "--nodes", "2", "--process-runtime"]
    )
    cluster = LocalCluster(args).start()
    try:
        client = Client(LocalTransport(cluster.api))
        resource_of = {
            "ReplicationController": "replicationcontrollers",
            "Service": "services",
        }

        def running(selector):
            pods, _ = client.list(
                "pods", namespace="default", label_selector=selector
            )
            return [p for p in pods if p.status.phase == "Running"]

        for fname in ("redis-master-rc.json", "redis-master-service.json"):
            wire = load(fname)
            client.create(resource_of[wire["kind"]], wire, namespace="default")
        assert wait_until(lambda: running("app=redis")), "redis never Running"

        # Frontend starts AFTER the redis service exists, so its env
        # carries REDIS_MASTER_SERVICE_HOST/PORT (capture-at-start
        # semantics, like the reference's guestbook ordering note).
        for fname in ("frontend-rc.json", "frontend-service.json"):
            wire = load(fname)
            client.create(resource_of[wire["kind"]], wire, namespace="default")
        assert wait_until(lambda: running("tier=frontend")), "frontend never Running"

        base = (
            f"{cluster.http.address}/api/v1/namespaces/default/"
            "services/frontend/proxy"
        )

        def frontend_answers():
            try:
                with urllib.request.urlopen(base + "/", timeout=3) as r:
                    return r.status == 200
            except Exception:
                return False


        def cluster_diagnostics():
            import subprocess

            pods, _ = client.list("pods", namespace="default")
            state = [
                (p.metadata.name, p.spec.node_name, p.status.phase,
                 [cs.restart_count for cs in p.status.container_statuses])
                for p in pods
            ]
            listeners = subprocess.run(
                "ss -tlnp | grep -E '16379|18080|6379'",
                shell=True, capture_output=True, text=True,
            ).stdout
            return f"pods={state} listeners=[{listeners}]"

        if not wait_until(frontend_answers, timeout=40):
            try:
                with urllib.request.urlopen(base + "/", timeout=3) as r:
                    last = f"status={r.status}"
            except Exception as e:
                last = f"{type(e).__name__}: {e}"
            raise AssertionError(
                f"frontend unreachable; last={last} {cluster_diagnostics()}"
            )

        # A 200 from the frontend does NOT prove the redis leg is up
        # yet (the example app answers 200 with an empty list while its
        # backend is still binding — same capture-at-start reality the
        # reference guestbook has). Retry the write+read round trip
        # until the message survives, like test/e2e/kubectl.go's
        # guestbook validation polls.
        msg = urllib.parse.quote("hello from the tpu cluster")

        def message_persists():
            try:
                with urllib.request.urlopen(f"{base}/add?msg={msg}", timeout=5) as r:
                    if r.status != 200:
                        return False
                with urllib.request.urlopen(base + "/", timeout=5) as r:
                    return "hello from the tpu cluster" in r.read().decode()
            except Exception:
                return False

        if not wait_until(message_persists, timeout=40):
            raise AssertionError(
                "guestbook entry never persisted through the service "
                f"chain; {cluster_diagnostics()}"
            )
    finally:
        cluster.stop()
