"""Event recording tests (reference behaviors: pkg/client/record/
event_test.go, events_cache.go dedup)."""

import time

from kubernetes_tpu.client.record import EventAggregator, EventBroadcaster
from kubernetes_tpu.client.rest import Client, LocalTransport
from kubernetes_tpu.server.api import APIServer


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return cond()


def mkpod(name="p1"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default", "uid": "u1"},
    }


class TestAggregator:
    def test_first_observation_not_a_repeat(self):
        agg = EventAggregator()
        ev = {
            "metadata": {"name": "e1", "namespace": "default"},
            "involvedObject": {"kind": "Pod", "name": "p1"},
            "reason": "Started",
            "message": "ok",
            "source": {"component": "kubelet"},
        }
        assert agg.observe(ev) is None
        agg.track(ev)
        entry = agg.observe(dict(ev, metadata={"name": "e2", "namespace": "default"}))
        assert entry is not None and entry.count == 2
        assert entry.name == "e1"  # repeats point at the stored event

    def test_different_message_not_aggregated(self):
        agg = EventAggregator()
        ev = {
            "metadata": {"name": "e1", "namespace": "default"},
            "involvedObject": {"kind": "Pod", "name": "p1"},
            "reason": "Failed",
            "message": "a",
            "source": {"component": "kubelet"},
        }
        agg.track(ev)
        other = dict(ev, message="b")
        assert agg.observe(other) is None


class TestBroadcasterSink:
    def setup_method(self):
        self.api = APIServer()
        self.client = Client(LocalTransport(self.api))

    def test_dedup_compresses_repeats(self):
        for _ in range(5):
            self.client.record_event(mkpod(), "FailedScheduling", "no fit", "scheduler")
        assert _wait(
            lambda: any(
                e.get("count") == 5
                for e in self.api.list("events", "default")["items"]
            )
        )
        events = self.api.list("events", "default")["items"]
        assert len(events) == 1  # ONE compressed event, not five
        ev = events[0]
        assert ev["reason"] == "FailedScheduling"
        assert ev["source"]["component"] == "scheduler"
        assert ev["count"] == 5

    def test_distinct_reasons_separate_events(self):
        self.client.record_event(mkpod(), "Started", "up", "kubelet")
        self.client.record_event(mkpod(), "Killing", "down", "kubelet")
        assert _wait(
            lambda: len(self.api.list("events", "default")["items"]) == 2
        )

    def test_eventf_formatting(self):
        rec = self.client.recorder("scheduler")
        rec.eventf(mkpod(), "Scheduled", "bound to %s", "node-3")
        assert _wait(
            lambda: any(
                e["message"] == "bound to node-3"
                for e in self.api.list("events", "default")["items"]
            )
        )

    def test_logging_watcher(self):
        lines = []
        b = EventBroadcaster().start_logging(lines.append)
        rec = b.new_recorder("test")
        rec.event(mkpod(), "Pulled", "image ready")
        assert _wait(lambda: len(lines) == 1)
        assert "default/p1 Pulled: image ready" in lines[0]
        b.shutdown()

    def test_sink_failure_never_raises(self):
        class BoomTransport(LocalTransport):
            def request(self, *a, **k):
                raise RuntimeError("sink down")

        bad = Client(BoomTransport(self.api))
        bad.record_event(mkpod(), "X", "y", "z")  # must not raise
        bad.flush_events()
