"""Columnar encoding tests — the matrix schema feeding the TPU solver."""

import numpy as np

from kubernetes_tpu.models import (
    Container,
    ContainerPort,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    Service,
    ServiceSpec,
)
from kubernetes_tpu.models.columnar import build_snapshot, pod_resource_limits
from kubernetes_tpu.models.objects import (
    GCEPersistentDiskVolumeSource,
    NodeCondition,
    ResourceRequirements,
    Volume,
)
from kubernetes_tpu.models.quantity import parse_quantity


def mk_pod(name, cpu="100m", mem="64Mi", node_name="", selector=None, host_port=0, pd=None, labels=None):
    vols = []
    if pd:
        vols.append(Volume(name="v", gce_persistent_disk=GCEPersistentDiskVolumeSource(pd_name=pd)))
    ports = [ContainerPort(container_port=80, host_port=host_port)] if host_port else []
    return Pod(
        metadata=ObjectMeta(name=name, namespace="default", labels=labels or {}),
        spec=PodSpec(
            containers=[
                Container(
                    name="c",
                    image="nginx",
                    ports=ports,
                    resources=ResourceRequirements(
                        limits={"cpu": parse_quantity(cpu), "memory": parse_quantity(mem)}
                    ),
                )
            ],
            volumes=vols,
            node_name=node_name,
            node_selector=selector or {},
        ),
    )


def mk_node(name, cpu="4", mem="8Gi", labels=None, ready=True):
    return Node(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        status=NodeStatus(
            capacity={"cpu": parse_quantity(cpu), "memory": parse_quantity(mem)},
            conditions=[NodeCondition(type="Ready", status="True" if ready else "False")],
        ),
    )


def test_resource_limits_sum_containers():
    pod = mk_pod("p")
    pod.spec.containers.append(
        Container(
            name="c2",
            image="x",
            resources=ResourceRequirements(
                limits={"cpu": parse_quantity("1"), "memory": parse_quantity("1Gi")}
            ),
        )
    )
    cpu, mem = pod_resource_limits(pod)
    assert cpu == 1100
    assert mem == 64 * 1024**2 + 1024**3


def test_snapshot_shapes_and_resources():
    pods = [mk_pod(f"p{i}", cpu="250m", mem="128Mi") for i in range(3)]
    nodes = [mk_node(f"n{j}") for j in range(2)]
    snap = build_snapshot(pods, nodes)
    assert snap.pods.count == 3
    assert snap.nodes.count == 2
    np.testing.assert_array_equal(snap.pods.cpu_milli, [250, 250, 250])
    np.testing.assert_array_equal(snap.pods.mem_mib, [128, 128, 128])
    np.testing.assert_array_equal(snap.nodes.cpu_cap, [4000, 4000])
    np.testing.assert_array_equal(snap.nodes.mem_cap, [8192, 8192])
    assert snap.nodes.schedulable.all()


def test_occupancy_from_assigned_pods():
    nodes = [mk_node("n0"), mk_node("n1")]
    assigned = [
        mk_pod("a0", cpu="1", mem="1Gi", node_name="n0"),
        mk_pod("a1", cpu="500m", mem="512Mi", node_name="n0"),
        mk_pod("a2", cpu="2", mem="2Gi", node_name="missing"),
    ]
    snap = build_snapshot([], nodes, assigned_pods=assigned)
    np.testing.assert_array_equal(snap.nodes.cpu_used, [1500, 0])
    np.testing.assert_array_equal(snap.nodes.mem_used, [1536, 0])


def test_selector_dedup_and_bits():
    pods = [
        mk_pod("p0", selector={"disk": "ssd"}),
        mk_pod("p1", selector={"disk": "ssd"}),
        mk_pod("p2"),
        mk_pod("p3", selector={"disk": "hdd", "zone": "a"}),
    ]
    nodes = [mk_node("n0", labels={"disk": "ssd"}), mk_node("n1", labels={"disk": "hdd", "zone": "a"})]
    snap = build_snapshot(pods, nodes)
    # p0 and p1 share a selector row; p2 is the empty row 0.
    assert snap.pods.selector_id[0] == snap.pods.selector_id[1]
    assert snap.pods.selector_id[2] == 0
    assert snap.pods.selector_id[3] not in (0, snap.pods.selector_id[0])
    assert snap.pods.sel_bits.shape[0] == 3  # empty, ssd, hdd+zone
    # Subset check host-side: p3's selector bits are all present on n1.
    sel = snap.pods.sel_bits[snap.pods.selector_id[3]]
    assert ((sel & snap.nodes.label_bits[1]) == sel).all()
    assert not ((sel & snap.nodes.label_bits[0]) == sel).all()


def test_ports_and_volumes_bits():
    pods = [mk_pod("p0", host_port=8080, pd="disk-1")]
    nodes = [mk_node("n0"), mk_node("n1")]
    assigned = [mk_pod("a0", host_port=8080, node_name="n0", pd="disk-1")]
    snap = build_snapshot(pods, nodes, assigned_pods=assigned)
    # Conflict on n0 (same hostPort + same PD), clean on n1.
    assert (snap.pods.port_bits[0] & snap.nodes.used_port_bits[0]).any()
    assert not (snap.pods.port_bits[0] & snap.nodes.used_port_bits[1]).any()
    assert (snap.pods.vol_any_bits[0] & snap.nodes.used_vol_any_bits[0]).any()


def test_pinned_node_and_readiness():
    pods = [mk_pod("p0", node_name="n1"), mk_pod("p1", node_name="ghost")]
    nodes = [mk_node("n0", ready=False), mk_node("n1")]
    snap = build_snapshot(pods, nodes)
    assert snap.pods.pinned_node[0] == 1
    assert snap.pods.pinned_node[1] == -2  # unknown node
    np.testing.assert_array_equal(snap.nodes.schedulable, [False, True])


def test_service_mapping_and_counts():
    svc = Service(
        metadata=ObjectMeta(name="web", namespace="default"),
        spec=ServiceSpec(selector={"app": "web"}),
    )
    pods = [mk_pod("p0", labels={"app": "web"}), mk_pod("p1", labels={"app": "db"})]
    nodes = [mk_node("n0"), mk_node("n1")]
    assigned = [
        mk_pod("a0", labels={"app": "web"}, node_name="n0"),
        mk_pod("a1", labels={"app": "web"}, node_name="n0"),
        mk_pod("a2", labels={"app": "web"}, node_name="n1"),
    ]
    snap = build_snapshot(pods, nodes, assigned_pods=assigned, services=[svc])
    assert snap.pods.service_id[0] == 0
    assert snap.pods.service_id[1] == -1
    np.testing.assert_array_equal(snap.nodes.service_counts[:, 0], [2, 1])
